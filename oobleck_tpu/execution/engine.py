"""The worker-side engine: planning, pipelines, training loop, recovery.

Capability match for the reference OobleckEngine / DataParallelEngine /
ReconfigurationEngine (/root/reference/oobleck/execution/engine.py:39-668).
Two deployment shapes share this code:

  * single-controller (default): one engine process drives every visible
    chip; "hosts" partition the chip list (chips_per_host each);
  * multi-host MPMD (OOBLECK_MULTIHOST=1): every host's worker joins one
    jax.distributed world (coordinator address via the control plane,
    elastic/). Pipelines span hosts with host-local stages; cross-host
    edges and the layer-granularity DP allreduce ride XLA collectives over
    process meshes (parallel/cross_host.py); recovery is respawn + live
    mirror refill (checkpoint-free, matching the reference's in-memory
    recovery, engine.py:238-309).

Key behaviors mirrored from the reference:
  * ctor builds dataset/model/profile/templates without any distributed
    state (engine.py:415-524), including the min-host memory bound
    (engine.py:490-513) from template memory requirements vs HBM;
  * instantiate_pipelines: best plan -> per-pipeline dataloaders (data
    position-aware) -> pipeline instances -> DP engine (engine.py:600-643);
  * train loop: pipeline step + layer-granularity cross-pipeline grad sync +
    optimizer step, step timing and memory logged every 10 steps, loss
    logged every step (the reference accumulates loss but never reports it —
    SURVEY §5 gap, closed here);
  * reconfiguration: host algebra (reconfigure.py) -> template re-match ->
    batch redistribution -> re-instantiation reusing surviving weights and
    optimizer state, dataloader position carried over (engine.py:182-309).
"""

from __future__ import annotations

import json
import logging
import threading
import time
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding

from oobleck_tpu.config import OobleckArguments
from oobleck_tpu.elastic.message import JOINED_KEY
from oobleck_tpu.execution.dataloader import (
    DeviceStager,
    OobleckDataLoader,
    OobleckSampler,
    PrefetchingLoader,
)
from oobleck_tpu.execution.dataset import build_dataset
from oobleck_tpu.execution.pipeline import PipelineInstance
from oobleck_tpu.execution.reconfigure import (
    fit_host_groups,
    hosts_to_ranks,
    reconfigure_hosts,
)
from oobleck_tpu.models import build_model
from oobleck_tpu.obs import goodput as obs_goodput
from oobleck_tpu.obs import incident as obs_incident
from oobleck_tpu.obs import spans as obs_spans
from oobleck_tpu.obs import telemetry as obs_telemetry
from oobleck_tpu.parallel.train import make_optimizer
from oobleck_tpu.planning.instantiator import HeterogeneousPlan, PipelineInstantiator
from oobleck_tpu.planning.profiler import load_profile, profile
from oobleck_tpu.planning.templates import PipelineTemplate, TemplateGenerator
from oobleck_tpu.policy import DECISION_KEY as POLICY_DECISION_KEY
from oobleck_tpu.policy import (
    GROW_MODES,
    MECH_ABSORB,
    MECH_GROW_DP,
    MECH_GROW_RESHAPE,
    MECH_REINSTANTIATE,
    MECH_REROUTE,
    MECH_RESTORE,
    decision_from_payload,
)
from oobleck_tpu.utils import background, metrics, recovery
from oobleck_tpu.utils.chaos import chaos
from oobleck_tpu.utils.timer import measure_time, sync_timers

logger = logging.getLogger("oobleck.engine")

DEFAULT_HBM_BYTES = 16 * 2**30  # v5e/v4 chip HBM, used when stats are absent


class HostSyncCounter:
    """Counts host-blocking device readbacks the engine performs (the
    `float(loss)` family). Test hook for the async-dispatch guarantee:
    with input prefetch on and loss_readback_every > 1, steady-state steps
    must not bump this at all."""

    def __init__(self) -> None:
        self.count = 0


host_sync_counter = HostSyncCounter()


def _host_sync(value) -> float:
    """The engine's ONLY device->host readback funnel (counted)."""
    host_sync_counter.count += 1
    return float(value)


class DeferredLoss:
    """Weighted on-device loss scalars whose host readback is postponed
    (execution.loss_readback_every > 1). Holding the jax arrays keeps them
    alive without forcing a sync; resolve() is the single point where the
    host finally blocks."""

    def __init__(self, parts: list[tuple[Any, int]]) -> None:
        self._parts = parts

    def resolve(self) -> float:
        total = sum(w for _, w in self._parts)
        return sum(
            _host_sync(l) * w for l, w in self._parts
        ) / max(1, total)


def _jax_distributed_active() -> bool:
    """Whether jax.distributed.initialize has already run in this process."""
    try:
        from jax._src.distributed import global_state

        return global_state.client is not None
    except Exception:
        # Never probe via jax.process_count() here: it initializes the local
        # backend, which is exactly what this gate exists to prevent.
        return False


class DataParallelEngine:
    """Layer-granularity gradient sync across heterogeneous pipelines
    (reference engine.py:363-412): each layer's grads are summed over every
    pipeline that owns it, at whatever sharding each owner uses.

    Transfers are BATCHED per pipeline pair: each non-anchor owner flattens
    every shared layer's grads into ONE buffer (a single fused concat on its
    own meshes), ships it to the anchor in one `jax.device_put`, and the
    anchor adds it back per-layer inside one jitted program — instead of a
    per-layer, per-leaf transfer loop on the step critical path (the
    reference issues one collective per layer, engine.py:404-412; round-2
    weak #5). Redistribution anchor -> owner batches the same way."""

    def __init__(self, pipelines: list[PipelineInstance]):
        self.pipelines = pipelines
        self.owners: dict[int, list[PipelineInstance]] = {}
        for p in pipelines:
            for li in p.params:
                self.owners.setdefault(li, []).append(p)
        self._jit_cache: dict = {}
        # Observability for tests/benchmarks: batched cross-mesh device_put
        # calls issued by the last do_allreduce (at most one per phase).
        self.last_transfer_count = 0

    # -- flat-buffer helpers ------------------------------------------- #

    @staticmethod
    def _group_key(pipe: PipelineInstance, li: int) -> tuple:
        """Transfer-group key: the stage (sub-mesh) owning layer li."""
        return (pipe.pipeline_id, pipe.stage_of_layer(li))

    def _pack(self, trees: list) -> Any:
        """One flat f32 buffer from same-mesh trees (single fused program)."""
        sig = ("pack",
               tuple((l.shape, str(l.dtype))
                     for t in trees for l in jax.tree.leaves(t)))
        if sig not in self._jit_cache:
            def pack(ts):
                leaves = [l for t in ts for l in jax.tree.leaves(t)]
                return jnp.concatenate(
                    [l.ravel().astype(jnp.float32) for l in leaves]
                )
            self._jit_cache[sig] = jax.jit(pack)
        return self._jit_cache[sig](trees)

    def _unpack_add(self, flat: Any, trees: list) -> list:
        """trees[i] + slices-of-flat, one jitted program on the dst mesh."""
        sig = ("unpack_add",
               tuple((l.shape, str(l.dtype))
                     for t in trees for l in jax.tree.leaves(t)))
        if sig not in self._jit_cache:
            def unpack(f, ts):
                out, off = [], 0
                for t in ts:
                    leaves, struct = jax.tree.flatten(t)
                    new = []
                    for l in leaves:
                        seg = f[off:off + l.size].reshape(l.shape).astype(l.dtype)
                        new.append(l + seg)
                        off += l.size
                    out.append(jax.tree.unflatten(struct, new))
                return out
            self._jit_cache[sig] = jax.jit(unpack)
        return self._jit_cache[sig](flat, trees)

    def _unpack_to(self, flat: Any, metas: list, shardings: list,
                   group: tuple) -> list:
        """Slice flat into trees with `metas` shapes, placed on `shardings`
        (one jitted program with explicit out_shardings on the dst mesh).
        `group` keys the cache: identical shapes on different destination
        stages need different baked-in out_shardings."""
        sig = ("unpack_to", group,
               tuple((shape, str(dtype))
                     for layer in metas for shape, dtype in layer[0]))
        if sig not in self._jit_cache:
            structs = [struct for _, struct in metas]
            leaf_metas = [lm for lm, _ in metas]

            def unpack(f):
                out, off = [], 0
                for lm, struct in zip(leaf_metas, structs):
                    new = []
                    for shape, dtype in lm:
                        size = int(np.prod(shape)) if shape else 1
                        new.append(
                            f[off:off + size].reshape(shape).astype(dtype)
                        )
                        off += size
                    out.append(jax.tree.unflatten(struct, new))
                return out
            self._jit_cache[sig] = jax.jit(
                unpack, out_shardings=shardings
            )
        return self._jit_cache[sig](flat)

    def do_allreduce(self) -> dict[int, dict[int, Any]]:
        """Returns {pipeline_id: {layer: synced_grad_tree}}.

        Transfer granularity is (src stage) -> (anchor stage): one packed
        buffer per stage pair per direction, because a jitted program's
        inputs must share one mesh — a stage IS a mesh here. The
        replicated-flat hop is the single-controller stand-in for the DCN
        allreduce a multi-slice deployment would issue."""
        synced: dict[int, dict[int, Any]] = {p.pipeline_id: {} for p in self.pipelines}
        self.last_transfer_count = 0
        # Group shared layers by (src stage, anchor stage).
        fwd_groups: dict[tuple, list[int]] = {}
        anchors: dict[int, PipelineInstance] = {}
        for li, owners in self.owners.items():
            if len(owners) == 1:
                synced[owners[0].pipeline_id][li] = owners[0].grads[li]
                continue
            anchor = owners[0]
            anchors[li] = anchor
            for other in owners[1:]:
                key = (self._group_key(other, li), self._group_key(anchor, li))
                fwd_groups.setdefault(key, []).append(li)
        by_id = {p.pipeline_id: p for p in self.pipelines}

        # Phase 1 — sum every remote stage's contribution on the anchor.
        # Pack one buffer per (src stage, anchor stage) pair, then ship ALL
        # buffers in a single jax.device_put: handing the runtime the whole
        # transfer set at once lets the copies ride ICI/DCN concurrently
        # instead of serializing through the Python loop.
        totals: dict[int, Any] = {li: anchors[li].grads[li] for li in anchors}
        fwd = []
        for ((src_id, _), (dst_id, dst_st)), lis in sorted(fwd_groups.items()):
            lis = sorted(lis)
            src, dst = by_id[src_id], by_id[dst_id]
            flat = self._pack([src.grads[li] for li in lis])
            sharding = NamedSharding(
                dst.stages[dst_st].mesh, jax.sharding.PartitionSpec()
            )
            fwd.append((lis, flat, sharding))
        if fwd:
            group_lis, flats, dst_shardings = zip(*fwd)
            moved = jax.device_put(list(flats), list(dst_shardings))
            self.last_transfer_count += 1
            for lis, flat in zip(group_lis, moved):
                added = self._unpack_add(flat, [totals[li] for li in lis])
                for li, tree in zip(lis, added):
                    totals[li] = tree

        # Phase 2 — redistribute anchor totals to the other owners.
        bwd_groups: dict[tuple, list[int]] = {}
        for li, anchor in anchors.items():
            synced[anchor.pipeline_id][li] = totals[li]
            for other in self.owners[li][1:]:
                key = (self._group_key(anchor, li), self._group_key(other, li))
                bwd_groups.setdefault(key, []).append(li)
        bwd = []
        for ((_, _), (dst_id, dst_st)), lis in sorted(bwd_groups.items()):
            lis = sorted(lis)
            dst = by_id[dst_id]
            flat = self._pack([totals[li] for li in lis])
            sharding = NamedSharding(
                dst.stages[dst_st].mesh, jax.sharding.PartitionSpec()
            )
            bwd.append((lis, flat, sharding, dst, dst_st))
        if bwd:
            group_lis, flats, dst_shardings, dsts, dst_sts = zip(*bwd)
            moved = jax.device_put(list(flats), list(dst_shardings))
            self.last_transfer_count += 1
            for lis, flat, dst, dst_st in zip(group_lis, moved, dsts, dst_sts):
                metas = []
                shardings = []
                for li in lis:
                    tree = totals[li]
                    leaves, struct = jax.tree.flatten(tree)
                    metas.append(
                        ([(l.shape, l.dtype) for l in leaves], struct)
                    )
                    sh = dst.stages[dst_st].param_shardings[li]
                    shardings.append(sh)
                unpacked = self._unpack_to(flat, metas, shardings,
                                           group=(dst.pipeline_id, dst_st))
                for li, tree in zip(lis, unpacked):
                    synced[dst.pipeline_id][li] = tree
        return synced


class MultiHostDataParallelEngine:
    """Layer-granularity DP sync when pipelines live across jax.distributed
    processes. The wire carries ONLY what DP requires (the reference's own
    discipline: per-layer groups spanning only that layer's owners,
    engine.py:363-412):

      * layers whose owning (pipeline, stage) processes form a SINGLE
        process never touch the wire — their cross-pipeline sum (if any) is
        a local jitted add;
      * layers with the same multi-process owner set are packed into one
        flat buffer per owner set and psummed over THAT process subset, in
        NATIVE dtypes (one lane per dtype — bf16 grads cost bf16 bytes);
      * the per-pipeline weighted losses ride one tiny f32 psum over all
        processes (every process logs the global loss).

    Each (pipeline, layer) gradient is owned by exactly one process (stages
    are host-local), so summing local contributions before the psum
    double-counts nothing. Groups are issued in ascending first-layer order
    — a total order every process derives identically, so overlapping
    owner-set collectives can never deadlock. A 1-pipeline plan (no DP) has
    no shared layers and transfers ~nothing beyond the loss scalar."""

    def __init__(self, pipelines: list[PipelineInstance], model, comm,
                 participants=None):
        from oobleck_tpu.parallel.cross_host import (
            TypedFlatLayout, layer_avals)

        self.pipelines = pipelines
        self.comm = comm
        # Loss-psum membership. Defaults to the whole world; an in-place
        # degrade (zero-respawn recovery) shrinks it to the survivors so
        # collectives never wait on the drained victim process.
        self.participants = (list(participants) if participants is not None
                            else list(range(comm.process_count)))
        # Union of owners across ALL pipelines (remote included): needed so
        # every process agrees on which layers are DP-shared.
        self.owners: dict[int, list[PipelineInstance]] = {}
        owner_procs: dict[int, set[int]] = {}
        for p in pipelines:
            for st in p.stages:
                for li in st.layer_ids:
                    self.owners.setdefault(li, []).append(p)
                    owner_procs.setdefault(li, set()).add(st.process)
        by_set: dict[tuple[int, ...], list[int]] = {}
        for li, procs in owner_procs.items():
            if len(procs) > 1:
                by_set.setdefault(tuple(sorted(procs)), []).append(li)
        # [(procs, sorted layer ids)] in ascending first-layer order.
        self.groups: list[tuple[tuple[int, ...], list[int]]] = [
            (procs, sorted(lis))
            for procs, lis in sorted(by_set.items(),
                                     key=lambda kv: min(kv[1]))
        ]
        avals = layer_avals(model)
        self.layouts = [
            TypedFlatLayout({li: avals[li] for li in lis})
            for _, lis in self.groups
        ]
        self._wire_layer_group = {
            li: gi for gi, (_, lis) in enumerate(self.groups) for li in lis
        }
        self._jit_cache: dict = {}
        self.last_transfer_count = 0
        self.last_wire_bytes = 0
        self.n_pipelines = len(pipelines)

    # -- device-side pack/sum/unpack ------------------------------------ #

    def _pack_group(self, gi: int, per_layer: dict[int, list]):
        """Per-dtype flat contribution vectors for group gi: local grad
        leaves are consolidated onto the local proc-mesh device (D2D) and a
        single jitted program sums same-layer contributions and
        ravels/concats them into layout order — no host staging, no f32
        widening."""
        _, lis = self.groups[gi]
        layout = self.layouts[gi]
        all_leaves = [
            l for li in lis for t in per_layer[li]
            for l in jax.tree.leaves(t)
        ]
        all_leaves = jax.device_put(
            all_leaves, self.comm.local_device_sharding
        )
        counts = tuple(len(per_layer[li]) for li in lis)
        key = ("pack", gi, counts)
        if key not in self._jit_cache:
            nleaves = {li: len(layout.leaf_metas[li]) for li in lis}

            def pack(leaves):
                it = iter(leaves)
                segs: dict[Any, list] = {dt: [] for dt in layout.dtypes}
                for li, cnt in zip(lis, counts):
                    per_tree = [
                        [next(it) for _ in range(nleaves[li])]
                        for _ in range(cnt)
                    ]
                    summed = [
                        sum(ls[1:], start=ls[0]) for ls in zip(*per_tree)
                    ]
                    for leaf, (shape, dtype, wdt, off, n) in zip(
                        summed, layout.leaf_metas[li]
                    ):
                        segs[wdt].append(jnp.ravel(leaf).astype(wdt))
                return tuple(
                    jnp.concatenate(segs[dt]) for dt in layout.dtypes
                )

            self._jit_cache[key] = jax.jit(pack)
        return self._jit_cache[key](all_leaves)

    def _unpack_layer_device(self, gi: int, totals, li: int):
        """Slice one layer's grad tree out of group gi's reduced vectors,
        on the local device (the subsequent device_put to the stage
        sharding is a D2D placement)."""
        key = ("unpack", gi, li)
        if key not in self._jit_cache:
            layout = self.layouts[gi]
            self._jit_cache[key] = jax.jit(
                lambda vs, _li=li: layout.unpack(vs, _li)
            )
        return self._jit_cache[key](totals)

    def _local_sum(self, trees: list):
        """Sum same-layer grads from multiple LOCAL pipelines (no wire)."""
        if len(trees) == 1:
            return trees[0]
        leaves = [l for t in trees for l in jax.tree.leaves(t)]
        leaves = jax.device_put(leaves, self.comm.local_device_sharding)
        n = len(jax.tree.leaves(trees[0]))
        struct = jax.tree.structure(trees[0])
        key = ("localsum", len(trees), n, struct)
        if key not in self._jit_cache:
            def add(ls):
                per_tree = [ls[i * n:(i + 1) * n] for i in range(len(trees))]
                return [sum(g[1:], start=g[0]) for g in zip(*per_tree)]
            self._jit_cache[key] = jax.jit(add)
        return jax.tree.unflatten(struct, self._jit_cache[key](leaves))

    def allreduce(self, local_losses: dict[int, tuple[float, int]]
                  ) -> tuple[dict[int, dict[int, Any]], float]:
        """local_losses: {pipeline_id: (loss, weight)} for pipelines whose
        last stage is local. Returns ({pipeline_id: {layer: summed grads}}
        for LOCAL (pipeline, layer) pairs, global weighted mean loss)."""
        me = self.comm.process_index
        wire0 = self.comm.wire_bytes
        per_layer: dict[int, list] = {}
        for pipe in self.pipelines:
            for li in sorted(pipe.grads):
                per_layer.setdefault(li, []).append(pipe.grads[li])

        # Wire phase: one per-dtype psum per owner set this process is in,
        # in the global group order (deadlock-free by construction).
        group_totals: dict[int, tuple] = {}
        self.last_transfer_count = 0
        for gi, ((procs, lis), layout) in enumerate(
            zip(self.groups, self.layouts)
        ):
            if me not in procs:
                continue
            vecs = self._pack_group(gi, per_layer)
            group_totals[gi] = tuple(
                self.comm.group_sum_device(v, layout.lengths[dt], procs, dt)
                for v, dt in zip(vecs, layout.dtypes)
            )
            self.last_transfer_count += len(layout.dtypes)

        # Loss psum (all processes): [weight * loss, weight] per pipeline.
        loss_vec = np.zeros(2 * self.n_pipelines, np.float32)
        for i, pipe in enumerate(self.pipelines):
            if pipe.pipeline_id in local_losses:
                loss, weight = local_losses[pipe.pipeline_id]
                # The multihost loss rides the host-side group_sum;
                # _defer_losses() documents this path cannot defer.
                # oobleck: allow[OBL002] -- multihost loss allreduce
                loss_vec[2 * i] = float(loss) * weight
                loss_vec[2 * i + 1] = weight
        tail = self.comm.group_sum(
            loss_vec, loss_vec.shape[0], self.participants
        )
        self.last_wire_bytes = self.comm.wire_bytes - wire0

        # Local phase: slice wire totals / sum local-only layers, placed on
        # each owning pipeline's stage sharding.
        local_sums: dict[int, Any] = {}
        synced: dict[int, dict[int, Any]] = {}
        for pipe in self.pipelines:
            if not pipe.participates_locally:
                continue
            out: dict[int, Any] = {}
            for li in pipe.params:
                gi = self._wire_layer_group.get(li)
                if gi is not None:
                    tree = self._unpack_layer_device(gi, group_totals[gi], li)
                else:
                    if li not in local_sums:
                        local_sums[li] = self._local_sum(per_layer[li])
                    tree = local_sums[li]
                out[li] = jax.device_put(
                    tree,
                    pipe.stages[pipe.stage_of_layer(li)].param_shardings[li],
                )
            synced[pipe.pipeline_id] = out
        wl = tail[0::2].sum()
        w = tail[1::2].sum()
        return synced, float(wl / w) if w else float("nan")


class ReconfigurationEngine:
    """Listens on the agent pipe for lost-host notifications and drives the
    engine's reconfiguration (reference engine.py:39-89, daemon thread)."""

    def __init__(self, engine: "OobleckEngine", pipe):
        self.engine = engine
        self.pipe = pipe
        self._thread = threading.Thread(
            target=self._listen, name="reconfig-listener", daemon=True
        )
        self._thread.start()

    def _listen(self) -> None:
        # Single reader for the agent pipe: other message kinds (coordinator
        # announcements during multi-host init) are routed to the engine's
        # control queue instead of being dropped — two readers on one pipe
        # would race and eat each other's messages.
        while True:
            try:
                msg = self.pipe.recv()
            except (EOFError, OSError):
                return
            if not isinstance(msg, dict):
                continue
            if msg.get("kind") == "drain":
                # Proactive preemption: flush durable state at the next
                # step boundary and exit cleanly (agent reports JOB_DONE).
                self.engine.request_drain(trace=obs_spans.extract(msg))
            elif (msg.get("kind") == "degrade" and msg.get("inplace")
                    and self.engine.multihost):
                # Multihost zero-respawn reroute: queued separately so every
                # process can agree on ONE apply boundary via the per-step
                # consensus collective (_maybe_inplace_degrade).
                self.engine.request_inplace_degrade(
                    msg["lost_ip"], trace=obs_spans.extract(msg),
                    decision=msg.get(POLICY_DECISION_KEY))
            elif msg.get("kind") in ("reconfigure", "degrade", "restore"):
                # The verbs funnel into the same pending queue: the policy
                # decision riding the payload (or, absent one, the engine's
                # own policy consult) picks the mechanism, so the verb is a
                # control-plane hint (and a distinct wire event for the
                # flight recorder), not a hard dispatch. The incident's
                # trace context rides along (obs/spans).
                self.engine.request_reconfiguration(
                    msg["lost_ip"], trace=obs_spans.extract(msg),
                    decision=msg.get(POLICY_DECISION_KEY))
            elif msg.get("kind") == "grow":
                # JOIN incident: capacity ARRIVING instead of leaving. The
                # grow direction rides the same pending-queue + step-
                # boundary pattern as losses (one correlated incident per
                # boundary), never a mid-step mutation.
                self.engine.request_grow(
                    list(msg.get(JOINED_KEY) or ()),
                    trace=obs_spans.extract(msg),
                    decision=msg.get(POLICY_DECISION_KEY))
            else:
                self.engine._control_msgs.put(msg)


class OobleckEngine:
    def __init__(self, args: OobleckArguments, agent_ip: str | None = None,
                 agent_pipe=None, devices: list | None = None):
        self.args = args
        self.agent_ip = agent_ip
        self.agent_pipe = agent_pipe
        self._injected_devices = devices

        self.model = build_model(args.model.model_name, args.model.model_args,
                                 execution=args.execution)
        if (args.execution.resolved_path() == "fused"
                and not getattr(self.model, "fused_supported", False)):
            raise ValueError(
                f"{args.model.model_name} ({getattr(self.model, 'data_kind', '?')}) "
                "is not supported by the fused SPMD step (causal LM only); "
                "set execution.engine_path: mpmd"
            )
        cfg = self.model.config
        seq_len = min(getattr(cfg, "max_position_embeddings", 1024), 1024)
        self.seq_len = seq_len
        self.dataset = build_dataset(
            args.model.dataset_path, args.model.dataset_name,
            model_name=args.model.model_name,
            vocab_size=getattr(cfg, "vocab_size", 0),
            seq_length=seq_len,
            data_kind=getattr(self.model, "data_kind", "causal_lm"),
            mask_token_id=getattr(cfg, "mask_token_id", 103),
            image_size=getattr(cfg, "image_size", 224),
            num_classes=getattr(cfg, "num_classes", 1000),
            num_channels=getattr(cfg, "num_channels", 3),
        )
        # Real validation split when the data source has one; else
        # evaluate() holds out the eval_fraction tail of the train set.
        # Built lazily on first evaluate() — tokenizing a whole extra split
        # at startup would tax exactly the recovery latency BASELINE bounds.
        self._eval_ds_cache: Any = _UNSET
        self._has_val_split: bool | None = None
        self._eval_state = (0, 0)  # rotating (iterations_done, epoch)

        # Planning inputs (profile-on-miss mirrors agent.ensure_profile).
        # The profiled model carries the same execution overrides as the
        # trained one — a bf16 profile must not plan an f32 run.
        from oobleck_tpu.planning.profiler import effective_tag

        tag = effective_tag(args.model.model_tag, args.execution)
        profile(args.model.model_name, args.model.model_args,
                model_tag=args.model.model_tag, execution=args.execution,
                microbatch_size=args.job.microbatch_size, seq_len=seq_len)
        self.profiles = load_profile(
            args.model.model_name, tag, args.job.microbatch_size
        )

        # Cluster geometry: hosts partition the device list. Ranks encode
        # ORIGINAL host indices (rank = original_index * chips_per_host +
        # local), and self.devices never shrinks — so lost-host lookups must
        # use this immutable map, never .index() on the shrinking host_ips
        # list (a second failure would resolve to the wrong host).
        self.host_ips = list(args.dist.node_ips)
        self._host_index = {ip: i for i, ip in enumerate(self.host_ips)}
        self.devices: list | None = None
        self.chips_per_host: int | None = None
        # Multi-host MPMD: one jax.distributed world, host h == process h.
        self.multihost = False
        self.comm = None
        self.templates: list[PipelineTemplate] = []
        self.pipelines: list[PipelineInstance] = []
        self.fused = None                    # FusedPipeline when engine_path=fused
        self._fused_hosts: list[int] = []    # surviving ORIGINAL host indices
        # Wall-clock seconds per completed reconfiguration — the paper's
        # headline recovery metric (BASELINE.md targets <60 s/failure).
        self.recovery_times: list[float] = []
        # Chips left idle by each fused-path recovery (shrink_to_fit drops
        # devices until microbatch divisibility holds); first-class next to
        # recovery_times so silent capacity loss is visible.
        self.stranded_chips: list[int] = []
        self.dataloaders: list[OobleckDataLoader] = []
        self.opt_states: dict[int, dict[int, Any]] = {}
        self.plan: HeterogeneousPlan | None = None
        self.dp_engine: DataParallelEngine | None = None
        self.step = 0
        self._exec_cache: dict = {}
        # Async-dispatch state: device-resident losses awaiting readback
        # (loss_readback_every > 1) and the resolved (step, loss) history —
        # identical in content between deferred and per-step readback, which
        # the parity tests pin down.
        self._pending_losses: list[tuple[int, DeferredLoss]] = []
        self.loss_history: list[tuple[int, float]] = []
        # Warm-recovery precompiler (execution/precompile.py); armed by
        # start_recovery_precompile and re-armed after each reconfigure.
        self._precompiler = None
        # RECOVERY_DEADLINE accounting: set when this engine's state came
        # out of a recovery (in-place reconfigure, or a respawned world
        # restoring live mirrors); cleared by the first completed step,
        # which emits the FIRST_STEP mark.
        self._recovering = False
        self._recovered_at: float | None = None
        # Incident forensics (obs/incident.py): opened by reconfigure(),
        # committed at the first post-recovery step; the digest rides the
        # next metrics push so the master's /status shows the phase
        # breakdown without pulling the full report file.
        self._incident: obs_incident.IncidentBuilder | None = None
        self._incident_record: dict | None = None
        # Live-mirror background writer: snapshots are immutable jax arrays,
        # so the step thread only hands over references; the device_get +
        # pack + npz write happen off-thread (round-4 weak #3).
        self._mirror_thread: threading.Thread | None = None
        self._mirror_skipped = 0
        self.mirror_write_s: list[float] = []
        # Durable-state plane (oobleck_tpu/ckpt): the persistent half of
        # the two-tier recovery story — mirrors refill peers, checkpoints
        # survive whole-slice preemption. Built lazily (needs the resolved
        # process/world identity); env vars can retarget it per deployment.
        args.execution.apply_durable_env_overrides()
        self._durable = None
        self.ckpt_stall_s: list[float] = []
        self._pending_lost: list[tuple[str, dict | None, dict | None]] = []
        # Grow direction (PR 13): JOIN batches waiting for the next step
        # boundary, hosts parked by an absorb_spare verdict (admitted into
        # geometry but not the plan), and chaos spot-lifetime deadlines
        # (monotonic) armed at admit — the priced-in churn actually lands.
        self._pending_joins: list[tuple[list[str], dict | None,
                                        dict | None]] = []
        self._spare_hosts: list[str] = []
        self._spot_deadlines: dict[str, float] = {}
        self._lock = threading.Lock()
        import queue as _queue

        self._control_msgs: _queue.Queue = _queue.Queue()
        # Policy plane (oobleck_tpu/policy): local decision engine for
        # losses the control plane never saw (in-process chaos). A decision
        # attached to the broadcast overrides it, so every process applies
        # the master's verdict. Built lazily.
        self._policy = None
        # Set by a preemption drain request (or by the victim of an
        # in-place degrade): flush durable state at the next step boundary
        # and leave the train loop cleanly.
        self._drain_requested = False
        # Multihost in-place degrade consensus (_maybe_inplace_degrade):
        # the listener thread enqueues under _lock; every process applies
        # entry k only once ALL live processes have seen it.
        self._inplace_queue: list[dict] = []
        self._inplace_applied = 0
        # Processes still in the per-step collectives; None = full world.
        self._live_procs: list[int] | None = None
        # EWMA of wall seconds per step: the policy scorer's unit for
        # converting checkpoint staleness into lost work.
        self._step_s_ewma: float | None = None
        # Fleet-health planes (obs/telemetry.py, obs/goodput.py): one
        # per-step host sample into the process-global ring (the digest
        # rides the agent's heartbeats), and the wall-clock ledger this
        # worker's time is partitioned into. Live-bytes is static leaf
        # metadata cached per plan adoption — summing nbytes every step
        # is wasted host work; ckpt stalls are consumed by cursor so
        # each flush is telemetered exactly once.
        self._ledger = obs_goodput.GoodputLedger()
        self._live_bytes = 0
        self._live_bytes_stale = True
        self._ckpt_stall_seen = 0
        self._data_wait_s = 0.0
        self._last_mfu: float | None = None

        # Training-quality metrics (utils/metrics.py): per-step gauges the
        # master aggregates cluster-wide via the METRICS push.
        reg = metrics.registry()
        self._m_step_seconds = reg.histogram(
            "oobleck_engine_step_seconds", "Wall time per training step")
        self._m_steps = reg.counter(
            "oobleck_engine_steps_total", "Completed training steps")
        self._m_loss = reg.gauge(
            "oobleck_engine_loss", "Training loss of the last step")
        self._m_tokens_per_sec = reg.gauge(
            "oobleck_engine_tokens_per_sec",
            "Global training throughput of the last step")
        self._m_mfu = reg.gauge(
            "oobleck_engine_mfu",
            "Model FLOPs utilization estimate of the last step")
        self._m_bubble = reg.gauge(
            "oobleck_engine_pipeline_bubble_fraction",
            "Pipeline bubble fraction (kind=schedule: closed form "
            "(S-1)/(vM+S-1); kind=measured: dependency replay of measured "
            "per-chunk dispatch times through the schedule graph, falling "
            "back to 1 - busy/(S*step) when no per-op times exist)")
        self._m_input_wait = reg.histogram(
            "oobleck_input_wait_seconds",
            "Blocking time per step waiting on the device-side input "
            "stager (~0 when staging keeps ahead of compute)")
        self._m_dispatch_stall = reg.histogram(
            "oobleck_dispatch_stall_seconds",
            "Time per step spent dispatching batched cross-stage "
            "activation/gradient transfers")
        self._m_reconfigs = reg.counter(
            "oobleck_engine_reconfigurations_total",
            "In-place reconfigurations completed")
        self._m_grows = reg.counter(
            "oobleck_engine_grows_total",
            "Grow incidents applied, by mechanism (absorb_spare / "
            "grow_dp / grow_reshape)")
        self._m_template = reg.gauge(
            "oobleck_engine_pipeline_template_info",
            "Current pipeline layout (labels); value = step when adopted")
        self._m_goodput = reg.gauge(
            "oobleck_goodput_fraction",
            "Fraction of this worker's wall-clock spent in productive "
            "training steps (obs/goodput.py ledger)")
        # (flops_per_token, peak_flops_per_chip|None, n_chips), resolved
        # lazily on the first step; None when the model defies estimation.
        self._flops_cache: Any = _UNSET
        # The engine owns its tracer so reconfigure() can close a mid-window
        # jax.profiler trace before tearing the old topology down.
        self._tracer = None

        self.optimizer = make_optimizer(
            learning_rate=args.job.learning_rate,
            warmup_steps=args.job.warmup_steps,
            weight_decay=args.job.weight_decay,
            max_grad_norm=args.job.max_grad_norm,
        )
        if agent_pipe is not None:
            ReconfigurationEngine(self, agent_pipe)

    # ------------------------------------------------------------------ #

    def initialize_distributed(self) -> None:
        """Bind to the visible devices and compute templates.

        Single-controller (default): all chips are local. Multi-host
        (OOBLECK_MULTIHOST=1): initialize the JAX runtime from the control
        plane's coordinator chain — the first host's worker announces
        `<its_ip>:port` through its agent pipe, the master relays it, and
        every worker passes it to jax.distributed.initialize. This is the
        TPU equivalent of the reference's rank-0 TCPStore port chain +
        NCCL world init (engine.py:563-593).
        """
        import os

        if (os.environ.get("OOBLECK_MULTIHOST") == "1"
                and self.agent_pipe is not None
                and not _jax_distributed_active()):
            # Normally worker_main brought the runtime up before the engine
            # was built (backends must not initialize first); this is the
            # embedded-engine path.
            self._initialize_multihost()
        n_hosts = len(self.host_ips)
        multihost_world = (
            jax.process_count() > 1
            # A 1-host survivor world stays on the multihost path (degenerate
            # 1-process collectives) so mirror-based recovery still runs.
            or (os.environ.get("OOBLECK_MULTIHOST") == "1"
                and _jax_distributed_active())
        )
        if (self._injected_devices is None and multihost_world
                and self.args.execution.resolved_path() == "mpmd"):
            # Multi-host MPMD: host h IS jax process h (worker_main passes
            # process_id = node_ips.index(agent_ip)). Order the global
            # device list host-major so rank = host * chips_per_host +
            # local, and bring up the cross-process comm backend.
            from oobleck_tpu.parallel.cross_host import ProcessComm

            if jax.process_count() != n_hosts:
                raise RuntimeError(
                    f"{jax.process_count()} jax processes != {n_hosts} hosts"
                )
            per_host = [
                sorted((d for d in jax.devices() if d.process_index == p),
                       key=lambda d: d.id)
                for p in range(n_hosts)
            ]
            if len({len(l) for l in per_host}) != 1:
                raise RuntimeError(
                    f"uneven chips per host: {[len(l) for l in per_host]}"
                )
            self.devices = [d for l in per_host for d in l]
            self.chips_per_host = len(per_host[0])
            self.multihost = True
            self.comm = ProcessComm()
            self._broadcast_profiles()
            self._measure_cross_host_allreduce()
        else:
            self.devices = (
                list(self._injected_devices)
                if self._injected_devices is not None
                else list(jax.devices())
            )
            if len(self.devices) % n_hosts != 0:
                raise ValueError(
                    f"{len(self.devices)} devices not divisible by "
                    f"{n_hosts} hosts"
                )
            self.chips_per_host = len(self.devices) // n_hosts

        if self.args.execution.resolved_path() == "fused":
            # Fused path: one global mesh instead of per-pipeline templates;
            # geometry comes from ExecutionArguments at instantiation time.
            self._fused_hosts = list(range(n_hosts))
            return

        self.templates = self._generate_templates(n_hosts)
        logger.info("templates for host counts %s",
                    [t.num_hosts for t in self.templates])

    def _generate_templates(self, max_hosts: int) -> list[PipelineTemplate]:
        """Pipeline templates for every feasible host count in
        [compute_min_hosts(), max_hosts]. Deterministic in its inputs
        (profiles, chip geometry, execution knobs), which is what lets
        grow re-instantiation regenerate with a LARGER ceiling and get the
        existing templates back bit-for-bit plus the new sizes — plan
        parity with a fresh larger-fleet bring-up holds by construction
        (_ensure_templates_for)."""
        min_hosts = self.compute_min_hosts()
        gen = TemplateGenerator()
        # Interleaving changes the cost model (warmup ramp / v), so the
        # planner must rank stage partitions under the schedule that will
        # actually run them.
        vstages = self.args.execution.resolved_virtual_stages
        tp = self.args.execution.tensor_parallel
        sp = max(1, self.args.execution.sequence_parallel)
        unit = tp * sp
        if unit > 1:
            # TP*SP groups are the planning unit: templates are generated
            # over chips_per_host // (tp*sp) "chip groups" and scaled back,
            # so every stage's chip count factors into its (fsdp, seq,
            # tensor) stage mesh.
            if self.chips_per_host % unit != 0:
                raise ValueError(
                    f"chips_per_host={self.chips_per_host} not divisible by "
                    f"tensor_parallel*sequence_parallel={tp}*{sp}"
                )
            base = gen.create_pipeline_templates(
                self.profiles, (min_hosts, max_hosts),
                self.chips_per_host // unit, virtual_stages=vstages,
            )
            templates = [_scale_template_chips(t, unit) for t in base]
        else:
            templates = gen.create_pipeline_templates(
                self.profiles, (min_hosts, max_hosts), self.chips_per_host,
                virtual_stages=vstages,
            )
        if not templates:
            raise RuntimeError(
                f"no feasible pipeline templates for hosts in "
                f"[{min_hosts}, {max_hosts}] x {self.chips_per_host} chips"
            )
        num_stages = self.args.execution.num_stages
        if num_stages > 0:
            filtered = [t for t in templates
                        if len(t.stages) == num_stages]
            if not filtered:
                raise RuntimeError(
                    f"execution.num_stages={num_stages} matches no feasible "
                    f"template (stage counts available: "
                    f"{sorted({len(t.stages) for t in templates})})"
                )
            templates = filtered
        return templates

    def _ensure_templates_for(self, n_hosts: int) -> None:
        """Raise the template ceiling to cover `n_hosts`. Templates were
        generated only up to the STARTUP fleet size (the reference never
        grows, so neither did the generator call); growing past that
        ceiling re-runs the generator with the same inputs and a larger
        range — the overlapping templates come back identical, so every
        cached plan/executable keyed on them stays valid."""
        if self.templates and max(
                t.num_hosts for t in self.templates) >= n_hosts:
            return
        self.templates = self._generate_templates(n_hosts)
        logger.info("templates extended for host counts %s",
                    [t.num_hosts for t in self.templates])

    def _broadcast_profiles(self) -> None:
        """Adopt process 0's layer profile on every process. Planning is
        cost-driven; per-process timing noise would otherwise produce
        different templates/plans per process and the global schedule (whose
        cross-process collectives rely on identical interpretation order)
        would diverge. One collective, at startup only.

        Timings ride an f32 lane; byte counts (mem_params/mem_activation)
        ride an exact int32 lane as two 31-bit halves — f32 silently rounds
        integers past 2**24 (16 MiB, routine for real layers), quietly
        perturbing the planner's memory-feasibility inputs (round-4
        advisor, low), and a single int32 lane would cap layers at 2 GiB
        (real for wide-vocab embeddings / long-context activations)."""
        import dataclasses

        vec: list[float] = []
        ints: list[int] = []
        for p in self.profiles:
            vec.extend([p.forward, p.backward])
            vec.extend(v for _, v in sorted(p.allreduce_in_host.items()))
            vec.extend(v for _, v in sorted(p.allreduce_across_hosts.items()))
            for v in (p.mem_params, p.mem_activation):
                ints.extend([v & 0x7FFFFFFF, v >> 31])  # lo, hi (< 2**62)
        # Profile broadcast happens once per reconfiguration, off the step
        # loop; the inputs are host floats, not device buffers.
        arr = np.asarray(vec, np.float32)  # oobleck: allow[OBL002] -- cold reconfigure path
        iarr = np.asarray(ints, np.int32)  # oobleck: allow[OBL002] -- cold reconfigure path
        if self.comm.process_index != 0:
            arr = np.zeros_like(arr)
            iarr = np.zeros_like(iarr)
        total = self.comm.group_sum(arr, arr.shape[0],
                                    range(self.comm.process_count))
        itotal = self.comm.group_sum(iarr, iarr.shape[0],
                                     range(self.comm.process_count),
                                     dtype=jnp.int32)
        it = iter(total.tolist())
        iit = iter(itotal.tolist())

        def next_int() -> int:
            lo, hi = next(iit), next(iit)
            return (int(hi) << 31) | int(lo)

        adopted = []
        for p in self.profiles:
            fwd, bwd = next(it), next(it)
            in_host = {k: next(it) for k in sorted(p.allreduce_in_host)}
            across = {k: next(it) for k in sorted(p.allreduce_across_hosts)}
            mp, ma = next_int(), next_int()
            adopted.append(dataclasses.replace(
                p, forward=fwd, backward=bwd,
                mem_params=mp, mem_activation=ma,
                allreduce_in_host=in_host, allreduce_across_hosts=across,
            ))
        self.profiles = adopted

    def _measure_cross_host_allreduce(self) -> None:
        """Replace the profile's modeled DCN allreduce costs with MEASURED
        psums over the live process meshes (the same collectives DP sync
        rides), then adopt process 0's measurements everywhere so plans
        stay identical. The reference feeds its planner measured cross-node
        allreduce latencies (profiler.py:141-234); before this, multi-host
        plan quality rested on hardcoded DCN_BW/DCN_LAT_MS constants
        (round-4 missing #2). The measured table is persisted to
        allreduce_across_nodes.json with a "measured" flag so offline
        planning reuses real numbers."""
        import dataclasses

        from oobleck_tpu.planning.profiler import (
            effective_tag, get_profile_path,
            measure_allreduce_across_processes)

        P = self.comm.process_count
        if P < 2:
            return
        sizes = sorted({p.mem_params for p in self.profiles})
        path = get_profile_path(
            self.args.model.model_name,
            effective_tag(self.args.model.model_tag, self.args.execution),
        )
        # Reuse a previously MEASURED table when process 0's cache holds
        # one covering this world size — a post-failure respawn re-enters
        # here and must not pay warmup+timed psums at real layer sizes
        # again (recovery latency is the headline metric). Only process 0
        # reads the file (caches are host-local); the flag + table ride
        # the same broadcast every startup cost does.
        flat = np.zeros(len(sizes) * (P - 1) + 1, np.float32)
        if self.comm.process_index == 0:
            cached = self._load_measured_allreduce(path, P)
            if cached is not None:
                flat[0] = 1.0
                for i, nbytes in enumerate(sizes):
                    for n in range(2, P + 1):
                        flat[1 + i * (P - 1) + (n - 2)] = cached[(nbytes, n)]
                logger.info(
                    "reusing measured cross-host allreduce profile from %s "
                    "(respawns skip re-measurement)", path,
                )
        have = self.comm.group_sum(flat[:1], 1, range(P))
        if have[0] < 1.0:
            table = measure_allreduce_across_processes(self.comm, sizes)
            if self.comm.process_index == 0:
                for i, nbytes in enumerate(sizes):
                    for n in range(2, P + 1):
                        flat[1 + i * (P - 1) + (n - 2)] = table[(nbytes, n)]
        flat = self.comm.group_sum(flat, flat.shape[0], range(P))[1:]
        by_size = {
            nbytes: {
                # oobleck: allow[OBL002] -- one-shot startup microbenchmark
                n: float(flat[i * (P - 1) + (n - 2)])
                for n in range(2, P + 1)
            }
            for i, nbytes in enumerate(sizes)
        }
        adopted = []
        for p in self.profiles:
            across = dict(p.allreduce_across_hosts)
            across.update(by_size[p.mem_params])
            across[1] = 0.0
            adopted.append(
                dataclasses.replace(p, allreduce_across_hosts=across)
            )
        self.profiles = adopted
        logger.info(
            "cross-host allreduce profile measured over %d processes "
            "(%d sizes); planner consumes measured DCN costs", P, len(sizes),
        )
        if self.comm.process_index == 0:
            try:
                # "measured_n" records how far the live measurement went:
                # rows keep modeled entries for n > P (offline planning
                # wants full coverage), so the flag alone must never let a
                # LARGER later world mistake those for measurements.
                rows = [
                    {**{str(k): v
                        for k, v in p.allreduce_across_hosts.items()},
                     "measured": True, "measured_n": P}
                    for p in self.profiles
                ]
                tmp = path / "allreduce_across_nodes.json.tmp"
                tmp.write_text(json.dumps(rows))
                tmp.rename(path / "allreduce_across_nodes.json")
            except OSError as e:
                logger.warning("could not persist measured allreduce "
                               "profile: %s", e)

    def _load_measured_allreduce(self, path, P: int
                                 ) -> dict[tuple[int, int], float] | None:
        """Previously MEASURED cross-host allreduce table from the profile
        cache, keyed (mem_params_bytes, n_hosts) — None unless every row is
        flagged "measured" AND its recorded measurement extent covers this
        world ("measured_n" >= P; rows also carry modeled entries for
        larger n, which must never pass as measurements). Modeled (offline)
        tables never short-circuit a live measurement."""
        f = path / "allreduce_across_nodes.json"
        if not f.exists():
            return None
        try:
            rows = json.loads(f.read_text())
        except (OSError, ValueError):
            return None
        if len(rows) != len(self.profiles):
            return None
        out: dict[tuple[int, int], float] = {}
        for p, row in zip(self.profiles, rows):
            if not row.get("measured") or int(row.get("measured_n", 0)) < P:
                return None
            for n in range(2, P + 1):
                if str(n) not in row:
                    return None
                # oobleck: allow[OBL002] -- parses JSON floats, no device value
                out[(p.mem_params, n)] = float(row[str(n)])
        return out

    def _initialize_multihost(self, timeout_s: float = 120.0) -> None:
        """Coordinator chain: host 0 announces, everyone initializes.

        Untested on real multi-host hardware in this environment (one
        tunneled chip); the chain mirrors the verified single-host relay
        path in elastic/ (worker -> agent -> master -> agents -> workers).
        """
        import socket
        import time as _time

        from oobleck_tpu.elastic.worker import (
            coordinator_address_if_current,
            coordinator_announcement,
        )

        world = len(self.host_ips)
        process_id = self.host_ips.index(self.agent_ip)
        if process_id == 0:
            port = 0
            with socket.socket() as s:
                s.bind(("", 0))
                port = s.getsockname()[1]
            address = f"{self.agent_ip}:{port}"
            self.agent_pipe.send(coordinator_announcement(address, world))
        else:
            # The ReconfigurationEngine thread owns the pipe; coordinator
            # messages arrive via the control queue it feeds.
            import queue as _queue

            deadline = _time.monotonic() + timeout_s
            address = None
            while _time.monotonic() < deadline:
                try:
                    msg = self._control_msgs.get(timeout=1.0)
                except _queue.Empty:
                    continue
                addr = coordinator_address_if_current(msg, world)
                if addr is not None:
                    address = addr
                    break
            if address is None:
                raise TimeoutError("no coordinator address from the agent")
        jax.distributed.initialize(
            coordinator_address=address,
            num_processes=len(self.host_ips),
            process_id=process_id,
        )
        logger.info("jax.distributed initialized: %s (process %d/%d)",
                    address, process_id, len(self.host_ips))

    def compute_min_hosts(self) -> int:
        """Memory lower bound on hosts per pipeline (reference
        engine.py:490-513): 6x param bytes + activations must fit."""
        total_mem = sum(6 * p.mem_params + p.mem_activation for p in self.profiles)
        hbm = DEFAULT_HBM_BYTES
        try:
            stats = jax.devices()[0].memory_stats()
            if stats and "bytes_limit" in stats:
                hbm = stats["bytes_limit"]
        except Exception:
            pass
        per_host = hbm * (self.chips_per_host or 1)
        return max(1, -(-total_mem // per_host))

    # ------------------------------------------------------------------ #

    def _restore_durable_state(self) -> dict | None:
        """ONE restore API over both persistence planes: live-state
        mirrors (peer recovery, freshest) and the durable checkpoint plane
        (survives whole-slice loss). The freshest source wins per the step
        election; checkpoint state fills layers no surviving mirror holds."""
        restored = self.try_restore_checkpoint()
        if self.multihost and self.args.execution.mirror_dir:
            # Collective — every process calls regardless of mirror state.
            mirrored = self._try_restore_mirror()
            if mirrored is not None and (
                restored is None
                or mirrored["meta"]["step"] >= restored["meta"]["step"]
            ):
                if restored is not None:
                    # Layers absent from every mirror keep checkpoint state.
                    for li, v in restored["params"].items():
                        mirrored["params"].setdefault(li, v)
                    for li, v in restored["opt"].items():
                        mirrored["opt"].setdefault(li, v)
                logger.info(
                    "recovered live state from surviving mirrors (step %s, "
                    "checkpoint-free)", mirrored["meta"]["step"],
                )
                restored = mirrored
                # This world exists because a peer died: the first step it
                # completes closes the RECOVERY_DEADLINE chain.
                self._recovering = True
                self._recovered_at = time.monotonic()
        return restored

    def instantiate_pipelines(self, global_num_microbatch: int,
                              num_iterations_done: int = 0, epoch: int = 0) -> None:
        old_params = old_opt = None
        restored = self._restore_durable_state()
        if restored is not None:
            old_params = restored["params"]
            # Optimizer leaves were stored flat; rebuild the optax structure.
            old_opt = {}
            for li, leaves in restored["opt"].items():
                struct = jax.tree.structure(
                    jax.eval_shape(self.optimizer.init, old_params[li])
                )
                old_opt[li] = jax.tree.unflatten(struct, leaves)
            meta = restored["meta"]
            self.step = int(meta["step"])
            num_iterations_done = int(meta["num_iterations_done"])
            epoch = int(meta["epoch"])

        if self.args.execution.resolved_path() == "fused":
            payload = None
            if restored is not None:
                payload = {"params": old_params, "opt": old_opt,
                           "meta": {"step": self.step}}
            self._materialize_fused(global_num_microbatch,
                                    num_iterations_done, epoch, payload)
            self._set_template_gauge()
            return

        ar_across = [p.allreduce_across_hosts for p in self.profiles]
        self.plan = PipelineInstantiator().get_best_execution_plan(
            self.templates, ar_across, len(self.host_ips), global_num_microbatch
        )
        logger.info("execution plan: %s", self.plan)
        self._materialize_plan(self.plan, num_iterations_done, epoch,
                               old_params=old_params, old_opt=old_opt)
        self._set_template_gauge()

    def _fused_devices(self) -> list:
        return [
            d
            for h in self._fused_hosts
            for d in self.devices[h * self.chips_per_host:
                                  (h + 1) * self.chips_per_host]
        ]

    def _fused_mesh(self, devices: list, *, shrink_to_fit: bool):
        """Resolve ExecutionArguments into a global fused mesh over `devices`.

        fsdp=-1 means "the chips left after stage*tensor*seq" (ZeRO-style
        param sharding, matching the MPMD meaning of -1); data absorbs any
        explicit-fsdp remainder. The fused step shards each microbatch's
        sample dim over (data, fsdp), so microbatch_size must divide by
        their product — a config error at startup, but during recovery
        (`shrink_to_fit`) the mesh drops chips instead of crashing the
        training loop it exists to save."""
        from oobleck_tpu.parallel.mesh import MeshShape, make_mesh

        ex = self.args.execution
        mb = self.args.job.microbatch_size
        stage = ex.num_stages if ex.num_stages > 0 else 1
        base = stage * ex.tensor_parallel * ex.sequence_parallel
        if len(devices) < base:
            raise RuntimeError(
                f"{len(devices)} devices cannot fit stage*tensor*seq={base}"
            )
        if self.seq_len % ex.sequence_parallel != 0:
            raise ValueError(
                f"seq_len={self.seq_len} not divisible by "
                f"sequence_parallel={ex.sequence_parallel}"
            )
        hidden = int(getattr(self.model.config, "hidden_size", 0) or 0)
        if ex.fsdp > 0:
            fsdp = ex.fsdp
            data = len(devices) // (base * fsdp)
            if data < 1:
                raise RuntimeError(
                    f"{len(devices)} devices cannot fit "
                    f"stage*tensor*seq*fsdp={base * fsdp}"
                )
        else:
            # Free fsdp: maximize chips used subject to BOTH divisibility
            # constraints (batch dim over data*fsdp, hidden dim over fsdp),
            # preferring larger fsdp (ZeRO memory savings) on ties. The old
            # "fsdp = all remaining chips" choice produced XLA sharding
            # errors whenever hidden_size wasn't divisible by the remainder.
            data, fsdp = _best_data_fsdp(len(devices) // base, mb, hidden)
            if not shrink_to_fit and data * fsdp * base < len(devices):
                # A config that strands chips must stay a LOUD startup
                # error (recovery is the only time quietly dropping chips
                # beats crashing the run it exists to save).
                raise ValueError(
                    f"no (data, fsdp) split uses all {len(devices)} devices: "
                    f"best uses {data * fsdp * base} "
                    f"(microbatch_size={mb} must divide by data*fsdp and "
                    f"hidden_size={hidden} by fsdp); adjust microbatch_size "
                    "or pin stage/tensor/seq via ExecutionArguments"
                )
        if mb % (data * fsdp) != 0 and not shrink_to_fit:
            raise ValueError(
                f"microbatch_size={mb} not divisible by data*fsdp="
                f"{data * fsdp}: the fused path shards each microbatch's "
                "sample dim over (data, fsdp); raise microbatch_size or "
                "pin more devices to stage/tensor/seq via "
                "ExecutionArguments"
            )
        if shrink_to_fit and (
            mb % (data * fsdp) != 0 or data * fsdp * base < len(devices)
        ):
            # Recovery re-plan: instead of only shrinking `data` (which can
            # strand chips, round-3 weak #7), search every feasible
            # (stage, fsdp, data) — stage must divide the model's blocks AND
            # the microbatch count; data*fsdp must divide microbatch_size —
            # and keep the one using the MOST surviving chips, preferring
            # the configured stage count on ties.
            num_mb = self.fused.num_microbatches if self.fused else 1
            layers = getattr(self.model.config, "num_layers", stage)
            best = None
            for s in range(1, len(devices) // (ex.tensor_parallel
                                               * ex.sequence_parallel) + 1):
                if layers % s or num_mb % s:
                    continue
                s_base = s * ex.tensor_parallel * ex.sequence_parallel
                cap = len(devices) // s_base
                if cap < 1:
                    continue
                if ex.fsdp > 0:
                    if mb % ex.fsdp:
                        continue
                    d = next((d for d in range(cap // ex.fsdp, 0, -1)
                              if mb % (d * ex.fsdp) == 0), 0)
                    if not d:
                        continue
                    cand = (d, ex.fsdp)
                else:
                    cand = _best_data_fsdp(cap, mb, hidden)
                used_chips = cand[0] * cand[1] * s_base
                rank = (used_chips, s == stage, -abs(s - stage))
                if best is None or rank > best[0]:
                    best = (rank, s, cand)
            if best is None:
                raise RuntimeError(
                    f"microbatch_size={mb} admits no runnable recovery mesh "
                    f"over {len(devices)} devices"
                )
            _, new_stage, (data, fsdp) = best
            if new_stage != stage:
                logger.warning(
                    "recovery re-plan: stage %d -> %d to reclaim chips",
                    stage, new_stage,
                )
                stage = new_stage
                base = stage * ex.tensor_parallel * ex.sequence_parallel
        used = data * fsdp * base
        if used < len(devices):
            logger.warning(
                "fused mesh uses %d of %d devices", used, len(devices)
            )
        shape = MeshShape(data=data, stage=stage, fsdp=fsdp,
                          seq=ex.sequence_parallel, tensor=ex.tensor_parallel)
        return make_mesh(shape, devices[:used])

    def _prefetch_enabled(self) -> bool:
        """Device-side input staging (execution/dataloader.DeviceStager):
        a background thread shapes AND device_puts iteration N+1's
        microbatches while step N computes. Default ON single-controller,
        OFF under jax.distributed (a staging thread issuing device_puts
        next to collectives is a hang risk not worth the default);
        OOBLECK_PREFETCH=0/1 overrides either way."""
        import os

        v = os.environ.get("OOBLECK_PREFETCH")
        if v is not None:
            return v.lower() not in ("0", "false", "no")
        return not self.multihost

    def _effective_virtual_stages(self, num_stages: int,
                                  num_microbatches: int,
                                  pipeline_index: int,
                                  record: bool = True) -> int:
        """The virtual-stage degree a pipeline can actually run: the
        configured one when its constraints hold (microbatches divisible by
        stages, enough layers), else 1 — with a flight-recorder event so a
        silent fallback after reconfiguration is diagnosable. The recovery
        precompiler calls this with record=False for PREDICTED plans (same
        decision, hence same exec-cache keys, without logging a fallback
        that has not happened)."""
        v = self.args.execution.resolved_virtual_stages
        if v <= 1 or num_stages <= 1:
            return 1
        reason = None
        if num_microbatches % num_stages != 0:
            reason = (f"num_microbatches {num_microbatches} not divisible "
                      f"by num_stages {num_stages}")
        elif self.model.num_pipeline_layers < num_stages * v:
            reason = (f"{self.model.num_pipeline_layers} pipeline layers < "
                      f"num_stages*virtual_stages {num_stages * v}")
        if reason is None:
            return v
        if record:
            logger.warning(
                "pipeline %d: interleaved schedule unavailable (%s); "
                "falling back to 1f1b", pipeline_index, reason,
            )
            metrics.flight_recorder().record(
                "interleave_fallback", pipeline=pipeline_index,
                requested=v, reason=reason, step=self.step,
            )
        return 1

    def _materialize_fused(self, global_num_microbatch: int,
                           num_iterations_done: int, epoch: int,
                           restored: dict | None) -> None:
        from oobleck_tpu.execution.fused import FusedPipeline

        mesh = self._fused_mesh(self._fused_devices(), shrink_to_fit=False)
        logger.info("fused mesh: %s", dict(mesh.shape))
        self.fused = FusedPipeline(
            self.model, mesh, num_microbatches=global_num_microbatch,
            microbatch_size=self.args.job.microbatch_size,
            seq_len=self.seq_len, optimizer=self.optimizer,
            restored=restored,
            overlap=self.args.execution.overlap_config(),
        )
        self.dataloaders = [self._fused_dataloader(
            global_num_microbatch, num_iterations_done, epoch)]
        self.pipelines = []
        self.dp_engine = None

    def _fused_dataloader(self, global_num_microbatch: int,
                          num_iterations_done: int, epoch: int):
        """A loader for the CURRENT self.fused — the stager's place_fn is
        bound to the fused pipeline's mesh, so reconfiguration must rebuild
        it (a batch staged for the old mesh carries the old sharding)."""
        sampler = OobleckSampler(
            num_samples=len(self.dataset) - self._eval_reserve(),
            microbatch_size=self.args.job.microbatch_size,
            pipeline_index=0,
            num_microbatches=[global_num_microbatch],
            num_iterations_done=num_iterations_done,
            epoch=epoch,
        )
        loader = OobleckDataLoader(self.dataset, sampler)
        if self._prefetch_enabled():
            return DeviceStager(loader, self.fused.place_batch)
        return PrefetchingLoader(loader)

    def _materialize_plan(self, plan: HeterogeneousPlan, num_iterations_done,
                          epoch, old_params, old_opt,
                          host_assignment: list[list[int]] | None = None) -> None:
        assignments = plan.assignments(
            ranks=None if host_assignment is None else [
                hosts_to_ranks(hosts, self.chips_per_host)
                for hosts in host_assignment
            ]
        )
        num_mb_list = [a.num_microbatches for a in assignments]
        total_mb = plan.total_num_microbatches
        self.pipelines = []
        for old_dl in self.dataloaders:
            if hasattr(old_dl, "close"):
                old_dl.close()
        self.dataloaders = []
        self.opt_states = {}
        train_samples = len(self.dataset) - self._eval_reserve()
        process_of_rank = (
            [r // self.chips_per_host for r in range(len(self.devices))]
            if self.multihost else None
        )
        for a in assignments:
            pipe = PipelineInstance(
                pipeline_id=a.pipeline_index,
                template=a.template,
                ranks=list(a.ranks),
                model=self.model,
                devices=self.devices,
                num_microbatches=a.num_microbatches,
                total_num_microbatches=total_mb,
                microbatch_size=self.args.job.microbatch_size,
                seq_len=self.seq_len,
                params=old_params,
                exec_cache=self._exec_cache,
                tensor_parallel=self.args.execution.tensor_parallel,
                sequence_parallel=self.args.execution.sequence_parallel,
                fsdp=self.args.execution.fsdp,
                process_of_rank=process_of_rank,
                comm=self.comm,
                virtual_stages=self._effective_virtual_stages(
                    a.template.num_stages, a.num_microbatches,
                    a.pipeline_index,
                ),
            )
            self.pipelines.append(pipe)
            # Train over the head split only; the tail is evaluate()'s
            # held-out reserve.
            sampler = OobleckSampler(
                num_samples=train_samples,
                microbatch_size=self.args.job.microbatch_size,
                pipeline_index=a.pipeline_index,
                num_microbatches=num_mb_list,
                num_iterations_done=num_iterations_done,
                epoch=epoch,
            )
            loader = OobleckDataLoader(self.dataset, sampler)
            # Double-buffering only pays where batches are consumed;
            # non-participating pipelines only track position (advance()).
            if not self.multihost or pipe.participates_locally:
                if self._prefetch_enabled():
                    loader = DeviceStager(
                        loader,
                        lambda b, _p=pipe: _p._place_batch(
                            _p._as_batch_dict(b))[0],
                    )
                else:
                    loader = PrefetchingLoader(loader)
            self.dataloaders.append(loader)
            if old_opt is not None:
                # Optimizer state mirrors params: re-place each layer's state
                # on its new stage sharding (surviving state is reused, as the
                # reference reuses surviving ranks' optimizer objects,
                # pipeline.py:509-519).
                self.opt_states[pipe.pipeline_id] = {
                    li: _place_opt_state(
                        self.optimizer, old_opt[li],
                        pipe.stages[pipe.stage_of_layer(li)].param_shardings[li],
                    )
                    for li in pipe.params
                }
            else:
                self.opt_states[pipe.pipeline_id] = pipe.init_opt_state(self.optimizer)
        self.dp_engine = (
            MultiHostDataParallelEngine(self.pipelines, self.model, self.comm)
            if self.multihost else DataParallelEngine(self.pipelines)
        )

    # ------------------------------------------------------------------ #

    def _defer_losses(self) -> bool:
        """Whether steady-state steps keep losses on-device. The multihost
        MPMD step cannot defer: its loss rides the gradient allreduce as a
        host-side collective value (_train_step_multihost)."""
        return (self.args.execution.loss_readback_every > 1
                and not self.multihost)

    def _wait_staged_inputs(self) -> None:
        """Pre-fence handshake with the input stagers: let every
        in-flight DeviceStager grab finish placing before the train
        thread takes the step's device_work fence (the stager needs the
        fence to place, so waiting on its future while holding the fence
        is a deadlock)."""
        for dl in self.dataloaders:
            if isinstance(dl, DeviceStager):
                dl.wait_staged()

    def _staged_batch(self, dl):
        """(host_batch, placed_or_None) from a loader, observing the input
        wait when a DeviceStager fronted it."""
        if isinstance(dl, DeviceStager):
            batch, placed = dl.next_placed()
            self._m_input_wait.observe(dl.last_wait_s)
            self._data_wait_s += dl.last_wait_s
            return batch, placed
        return dl.next_batch(), None

    @measure_time("step")
    def _train_step(self) -> "float | DeferredLoss":
        from oobleck_tpu.utils.tracing import annotate

        if self.fused is not None:
            with annotate("staging"):
                batch, placed = self._staged_batch(self.dataloaders[0])
            with annotate("fused_step"):
                loss = self.fused.train_step(batch, placed=placed)
            self.step += 1
            if self._defer_losses():
                return DeferredLoss([(loss, 1)])
            return _host_sync(loss)

        if self.multihost:
            return self._train_step_multihost()

        losses = []
        weights = []
        stall_s = 0.0
        with annotate("pipelines"):
            for pipe, dl in zip(self.pipelines, self.dataloaders):
                with annotate("staging"):
                    batch, placed = self._staged_batch(dl)
                losses.append(pipe.train_step(batch, placed=placed))
                weights.append(pipe.num_microbatches)
                stall_s += pipe.last_dispatch_stall_s
        with annotate("dp_allreduce"):
            synced = self.dp_engine.do_allreduce()
        with annotate("optimizer"):
            for pipe in self.pipelines:
                self.opt_states[pipe.pipeline_id] = pipe.apply_updates(
                    self.optimizer, self.opt_states[pipe.pipeline_id],
                    synced[pipe.pipeline_id],
                )
        self._m_dispatch_stall.observe(stall_s)
        self.step += 1
        if self._defer_losses():
            return DeferredLoss(list(zip(losses, weights)))
        total = sum(w for w in weights)
        loss = sum(
            _host_sync(l) * w for l, w in zip(losses, weights)) / total
        return loss

    def _train_step_multihost(self) -> float:
        """One step across the jax.distributed world: every process
        interprets every pipeline (executing only its own stages and the
        cross-process edges it borders), then ONE flat allreduce syncs all
        layer grads and the per-pipeline losses, then each process steps its
        local layers. The reference's cross-node train step decomposes the
        same way (pipeline.train per rank + DataParallelEngine.do_allreduce,
        engine.py:645-649)."""
        from oobleck_tpu.utils.tracing import annotate

        local_losses: dict[int, tuple[float, int]] = {}
        with annotate("pipelines"):
            for pipe, dl in zip(self.pipelines, self.dataloaders):
                # EVERY process advances EVERY sampler in lockstep
                # (deterministic positions), but only participants pay for
                # batch materialization — non-owners advance position only.
                if not pipe.participates_locally:
                    dl.advance()
                    continue
                with annotate("staging"):
                    batch = dl.next_batch()
                loss = pipe.train_step(batch)
                if loss is not None:
                    local_losses[pipe.pipeline_id] = (
                        _host_sync(loss), pipe.num_microbatches
                    )
        with annotate("dp_allreduce"):
            synced, global_loss = self.dp_engine.allreduce(local_losses)
        with annotate("optimizer"):
            for pipe in self.pipelines:
                if pipe.participates_locally:
                    self.opt_states[pipe.pipeline_id] = pipe.apply_updates(
                        self.optimizer, self.opt_states[pipe.pipeline_id],
                        synced[pipe.pipeline_id],
                    )
        self.step += 1
        return global_loss

    def _set_template_gauge(self) -> None:
        """Current pipeline layout for /status: labels describe the plan,
        the value is the step it was adopted at (the master picks the
        series with the highest value as current)."""
        if self.plan is not None:
            self._m_template.set(
                self.step,
                pipelines=str(self.plan.total_num_pipelines),
                stages="/".join(str(t.num_stages)
                                for t in self.plan.instances),
                microbatches="/".join(str(m)
                                      for m in self.plan.num_microbatches),
                hosts=str(len(self.host_ips)),
            )
            # Refresh the projected reroute-retention gauge for the NEW
            # topology (a representative single-host loss): the master's
            # policy scorer reads it from the next snapshot push, so its
            # decisions price degraded throughput from the live plan, not
            # a prior.
            if self.pipelines and self.host_ips:
                self._projected_degrade_retention([self.host_ips[0]])
        elif self.fused is not None:
            self._m_template.set(
                self.step, path="fused", hosts=str(len(self.host_ips)))
        # Plan adoption changed what lives on-device: refresh the
        # live-bytes telemetry estimate at the next step sample.
        self._live_bytes_stale = True

    def _flops_info(self):
        """(flops_per_token, peak_flops_per_chip|None, n_chips) for the MFU
        gauge; None when the model defies the 6N estimate (cached)."""
        if self._flops_cache is not _UNSET:
            return self._flops_cache
        try:
            from oobleck_tpu.parallel.train import (
                count_params,
                estimate_flops_per_token,
                peak_flops,
            )

            cfg = self.model.config
            fpt = estimate_flops_per_token(
                count_params(self.model), self.seq_len,
                num_layers=getattr(cfg, "num_layers", 0),
                hidden_size=getattr(cfg, "hidden_size", 0),
            )
            devices = self.devices or jax.devices()
            self._flops_cache = (
                fpt, peak_flops(devices[0].device_kind), len(devices))
        except Exception as e:  # MFU is best-effort; training never pays
            logger.info("MFU estimate unavailable: %s", e)
            self._flops_cache = None
        return self._flops_cache

    def _bubble_fractions(self, step_s: float) -> dict[str, float]:
        """kind=schedule: the closed form (S-1)/(vM+S-1), microbatch-
        weighted over pipelines. kind=measured: replay of the measured
        per-(stage, chunk) fwd/bwd dispatch durations through the
        schedule's dependency graph (schedule.simulate_bubble) — this
        isolates the schedule-shape bubble from host serialization, which
        a raw busy/step wall-clock ratio cannot do when one process
        dispatches every stage. Falls back to 1 - busy/(S*step) when no
        per-op times exist."""
        from oobleck_tpu.execution.schedule import (
            Op,
            bubble_fraction,
            simulate_bubble,
        )

        out: dict[str, float] = {}
        sched_num = sched_den = 0.0
        sim_num = sim_den = 0.0
        busy_s = 0.0
        busy_slots = 0
        for pipe in self.pipelines:
            s = pipe.num_stages
            m = pipe.num_microbatches
            v = getattr(pipe, "virtual_stages", 1)
            if m + s > 1:
                sched_num += m * bubble_fraction(s, m, v)
                sched_den += m
            op_times = getattr(pipe, "last_op_times", None)
            if op_times:
                def dur(inst, _t=op_times):
                    kind = "f" if inst.op is Op.FORWARD else "b"
                    tot, n = _t.get((inst.stage, inst.chunk, kind),
                                    (0.0, 0))
                    if n:
                        return tot / n
                    vals = [t / c for (_, _, k), (t, c) in _t.items()
                            if k == kind and c]
                    return sum(vals) / len(vals) if vals else 1.0

                try:
                    sim_num += m * simulate_bubble(s, m, v, dur)
                    sim_den += m
                except RuntimeError:  # replay deadlock: fall through
                    pass
            if pipe.last_stage_busy_s:
                busy_s += sum(pipe.last_stage_busy_s.values())
                busy_slots += s
        if sched_den:
            out["schedule"] = sched_num / sched_den
        if sim_den:
            out["measured"] = sim_num / sim_den
        elif busy_slots and step_s > 0:
            out["measured"] = max(0.0, 1.0 - busy_s / (busy_slots * step_s))
        return out

    def _record_step_metrics(self, loss: "float | None",
                             step_s: float) -> None:
        """Per-step timing/throughput metrics; loss is None while its
        readback is deferred (the gauge updates at drain time)."""
        self._m_steps.inc()
        self._m_step_seconds.observe(step_s)
        if loss is not None:
            self._m_loss.set(loss)
        if step_s > 0:
            tokens = self.args.job.global_microbatch_size * self.seq_len
            tps = tokens / step_s
            self._m_tokens_per_sec.set(tps)
            info = self._flops_info()
            if info is not None:
                from oobleck_tpu.parallel.train import mfu_estimate

                fpt, peak, n_chips = info
                mfu = mfu_estimate(tps, fpt, n_chips, peak)
                if mfu is not None:
                    self._m_mfu.set(mfu)
                    self._last_mfu = mfu
        fracs = self._bubble_fractions(step_s)
        for kind, frac in fracs.items():
            self._m_bubble.set(frac, kind=kind)
        self._record_telemetry(step_s, fracs.get("measured", 0.0))

    def _record_telemetry(self, step_s: float,
                          bubble_frac: float) -> None:
        """Feed the fleet-health planes one step's worth of wall-clock:
        a per-host sample into the telemetry ring (the compact digest
        rides the agent's next heartbeat to the master's FleetTracker)
        and the matching split into the goodput ledger. Everything here
        is host arithmetic over already-host values — no device syncs
        (obs/telemetry.py is under the OBL002 fence)."""
        if self._live_bytes_stale:
            self._live_bytes_stale = False
            self._live_bytes = self._estimate_live_bytes()
        compute_s = comm_s = 0.0
        for pipe in self.pipelines:
            c, m = pipe.op_time_split()
            compute_s += c
            comm_s += m
        # Checkpoint flushes land outside step_s (step-boundary stalls),
        # so they are a separate ledger bucket, not a step subdivision.
        ckpt_s = sum(self.ckpt_stall_s[self._ckpt_stall_seen:])
        self._ckpt_stall_seen = len(self.ckpt_stall_s)
        obs_telemetry.telemetry().record_step(
            self.step, step_s, compute_s=compute_s, comm_s=comm_s,
            data_wait_s=self._data_wait_s, ckpt_s=ckpt_s,
            live_bytes=self._live_bytes)
        self._ledger.account_step(step_s, bubble_frac=bubble_frac,
                                  data_wait_s=self._data_wait_s)
        if ckpt_s > 0:
            self._ledger.account("checkpoint", ckpt_s)
        self._m_goodput.set(self._ledger.goodput_fraction())

    def _estimate_live_bytes(self) -> int:
        """Σ nbytes over this process's live params + optimizer leaves.
        Array.nbytes is shape/dtype metadata, not a device readback."""
        try:
            if self.fused is not None:
                st = self.fused.state
                leaves = (jax.tree.leaves(st.params)
                          + jax.tree.leaves(st.opt_state))
            else:
                leaves = []
                for pipe in self.pipelines:
                    leaves += jax.tree.leaves(pipe.params)
                    leaves += jax.tree.leaves(
                        self.opt_states.get(pipe.pipeline_id, {}))
            return sum(int(getattr(x, "nbytes", 0)) for x in leaves)
        except Exception:  # mid-reconfigure topology: skip this sample
            return 0

    def _drain_pending_losses(self, max_steps: int | None = None) -> None:
        """Resolve every deferred loss (one readback per step, but off the
        steady-state critical path): log each step's line in the classic
        format, update the loss gauge to the newest value, and append to
        loss_history. Resolution can fail after a reconfiguration freed
        the backing devices; those steps report as unavailable rather than
        killing the loop."""
        if not self._pending_losses:
            return
        if max_steps is None:
            max_steps = self.args.job.steps
        # The readbacks are device work: fence them so they can't
        # interleave with a stager placing the next batch (same runtime
        # race class as the precompile x checkpoint flake).
        with background.device_work("loss_drain"):
            for step_i, pending in self._pending_losses:
                try:
                    val = pending.resolve()
                except Exception as e:  # backing buffers gone (reconfig)
                    logger.warning(
                        "step %d loss unavailable (deferred readback: %s)",
                        step_i, e,
                    )
                    continue
                self.loss_history.append((step_i, val))
                self._m_loss.set(val)
                logger.info("step %d/%d loss %.4f", step_i, max_steps, val)
        self._pending_losses.clear()

    def _commit_incident(self) -> None:
        """Close the open incident at the first post-recovery step: stamp
        the first_step mark, commit incident-<n>.json, and stage a digest
        for the next metrics push (the agent relays it to the master's
        /status forensics)."""
        inc = self._incident
        if inc is None:
            return
        self._incident = None
        t = inc.mark("first_step")
        obs_spans.span_recorder().record(
            "incident.first_step", t, t, trace_id=inc.trace_id,
            step=self.step)
        # Goodput attribution: the detect -> first_step window is wall-
        # clock this worker did not train. Charge it to the incident's
        # trace so the ledger, /status, and the committed record all
        # agree on what the incident cost.
        lost_s = inc.phase_breakdown().get("total_s", 0.0)
        if lost_s > 0:
            self._ledger.attribute(inc.trace_id, lost_s,
                                   cause=inc.cause or "")
        inc.goodput_cost = self._ledger.incident_cost(inc.trace_id)
        path = inc.commit()
        digest = {"trace_id": inc.trace_id, "lost_ip": inc.lost_ip,
                  "cause": inc.cause, "marks": dict(inc.marks),
                  **inc.phase_breakdown(), "committed_at": t}
        if path:
            digest["path"] = path
        self._incident_record = digest

    def export_pipeline_trace(self, path: str | None = None) -> dict | None:
        """Write the live pipelines' per-(stage, chunk, microbatch) Perfetto
        timeline (obs/pipeline_trace); `path` defaults to
        $OOBLECK_PIPELINE_TRACE, and no path means no export."""
        import os

        from oobleck_tpu.obs import pipeline_trace as ptrace

        path = path or os.environ.get(ptrace.ENV_PIPELINE_TRACE)
        if not path or not self.pipelines:
            return None
        try:
            return ptrace.write_pipeline_trace(path, self.pipelines)
        except OSError as e:
            logger.warning("pipeline trace export failed: %s", e)
            return None

    def _publish_metrics(self) -> None:
        """Ship the registry snapshot up the agent pipe (relayed to the
        master's /metrics) and append it to the JSONL sink."""
        snap = metrics.registry().snapshot()
        snap["step"] = self.step
        d = obs_telemetry.telemetry().digest()
        if d is not None:
            # The agent keeps the latest digest and epoch-stamps it onto
            # every heartbeat (TELEMETRY_KEY) — fleet health costs zero
            # extra control-plane messages.
            snap["telemetry"] = d
        snap["goodput"] = self._ledger.snapshot(mfu=self._last_mfu)
        if self._incident_record is not None:
            # One-shot piggyback, consumed only once the relay succeeds:
            # the master dedups by trace_id, so resending after a pipe
            # hiccup is safe while dropping the digest is not.
            snap["incident"] = self._incident_record
        if self.agent_pipe is not None:
            try:
                self.agent_pipe.send({"kind": "metrics", "snapshot": snap})
                self._incident_record = None
            except (OSError, ValueError):
                pass  # agent gone; the digest stays staged for next push
        else:
            self._incident_record = None  # no relay; the JSONL sink has it
        metrics.dump_jsonl(snap)

    def train(self) -> None:
        """Reference train loop (engine.py:651-668) + loss reporting and
        periodic checkpointing (capability the reference lacks)."""
        from oobleck_tpu.utils.tracing import StepTracer

        max_steps = self.args.job.steps
        interval = self.args.execution.checkpoint_interval
        sync_interval = self.args.execution.replica_sync_interval
        self._tracer = StepTracer()
        plane = self._durable_plane()
        if plane is not None:
            # SIGTERM (TPU maintenance / preemption notice) drains the
            # in-flight snapshot before the process obeys the signal.
            plane.install_preemption_hook()
        try:
            while self.step < max_steps:
                self._tracer.on_step(self.step)
                self._maybe_chaos_kill_stage()
                self._maybe_chaos_kill_hosts()
                self._maybe_chaos_join()
                self._maybe_spot_expire()
                self._maybe_reconfigure()
                self._maybe_grow()
                self._maybe_inplace_degrade()
                if self._drain_requested:
                    # Preemption drain (or in-place-degrade victim): flush
                    # durable state and leave cleanly — the agent reports
                    # JOB_DONE, not a failure.
                    logger.warning(
                        "drain requested: flushing durable state and "
                        "exiting cleanly at step %d", self.step)
                    self.save_checkpoint(wait=True)
                    metrics.flight_recorder().record(
                        "drain_complete", ip=self.agent_ip, step=self.step)
                    break
                # Fault-injection points (utils/chaos.py): the barrier ip/
                # ordinal selectors let a test SIGKILL exactly one worker at
                # exactly one step boundary.
                chaos().barrier("step_start", ip=self.agent_ip)
                # Fence the step dispatch against background XLA work
                # (recovery precompiles, mirror device_get, input staging)
                # — see utils/background.py. The stagers place under their
                # own fence hold, so the in-flight grab must finish BEFORE
                # we take the fence; waiting inside it would deadlock.
                # t0 sits inside the fence so step_s measures the step,
                # not lock contention (the wait is flight-recorded
                # separately as background_work_wait).
                self._wait_staged_inputs()
                self._data_wait_s = 0.0
                with background.device_work("train_step"):
                    t0 = time.perf_counter()
                    loss = self._train_step()
                    step_s = time.perf_counter() - t0
                factor = chaos().slow_factor(self.agent_ip)
                if factor is not None:
                    # Gray-failure injection: stretch this host's step by
                    # sleeping host-side (no device sync involved), so the
                    # telemetry sample reports the same wall time a
                    # genuinely degraded host would.
                    time.sleep((factor - 1.0) * step_s)
                    step_s *= factor
                self._step_s_ewma = (
                    step_s if self._step_s_ewma is None
                    else 0.8 * self._step_s_ewma + 0.2 * step_s)
                chaos().barrier("step_end", ip=self.agent_ip)
                first_after_recovery = self._recovering
                if first_after_recovery:
                    self._recovering = False
                    recovery.mark(
                        recovery.FIRST_STEP, step=self.step, ip=self.agent_ip,
                        elapsed=None if self._recovered_at is None else round(
                            time.monotonic() - self._recovered_at, 3),
                    )
                    self._commit_incident()
                deferred = isinstance(loss, DeferredLoss)
                if deferred:
                    self._pending_losses.append((self.step, loss))
                self._record_step_metrics(
                    None if deferred else loss, step_s)
                if first_after_recovery:
                    # Push at once: the master resolves the in-flight
                    # recovery in /status on the first worker snapshot, and
                    # must not wait out the periodic publish interval.
                    self._publish_metrics()
                if deferred:
                    every = self.args.execution.loss_readback_every
                    if (self.step % every == 0 or self.step >= max_steps
                            or first_after_recovery):
                        self._drain_pending_losses(max_steps)
                else:
                    self.loss_history.append((self.step, loss))
                    logger.info("step %d/%d loss %.4f",
                                self.step, max_steps, loss)
                if self.step % 10 == 0:
                    timers = sync_timers()
                    wire = (
                        f" | dp wire {self.dp_engine.last_wire_bytes} B/step"
                        if self.multihost and self.dp_engine is not None
                        else ""
                    )
                    logger.info("step timer: %s | %s%s",
                                timers.get("step"), _device_memory_summary(),
                                wire)
                    self._publish_metrics()
                if sync_interval and self.step % sync_interval == 0:
                    self._sync_replicas()
                if interval and self.step % interval == 0:
                    # Async submit: the loop stalls only for drain+capture;
                    # the write happens off-thread (oobleck_tpu/ckpt).
                    self.save_checkpoint(wait=False)
                mirror_every = self.args.execution.mirror_interval
                if (self.multihost and self.args.execution.mirror_dir
                        and mirror_every
                        and self.step % mirror_every == 0):
                    self._write_mirror()
            if interval and self.step % interval != 0:
                self.save_checkpoint()
        finally:
            self._drain_pending_losses(max_steps)
            self._mirror_flush()
            if self._durable is not None:
                self._durable.flush()
            self._publish_metrics()
            # Observability exports: the per-op pipeline timeline (only
            # when OOBLECK_PIPELINE_TRACE names a file) and the span ring
            # (only when the JSONL metrics sink is enabled).
            self.export_pipeline_trace()
            if metrics.metrics_dir() is not None:
                obs_spans.span_recorder().dump("train_end")
            if self._tracer is not None:
                self._tracer.close()
                self._tracer = None

    # ------------------------------------------------------------------ #

    def _collect_layer_state(self):
        params: dict[int, Any] = {}
        opt: dict[int, Any] = {}
        for pipe in self.pipelines:
            for li, p in pipe.params.items():
                params.setdefault(li, p)
                opt.setdefault(li, self.opt_states[pipe.pipeline_id][li])
        return params, opt

    def _sync_replicas(self) -> None:
        """Re-broadcast each DP-replicated layer from its canonical owner
        (the first pipeline holding it) to every other owner, bounding the
        bit-wise replica drift that accumulates from different per-mesh
        reduction orders (reference _copy_model_states broadcasts from an
        owner, engine.py:238-309; here a cross-mesh device_put)."""
        if not self.dp_engine:
            return
        if self.multihost:
            self._sync_replicas_multihost()
            return
        for li, owners in self.dp_engine.owners.items():
            if len(owners) <= 1:
                continue
            anchor = owners[0]
            for other in owners[1:]:
                dst = other.stages[other.stage_of_layer(li)].param_shardings[li]
                other.params[li] = jax.device_put(anchor.params[li], dst)
                self.opt_states[other.pipeline_id][li] = _place_opt_state(
                    self.optimizer,
                    self.opt_states[anchor.pipeline_id][li],
                    dst,
                )

    def _fill_full_state(self) -> dict[int, Any]:
        """COLLECTIVE: elect, per layer, the lowest process holding it
        live, and refill the FULL {layer: {"p": params, "o": opt}} state on
        every process with one native-dtype psum per dtype lane — the
        workhorse behind multi-host replica sync and multi-host checkpoint
        collection (the reference's _copy_model_states broadcast,
        engine.py:238-309)."""
        layout = self._live_layout
        nl = len(layout.layers)
        P = self.comm.process_count
        me = self.comm.process_index
        local_state: dict[int, Any] = {}
        for pipe in self.pipelines:
            if not pipe.participates_locally:
                continue
            for li in pipe.params:
                if li not in local_state:
                    local_state[li] = {
                        "p": pipe.params[li],
                        "o": self.opt_states[pipe.pipeline_id][li],
                    }
        votes = np.full(nl, np.inf, np.float32)
        for i, li in enumerate(layout.layers):
            if li in local_state:
                votes[i] = me
        winners = self.comm.group_min(votes, nl, range(P))
        bufs = {dt: np.zeros(layout.lengths[dt], dt)
                for dt in layout.dtypes}
        for i, li in enumerate(layout.layers):
            if np.isfinite(winners[i]) and winners[i] == me:
                layout.pack_into(bufs, li, local_state[li])
        totals = tuple(
            self.comm.group_sum(bufs[dt], layout.lengths[dt], range(P),
                                dtype=dt)
            for dt in layout.dtypes
        )
        return {
            li: layout.unpack(totals, li)
            for i, li in enumerate(layout.layers) if np.isfinite(winners[i])
        }

    def _sync_replicas_multihost(self) -> None:
        """COLLECTIVE anchor re-broadcast across processes: every local
        owner of a DP-shared layer adopts the elected anchor's replica."""
        shared = {li for li, ow in self.dp_engine.owners.items()
                  if len(ow) > 1}
        if not shared:
            return
        full = self._fill_full_state()
        for pipe in self.pipelines:
            if not pipe.participates_locally:
                continue
            for li in pipe.params:
                if li not in shared or li not in full:
                    continue
                dst = pipe.stages[pipe.stage_of_layer(li)].param_shardings[li]
                pipe.params[li] = jax.device_put(full[li]["p"], dst)
                self.opt_states[pipe.pipeline_id][li] = _place_opt_state(
                    self.optimizer, full[li]["o"], dst,
                )

    def _durable_plane(self):
        """Lazy handle on the durable-state plane (oobleck_tpu/ckpt), or
        None when checkpointing is off. Rebuilt if the process identity or
        target dir changed (a respawned multi-host world resolves its comm
        after __init__)."""
        ckpt_dir = self.args.execution.checkpoint_dir
        if not ckpt_dir:
            return None
        from pathlib import Path

        from oobleck_tpu import ckpt

        pi = ws = None
        if self.multihost and self.comm is not None:
            pi, ws = self.comm.process_index, self.comm.process_count
        else:
            # Fused multi-host worlds have no MPMD comm; their process
            # identity is jax.distributed's (1/1 when uninitialized).
            pi, ws = jax.process_index(), jax.process_count()
        d = self._durable
        if (d is None or str(d.root) != str(Path(ckpt_dir).resolve())
                or d.process_index != pi or d.world_size != ws):
            if d is not None:
                d.close()
            ex = self.args.execution
            self._durable = ckpt.DurableStatePlane(
                ckpt_dir, process_index=pi, world_size=ws,
                keep_last=ex.checkpoint_keep_last,
                asynchronous=ex.checkpoint_async, ip=self.agent_ip)
        return self._durable

    def _elected_local_layer_state(self):
        """Multi-host MPMD, NO collective: every layer's writer is the
        minimum process owning it — derivable from the plan on every
        process identically — so each process contributes a disjoint slice
        of the global layer set and the plane's manifest merge makes the
        checkpoint whole. Replaces the old _fill_full_state collective on
        the save path (which shipped every layer to every host just so
        one of them could write)."""
        me = self.comm.process_index if self.comm is not None else 0
        owner: dict[int, int] = {}
        for pipe in self.pipelines:
            for st in pipe.stages:
                proc = st.process if st.process is not None else 0
                for li in st.layer_ids:
                    owner[li] = min(owner.get(li, 1 << 30), proc)
        params: dict[int, Any] = {}
        opt: dict[int, Any] = {}
        for pipe in self.pipelines:
            if not pipe.participates_locally:
                continue
            for li, p in pipe.params.items():
                if owner.get(li) == me and li not in params:
                    params[li] = p
                    opt[li] = self.opt_states[pipe.pipeline_id][li]
        return params, opt

    def save_checkpoint(self, wait: bool = True) -> None:
        """Snapshot + submit to the durable-state plane. Every process
        calls this (each writes only its elected layers' shards; process 0
        commits the manifest — no collective, no barrier). `wait=False` is
        the train-loop mode: the call returns once the snapshot is staged
        to host and enqueued; the stall is drain + staging, not the
        write."""
        plane = self._durable_plane()
        if plane is None:
            return
        meta = dict(
            num_iterations_done=self.dataloaders[0].num_iterations_done,
            epoch=self.dataloaders[0].epoch,
            extra={"model_name": self.args.model.model_name},
        )
        if self.fused is not None:
            try:
                params, opt = self.fused.layer_state()
            except ValueError:
                # Cross-host-sharded fused state: host-local layer assembly
                # is impossible (to_host_local raises). Write the raw
                # stacked leaves shard-wise instead — restore layerizes
                # them (_layerize_stacked) where model+optimizer live.
                st = self.fused.state
                stall = plane.save_stacked(
                    step=self.step, params=st.params,
                    opt_leaves=jax.tree.leaves(st.opt_state), **meta)
                self.ckpt_stall_s.append(stall)
                if wait:
                    plane.flush()
                return
        elif self.multihost:
            params, opt = self._elected_local_layer_state()
        else:
            self._sync_replicas()
            params, opt = self._collect_layer_state()
        stall = plane.save(step=self.step, params=params, opt_state=opt,
                           **meta)
        self.ckpt_stall_s.append(stall)
        if wait:
            plane.flush()

    def try_restore_checkpoint(self) -> dict | None:
        """Load the newest restorable checkpoint from the durable-state
        plane, if any. Torn/corrupt step dirs are quarantined (by process
        0) and skipped. Returns the payload for instantiate_pipelines-time
        consumption."""
        plane = self._durable_plane()
        if plane is None:
            return None
        res = plane.load_latest()  # shared step-selection (ckpt/restore.py)
        if res is None:
            return None
        step, payload = res
        if payload.get("kind") == "fused_stacked":
            payload = self._layerize_stacked(payload)
        from oobleck_tpu.ckpt import manifest as _mf
        logger.info("restoring from durable checkpoint %s (step %s)",
                    _mf.step_dir_name(step), step)
        return payload

    def _layerize_stacked(self, payload: dict) -> dict:
        """Convert a fused_stacked payload (raw stacked TrainState on
        host) into the layer-keyed checkpoint form — pure host-side tree
        restructuring via the fused path's own converters."""
        from oobleck_tpu.execution.fused import (
            opt_state_to_layers,
            params_to_layers,
        )

        params = payload["params"]
        struct = jax.tree.structure(
            jax.eval_shape(self.optimizer.init, params))
        opt_state = jax.tree.unflatten(struct, payload["opt"])
        p_layers = params_to_layers(self.model, params)
        o_layers = opt_state_to_layers(self.model, self.optimizer, params,
                                       opt_state)
        return {"params": p_layers,
                "opt": {li: jax.tree.leaves(v) for li, v in o_layers.items()},
                "meta": payload["meta"]}

    # -- checkpoint-free live-state mirror (multi-host MPMD) ------------ #

    _MAX_MIRROR_STEP = 2**18 - 1  # election votes must fit f32 exactly

    @property
    def _live_layout(self):
        """TypedFlatLayout over {layer: {"p": params, "o": opt leaves}} —
        the shared NATIVE-dtype wire format for mirrors, recovery fill, and
        replica sync (one lane per leaf dtype; no f32 widening)."""
        if getattr(self, "_live_layout_cache", None) is None:
            from oobleck_tpu.parallel.cross_host import (
                TypedFlatLayout, layer_avals)

            avals = layer_avals(self.model)
            self._live_layout_cache = TypedFlatLayout({
                li: {"p": avals[li],
                     "o": jax.eval_shape(self.optimizer.init, avals[li])}
                for li in avals
            })
        return self._live_layout_cache

    def _mirror_file(self):
        """Mirror path. mirror_dir should be host-local storage; the file
        name still carries the host identity so same-machine test clusters
        (loopback-alias "hosts" sharing a filesystem) don't collide."""
        from pathlib import Path

        d = self.args.execution.mirror_dir
        if not d:
            return None
        tag = (self.agent_ip or "local").replace(":", "_").replace("/", "_")
        return Path(d) / f"live_state_{tag}.npz"

    def _write_mirror(self) -> None:
        """Persist this process's LOCAL layers' live state to host-local
        storage (atomic replace). The failure-time cost this buys: recovery
        needs no checkpoint reload and loses at most mirror_interval-1
        steps (reference in-memory recovery loses none but requires
        survivors' processes to outlive the broken world, which the JAX
        runtime cannot guarantee — respawn + mirror is the TPU-shaped
        equivalent).

        The step thread only snapshots REFERENCES (jax arrays are
        immutable — the optimizer step creates new ones); device_get,
        native-dtype packing, and the npz write run on a background
        thread. A write requested while the previous one is in flight is
        skipped (the next interval supersedes it) so mirroring never backs
        up the training loop."""
        path = self._mirror_file()
        if path is None:
            return
        if self._mirror_thread is not None and self._mirror_thread.is_alive():
            self._mirror_skipped += 1
            return
        params, opt = self._collect_layer_state()
        state = {li: {"p": params[li], "o": opt[li]} for li in params}
        meta = {
            "step": self.step,
            "num_iterations_done": self.dataloaders[0].num_iterations_done,
            "epoch": self.dataloaders[0].epoch,
        }
        t = threading.Thread(
            target=self._mirror_write_worker, args=(path, state, meta),
            daemon=True,
        )
        self._mirror_thread = t
        t.start()

    def _mirror_write_worker(self, path, state: dict[int, Any],
                             meta: dict) -> None:
        import os as _os

        t0 = time.monotonic()
        layout = self._live_layout
        # Per-dtype buffers stored as raw bytes: np.save has no portable
        # descr for ml_dtypes (bf16), so every lane rides uint8 and views
        # back to its wire dtype on load.
        bufs = {dt: np.zeros(layout.lengths[dt], dt)
                for dt in layout.dtypes}
        have = np.zeros(len(layout.layers), bool)
        # pack_into device_gets live jax arrays — fence it against the
        # train thread's dispatch/readback (utils/background.py). The npz
        # write below is pure host I/O and runs outside the fence.
        with background.device_work("mirror"):
            for li, tree in state.items():
                layout.pack_into(bufs, li, tree)
                have[layout.layers.index(li)] = True
        path.parent.mkdir(parents=True, exist_ok=True)
        tmp = path.with_suffix(".tmp.npz")
        np.savez(tmp, have=have, **meta,
                 **{f"buf_{dt.name}": b.view(np.uint8)
                    for dt, b in bufs.items()})
        _os.replace(tmp, path)
        dur = time.monotonic() - t0
        self.mirror_write_s.append(dur)
        logger.info(
            "mirror write %.3fs (%d B native-dtype, off-thread, "
            "%d skipped)", dur,
            sum(b.nbytes for b in bufs.values()), self._mirror_skipped,
        )

    def _mirror_flush(self) -> None:
        """Join any in-flight mirror write (restore paths + shutdown)."""
        t = self._mirror_thread
        if t is not None and t.is_alive():
            t.join()

    def _try_restore_mirror(self) -> dict | None:
        """COLLECTIVE (every process must call, mirror or not): elect ONE
        GLOBAL step — the minimum of the survivors' mirror steps, i.e. the
        newest step the laggard still holds — then restore every layer from
        a mirror AT exactly that step (ties -> lowest process). Layers no
        step-S mirror holds fall back to the freshest available copy with a
        loud cross-step-mix warning; without the global election first, a
        failure landing between survivors' mirror writes would silently mix
        layer states from different steps while meta claimed the freshest
        (round-4 advisor, medium). Refills ride one native-dtype psum per
        dtype lane; meta rides an exact int32 lane. Returns a payload
        shaped like try_restore_checkpoint's; None when no process holds a
        mirror. Matches the reference's survivor-broadcast recovery
        (engine.py:238-309) with the state moving over DCN collectives."""
        self._mirror_flush()
        layout = self._live_layout
        nl = len(layout.layers)
        P = self.comm.process_count
        me = self.comm.process_index
        path = self._mirror_file()
        local = None
        if path is not None and path.exists():
            try:
                local = np.load(path)
                # Format check BEFORE any collective round: a mirror from
                # an older wire format (e.g. the pre-round-5 single f32
                # 'buf') must count as unreadable here — discovering a
                # missing key mid-election would kill this process while
                # the other survivors block in the next collective.
                needed = {"have", "step", "num_iterations_done", "epoch"}
                needed |= {f"buf_{dt.name}" for dt in layout.dtypes}
                missing_keys = needed - set(local.files)
                if missing_keys:
                    logger.warning(
                        "mirror %s lacks keys %s (stale wire format?); "
                        "treating as absent", path, sorted(missing_keys),
                    )
                    local = None
            except Exception as e:
                logger.warning("unreadable mirror %s: %s", path, e)
        # Vote encoding (MAX-step)*64 + process must stay exact in f32 and
        # decode via % 64: both break past 64 processes (the control plane
        # caps clusters at MAX_NUM_HOSTS=32, master.py).
        if P > 64:
            raise RuntimeError(
                f"mirror election supports <= 64 processes, got {P}"
            )
        INF = np.float32(np.inf)
        step = have = None
        if local is not None:
            step = int(local["step"])
            if step > self._MAX_MIRROR_STEP:
                # Clamped steps tie in the election (lowest process wins
                # regardless of freshness) — keep recovering, but say so.
                logger.warning(
                    "mirror step %d exceeds the election's exact range "
                    "(%d); freshness ties break by process index",
                    step, self._MAX_MIRROR_STEP,
                )
                step = self._MAX_MIRROR_STEP
            have = np.asarray(local["have"], bool)  # oobleck: allow[OBL002] -- recovery path, host mirror
        # Round 0: the global step S = min over survivors' mirror steps.
        svec = np.full(1, INF, np.float32)
        if local is not None:
            svec[0] = step
        smin = self.comm.group_min(svec, 1, range(P))
        if not np.isfinite(smin[0]):
            return None
        S = int(smin[0])
        at_S = local is not None and step == S
        # Round 1: per-layer owner among mirrors AT step S (lowest process).
        votes1 = np.full(nl, INF, np.float32)
        if at_S:
            votes1[have] = me
        w1 = self.comm.group_min(votes1, nl, range(P))
        # Round 2: freshest-any fallback for layers uncovered at step S.
        votes2 = np.full(nl, INF, np.float32)
        if local is not None:
            votes2[have] = np.float32(
                (self._MAX_MIRROR_STEP - step) * 64 + me
            )
        w2 = self.comm.group_min(votes2, nl, range(P))
        covered = np.isfinite(w2)
        mixed = [layout.layers[i] for i in range(nl)
                 if covered[i] and not np.isfinite(w1[i])]
        if mixed:
            logger.warning(
                "layers %s have no surviving mirror at the elected global "
                "step %d; restoring them from fresher mirrors — their "
                "layer/optimizer state mixes steps", mixed, S,
            )
        missing = [layout.layers[i] for i in range(nl) if not covered[i]]
        if missing:
            logger.warning(
                "no surviving mirror holds layers %s; they fall back to "
                "checkpoint or fresh init", missing,
            )
        # Winners pack their raw slices (vote encodings embed the process
        # index, so winners are unique per layer and round).
        bufs = {dt: np.zeros(layout.lengths[dt], dt)
                for dt in layout.dtypes}
        if local is not None:
            # oobleck: allow[OBL002] -- recovery path, host mirror buffers
            raw = {dt: np.asarray(local[f"buf_{dt.name}"]).view(dt)
                   for dt in layout.dtypes}
            for i, li in enumerate(layout.layers):
                won = (w1[i] == np.float32(me)) or (
                    not np.isfinite(w1[i])
                    and np.isfinite(votes2[i]) and votes2[i] == w2[i]
                )
                if won:
                    for _, _, wdt, off, n in layout.leaf_metas[li]:
                        bufs[wdt][off:off + n] = raw[wdt][off:off + n]
        totals = tuple(
            self.comm.group_sum(bufs[dt], layout.lengths[dt], range(P),
                                dtype=dt)
            for dt in layout.dtypes
        )
        # Meta (data position) from the lowest process AT step S, over an
        # exact int32 lane (f32 would round num_iterations_done past 2**24).
        mvote = np.full(1, INF, np.float32)
        if at_S:
            mvote[0] = me
        mwin = self.comm.group_min(mvote, 1, range(P))
        mvec = np.zeros(3, np.int32)
        if at_S and mwin[0] == np.float32(me):
            mvec[:] = (int(local["step"]),
                       int(local["num_iterations_done"]),
                       int(local["epoch"]))
        mtotal = self.comm.group_sum(mvec, 3, range(P), dtype=jnp.int32)
        params = {}
        opt = {}
        for i, li in enumerate(layout.layers):
            if covered[i]:
                tree = layout.unpack(totals, li)
                params[li] = tree["p"]
                opt[li] = jax.tree.leaves(tree["o"])
        return {
            "params": params,
            "opt": opt,
            "meta": {
                "step": int(mtotal[0]),
                "num_iterations_done": int(mtotal[1]),
                "epoch": int(mtotal[2]),
            },
        }

    # ------------------------------------------------------------------ #

    def _has_validation_split(self) -> bool:
        """Whether a USABLE validation split exists.

        The raw split probe is not enough: a split that tokenizes to zero
        full sequences must count as absent, or the reserve is sized 0 and
        evaluate() would score training data. So a present split is
        tokenized here (validation splits are small) and cached for
        evaluate()."""
        if self._has_val_split is None:
            from oobleck_tpu.execution.dataset import (
                build_eval_dataset, has_validation_split)

            present = has_validation_split(
                self.args.model.dataset_path, self.args.model.dataset_name
            )
            if present:
                ds = build_eval_dataset(
                    self.args.model.dataset_path,
                    self.args.model.dataset_name,
                    model_name=self.args.model.model_name,
                    seq_length=self.seq_len,
                    data_kind=getattr(self.model, "data_kind", "causal_lm"),
                    vocab_size=getattr(self.model.config, "vocab_size", 0),
                    mask_token_id=getattr(self.model.config,
                                          "mask_token_id", 103),
                )
                if len(ds) == 0:
                    logger.warning(
                        "validation split tokenizes to 0 sequences at "
                        "seq_length %d; treating it as absent (held-out "
                        "tail reserve applies)", self.seq_len,
                    )
                    present = False
                else:
                    self._eval_ds_cache = ds
            self._has_val_split = present
        return self._has_val_split

    @property
    def eval_dataset(self):
        if self._eval_ds_cache is _UNSET:
            # _has_validation_split tokenizes and caches a usable split.
            if not self._has_validation_split():
                self._eval_ds_cache = None
        return self._eval_ds_cache

    def _eval_reserve(self) -> int:
        if self._has_validation_split():
            return 0  # a real validation split exists; train on everything
        return int(len(self.dataset) * self.args.execution.eval_fraction)

    def evaluate(self, num_batches: int = 8) -> float:
        """Forward-only mean loss over held-out data.

        The pool is a real validation split when the data source has one,
        else the eval_fraction tail reserve — training samplers cover only
        the head split (_materialize_plan), so the tail is genuinely
        unseen. Windows ROTATE: the eval position persists across calls
        (epoch wrap in the sampler), so repeated evaluate() calls sweep the
        whole pool instead of replaying its first window. (The reference
        defines an Evaluation LoaderType but never drives it,
        dataloader.py:101.)"""
        mb_counts = (
            [self.fused.num_microbatches] if self.fused is not None
            else [p.num_microbatches for p in self.pipelines]
        )
        bucket = self.args.job.microbatch_size * sum(mb_counts)
        pool = self.eval_dataset
        if pool is not None and len(pool) == 0:
            # A real validation split can tokenize to zero full sequences
            # (fewer than seq_length tokens); treat it as absent rather than
            # dividing by zero in _CyclicView.
            logger.warning(
                "validation split tokenizes to 0 sequences at seq_length %d; "
                "falling back to the held-out training tail", self.seq_len,
            )
            pool = None
        if pool is None:
            n = len(self.dataset)
            eval_n = self._eval_reserve()
            if eval_n < bucket:
                logger.warning(
                    "eval reserve %d < one bucket %d; eval overlaps the "
                    "training tail (raise execution.eval_fraction)",
                    eval_n, bucket,
                )
                eval_n = bucket
            pool = _TailView(self.dataset, n - eval_n, eval_n)
        elif len(pool) < bucket:
            logger.warning(
                "validation split of %d samples smaller than one eval "
                "bucket (%d); samples repeat within a window",
                len(pool), bucket,
            )
            pool = _CyclicView(pool, bucket)

        it_done, epoch = self._eval_state
        correct_sum = 0.0
        count_sum = 0.0
        samplers = [
            OobleckSampler(
                num_samples=len(pool),
                microbatch_size=self.args.job.microbatch_size,
                pipeline_index=i,
                num_microbatches=mb_counts,
                num_iterations_done=it_done,  # sampler wraps epochs itself
                epoch=epoch,
            )
            for i in range(len(mb_counts))
        ]
        loaders = [OobleckDataLoader(pool, s) for s in samplers]
        # Losses stay on-device through the whole eval sweep (each float()
        # readback would serialize dispatch); the single drain below
        # resolves them after every batch's compute is in flight.
        device_losses: list[tuple[Any, int]] = []
        weight_sum = 0
        for _ in range(max(1, num_batches // len(mb_counts))):
            if self.fused is not None:
                device_losses.append(
                    (self.fused.eval_step(loaders[0].next_batch()), 1))
                weight_sum += 1
            else:
                for pipe, dl in zip(self.pipelines, loaders):
                    if self.multihost and not pipe.participates_locally:
                        # Lockstep position only — no batch materialization
                        # for pipelines with no local stage (mirrors
                        # _train_step_multihost; round-4 advisor, low).
                        dl.advance()
                        continue
                    batch = dl.next_batch()
                    loss = pipe.eval_step(batch)
                    if pipe.last_eval_metrics is not None:
                        correct_sum += pipe.last_eval_metrics[0]
                        count_sum += pipe.last_eval_metrics[1]
                    if loss is None:
                        continue  # last stage lives on another process
                    device_losses.append((loss, pipe.num_microbatches))
                    weight_sum += pipe.num_microbatches
        loss_sum = sum(_host_sync(l) * w for l, w in device_losses)
        self._eval_state = (samplers[0].num_iterations_done, samplers[0].epoch)
        if self.multihost:
            total = self.comm.group_sum(
                # oobleck: allow[OBL002] -- eval sweep, off the step loop
                np.asarray([loss_sum, weight_sum, correct_sum, count_sum],
                           np.float32), 4,
                range(self.comm.process_count),
            )
            # oobleck: allow[OBL002] -- eval sweep, off the step loop
            loss_sum, weight_sum = float(total[0]), float(total[1])
            # oobleck: allow[OBL002] -- eval sweep, off the step loop
            correct_sum, count_sum = float(total[2]), float(total[3])
        mean_loss = loss_sum / weight_sum
        # Task metric alongside the loss (reference builds accuracy via
        # `evaluate` but never reports it, dataset.py:39-54): reported for
        # every non-causal-LM family through accuracy_from_logits.
        self.last_eval_metrics = {"loss": mean_loss}
        if count_sum > 0:
            self.last_eval_metrics["accuracy"] = correct_sum / count_sum
            logger.info("eval loss %.4f accuracy %.4f (%d predictions)",
                        mean_loss, correct_sum / count_sum, int(count_sum))
        else:
            logger.info("eval loss %.4f", mean_loss)
        return mean_loss

    def predict_replan(self, lost_hosts: set[int],
                       current: list[list[int]] | None = None):
        """Host algebra + template re-match for losing `lost_hosts`, WITHOUT
        mutating engine state: returns (plan, host_assignment, idle_hosts).

        reconfigure() applies the prediction at failure time; the recovery
        precompiler (execution/precompile.py) walks the same function AHEAD
        of failure — sharing one code path is what guarantees the
        precompiled executables carry byte-identical cache keys (stage
        ranks included) to the ones recovery will ask for."""
        if current is None:
            current = [
                sorted({r // self.chips_per_host for r in p.ranks})
                for p in self.pipelines
            ]
        min_hosts = min(t.num_hosts for t in self.templates)
        new_hosts = reconfigure_hosts(current, lost_hosts, min_hosts)

        # Match each host group to the largest template it can fill,
        # re-folding surplus hosts instead of silently idling them
        # (fit_host_groups; round-1 advisor finding).
        by_hosts = {t.num_hosts: t for t in self.templates}
        sizes = sorted(by_hosts)
        new_hosts, idle = fit_host_groups(new_hosts, sizes)
        new_instances: dict[PipelineTemplate, int] = {}
        for hosts in new_hosts:
            t = by_hosts[len(hosts)]
            new_instances[t] = new_instances.get(t, 0) + 1

        ar_across = [p.allreduce_across_hosts for p in self.profiles]
        plan = PipelineInstantiator().get_new_execution_plan(
            new_instances, ar_across, self.plan.total_num_microbatches
        )
        # Pair each plan instance with a host group of exactly its size —
        # explicit matching rather than relying on two separate sorts
        # (plan.instances' canonical order vs a host-list sort) agreeing.
        groups_by_size: dict[int, list[list[int]]] = {}
        for g in new_hosts:
            groups_by_size.setdefault(len(g), []).append(g)
        host_assignment = [
            groups_by_size[t.num_hosts].pop(0) for t in plan.instances
        ]
        return plan, host_assignment, idle

    def start_recovery_precompile(self, wait: bool = False):
        """Arm the warm-recovery precompiler: AOT-compile the stage
        executables of the plans `predict_replan` would produce after
        likely failures into the persistent compilation cache, on a
        background thread (execution/precompile.py).

        No-op (returns None) when disabled (`precompile_recovery_depth` 0 /
        OOBLECK_PRECOMPILE=0), when there is no MPMD plan to predict from
        (fused path recovers by mesh shrink — same program geometry class,
        not a template re-match), or when the persistent compilation cache
        is off (AOT warmth cannot outlive the in-process caches without
        it). `wait=True` blocks until warm — tests that inject a failure at
        a fixed early step need the warmth guaranteed, production wants the
        background default."""
        import os

        from oobleck_tpu.utils.compile_cache import ensure_persistent_cache

        depth = self.args.execution.precompile_recovery_depth
        env = os.environ.get("OOBLECK_PRECOMPILE")
        if env is not None:
            try:
                depth = int(env)
            except ValueError:
                logger.warning("ignoring malformed OOBLECK_PRECOMPILE=%r", env)
        if depth <= 0 or self.fused is not None or self.plan is None:
            return None
        if ensure_persistent_cache() is None:
            logger.info(
                "recovery precompile skipped: persistent compilation cache "
                "disabled (OOBLECK_JAX_CC=0)"
            )
            return None
        from oobleck_tpu.execution.precompile import RecoveryPrecompiler

        if self._precompiler is not None:
            # Re-arm: stop the previous walk before starting a new one —
            # two threads predicting from different topologies would race
            # each other (and the training thread) on the shared caches.
            self._precompiler.cancel()
        self._precompiler = RecoveryPrecompiler(self, depth=depth)
        self._precompiler.start()
        if wait:
            self._precompiler.wait()
        return self._precompiler

    # -- adaptive fault-tolerance policy (oobleck_tpu/policy) ----------- #

    def _policy_engine(self):
        if self._policy is None:
            from oobleck_tpu.policy import PolicyEngine

            self._policy = PolicyEngine(multihost=self.multihost)
        return self._policy

    def _consult_policy(self, lost_ips: list[str], *, cause: str = ""):
        """Score the recovery arms for an in-process-detected loss with
        the same signals the master would use: planner-projected reroute
        retention, durable-checkpoint staleness, measured step time, and
        the local MTBF history."""
        pol = self._policy_engine()
        for ip in lost_ips:
            pol.observe_failure(ip, cause)
        staleness = None
        plane = self._durable_plane()
        if plane is not None:
            durable = plane.last_durable_step
            if durable is not None and durable >= 0:
                staleness = max(float(self.step - durable), 0.0)
        n = len(self.host_ips)
        survivor_frac = (max(n - len(lost_ips), 0) / n) if n else 1.0
        return pol.decide(
            lost_ips,
            degrade_enabled=(self.args.execution.degrade_enabled
                             and self.fused is None),
            reroute_retention=self._projected_degrade_retention(lost_ips),
            survivor_frac=survivor_frac,
            staleness_steps=staleness,
            step_seconds=self._step_s_ewma,
            cause=cause)

    def _observe_policy_measured(self, mechanism: str,
                                 seconds: float | None) -> None:
        """Close the projected-vs-measured loop on the local policy engine
        (and, via its histogram, on the master's next snapshot scan)."""
        if seconds is not None:
            self._policy_engine().observe_measured(mechanism, seconds)

    def _projected_degrade_retention(self, lost_ips: list[str]
                                     ) -> float | None:
        """Planner-projected survivor throughput retention if `lost_ips`
        were rerouted — the scorer's reroute-retention signal, published
        as a gauge so the master scores from the same number. None when
        the reroute is structurally off the table."""
        if (len(lost_ips) != 1 or not self.pipelines
                or self.fused is not None
                or lost_ips[0] not in self.host_ips):
            return None
        try:
            from oobleck_tpu.degrade.apply import specs_from_pipelines
            from oobleck_tpu.degrade.classify import classify_failure
            from oobleck_tpu.degrade.planner import plan_reroute

            report = classify_failure(
                self._host_index[lost_ips[0]],
                [p.ranks for p in self.pipelines], self.chips_per_host)
            plan = plan_reroute(
                report, specs_from_pipelines(self.pipelines),
                max_slowdown=self.args.execution.degrade_max_slowdown)
        except Exception:
            logger.debug("reroute projection failed", exc_info=True)
            return None
        if not plan.feasible:
            return None
        metrics.registry().gauge(
            "oobleck_degrade_projected_retention",
            "Planner-projected survivor throughput retention of a "
            "single-host reroute from the current topology",
        ).set(plan.throughput_retention)
        return plan.throughput_retention

    def _restore_recover(self, lost_ips: list[str], t0: float) -> bool:
        """Checkpoint-restore recovery: the same survivor re-plan as
        re-instantiation, but the state comes from the last durable
        checkpoint instead of the surviving live arrays — the policy plane
        picks this when a churn storm makes in-memory recovery a losing
        bet (the next failure would eat the replayed work anyway). Returns
        False when no checkpoint is loadable; the caller falls back."""
        restored = self.try_restore_checkpoint()
        if restored is None:
            return False
        rolled_back = self.step
        with obs_spans.span("engine.restore",
                            lost_ips=",".join(lost_ips)):
            old_params = restored["params"]
            old_opt = {}
            for li, leaves in restored["opt"].items():
                struct = jax.tree.structure(
                    jax.eval_shape(self.optimizer.init, old_params[li]))
                old_opt[li] = jax.tree.unflatten(struct, leaves)
            meta = restored["meta"]
            plan, host_assignment, idle = self.predict_replan(
                {self._host_index[ip] for ip in lost_ips})
            if idle:
                logger.warning("hosts %s idle after restore", idle)
            for ip in lost_ips:
                self.host_ips.remove(ip)
            self.step = int(meta["step"])
            self.plan = plan
            self._materialize_plan(
                plan, int(meta["num_iterations_done"]), int(meta["epoch"]),
                old_params, old_opt, host_assignment=host_assignment)
        rolled_back -= self.step
        elapsed = time.perf_counter() - t0
        self.recovery_times.append(elapsed)
        self._recovering = True
        self._recovered_at = time.monotonic()
        self._m_reconfigs.inc(path="restore")
        self._set_template_gauge()
        recovery.observe_latency(elapsed, stage="restore")
        self._observe_policy_measured(MECH_RESTORE, elapsed)
        metrics.flight_recorder().record(
            "engine_restored", lost_ips=lost_ips, path="restore",
            elapsed_s=round(elapsed, 3), step=self.step,
            rolled_back_steps=rolled_back)
        logger.warning(
            "restored from durable checkpoint after losing %s in %.2fs "
            "(rolled back %d step(s)): %s",
            lost_ips, elapsed, rolled_back, plan)
        if self._precompiler is not None:
            self.start_recovery_precompile()
        return True

    # -- multihost zero-respawn degrade --------------------------------- #

    def _maybe_inplace_degrade(self) -> None:
        """Multihost in-place DEGRADE (ROADMAP item 1 remainder): apply a
        queued reroute once EVERY live process has seen it, at the same
        step boundary, via a 1-float group-min each step. The collective
        runs unconditionally on the (multihost, degrade-enabled) path — a
        conditionally-entered collective would deadlock against the step's
        own allreduce when one process enters it and another does not."""
        if (not self.multihost or self.comm is None
                or self.fused is not None
                or not self.args.execution.degrade_enabled):
            return
        if self._live_procs is None:
            self._live_procs = list(range(self.comm.process_count))
        if self.comm.process_index not in self._live_procs:
            return
        with self._lock:
            pending = len(self._inplace_queue) > self._inplace_applied
        ready = np.array([1.0 if pending else 0.0], np.float32)
        agreed = self.comm.group_min(ready, 1, self._live_procs)
        if agreed[0] < 1.0:
            return
        with self._lock:
            entry = self._inplace_queue[self._inplace_applied]
            self._inplace_applied += 1
        lost_ip = entry["lost_ip"]
        if lost_ip not in self.host_ips:
            return
        if self.agent_ip == lost_ip:
            # Victim at the agreed boundary: flush what only this process
            # holds, then leave the train loop cleanly — the survivors
            # drop this process from their collectives at the same step.
            metrics.flight_recorder().record(
                "inplace_drain", ip=self.agent_ip, step=self.step,
                trace_id=(entry["trace"] or {}).get("trace_id"))
            self._mirror_flush()
            self._drain_requested = True
            return
        self.reconfigure(lost_ip, trace=entry["trace"],
                         decision=entry["decision"], inplace=True)

    def _do_inplace_reroute(self, lost_ip: str, decision: dict | None,
                            t0: float) -> None:
        """Survivor side of the multihost zero-respawn DEGRADE. The plan
        is deterministic from shared state, so every survivor computes —
        and applies — the identical reroute without exchanging it; only
        the boundary needed consensus. Infeasibility is equally
        deterministic: every survivor falls back to respawn via its
        agent."""
        from oobleck_tpu.degrade.apply import try_degrade

        if self._tracer is not None:
            self._tracer.close()
        ddec = try_degrade(self, lost_ip, self._host_index[lost_ip], t0)
        if ddec.mechanism == "reroute":
            self._observe_policy_measured(
                MECH_REROUTE, ddec.measured_recovery_s)
            return
        metrics.flight_recorder().record(
            "degrade_fallback", lost_ip=lost_ip, reason=ddec.reason,
            step=self.step)
        logger.warning("in-place degrade infeasible (%s); requesting "
                       "respawn fallback", ddec.reason)
        if self.agent_pipe is not None:
            try:
                self.agent_pipe.send({"kind": "degrade_fallback",
                                      "lost_ip": lost_ip,
                                      "reason": ddec.reason})
            except (OSError, ValueError):
                pass

    def _maybe_chaos_kill_hosts(self) -> None:
        """Correlated fault injection (OOBLECK_CHAOS=kill_hosts=
        <ip1+ip2+...>): declare several hosts lost in the same detection
        window, exercising the policy plane's correlated-failure path
        (reroute infeasible, one incident covering the whole blast
        radius)."""
        if not chaos().active or not self.pipelines:
            return
        ips = chaos().kill_hosts_target()
        if not ips:
            return
        known = [ip for ip in ips if ip in self.host_ips]
        if not known:
            logger.warning("chaos kill_hosts: no known hosts in %s", ips)
            return
        detected_at = time.time()
        trace = {"trace_id": obs_spans.new_trace_id(),
                 "detected_at": detected_at, "cause": "chaos_kill_hosts"}
        metrics.flight_recorder().record(
            "chaos_kill_hosts_resolved", lost_ips=known, step=self.step)
        obs_spans.span_recorder().record(
            "incident.detect", detected_at, detected_at,
            trace_id=trace["trace_id"], lost_ip=",".join(known),
            cause="chaos_kill_hosts")
        logger.warning("chaos kill_hosts: declaring %s lost together",
                       known)
        for ip in known:
            # Same trace, same drain window -> one correlated incident.
            self.request_reconfiguration(ip, trace=trace)

    def _maybe_chaos_join(self) -> None:
        """Chaos capacity arrival (OOBLECK_CHAOS=join_host=<ip>[@<delay>]
        / join_hosts=<ip1+ip2>): declare freshly provisioned hosts at a
        step boundary — the in-process mirror of a real JOIN handshake,
        so the grow plane is exercisable without a control plane. Hosts
        maturing at the same boundary arrive as ONE batch (the grow
        mirror of kill_hosts' correlated loss)."""
        if not chaos().active or not self.pipelines:
            return
        ips = chaos().join_targets()
        if not ips:
            return
        fresh = [ip for ip in ips
                 if ip not in self.host_ips and ip not in self._spare_hosts]
        if not fresh:
            logger.warning("chaos join: hosts %s already present", ips)
            return
        detected_at = time.time()
        trace = {"trace_id": obs_spans.new_trace_id(),
                 "detected_at": detected_at, "cause": "chaos_join_host"}
        obs_spans.span_recorder().record(
            "incident.detect", detected_at, detected_at,
            trace_id=trace["trace_id"], joined_ips=",".join(fresh),
            cause="chaos_join_host")
        logger.warning("chaos join: hosts %s arriving together", fresh)
        self.request_grow(fresh, trace=trace)

    def _maybe_spot_expire(self) -> None:
        """Spot-lifetime deadlines armed at admit (chaos spot_lifetime
        directive): when a joined host's advertised lifetime runs out,
        the churn the policy's amortization horizon priced in actually
        happens. An active host leaves through the REGULAR loss path
        (one synthetic incident); a parked spare just unparks — it was
        never in the plan, so its departure interrupts nothing."""
        if not self._spot_deadlines:
            return
        now = time.monotonic()
        expired = [ip for ip, t in self._spot_deadlines.items() if now >= t]
        for ip in expired:
            del self._spot_deadlines[ip]
            if ip in self._spare_hosts:
                self._spare_hosts.remove(ip)
                metrics.flight_recorder().record(
                    "spot_lifetime_expired", ip=ip, step=self.step,
                    was_spare=True)
                logger.warning("spare host %s reached its spot lifetime; "
                               "unparked", ip)
                continue
            if ip not in self.host_ips:
                continue
            detected_at = time.time()
            trace = {"trace_id": obs_spans.new_trace_id(),
                     "detected_at": detected_at, "cause": "spot_lifetime"}
            obs_spans.span_recorder().record(
                "incident.detect", detected_at, detected_at,
                trace_id=trace["trace_id"], lost_ip=ip,
                cause="spot_lifetime")
            metrics.flight_recorder().record(
                "spot_lifetime_expired", ip=ip, step=self.step,
                was_spare=False)
            logger.warning("host %s reached its advertised spot lifetime; "
                           "declaring it lost", ip)
            self.request_reconfiguration(ip, trace=trace)

    def _maybe_chaos_kill_stage(self) -> None:
        """Stage-addressed fault injection (OOBLECK_CHAOS=kill_stage=
        <stage>:<replica>): declare the host owning that stage of that
        pipeline lost, in place of an out-of-band SIGKILL — the
        single-controller analog of killing one DP peer, deterministic
        enough for the degraded-mode tests to target a specific peer."""
        if not chaos().active or not self.pipelines:
            return
        target = chaos().kill_stage_target()
        if target is None:
            return
        stage, replica = target
        if replica >= len(self.pipelines):
            logger.warning("chaos kill_stage: no pipeline replica %d "
                           "(have %d); ignoring", replica, len(self.pipelines))
            return
        pipe = self.pipelines[replica]
        if stage >= pipe.num_stages:
            logger.warning("chaos kill_stage: pipeline %d has no stage %d; "
                           "ignoring", replica, stage)
            return
        host = pipe.stages[stage].ranks[0] // self.chips_per_host
        ip = next((p for p in self.host_ips
                   if self._host_index[p] == host), None)
        if ip is None:
            logger.warning("chaos kill_stage: host %d already gone", host)
            return
        logger.warning(
            "chaos kill_stage: stage %d of replica %d lives on host %s; "
            "declaring it lost", stage, replica, ip)
        metrics.flight_recorder().record(
            "chaos_kill_stage_resolved", stage=stage, replica=replica,
            lost_ip=ip, step=self.step)
        # In-process detection: the engine is both detector and responder,
        # so it mints the incident's trace_id itself (the master would on
        # a real host loss).
        detected_at = time.time()
        trace = {"trace_id": obs_spans.new_trace_id(),
                 "detected_at": detected_at, "cause": "chaos_kill_stage"}
        obs_spans.span_recorder().record(
            "incident.detect", detected_at, detected_at,
            trace_id=trace["trace_id"], lost_ip=ip, cause="chaos_kill_stage")
        self.request_reconfiguration(ip, trace=trace)

    def request_reconfiguration(self, lost_ip: str,
                                trace: dict | None = None,
                                decision: dict | None = None) -> None:
        with self._lock:
            self._pending_lost.append((lost_ip, trace, decision))

    def request_grow(self, joined_ips: list[str],
                     trace: dict | None = None,
                     decision: dict | None = None) -> None:
        """Queue a JOIN batch; applied at the next step boundary
        (_maybe_grow), never mid-step."""
        if not joined_ips:
            return
        with self._lock:
            self._pending_joins.append((list(joined_ips), trace, decision))

    def request_drain(self, trace: dict | None = None) -> None:
        """Proactive preemption drain: the host got an advance notice, so
        flush durable state at the next step boundary and exit cleanly
        (the agent reports JOB_DONE, not a failure)."""
        metrics.flight_recorder().record(
            "drain_requested", ip=self.agent_ip, step=self.step,
            trace_id=(trace or {}).get("trace_id"))
        with self._lock:
            self._drain_requested = True

    def request_inplace_degrade(self, lost_ip: str,
                                trace: dict | None = None,
                                decision: dict | None = None) -> None:
        """Multihost zero-respawn reroute request; applied at the next
        step boundary ALL live processes agree on."""
        with self._lock:
            self._inplace_queue.append(
                {"lost_ip": lost_ip, "trace": trace, "decision": decision})

    def _maybe_reconfigure(self) -> None:
        with self._lock:
            lost = list(self._pending_lost)
            self._pending_lost.clear()
        if not lost:
            return
        # Losses pending at the same boundary are ONE correlated incident:
        # recovering them serially would let the first re-plan route work
        # onto hosts the second is about to remove (and the policy plane
        # must see the full blast radius to rule out rerouting).
        seen: dict[str, None] = {}
        for ip, _, _ in lost:
            seen.setdefault(ip)
        ip0, trace, decision = lost[0]
        extra = [ip for ip in seen if ip != ip0]
        self.reconfigure(ip0, trace=trace, decision=decision,
                         extra_lost=extra)

    def _maybe_grow(self) -> None:
        with self._lock:
            pending = list(self._pending_joins)
            self._pending_joins.clear()
        if not pending:
            return
        # Arrivals pending at one step boundary are ONE grow incident:
        # the policy must price the whole batch (three spares vs one
        # 3-host pipeline are different verdicts), mirroring the
        # correlated-loss batching above. First trace/decision wins.
        seen: dict[str, None] = {}
        for ips, _, _ in pending:
            for ip in ips:
                seen.setdefault(ip)
        _, trace, decision = pending[0]
        self.grow(list(seen), trace=trace, decision=decision)

    def reconfigure(self, lost_ip: str, trace: dict | None = None,
                    decision: dict | None = None,
                    extra_lost: tuple | list = (),
                    inplace: bool = False) -> None:
        """Incident-instrumented recovery entry point: opens the incident
        (adopting the upstream detect/broadcast/notified marks the trace
        context carried), pins the trace as the process ambient so every
        span recorded during recovery stitches onto it, and runs the
        actual recovery (_do_reconfigure). When recovery was applied, the
        incident stays open until the first post-recovery step commits
        incident-<n>.json (train loop -> _commit_incident)."""
        incident = obs_incident.IncidentBuilder(
            lost_ip,
            trace_id=(trace or {}).get("trace_id"),
            cause=(trace or {}).get("cause"))
        incident.adopt(trace)
        incident.mark("apply_start")
        obs_spans.set_ambient({"trace_id": incident.trace_id})
        prev_recovered = self._recovered_at
        try:
            with obs_spans.span("engine.reconfigure",
                                trace_id=incident.trace_id, lost_ip=lost_ip,
                                extra_lost=",".join(extra_lost)):
                self._do_reconfigure(lost_ip, decision=decision,
                                     extra_lost=extra_lost, inplace=inplace)
        finally:
            obs_spans.set_ambient(None)
            if self._recovering and self._recovered_at != prev_recovered:
                incident.mark("apply_end")
                self._incident = incident

    def _do_reconfigure(self, lost_ip: str, decision: dict | None = None,
                        extra_lost: tuple | list = (),
                        inplace: bool = False) -> None:
        """Full recovery path (reference on_reconfigure, engine.py:91-180),
        dispatched on the policy verdict: reroute mutates the live topology
        in place (degrade/), reinstantiate runs host algebra -> template
        re-match -> batch redistribution -> re-instantiation reusing
        surviving weights + optimizer state and the data position, restore
        does the same re-plan but from the last durable checkpoint (the
        policy plane picks it when in-memory recovery is a losing bet)."""
        t0 = time.perf_counter()
        # Deferred losses reference arrays on the pre-failure meshes; read
        # them back now, while (most of) the backing buffers still exist.
        self._drain_pending_losses()
        if self.multihost:
            if inplace:
                self._do_inplace_reroute(lost_ip, decision, t0)
                return
            # A lost peer breaks the shared jax.distributed world; the agent
            # respawns the worker over the survivors (live mirrors make the
            # restart checkpoint-free). In-place RECONFIGURATION stays
            # single-controller; an in-place DEGRADE rides the consensus
            # queue (_maybe_inplace_degrade) instead of this path.
            logger.warning(
                "multihost MPMD reconfigures by respawn; ignoring in-place "
                "request for %s", lost_ip,
            )
            return
        lost_ips = [ip for ip in (lost_ip, *extra_lost)
                    if ip in self.host_ips]
        if not lost_ips:
            logger.warning("unknown lost host %s", lost_ip)
            return
        lost_ip = lost_ips[0]
        lost_host = self._host_index[lost_ip]
        correlated = len(lost_ips) > 1
        # A mid-window jax.profiler trace must not straddle the topology
        # change: close it now; the tracer re-arms on its next window.
        if self._tracer is not None:
            self._tracer.close()
        if self.fused is not None:
            # Fused recovery is a mesh shrink; one host at a time.
            for ip in lost_ips:
                self._reconfigure_fused(ip, self._host_index[ip], t0)
            return

        # Policy verdict: the broadcast decision when the master attached
        # one (every process applies the same verdict), the local policy
        # engine's otherwise (in-process detection never crossed the
        # control plane).
        pdec = decision_from_payload(decision)
        if pdec is None:
            pdec = self._consult_policy(lost_ips, cause="engine_detected")
        mechanism = pdec.mechanism

        if mechanism == MECH_RESTORE:
            if self._restore_recover(lost_ips, t0):
                return
            logger.warning("policy chose restore but no durable checkpoint "
                           "is loadable; re-instantiating instead")
            mechanism = MECH_REINSTANTIATE

        # Degraded-mode fast path (oobleck_tpu/degrade): reroute the dead
        # replica's microbatches into the survivors' bubbles on the same
        # topology — no re-plan, no recompile. try_degrade returns one
        # DegradeDecision either way; on fallback it is recorded below with
        # the measured re-instantiation latency so estimate and actual land
        # in the same flight-recorder event.
        ddec = None
        if (mechanism == MECH_REROUTE and not correlated
                and self.args.execution.degrade_enabled):
            from oobleck_tpu.degrade.apply import try_degrade

            ddec = try_degrade(self, lost_ip, lost_host, t0)
            if ddec.mechanism == "reroute":
                self._observe_policy_measured(
                    MECH_REROUTE, ddec.measured_recovery_s)
                return
        else:
            from oobleck_tpu.degrade.decision import (
                MECH_DISABLED,
                DegradeDecision,
            )

            if not self.args.execution.degrade_enabled:
                reason = "degrade_disabled"
            elif correlated:
                # Correlated loss: the survivors' bubbles cannot absorb
                # several replicas' worth of work (policy marks the reroute
                # arm infeasible); fall straight through to a full re-plan.
                reason = "correlated_failure"
            else:
                reason = f"policy:{pdec.reason}"
            ddec = DegradeDecision(
                lost_ip=lost_ip, lost_host=lost_host,
                mechanism=(MECH_DISABLED
                           if not self.args.execution.degrade_enabled
                           else MECH_REINSTANTIATE),
                reason=reason)

        # Host algebra + template re-match, shared verbatim with the
        # recovery precompiler so its AOT executables hit here.
        plan, host_assignment, idle = self.predict_replan(
            {self._host_index[ip] for ip in lost_ips})
        if idle:
            logger.warning(
                "hosts %s idle after reconfiguration: no template extension "
                "fits them (feasible sizes %s)", idle,
                sorted({t.num_hosts for t in self.templates}),
            )

        # Surviving weights + optimizer state by layer (reference
        # _copy_model_states, engine.py:238-309: broadcast from an owner —
        # single-controller, a device_put from any survivor).
        old_params, old_opt = self._collect_layer_state()

        # Data position carries over (reference engine.py:203-214).
        it_done = self.dataloaders[0].num_iterations_done
        epoch = self.dataloaders[0].epoch

        for ip in lost_ips:
            self.host_ips.remove(ip)
        self.plan = plan
        self._materialize_plan(
            plan, it_done, epoch, old_params, old_opt,
            host_assignment=host_assignment,
        )
        elapsed = time.perf_counter() - t0
        self.recovery_times.append(elapsed)
        self._recovering = True
        self._recovered_at = time.monotonic()
        self._m_reconfigs.inc(path="mpmd")
        self._set_template_gauge()
        recovery.observe_latency(elapsed, stage="reconfigure")
        if ddec is not None:
            ddec.measured_recovery_s = elapsed
            ddec.record()
        self._observe_policy_measured(MECH_REINSTANTIATE, elapsed)
        metrics.flight_recorder().record(
            "engine_reconfigured", lost_ip=lost_ip, path="mpmd",
            lost_ips=lost_ips, correlated=correlated,
            elapsed_s=round(elapsed, 3), step=self.step)
        logger.warning(
            "reconfigured after losing %s in %.2fs: %s", lost_ip, elapsed, plan,
        )
        if self._precompiler is not None:
            # Re-arm for the NEXT failure from the new (smaller) topology.
            self.start_recovery_precompile()

    # -- grow direction (JOIN incidents, PR 13) ------------------------- #

    def grow(self, joined_ips: list[str], trace: dict | None = None,
             decision: dict | None = None) -> None:
        """Incident-instrumented grow entry point, mirroring
        reconfigure(): opens the incident (adopting upstream detect/
        broadcast/notified marks), pins the trace as the process ambient,
        and runs _do_grow. The incident stays open until the first
        post-grow step commits incident-<n>.json — one committed file per
        JOIN batch, with the policy decision (all three arm costs)
        attached."""
        incident = obs_incident.IncidentBuilder(
            "",
            trace_id=(trace or {}).get("trace_id"),
            cause=(trace or {}).get("cause") or "join",
            joined_ips=list(joined_ips), direction="grow")
        incident.adopt(trace)
        incident.mark("apply_start")
        obs_spans.set_ambient({"trace_id": incident.trace_id})
        prev_recovered = self._recovered_at
        pdec = None
        try:
            with obs_spans.span("engine.grow",
                                trace_id=incident.trace_id,
                                joined_ips=",".join(joined_ips)):
                pdec = self._do_grow(joined_ips, decision=decision)
        finally:
            obs_spans.set_ambient(None)
            if self._recovering and self._recovered_at != prev_recovered:
                if pdec is not None:
                    incident.attrs["decision"] = pdec.as_payload()
                incident.mark("apply_end")
                self._incident = incident

    def _do_grow(self, joined_ips: list[str], decision: dict | None = None):
        """Apply one grow incident: bind the arrivals into the engine's
        geometry, resolve the policy verdict (a broadcast-attached grow
        decision wins; anything else consults the local policy engine),
        and execute the chosen arm. Returns the resolved PolicyDecision
        (None when nothing was admitted)."""
        t0 = time.perf_counter()
        if self.multihost or self.fused is not None:
            # Growing a jax.distributed world takes a coordinated restart
            # of every process (world size is baked into the runtime);
            # the fused path would need a mesh re-grow. Both park the
            # arrivals as spares so the capacity is tracked, never lost.
            for ip in joined_ips:
                if ip not in self.host_ips and ip not in self._spare_hosts:
                    self._spare_hosts.append(ip)
            metrics.flight_recorder().record(
                "grow_deferred", joined_ips=joined_ips, step=self.step,
                reason="multihost" if self.multihost else "fused")
            logger.warning("grow deferred (%s path): %s parked as spares",
                           "multihost" if self.multihost else "fused",
                           joined_ips)
            return None
        admitted = self._admit_hosts(joined_ips)
        if not admitted:
            return None
        # Deferred losses reference arrays on the pre-grow meshes; read
        # them back before a re-materialization can drop the buffers.
        self._drain_pending_losses()
        pdec = decision_from_payload(decision)
        if pdec is None or pdec.mechanism not in GROW_MODES:
            pdec = self._consult_policy_grow(admitted,
                                             cause="engine_detected")
        mechanism = pdec.mechanism

        if mechanism == MECH_GROW_DP:
            if self._grow_dp_apply(admitted, t0):
                return pdec
            logger.warning("grow_dp chosen but no template fits the "
                           "arrivals; absorbing %s as spares", admitted)
            mechanism = MECH_ABSORB
        if mechanism == MECH_GROW_RESHAPE:
            self._grow_reshape_apply(admitted, t0)
            return pdec

        # absorb_spare (chosen, or the grow_dp apply-time fallback):
        # park the arrivals in the spare pool — zero interruption, the
        # live pipelines never notice. The incident still commits (the
        # decision and its costs are the forensic record).
        self._spare_hosts.extend(admitted)
        elapsed = time.perf_counter() - t0
        self._recovering = True
        self._recovered_at = time.monotonic()
        self._m_grows.inc(mechanism=MECH_ABSORB)
        self._observe_policy_measured(MECH_ABSORB, elapsed)
        metrics.flight_recorder().record(
            "grow_absorbed", joined_ips=admitted,
            spares=list(self._spare_hosts),
            elapsed_s=round(elapsed, 3), step=self.step)
        logger.warning(
            "absorbed %s into the spare pool in %.3fs (zero interruption; "
            "spares now %s)", admitted, elapsed, self._spare_hosts)
        return pdec

    def _admit_hosts(self, ips: list[str]) -> list[str]:
        """Bind arriving hosts into the engine's immutable geometry: a
        NEW host gets the next ORIGINAL index and chips_per_host fresh
        devices (self.devices only ever grows — the rank encoding
        rank = original_index * chips_per_host + local stays valid);
        a previously-lost host rejoining reuses its original index, whose
        device slice never left self.devices. Arms the chaos
        spot-lifetime deadline when one is advertised. Returns the ips
        actually admitted."""
        admitted = []
        for ip in ips:
            if ip in self.host_ips or ip in self._spare_hosts:
                logger.warning("join: host %s already present; ignoring",
                               ip)
                continue
            if ip not in self._host_index:
                cph = self.chips_per_host or 1
                bound = {id(d) for d in self.devices}
                pool = [d for d in jax.devices() if id(d) not in bound]
                if len(pool) < cph:
                    logger.warning(
                        "join: no %d free devices for %s (have %d); "
                        "refusing", cph, ip, len(pool))
                    metrics.flight_recorder().record(
                        "join_refused", ip=ip, reason="no_free_devices",
                        step=self.step)
                    continue
                self._host_index[ip] = len(self._host_index)
                self.devices.extend(pool[:cph])
            lifetime = chaos().spot_lifetime(ip)
            if lifetime is not None:
                self._spot_deadlines[ip] = time.monotonic() + lifetime
            admitted.append(ip)
        return admitted

    def predict_grow(self, new_hosts: set[int],
                     current: list[list[int]] | None = None):
        """predict_replan's grow-direction mirror: keep every current
        pipeline's host group intact and fold `new_hosts` into
        additional DP pipeline(s) from the existing templates, WITHOUT
        mutating engine state. Returns (plan, host_assignment,
        idle_hosts); plan is None when no template fits the arrivals
        (the caller absorbs them instead). Shared with the recovery
        precompiler so predicted post-grow executables carry
        byte-identical cache keys to the ones a real JOIN will ask
        for."""
        if current is None:
            current = [
                sorted({r // self.chips_per_host for r in p.ranks})
                for p in self.pipelines
            ]
        by_hosts = {t.num_hosts: t for t in self.templates}
        sizes = sorted(by_hosts)
        fitted, idle = fit_host_groups([sorted(new_hosts)], sizes)
        if not fitted:
            return None, None, sorted(new_hosts)
        groups = [list(g) for g in current] + fitted
        new_instances: dict[PipelineTemplate, int] = {}
        for hosts in groups:
            t = by_hosts[len(hosts)]
            new_instances[t] = new_instances.get(t, 0) + 1
        ar_across = [p.allreduce_across_hosts for p in self.profiles]
        plan = PipelineInstantiator().get_new_execution_plan(
            new_instances, ar_across, self.plan.total_num_microbatches
        )
        groups_by_size: dict[int, list[list[int]]] = {}
        for g in groups:
            groups_by_size.setdefault(len(g), []).append(g)
        host_assignment = [
            groups_by_size[t.num_hosts].pop(0) for t in plan.instances
        ]
        return plan, host_assignment, idle

    def _grow_dp_apply(self, admitted: list[str], t0: float) -> bool:
        """grow_dp: keep every surviving pipeline's host group intact and
        add DP pipeline(s) over the arriving hosts from the EXISTING
        templates — no restore, no survivor respawn; the batch
        redistribution and the new replicas materializing from the live
        weights (the DP copy IS the state transfer) are the whole
        interruption. Returns False when no template fits."""
        new_group = {self._host_index[ip] for ip in admitted}
        plan, host_assignment, idle = self.predict_grow(new_group)
        if plan is None:
            return False
        active = {h for g in host_assignment for h in g}
        joined_active = [ip for ip in admitted
                        if self._host_index[ip] in active]
        joined_idle = [ip for ip in admitted if ip not in joined_active]
        if joined_idle:
            logger.warning(
                "hosts %s idle after grow_dp (no template extension fits "
                "them); parked as spares", joined_idle)
            self._spare_hosts.extend(joined_idle)
        old_params, old_opt = self._collect_layer_state()
        it_done = self.dataloaders[0].num_iterations_done
        epoch = self.dataloaders[0].epoch
        self.host_ips.extend(joined_active)
        self.plan = plan
        self._materialize_plan(plan, it_done, epoch, old_params, old_opt,
                               host_assignment=host_assignment)
        self._finish_grow(MECH_GROW_DP, joined_active, t0, rolled_back=0)
        return True

    def _grow_reshape_apply(self, admitted: list[str], t0: float) -> None:
        """grow_reshape: re-instantiate on the larger template set,
        planned exactly as a fresh bring-up at the new fleet size would
        plan — the LIVE promotion of the offline 2->4
        restore-across-reshape path. State comes from the last durable
        checkpoint when one exists (honest rollback, the step counter
        rewinds); else the live layer state reshapes in place (nothing
        replayed)."""
        self._ensure_templates_for(len(self.host_ips) + len(admitted))
        restored = self.try_restore_checkpoint()
        rolled_back = 0
        if restored is not None:
            old_params = restored["params"]
            old_opt = {}
            for li, leaves in restored["opt"].items():
                struct = jax.tree.structure(
                    jax.eval_shape(self.optimizer.init, old_params[li]))
                old_opt[li] = jax.tree.unflatten(struct, leaves)
            meta = restored["meta"]
            it_done = int(meta["num_iterations_done"])
            epoch = int(meta["epoch"])
            rolled_back = self.step - int(meta["step"])
            self.step = int(meta["step"])
        else:
            old_params, old_opt = self._collect_layer_state()
            it_done = self.dataloaders[0].num_iterations_done
            epoch = self.dataloaders[0].epoch
        self.host_ips.extend(admitted)
        ar_across = [p.allreduce_across_hosts for p in self.profiles]
        plan = PipelineInstantiator().get_best_execution_plan(
            self.templates, ar_across, len(self.host_ips),
            self.plan.total_num_microbatches,
        )
        # Contiguous blocks over the sorted available indices — for a
        # never-shrunk fleet this is exactly the assignment a fresh
        # bring-up materializes, which is what the live-grow parity test
        # pins against its uninterrupted twin.
        avail = sorted(self._host_index[ip] for ip in self.host_ips)
        host_assignment = []
        pos = 0
        for t in plan.instances:
            host_assignment.append(avail[pos:pos + t.num_hosts])
            pos += t.num_hosts
        self.plan = plan
        self._materialize_plan(plan, it_done, epoch, old_params, old_opt,
                               host_assignment=host_assignment)
        self._finish_grow(MECH_GROW_RESHAPE, admitted, t0,
                          rolled_back=rolled_back)

    def _finish_grow(self, mechanism: str, admitted: list[str], t0: float,
                     *, rolled_back: int) -> None:
        elapsed = time.perf_counter() - t0
        self.recovery_times.append(elapsed)
        self._recovering = True
        self._recovered_at = time.monotonic()
        self._m_grows.inc(mechanism=mechanism)
        self._set_template_gauge()
        recovery.observe_latency(elapsed, stage="grow")
        self._observe_policy_measured(mechanism, elapsed)
        metrics.flight_recorder().record(
            "engine_grown", joined_ips=admitted, mechanism=mechanism,
            elapsed_s=round(elapsed, 3), step=self.step,
            rolled_back_steps=rolled_back)
        logger.warning(
            "grew onto %s via %s in %.2fs%s: %s", admitted, mechanism,
            elapsed,
            f" (rolled back {rolled_back} step(s))" if rolled_back else "",
            self.plan)
        if self._precompiler is not None:
            # Re-arm for the NEXT incident from the new (larger) topology.
            self.start_recovery_precompile()

    def _grow_dp_feasibility(self, k: int) -> tuple[bool, str]:
        """Whether k arriving hosts can form new DP pipeline(s) from the
        EXISTING templates alone (grow_dp's apply-time requirement)."""
        if not self.templates or self.plan is None:
            return False, "no_plan"
        smallest = min(t.num_hosts for t in self.templates)
        if k >= smallest:
            return True, ""
        return False, f"arrivals({k})<smallest_template({smallest})"

    def _consult_policy_grow(self, joined_ips: list[str], *,
                             cause: str = ""):
        """Score the grow arms for an in-process-detected JOIN with the
        same signals the master would use, plus the chaos spot-lifetime
        hints only this process can see."""
        pol = self._policy_engine()
        staleness = None
        plane = self._durable_plane()
        if plane is not None:
            durable = plane.last_durable_step
            if durable is not None and durable >= 0:
                staleness = max(float(self.step - durable), 0.0)
        hints: dict[str, float] = {}
        for ip in joined_ips:
            lt = chaos().spot_lifetime(ip)
            if lt:
                hints[ip] = lt
        dp_ok, dp_why = self._grow_dp_feasibility(len(joined_ips))
        return pol.decide_grow(
            joined_ips,
            current_hosts=len(self.host_ips),
            dp_feasible=dp_ok,
            dp_reason=dp_why,
            staleness_steps=staleness,
            step_seconds=self._step_s_ewma,
            lifetime_hints=hints,
            cause=cause)

    def _reconfigure_fused(self, lost_ip: str, lost_host: int, t0: float) -> None:
        """Fused-path recovery: shrink the global mesh to the surviving
        chips and re-place the live TrainState on it (the sharded-state
        analog of the reference's template re-match + weight copy)."""
        # Build the new mesh BEFORE mutating host bookkeeping: if the
        # survivors genuinely cannot run (fewer than stage*tensor*seq
        # chips), the raise leaves the engine state consistent.
        survivors = [h for h in self._fused_hosts if h != lost_host]
        devices = [
            d for h in survivors
            for d in self.devices[h * self.chips_per_host:
                                  (h + 1) * self.chips_per_host]
        ]
        mesh = self._fused_mesh(devices, shrink_to_fit=True)
        new_fused = self.fused.replace_mesh(mesh)
        self._fused_hosts = survivors
        self.host_ips.remove(lost_ip)
        self.fused = new_fused
        # Rebuild the loader from the CONSUMED position: any staged batch
        # was placed with the dead mesh's sharding, and the stager's
        # place_fn is bound to the old FusedPipeline.
        old_dl = self.dataloaders[0]
        it_done, ep = old_dl.num_iterations_done, old_dl.epoch
        if hasattr(old_dl, "close"):
            old_dl.close()
        self.dataloaders = [self._fused_dataloader(
            new_fused.num_microbatches, it_done, ep)]
        elapsed = time.perf_counter() - t0
        self.recovery_times.append(elapsed)
        self._m_reconfigs.inc(path="fused")
        self._set_template_gauge()
        recovery.observe_latency(elapsed, stage="reconfigure")
        metrics.flight_recorder().record(
            "engine_reconfigured", lost_ip=lost_ip, path="fused",
            elapsed_s=round(elapsed, 3), step=self.step)
        stranded = len(devices) - mesh.devices.size
        self.stranded_chips.append(stranded)
        logger.warning(
            "reconfigured (fused) after losing %s in %.2fs: mesh %s"
            "%s", lost_ip, elapsed, dict(mesh.shape),
            f" ({stranded} surviving chips STRANDED)" if stranded else "",
        )


_UNSET = object()


class _CyclicView:
    """Repeat a too-small eval pool up to `length` samples (i mod len) so a
    tiny validation split can still fill one iteration bucket."""

    def __init__(self, ds, length: int):
        self.ds = ds
        self.length = length

    def __len__(self) -> int:
        return self.length

    def __getitem__(self, i: int):
        return self.ds[i % len(self.ds)]

    def set_epoch(self, epoch: int) -> None:
        if hasattr(self.ds, "set_epoch"):
            self.ds.set_epoch(epoch)


class _TailView:
    """A length-`length` window of `ds` starting at `offset` (the held-out
    evaluation tail)."""

    def __init__(self, ds, offset: int, length: int):
        self.ds = ds
        self.offset = offset
        self.length = length

    def __len__(self) -> int:
        return self.length

    def __getitem__(self, i: int):
        return self.ds[self.offset + i]

    def set_epoch(self, epoch: int) -> None:
        if hasattr(self.ds, "set_epoch"):
            self.ds.set_epoch(epoch)


def _best_data_fsdp(cap: int, mb: int, hidden: int) -> tuple[int, int]:
    """Pick (data, fsdp) with data*fsdp <= cap maximizing chips used, s.t.
    mb % (data*fsdp) == 0 and (when known) hidden % fsdp == 0; ties prefer
    larger fsdp. (1, 1) always qualifies."""
    best = (0, 1, 1)  # (used, fsdp, data)
    for f in range(cap, 0, -1):
        if hidden and hidden % f:
            continue
        d = next((d for d in range(cap // f, 0, -1)
                  if mb % (d * f) == 0), 0)
        if d and (d * f > best[0] or (d * f == best[0] and f > best[1])):
            best = (d * f, f, d)
    return best[2], best[1]


def _scale_template_chips(t: PipelineTemplate, tp: int) -> PipelineTemplate:
    """Scale a template generated over TP chip-groups back to real chips."""
    import dataclasses

    stages = tuple(
        dataclasses.replace(s, num_chips=s.num_chips * tp) for s in t.stages
    )
    return dataclasses.replace(
        t, stages=stages, chips_per_host=t.chips_per_host * tp
    )


def _device_memory_summary() -> str:
    """Peak/in-use device memory (reference logs CUDA memory every 10 steps,
    engine.py:657-659); CPU backends report no stats."""
    try:
        # local_devices: on multi-host, devices()[0] is process 0's chip and
        # is non-addressable from other workers.
        stats = jax.local_devices()[0].memory_stats() or {}
        used = stats.get("bytes_in_use", 0)
        peak = stats.get("peak_bytes_in_use", used)
        limit = stats.get("bytes_limit", 0)
        return (f"mem {used / 2**30:.2f}GiB (peak {peak / 2**30:.2f}"
                f"{f' / limit {limit / 2**30:.0f}' if limit else ''}GiB)")
    except Exception:
        return "mem n/a"


def _place_opt_state(optimizer, state, param_sharding_tree):
    """Re-place one layer's optimizer state onto new param shardings.

    Adam mu/nu mirror the param tree (placed like the params); scalar
    bookkeeping leaves (count) go replicated on the same mesh."""
    import optax
    from jax.sharding import NamedSharding, PartitionSpec

    mesh = jax.tree.leaves(
        param_sharding_tree, is_leaf=lambda x: hasattr(x, "mesh")
    )[0].mesh
    replicated = NamedSharding(mesh, PartitionSpec())
    return optax.tree_map_params(
        optimizer,
        lambda leaf, sh: jax.device_put(leaf, sh),
        state,
        param_sharding_tree,
        transform_non_params=lambda leaf: jax.device_put(leaf, replicated),
        is_leaf=lambda x: hasattr(x, "mesh"),
    )
