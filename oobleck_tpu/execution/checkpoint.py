"""Checkpoint / resume.

The reference does NOT support checkpointing (README.md:103; weights are
randomly re-materialized at startup, layer.py:26-37) — its recovery story is
purely in-memory. On TPU, preemption is routine, so this is a required
capability gap to close (SURVEY §5 "Checkpoint / resume").

Design: one orbax checkpoint per save step holding a plain pytree:

    {"params": {str(layer): tree}, "opt": {str(layer): tree},
     "meta": {"step", "num_iterations_done", "epoch", "model_name",
              "global_num_microbatch"}}

Layer-keyed (not pipeline-keyed) so a restore can re-instantiate ANY plan
shape — checkpoints survive cluster-size changes the same way reconfiguration
does. Saves collect each layer once from whichever pipeline owns it.
"""

from __future__ import annotations

import logging
from pathlib import Path
from typing import Any

import jax
import numpy as np

logger = logging.getLogger("oobleck.checkpoint")


def to_host_local(x):
    """Fetch one array to host from this process's addressable shards.

    Multi-process arrays are not fully addressable, but whenever every global
    index is covered by SOME local shard (params replicated across the data
    axis, or sharded only along within-host axes) the full value can be
    assembled locally with no collective. Raises when local coverage is
    incomplete (cross-host FSDP needs a distributed checkpoint format)."""
    if not isinstance(x, jax.Array) or x.is_fully_replicated or x.is_fully_addressable:
        return np.asarray(x)
    out = np.empty(x.shape, x.dtype)
    covered = np.zeros(x.shape, bool)
    seen: set = set()
    for sh in x.addressable_shards:
        # Replicated local shards repeat the same index; transfer each
        # distinct region once.
        key = tuple((s.start, s.stop, s.step) for s in sh.index)
        if key in seen:
            continue
        seen.add(key)
        out[sh.index] = np.asarray(sh.data)
        covered[sh.index] = True
    if not covered.all():
        raise ValueError(
            "array shards span non-addressable devices (cross-host parameter "
            "sharding); local checkpoint assembly is impossible — keep fsdp "
            "within a host or add a distributed checkpoint backend"
        )
    return out


def _to_host(tree):
    return jax.tree.map(to_host_local, tree)


def save_checkpoint(path: str | Path, *, step: int, params: dict[int, Any],
                    opt_state: dict[int, Any], num_iterations_done: int,
                    epoch: int, extra: dict | None = None) -> Path:
    """Write checkpoint for `step`; returns its directory."""
    import orbax.checkpoint as ocp

    path = Path(path).resolve()
    path.mkdir(parents=True, exist_ok=True)
    target = path / f"step_{step}"
    payload = {
        "params": {str(k): _to_host(v) for k, v in params.items()},
        # Optimizer states are stored as flat leaf lists: optax states are
        # NamedTuple pytrees whose node types a structure-free restore cannot
        # rebuild; the engine re-derives the structure from optimizer.init
        # and refills these leaves.
        "opt": {str(k): [to_host_local(l) for l in jax.tree.leaves(v)]
                for k, v in opt_state.items()},
        "meta": {
            "step": step,
            "num_iterations_done": num_iterations_done,
            "epoch": epoch,
            **(extra or {}),
        },
    }
    ckpt = ocp.PyTreeCheckpointer()
    ckpt.save(target, payload, force=True)
    logger.info("saved checkpoint %s", target)
    return target


def latest_checkpoint(path: str | Path) -> Path | None:
    path = Path(path)
    if not path.exists():
        return None
    steps = []
    for p in path.iterdir():
        if p.is_dir() and p.name.startswith("step_"):
            try:
                steps.append((int(p.name.split("_", 1)[1]), p))
            except ValueError:
                continue
    return max(steps)[1] if steps else None


def load_checkpoint(target: str | Path) -> dict:
    """Load a checkpoint directory into host-memory pytrees with int layer
    keys restored."""
    import orbax.checkpoint as ocp

    ckpt = ocp.PyTreeCheckpointer()
    payload = ckpt.restore(Path(target).resolve())
    return {
        "params": {int(k): v for k, v in payload["params"].items()},
        "opt": {int(k): v for k, v in payload["opt"].items()},
        "meta": payload["meta"],
    }
