"""Checkpoint / resume — thin compat shim over the durable-state plane.

The reference does NOT support checkpointing (README.md:103; weights are
randomly re-materialized at startup, layer.py:26-37) — its recovery story is
purely in-memory. On TPU, preemption is routine, so this is a required
capability gap to close (SURVEY §5 "Checkpoint / resume").

The implementation lives in `oobleck_tpu/ckpt` (async sharded writes,
atomic manifests, crash-consistent restore); the engine talks to that
plane directly. This module keeps the original synchronous function
signatures for existing callers and tests, with the original payload
shape:

    {"params": {layer: tree}, "opt": {layer: [flat leaves]},
     "meta": {"step", "num_iterations_done", "epoch", "model_name", ...}}

Layer-keyed (not pipeline-keyed) so a restore can re-instantiate ANY plan
shape — checkpoints survive cluster-size changes the same way
reconfiguration does.

Behavior changes vs the old orbax wrapper, both deliberate:
  * `latest_checkpoint` only returns step dirs with a COMMITTED manifest —
    a crash mid-save can no longer poison resume with a torn directory;
  * saves need no cross-process barrier (each process's write is
    independent; rank 0 commits via the filesystem), so in a multi-process
    world only process 0 writes here — it receives the full collected
    state, matching the old orbax primary-writes semantics.
"""

from __future__ import annotations

import logging
from pathlib import Path
from typing import Any

import jax
import numpy as np

logger = logging.getLogger("oobleck.checkpoint")


def to_host_local(x):
    """Fetch one array to host from this process's addressable shards.

    Multi-process arrays are not fully addressable, but whenever every global
    index is covered by SOME local shard (params replicated across the data
    axis, or sharded only along within-host axes) the full value can be
    assembled locally with no collective. Raises when local coverage is
    incomplete (cross-host FSDP takes the ckpt plane's sharded-write path
    instead — engine.save_checkpoint falls back to `save_stacked`).

    jax arrays are COPIED: np.asarray of an XLA CPU buffer is a zero-copy
    view, and the train step donates its state buffers — a view would
    alias memory the next step reuses (SIGSEGV)."""
    if not isinstance(x, jax.Array):
        return np.asarray(x)
    if x.is_fully_replicated or x.is_fully_addressable:
        return np.array(x)
    out = np.empty(x.shape, x.dtype)
    covered = np.zeros(x.shape, bool)
    seen: set = set()
    for sh in x.addressable_shards:
        # Replicated local shards repeat the same index; transfer each
        # distinct region once.
        key = tuple((s.start, s.stop, s.step) for s in sh.index)
        if key in seen:
            continue
        seen.add(key)
        out[sh.index] = np.asarray(sh.data)
        covered[sh.index] = True
    if not covered.all():
        raise ValueError(
            "array shards span non-addressable devices (cross-host parameter "
            "sharding); local checkpoint assembly is impossible — keep fsdp "
            "within a host or add a distributed checkpoint backend"
        )
    return out


def save_checkpoint(path: str | Path, *, step: int, params: dict[int, Any],
                    opt_state: dict[int, Any], num_iterations_done: int,
                    epoch: int, extra: dict | None = None) -> Path:
    """Write checkpoint for `step` synchronously; returns its directory.

    Callers pass the full collected layer state (the engine's multi-host
    path collects it first); in a multi-process world only process 0
    writes, everyone else returns the target path untouched."""
    from oobleck_tpu import ckpt

    path = Path(path).resolve()
    target = path / ckpt.manifest.step_dir_name(step)
    if jax.process_count() > 1 and jax.process_index() != 0:
        return target
    plane = ckpt.DurableStatePlane(path, asynchronous=False, keep_last=0)
    plane.save(step=step, params=params, opt_state=opt_state,
               num_iterations_done=num_iterations_done, epoch=epoch,
               extra=extra)
    plane.close()
    logger.info("saved checkpoint %s", target)
    return target


def latest_checkpoint(path: str | Path) -> Path | None:
    """Newest step dir with a COMMITTED manifest; torn dirs are invisible."""
    from oobleck_tpu.ckpt.restore import complete_step_dirs

    dirs = complete_step_dirs(path)
    return dirs[0][1] if dirs else None


def load_checkpoint(target: str | Path) -> dict:
    """Load one checkpoint directory into host-memory pytrees with int
    layer keys; validates checksums (raises ckpt.CheckpointCorrupt)."""
    from oobleck_tpu import ckpt

    return ckpt.load_step_dir(target)
