// Pipeline-template generator: memoized divide-and-conquer over layer ranges.
//
// Native twin of oobleck_tpu/planning/templates.py (same semantics as the
// reference planner, /root/reference/oobleck/csrc/planning/
// pipeline_template.cpp:82-339 + execution_result.h:60-204, re-implemented
// from its documented behavior): for every host count in [min,max] and every
// stage count in [hosts, layers], find the stage partition minimizing the
// t1+t2+t3 pipeline cost model. Work is spread over a std::thread pool with
// a mutex-sharded memo table (the reference uses cppcoro+TBB); exposed as a
// plain C API for ctypes (pybind11 is not available in this image).
//
// Build: oobleck_tpu/csrc/Makefile (g++ -O2 -std=c++20 -shared -fPIC).

#include <algorithm>
#include <atomic>
#include <cmath>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <functional>
#include <map>
#include <memory>
#include <mutex>
#include <queue>
#include <sstream>
#include <string>
#include <thread>
#include <tuple>
#include <unordered_map>
#include <vector>

namespace {

struct LayerCost {
  double forward;
  double backward;
  std::map<int, double> allreduce_in_host;  // chips -> ms
  int64_t mem_params;
  int64_t mem_activation;
};

struct Stage {
  int start, end;  // layer range [start, end)
  int num_chips;
  double forward = 0, backward = 0;
  int64_t mem_required = 0;
  double latency() const { return forward + backward; }
};

Stage build_stage(const std::vector<LayerCost>& layers, int start, int end,
                  int num_chips) {
  Stage s;
  s.start = start;
  s.end = end;
  s.num_chips = num_chips;
  for (int i = start; i < end; ++i) {
    const auto& l = layers[i];
    s.forward += l.forward / num_chips;
    s.backward += l.backward / num_chips;
    if (num_chips > 1) {
      auto it = l.allreduce_in_host.find(num_chips);
      double ar = it == l.allreduce_in_host.end() ? 0.0 : it->second;
      s.forward += ar;
      s.backward += ar;
    }
    s.mem_required += 6 * l.mem_params + l.mem_activation;
  }
  return s;
}

// Divide-and-conquer cost node; mirrors the t1/t2/t3 + kstar model.
struct DCResult {
  double t1 = 0, t2 = 0, t3 = 0;
  int kstar = 0;
  std::vector<Stage> stages;
  double t() const { return t1 + t2 + t3; }
  double kstar_latency() const { return stages[kstar].latency(); }
};

using DCPtr = std::shared_ptr<DCResult>;

DCPtr make_base(Stage stage) {
  auto r = std::make_shared<DCResult>();
  double lat = stage.latency();
  r->t1 = lat;
  r->t2 = 2 * lat;
  r->t3 = lat;
  r->kstar = 0;
  r->stages = {std::move(stage)};
  return r;
}

DCPtr combine(const DCPtr& left, const DCPtr& right) {
  auto r = std::make_shared<DCResult>();
  if (left->kstar_latency() > right->kstar_latency()) {
    r->kstar = left->kstar;
  } else {
    r->kstar = right->kstar + static_cast<int>(left->stages.size());
  }
  r->t1 = left->t1 + right->t1;
  int num_stages =
      static_cast<int>(left->stages.size() + right->stages.size());
  int mb_factor = 2 * num_stages + r->kstar + 1;
  double tail = 0;
  if (r->kstar == left->kstar) {
    r->t2 = mb_factor * left->kstar_latency();
    for (size_t i = left->kstar; i < left->stages.size(); ++i)
      tail += left->stages[i].latency();
    for (const auto& s : right->stages) tail += s.latency();
  } else {
    r->t2 = mb_factor * right->kstar_latency();
    for (size_t i = right->kstar; i < right->stages.size(); ++i)
      tail += right->stages[i].latency();
  }
  r->t3 = tail;
  r->stages = left->stages;
  r->stages.insert(r->stages.end(), right->stages.begin(),
                   right->stages.end());
  return r;
}

// Memo key: (num_stages, start, end, num_hosts, chips_per_host)
using Key = std::tuple<int, int, int, int, int>;
struct KeyHash {
  size_t operator()(const Key& k) const {
    size_t h = 1469598103934665603ull;
    auto mix = [&h](int v) {
      h ^= static_cast<size_t>(v) + 0x9e3779b97f4a7c15ull + (h << 6) + (h >> 2);
    };
    mix(std::get<0>(k));
    mix(std::get<1>(k));
    mix(std::get<2>(k));
    mix(std::get<3>(k));
    mix(std::get<4>(k));
    return h;
  }
};

// Mutex-sharded memo table (the reference uses a TBB concurrent map).
class Memo {
 public:
  static constexpr int kShards = 64;
  bool lookup(const Key& k, DCPtr* out) {
    auto& sh = shard(k);
    std::lock_guard<std::mutex> g(sh.mu);
    auto it = sh.map.find(k);
    if (it == sh.map.end()) return false;
    *out = it->second;
    return true;
  }
  void insert(const Key& k, DCPtr v) {
    auto& sh = shard(k);
    std::lock_guard<std::mutex> g(sh.mu);
    sh.map.emplace(k, std::move(v));
  }

 private:
  struct Shard {
    std::mutex mu;
    std::unordered_map<Key, DCPtr, KeyHash> map;
  };
  Shard& shard(const Key& k) { return shards_[KeyHash{}(k) % kShards]; }
  Shard shards_[kShards];
};

DCPtr divide_and_conquer(const std::vector<LayerCost>& layers, int start,
                         int end, int num_stages, int num_hosts,
                         int chips_per_host, Memo& memo) {
  Key key{num_stages, start, end, num_hosts, chips_per_host};
  DCPtr cached;
  if (memo.lookup(key, &cached)) return cached;

  // Feasibility rules (see templates.py:_dc and the reference
  // pipeline_template.cpp:193-214).
  bool infeasible = false;
  if (num_stages > end - start) infeasible = true;
  if (num_hosts == 1) {
    if (chips_per_host < num_stages) infeasible = true;
    if (num_stages == 1 && (chips_per_host & (chips_per_host - 1)) != 0)
      infeasible = true;
  } else if (num_hosts > num_stages) {
    infeasible = true;
  }
  if (infeasible) {
    memo.insert(key, nullptr);
    return nullptr;
  }

  if (num_stages == 1) {
    auto r = make_base(build_stage(layers, start, end, chips_per_host));
    memo.insert(key, r);
    return r;
  }

  DCPtr best;
  for (int k = start + 1; k < end; ++k) {
    if (num_hosts == 1) {
      int half = chips_per_host / 2;  // even bisection only
      if (half * 2 != chips_per_host || half == 0) continue;
      for (int s_left = 1; s_left < num_stages; ++s_left) {
        auto left = divide_and_conquer(layers, start, k, s_left, 1, half, memo);
        auto right = divide_and_conquer(layers, k, end, num_stages - s_left, 1,
                                        chips_per_host - half, memo);
        if (!left || !right) continue;
        auto cand = combine(left, right);
        if (!best || cand->t() < best->t()) best = cand;
      }
    } else {
      for (int h_left = 1; h_left < num_hosts; ++h_left) {
        for (int s_left = 1; s_left < num_stages; ++s_left) {
          auto left = divide_and_conquer(layers, start, k, s_left, h_left,
                                         chips_per_host, memo);
          auto right =
              divide_and_conquer(layers, k, end, num_stages - s_left,
                                 num_hosts - h_left, chips_per_host, memo);
          if (!left || !right) continue;
          auto cand = combine(left, right);
          if (!best || cand->t() < best->t()) best = cand;
        }
      }
    }
  }
  memo.insert(key, best);
  return best;
}

// Tiny fixed thread pool for the top-level (host count x stage count) tasks.
class ThreadPool {
 public:
  explicit ThreadPool(int n) {
    for (int i = 0; i < n; ++i)
      workers_.emplace_back([this] { loop(); });
  }
  ~ThreadPool() {
    {
      std::lock_guard<std::mutex> g(mu_);
      done_ = true;
    }
    cv_.notify_all();
    for (auto& w : workers_) w.join();
  }
  void submit(std::function<void()> f) {
    {
      std::lock_guard<std::mutex> g(mu_);
      q_.push(std::move(f));
    }
    cv_.notify_one();
  }
  void wait_idle() {
    std::unique_lock<std::mutex> g(mu_);
    idle_cv_.wait(g, [this] { return q_.empty() && active_ == 0; });
  }

 private:
  void loop() {
    for (;;) {
      std::function<void()> f;
      {
        std::unique_lock<std::mutex> g(mu_);
        cv_.wait(g, [this] { return done_ || !q_.empty(); });
        if (done_ && q_.empty()) return;
        f = std::move(q_.front());
        q_.pop();
        ++active_;
      }
      f();
      {
        std::lock_guard<std::mutex> g(mu_);
        --active_;
        if (q_.empty() && active_ == 0) idle_cv_.notify_all();
      }
    }
  }
  std::vector<std::thread> workers_;
  std::queue<std::function<void()>> q_;
  std::mutex mu_;
  std::condition_variable cv_, idle_cv_;
  int active_ = 0;
  bool done_ = false;
};

std::string to_json(const std::vector<std::pair<int, DCPtr>>& results,
                    int chips_per_host) {
  std::ostringstream os;
  os.precision(17);
  os << "[";
  bool first_t = true;
  for (const auto& [hosts, r] : results) {
    if (!r) continue;
    if (!first_t) os << ",";
    first_t = false;
    os << "{\"num_hosts\":" << hosts
       << ",\"chips_per_host\":" << chips_per_host
       << ",\"iteration_time\":" << r->t() << ",\"stages\":[";
    for (size_t i = 0; i < r->stages.size(); ++i) {
      const auto& s = r->stages[i];
      if (i) os << ",";
      os << "{\"layers\":[" << s.start << "," << s.end << "]"
         << ",\"num_chips\":" << s.num_chips << ",\"forward\":" << s.forward
         << ",\"backward\":" << s.backward
         << ",\"mem_required\":" << s.mem_required << "}";
    }
    os << "]}";
  }
  os << "]";
  return os.str();
}

std::string* g_result = nullptr;

}  // namespace

extern "C" {

// Inputs are flat arrays over `num_layers` layers:
//   fwd/bwd:        per-layer times (ms)
//   ar_chips:       `num_ar` chip counts with in-host allreduce entries
//   ar_in_host:     [num_layers x num_ar] times, row-major
//   mem_params/mem_activation: per-layer bytes
// Returns a malloc'd JSON string (caller frees via planner_free).
const char* planner_create_templates(
    int num_layers, const double* fwd, const double* bwd, int num_ar,
    const int* ar_chips, const double* ar_in_host, const int64_t* mem_params,
    const int64_t* mem_activation, int min_hosts, int max_hosts,
    int chips_per_host, int num_threads) {
  std::vector<LayerCost> layers(num_layers);
  for (int i = 0; i < num_layers; ++i) {
    layers[i].forward = fwd[i];
    layers[i].backward = bwd[i];
    layers[i].mem_params = mem_params[i];
    layers[i].mem_activation = mem_activation[i];
    for (int j = 0; j < num_ar; ++j)
      layers[i].allreduce_in_host[ar_chips[j]] = ar_in_host[i * num_ar + j];
  }

  if (num_threads <= 0)
    num_threads = std::max(1u, std::thread::hardware_concurrency());

  std::vector<std::pair<int, DCPtr>> results;
  for (int n = min_hosts; n <= max_hosts; ++n) results.push_back({n, nullptr});

  {
    // One memo shared across all host counts and tasks: keys include the
    // host count, and multi-host splits recurse into smaller host counts, so
    // sharing is safe and avoids recomputing overlapping subtrees.
    Memo memo;
    std::vector<std::unique_ptr<std::mutex>> best_mus;
    ThreadPool pool(num_threads);
    for (auto& [hosts, slot] : results) {
      best_mus.push_back(std::make_unique<std::mutex>());
      auto* best_mu = best_mus.back().get();
      auto* slot_ptr = &slot;
      int n = hosts;
      for (int num_stages = n; num_stages <= num_layers; ++num_stages) {
        pool.submit([&layers, &memo, slot_ptr, n, num_stages, chips_per_host,
                     best_mu] {
          auto r = divide_and_conquer(layers, 0, (int)layers.size(),
                                      num_stages, n, chips_per_host, memo);
          if (!r) return;
          std::lock_guard<std::mutex> g(*best_mu);
          if (!*slot_ptr || r->t() < (*slot_ptr)->t()) *slot_ptr = r;
        });
      }
    }
    pool.wait_idle();
  }

  delete g_result;
  g_result = new std::string(to_json(results, chips_per_host));
  return g_result->c_str();
}

void planner_free() {
  delete g_result;
  g_result = nullptr;
}

}  // extern "C"
