"""oobleck-lint: project-native static analysis.

Generic linters cannot see this repo's load-bearing invariants — the
``device_work`` fence between background XLA work and the train thread
(PR-9 flake), the zero-steady-state-host-sync hot-path contract (PR 5),
the no-views-of-donated-buffers rule (PR-3 checkpoint corruption), the
legacy-tolerant wire protocol (PR 9), the single metric/flight-event
namespace, and no-blocking-I/O-in-async control planes. This package
turns each of them into a machine-checked rule:

    OBL001  fence-discipline     device calls on background threads must
                                 hold ``utils/background.py:device_work``
    OBL002  host-sync leak       float()/.item()/np.asarray/
                                 block_until_ready in step-loop modules
                                 outside the DeferredLoss funnel
    OBL003  use-after-donation   views of arguments passed to jitted
                                 callables with donate_argnums
    OBL004  verb exhaustiveness  every ResponseType verb dispatched in
                                 agent + engine; broadcast payload keys
                                 through named-constant helpers
    OBL005  name registry        metric families / flight-event kinds
                                 declared in obs/registry.py (generated)
    OBL006  blocking-in-async    time.sleep / blocking file + socket I/O
                                 inside ``async def``

Run ``python -m oobleck_tpu.analysis`` (wired as ``make analyze``, part
of ``make lint``). Inline suppressions: ``# oobleck: allow[OBL002] --
reason`` on the offending line or the comment line just above it.
Grandfathered findings live in ``analysis/baseline.json`` with a reason
each; the analyzer exits non-zero only on NEW findings.
"""

from oobleck_tpu.analysis.core import (
    AnalysisResult,
    Finding,
    ModuleInfo,
    Project,
    Rule,
    all_rules,
    default_baseline_path,
    load_baseline,
    run_analysis,
)

__all__ = [
    "AnalysisResult",
    "Finding",
    "ModuleInfo",
    "Project",
    "Rule",
    "all_rules",
    "default_baseline_path",
    "load_baseline",
    "run_analysis",
]
