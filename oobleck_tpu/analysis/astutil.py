"""Shared AST helpers for the oobleck-lint rules.

Everything here is stdlib-``ast`` only: the analyzer must never import
the code under analysis (importing the engine drags in jax), and must
run in well under a second on the whole tree so ``make lint`` stays
cheap.
"""

from __future__ import annotations

import ast
from collections.abc import Iterator


def attach_parents(tree: ast.AST) -> None:
    """Stamp a ``_oobleck_parent`` backlink on every node so rules can
    walk ancestor chains (enclosing function, enclosing With, ...)."""
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            child._oobleck_parent = node  # type: ignore[attr-defined]


def parent(node: ast.AST) -> ast.AST | None:
    return getattr(node, "_oobleck_parent", None)


def ancestors(node: ast.AST) -> Iterator[ast.AST]:
    cur = parent(node)
    while cur is not None:
        yield cur
        cur = parent(cur)


def enclosing_function(node: ast.AST) -> ast.FunctionDef | ast.AsyncFunctionDef | None:
    for anc in ancestors(node):
        if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return anc
    return None


def enclosing_class(node: ast.AST) -> ast.ClassDef | None:
    for anc in ancestors(node):
        if isinstance(anc, ast.ClassDef):
            return anc
    return None


def scope_name(node: ast.AST) -> str:
    """Dotted enclosing scope, e.g. ``DeviceStager._grab`` — the stable
    half of a finding fingerprint (line numbers churn, scopes rarely)."""
    parts: list[str] = []
    for anc in ancestors(node):
        if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            parts.append(anc.name)
    return ".".join(reversed(parts)) or "<module>"


def call_name(call: ast.Call) -> str:
    """Last path segment of the callee: ``jax.jit`` -> ``jit``,
    ``self.engine.decode`` -> ``decode``, ``float`` -> ``float``."""
    func = call.func
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return ""


def dotted_name(node: ast.AST) -> str:
    """Best-effort dotted rendering of a Name/Attribute chain
    (``self.engine.decode``); '' for anything non-trivial."""
    parts: list[str] = []
    cur = node
    while isinstance(cur, ast.Attribute):
        parts.append(cur.attr)
        cur = cur.value
    if isinstance(cur, ast.Name):
        parts.append(cur.id)
    elif isinstance(cur, ast.Call):
        parts.append(dotted_name(cur.func) + "()")
    else:
        return ""
    return ".".join(reversed(parts))


def receiver_name(call: ast.Call) -> str:
    """The attribute segment the method hangs off: ``self.engine.decode``
    -> ``engine``, ``re.compile`` -> ``re``, ``decode(x)`` -> ''."""
    func = call.func
    if not isinstance(func, ast.Attribute):
        return ""
    value = func.value
    if isinstance(value, ast.Attribute):
        return value.attr
    if isinstance(value, ast.Name):
        return value.id
    return ""


def first_str_arg(call: ast.Call) -> str | None:
    """The literal first positional argument, or None when absent or
    dynamic (f-string, variable)."""
    if not call.args:
        return None
    arg = call.args[0]
    if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
        return arg.value
    return None


def inside_with_call(node: ast.AST, callee_names: set[str]) -> bool:
    """True when any ancestor ``with`` statement's context manager is a
    call whose name is in ``callee_names`` (e.g. {"device_work"})."""
    for anc in ancestors(node):
        if isinstance(anc, (ast.With, ast.AsyncWith)):
            for item in anc.items:
                expr = item.context_expr
                if isinstance(expr, ast.Call) and call_name(expr) in callee_names:
                    return True
    return False


def functions_of(tree: ast.AST) -> dict[str, list[ast.FunctionDef | ast.AsyncFunctionDef]]:
    """All function/method defs in a module keyed by bare name. Collisions
    (same method name on two classes) keep every definition — callers over-
    approximate, which for a reachability lint errs on the safe side."""
    out: dict[str, list] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            out.setdefault(node.name, []).append(node)
    return out


def called_names(fn: ast.AST) -> set[str]:
    """Bare names of everything a function calls, including ``self.x()``
    method calls (-> ``x``) — the intra-module call-graph edge set."""
    names: set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            name = call_name(node)
            if name:
                names.add(name)
    return names


def resolve_recorder_vars(fn: ast.AST, factory_names: set[str]) -> set[str]:
    """Local variable names assigned from a factory call anywhere in
    ``fn`` — e.g. ``fr = metrics.flight_recorder()`` with
    factory_names={"flight_recorder"} yields {"fr"}. Also follows
    ``self._x = flight_recorder()`` to ``_x``."""
    out: set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            if call_name(node.value) in factory_names:
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        out.add(tgt.id)
                    elif isinstance(tgt, ast.Attribute):
                        out.add(tgt.attr)
    return out
