"""CLI for oobleck-lint: ``python -m oobleck_tpu.analysis [targets...]``.

Exit status is 0 when the tree is clean (no findings beyond inline
suppressions and the checked-in baseline) and 1 when there is anything
new — which is what lets ``make analyze`` gate the build. ``--json``
emits the machine-readable report bench.py embeds as provenance;
``--write-baseline`` grandfathers the current findings (use sparingly:
the intended fix for a finding is a fix).
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

from oobleck_tpu.analysis.core import (
    DEFAULT_TARGETS,
    all_rules,
    default_baseline_path,
    load_baseline,
    run_analysis,
    write_baseline,
)


def _find_root(start: Path) -> Path:
    """Nearest ancestor containing the ``oobleck_tpu`` package."""
    for cand in (start, *start.parents):
        if (cand / "oobleck_tpu" / "__init__.py").is_file():
            return cand
    return start


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m oobleck_tpu.analysis",
        description="project-native static analysis (rules OBL001-OBL006)")
    parser.add_argument("targets", nargs="*", default=None,
                        help=f"files/dirs relative to the repo root "
                             f"(default: {' '.join(DEFAULT_TARGETS)})")
    parser.add_argument("--root", type=Path, default=None,
                        help="repo root (default: auto-detect from cwd)")
    parser.add_argument("--json", action="store_true",
                        help="emit a JSON report instead of text")
    parser.add_argument("--explain", action="store_true",
                        help="print the rule table and exit")
    parser.add_argument("--baseline", type=Path, default=None,
                        help="baseline file (default: the checked-in one)")
    parser.add_argument("--no-baseline", action="store_true",
                        help="ignore the baseline (report everything)")
    parser.add_argument("--write-baseline", action="store_true",
                        help="grandfather all current findings and exit 0")
    parser.add_argument("--show-suppressed", action="store_true",
                        help="also list suppressed/baselined findings")
    args = parser.parse_args(argv)

    rules = all_rules()
    if args.explain:
        for rule in rules:
            print(f"{rule.code}  {rule.name:<20} [{rule.severity}]  "
                  f"{rule.rationale}")
        return 0

    root = (args.root or _find_root(Path.cwd())).resolve()
    targets = tuple(args.targets) if args.targets else DEFAULT_TARGETS
    baseline_path = args.baseline or default_baseline_path(root)
    baseline = {} if (args.no_baseline or args.write_baseline) \
        else load_baseline(baseline_path)

    result = run_analysis(root, targets, rules=rules, baseline=baseline)

    if args.write_baseline:
        write_baseline(baseline_path, result.new)
        print(f"wrote {len(result.new)} finding(s) to {baseline_path}")
        return 0

    if args.json:
        print(json.dumps({
            "summary": result.summary(),
            "new": [f.as_dict() for f in result.new],
            "suppressed": [f.as_dict() for f in result.suppressed],
            "baselined": [f.as_dict() for f in result.baselined],
            "unused_baseline": result.unused_baseline,
            "parse_errors": result.parse_errors,
        }, indent=2))
        return result.exit_code

    for err in result.parse_errors:
        print(f"PARSE ERROR: {err}")
    for f in result.new:
        print(f.render())
    if args.show_suppressed:
        for f in result.suppressed:
            print(f"suppressed: {f.render()}")
        for f in result.baselined:
            print(f"baselined:  {f.render()}")
    for fp in result.unused_baseline:
        print(f"note: baseline entry no longer fires (remove it): {fp}")

    s = result.summary()
    print(f"oobleck-lint: {s['files']} file(s), {s['rules']} rule(s): "
          f"{s['findings_new']} new, {s['findings_suppressed']} suppressed, "
          f"{s['findings_baselined']} baselined")
    return result.exit_code


if __name__ == "__main__":
    sys.exit(main())
