"""OBL006 — blocking calls inside ``async def`` bodies.

History: the elastic master is a single asyncio event loop multiplexing
heartbeats, agent connections, and recovery broadcasts. One blocking
call in a coroutine stalls every timer on the loop — a stalled heartbeat
scan reads as a dead agent and can trigger a spurious (expensive)
recovery. This nearly shipped in PR 9: a synchronous ``open()`` in the
SSH launch path, invisible in tests because the loop was otherwise idle.

The rule is lexical: inside an ``async def`` body (NOT descending into
nested ``def``/``lambda``, which run wherever they are called), flag
``time.sleep``, builtin ``open``, ``subprocess.run/call/check_output/
check_call``, ``os.system``, and ``socket.create_connection``. The
sanctioned escapes are ``await asyncio.to_thread(...)`` and
``loop.run_in_executor(...)`` — both take the callable uncalled, so they
never match. ``Popen`` (non-blocking spawn) and pipe ``send``/``recv``
are deliberately not flagged.

Scope: ``elastic/master.py`` — the only event loop in the tree.
"""

from __future__ import annotations

import ast
from collections.abc import Iterator

from oobleck_tpu.analysis import astutil
from oobleck_tpu.analysis.core import Finding, ModuleInfo, Project, Rule

ASYNC_MODULES = ("oobleck_tpu/elastic/master.py",)

# bare-name builtins that block
BLOCKING_BUILTINS = {"open"}
# receiver -> blocking attribute calls
BLOCKING_METHODS = {
    "time": {"sleep"},
    "subprocess": {"run", "call", "check_output", "check_call"},
    "os": {"system"},
    "socket": {"create_connection"},
}


def _async_body_calls(fn: ast.AsyncFunctionDef) -> Iterator[ast.Call]:
    """Calls lexically in the coroutine body, skipping nested function
    definitions (they execute in whatever context calls them)."""

    def walk(node: ast.AST) -> Iterator[ast.Call]:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                continue
            if isinstance(child, ast.Call):
                yield child
            yield from walk(child)

    yield from walk(fn)


def _blocking_kind(call: ast.Call) -> str | None:
    func = call.func
    if isinstance(func, ast.Name) and func.id in BLOCKING_BUILTINS:
        return func.id + "()"
    if isinstance(func, ast.Attribute):
        recv = astutil.receiver_name(call)
        if func.attr in BLOCKING_METHODS.get(recv, ()):
            return f"{recv}.{func.attr}()"
    return None


class AsyncBlockingRule(Rule):
    code = "OBL006"
    name = "blocking-in-async"
    rationale = ("no blocking I/O or sleeps on the master's event loop — "
                 "a stalled heartbeat scan looks like a dead agent")

    def check_module(self, module: ModuleInfo,
                     project: Project) -> Iterator[Finding]:
        if not module.relpath.endswith(ASYNC_MODULES):
            return
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.AsyncFunctionDef):
                continue
            for call in _async_body_calls(node):
                kind = _blocking_kind(call)
                if kind is None:
                    continue
                yield module.finding(
                    self, call,
                    f"{kind} blocks the event loop inside "
                    f"`async def {node.name}`; use "
                    f"`await asyncio.to_thread(...)` or an executor")
