"""OBL005 — metric / flight-event / span names must be registered.

History: the PR-8 forensics work found the observability plane's worst
failure mode is silent: a typo'd metric family (``oobleck_step_secnds``)
or flight-event kind just creates a parallel, never-read series, and the
dashboards/bench diffs that key on the real name read zero forever. The
generated registry (``obs/registry.py``, built by
``python -m oobleck_tpu.analysis.genregistry``) is the single source of
truth; this rule checks every statically-visible name against it, and
``OOBLECK_STRICT_REGISTRY=1`` makes the runtime enforce the same sets.

The name-collection logic lives here and is reused by the generator, so
the lint and the registry can never disagree about what counts as a
name-introducing call site.

Dynamic names (f-strings, variables) cannot be checked statically and
are flagged; intentionally-dynamic sites (``utils/recovery.py``'s
``recovery.{event}`` spans) carry ``# oobleck: allow[OBL005]``.
"""

from __future__ import annotations

import ast
from collections.abc import Iterator
from dataclasses import dataclass, field

from oobleck_tpu.analysis import astutil
from oobleck_tpu.analysis.core import Finding, ModuleInfo, Project, Rule

REGISTRY_MODULE = "obs/registry.py"
METRIC_METHODS = {"counter", "gauge", "histogram"}
REGISTRY_FACTORIES = {"registry"}
FLIGHT_FACTORIES = {"flight_recorder"}
SPAN_FACTORIES = {"span_recorder"}
# Module-alias receivers for the ``spans.span("name")`` / ``spans.event``
# free functions (each importer picks its own alias).
SPAN_MODULE_RECEIVERS = {"spans", "obs_spans", "spans_mod", "_spans"}
# Conventional local receiver names for a Registry (``reg = ... or
# metrics.registry()`` defeats assignment tracing; the idiom is stable).
REGISTRY_LOCAL_RECEIVERS = {"reg", "registry"}


@dataclass
class NameSite:
    """One statically-visible name-introducing call."""

    kind: str  # "metric" | "flight_event" | "span"
    name: str | None  # None when dynamic
    node: ast.Call
    module: ModuleInfo


@dataclass
class CollectedNames:
    metrics: set[str] = field(default_factory=set)
    flight_events: set[str] = field(default_factory=set)
    spans: set[str] = field(default_factory=set)

    def bucket(self, kind: str) -> set[str]:
        return {"metric": self.metrics, "flight_event": self.flight_events,
                "span": self.spans}[kind]


def _chained_factory(call: ast.Call) -> str | None:
    """``metrics.flight_recorder().record(...)`` -> ``flight_recorder``."""
    func = call.func
    if isinstance(func, ast.Attribute) and isinstance(func.value, ast.Call):
        return astutil.call_name(func.value)
    return None


def _site_kind(call: ast.Call, flight_vars: set[str],
               span_vars: set[str]) -> str | None:
    name = astutil.call_name(call)
    chained = _chained_factory(call)
    recv = astutil.receiver_name(call)
    if name in METRIC_METHODS:
        if chained in REGISTRY_FACTORIES or recv in REGISTRY_LOCAL_RECEIVERS:
            return "metric"
        return None
    if name == "record":
        if chained in FLIGHT_FACTORIES or recv in flight_vars:
            return "flight_event"
        if chained in SPAN_FACTORIES or recv in span_vars:
            return "span"
        return None
    if name in ("span", "event") and recv in SPAN_MODULE_RECEIVERS:
        return "span"
    return None


def iter_name_sites(module: ModuleInfo) -> Iterator[NameSite]:
    """Every metric/flight-event/span name-introducing call in a module.
    Shared between this rule and the registry generator."""
    flight_vars: set[str] = set()
    span_vars: set[str] = set()
    for fns in astutil.functions_of(module.tree).values():
        for fn in fns:
            flight_vars |= astutil.resolve_recorder_vars(fn, FLIGHT_FACTORIES)
            span_vars |= astutil.resolve_recorder_vars(fn, SPAN_FACTORIES)
    for call in ast.walk(module.tree):
        if not isinstance(call, ast.Call):
            continue
        kind = _site_kind(call, flight_vars, span_vars)
        if kind is None:
            continue
        yield NameSite(kind=kind, name=astutil.first_str_arg(call),
                       node=call, module=module)


def collect_names(project: Project) -> CollectedNames:
    """All statically-known names across the project — the generator's
    input. Dynamic sites contribute nothing (they carry suppressions)."""
    out = CollectedNames()
    for module in project.modules:
        if module.relpath.endswith(REGISTRY_MODULE):
            continue
        for site in iter_name_sites(module):
            if site.name is not None:
                out.bucket(site.kind).add(site.name)
    return out


def parse_registry(module: ModuleInfo) -> dict[str, set[str]]:
    """String constants of each top-level frozenset assignment in the
    generated registry module, keyed by the assigned name."""
    out: dict[str, set[str]] = {}
    for node in module.tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            out[node.targets[0].id] = {
                c.value for c in ast.walk(node.value)
                if isinstance(c, ast.Constant) and isinstance(c.value, str)
            }
    return out


KIND_TO_REGISTRY_NAME = {
    "metric": "METRIC_FAMILIES",
    "flight_event": "FLIGHT_EVENT_KINDS",
    "span": "SPAN_NAMES",
}


class RegistryNamesRule(Rule):
    code = "OBL005"
    name = "registry-names"
    rationale = ("metric/flight-event/span names must exist in the "
                 "generated obs/registry.py — typos create silent "
                 "never-read series")

    def check_project(self, project: Project) -> Iterator[Finding]:
        reg_mods = project.modules_matching(REGISTRY_MODULE)
        if not reg_mods:
            return  # registry not part of this project (rule fixtures)
        registered = parse_registry(reg_mods[0])
        for module in project.modules:
            if module.relpath.endswith(REGISTRY_MODULE):
                continue
            for site in iter_name_sites(module):
                reg_name = KIND_TO_REGISTRY_NAME[site.kind]
                allowed = registered.get(reg_name, set())
                if site.name is None:
                    yield module.finding(
                        self, site.node,
                        f"dynamic {site.kind} name cannot be checked "
                        f"against {reg_name}; use a literal, or suppress "
                        f"with a reason if dynamism is the point")
                elif site.name not in allowed:
                    yield module.finding(
                        self, site.node,
                        f"{site.kind} name '{site.name}' is not in "
                        f"obs/registry.py:{reg_name} — regenerate with "
                        f"`make gen-registry` (a typo here would emit a "
                        f"series nothing ever reads)")
