"""The six project-native rules, in code order. Each encodes one
invariant a past incident proved this repo cannot keep by review alone —
see the class docstrings (and README "Static analysis") for the history.
"""

from oobleck_tpu.analysis.rules.asyncio_blocking import AsyncBlockingRule
from oobleck_tpu.analysis.rules.donation import DonationRule
from oobleck_tpu.analysis.rules.fence import FenceRule
from oobleck_tpu.analysis.rules.hotpath import HotPathRule
from oobleck_tpu.analysis.rules.protocol import ProtocolRule
from oobleck_tpu.analysis.rules.registry_names import RegistryNamesRule

RULES = [
    FenceRule,
    HotPathRule,
    DonationRule,
    ProtocolRule,
    RegistryNamesRule,
    AsyncBlockingRule,
]

__all__ = ["RULES"]
