"""OBL001 — fence discipline for background device work.

History: the PR-3 slow-suite flake. A respawned worker died one step
after its first post-restore checkpoint save — the warm-recovery
precompiler was AOT-compiling on a daemon thread while the train thread
dispatched steps and read losses back, and the XLA CPU runtime does not
tolerate that interleaving (``utils/background.py`` has the full
postmortem). The fix was the process-wide ``device_work(owner)`` fence;
this rule makes holding it a checked obligation, not a convention:

    any device-touching call reachable from a ``threading.Thread``
    target or an ``Executor.submit`` callback must be lexically inside
    ``with device_work(...)`` — either in the function itself or in the
    call frame that reached it.

Reachability is intra-module (call graph by bare name, depth-bounded).
"Fenced by the caller" propagates: if every call edge into a helper sits
inside a ``device_work`` block, the helper's device calls pass.
"""

from __future__ import annotations

import ast
from collections.abc import Iterator

from oobleck_tpu.analysis import astutil
from oobleck_tpu.analysis.core import Finding, ModuleInfo, Project, Rule

# Unambiguous device-touching callables (bare or attribute form).
DEVICE_CALLS = {
    "jit", "device_put", "device_get", "block_until_ready",
    "aot_compile", "stage_to_host",
}
# `.compile()` is device work (AOT executable build) except `re.compile`.
COMPILE_NAME = "compile"
COMPILE_SAFE_RECEIVERS = {"re", "regex"}
# Project device entry points that only count behind a `.engine` receiver
# (serve plane: `self.engine.decode(...)`); bare `decode` would collide
# with bytes.decode.
ENGINE_QUALIFIED = {"decode", "prefill", "set_params", "stage_params",
                    "warmup"}
ENGINE_RECEIVERS = {"engine"}
# Placement callbacks (DeviceStager et al.): device_put under any name.
PLACE_CALLS = {"place_fn", "_place_fn", "place_batch", "_place_batch"}

FENCE_NAMES = {"device_work"}
MAX_VISITS = 4096  # worklist bound: call graphs here are tiny


def _is_device_call(call: ast.Call) -> bool:
    name = astutil.call_name(call)
    if name in DEVICE_CALLS or name in PLACE_CALLS:
        return True
    if name == COMPILE_NAME:
        return astutil.receiver_name(call) not in COMPILE_SAFE_RECEIVERS
    if name in ENGINE_QUALIFIED:
        return astutil.receiver_name(call) in ENGINE_RECEIVERS
    return False


def _entry_targets(tree: ast.AST) -> list[ast.AST | str]:
    """Thread(target=...) / pool.submit(fn, ...) callbacks: bare names
    for Name/Attribute callbacks, the Lambda node itself for lambdas."""
    out: list[ast.AST | str] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        name = astutil.call_name(node)
        cb: ast.AST | None = None
        if name == "Thread":
            for kw in node.keywords:
                if kw.arg == "target":
                    cb = kw.value
        elif name == "submit" and node.args:
            cb = node.args[0]
        if cb is None:
            continue
        if isinstance(cb, ast.Lambda):
            out.append(cb)
        elif isinstance(cb, ast.Name):
            out.append(cb.id)
        elif isinstance(cb, ast.Attribute):
            out.append(cb.attr)
    return out


class FenceRule(Rule):
    code = "OBL001"
    name = "fence-discipline"
    rationale = ("device calls on Thread/submit paths must hold "
                 "device_work() — the PR-9 precompile x checkpoint race")

    def check_module(self, module: ModuleInfo,
                     project: Project) -> Iterator[Finding]:
        functions = astutil.functions_of(module.tree)
        entries = _entry_targets(module.tree)
        if not entries:
            return

        # Worklist over (function node, fenced-on-this-path). A function
        # counts as unfenced if ANY path reaches it unfenced; `state`
        # holds True ("all observed paths fenced") / False.
        state: dict[int, bool] = {}
        nodes: dict[int, ast.AST] = {}
        work: list[tuple[ast.AST, bool]] = []
        for entry in entries:
            if isinstance(entry, str):
                work.extend((fn, False) for fn in functions.get(entry, ()))
            else:
                work.append((entry, False))

        visits = 0
        while work and visits < MAX_VISITS:
            visits += 1
            fn, fenced = work.pop()
            prev = state.get(id(fn))
            if prev is not None and prev <= fenced:
                continue  # already seen at least this unfenced
            state[id(fn)] = fenced if prev is None else (prev and fenced)
            nodes[id(fn)] = fn
            for call in ast.walk(fn):
                if not isinstance(call, ast.Call):
                    continue
                edge_fenced = fenced or astutil.inside_with_call(
                    call, FENCE_NAMES)
                for callee in functions.get(astutil.call_name(call), ()):
                    if callee is not fn:
                        work.append((callee, edge_fenced))

        reported: set[tuple[int, int]] = set()
        for fn_id, fenced in state.items():
            if fenced:
                continue
            yield from self._check_body(module, nodes[fn_id], reported)

    def _check_body(self, module: ModuleInfo, fn: ast.AST,
                    reported: set[tuple[int, int]]) -> Iterator[Finding]:
        for call in ast.walk(fn):
            if not isinstance(call, ast.Call) or not _is_device_call(call):
                continue
            if astutil.inside_with_call(call, FENCE_NAMES):
                continue
            key = (call.lineno, call.col_offset)
            if key in reported:
                continue
            reported.add(key)
            yield module.finding(
                self, call,
                f"device-touching call `{astutil.call_name(call)}` is "
                f"reachable from a background-thread entry point but not "
                f"inside `with device_work(...)` "
                f"(utils/background.py fence)")
