"""OBL004 — wire-protocol verb exhaustiveness and payload-key hygiene.

History: PR 7 added the DEGRADE verb and PR 9 added RESTORE plus the
policy payload. Each rode the legacy-tolerance rules of
``elastic/message.py`` — receivers that predate a verb fall back to
RECONFIGURATION, and extra payload keys are carried by named constants
(``spans.TRACE_KEY``, ``policy.DECISION_KEY``) that old receivers
ignore. Those rules lived in reviewer memory; the PR-8 cleanup found
stale dispatch code precisely because nothing machine-checked them.

Four checks, all cross-file:

1. every ``ResponseType`` member is dispatched in the agent
   (``ResponseType.X`` must appear in ``elastic/agent.py``);
2. every verb the engine is expected to receive has its pipe-kind
   literal in ``ReconfigurationEngine`` (``execution/engine.py``); a new
   verb outside the known map needs BOTH a dispatch arm and a map entry
   here — that forced stop is the point;
3. broadcast payload construction in ``elastic/master.py`` may only use
   the core literal keys; anything new must be a named constant
   (the TRACE_KEY / DECISION_KEY legacy-tolerant pattern) — this is
   what forces epoch stamps to ride ``EPOCH_KEY``;
4. every ``RequestType`` member is dispatched in the master
   (``RequestType.X`` must appear in ``elastic/master.py``) — PR 16's
   REATTACH rode this: an agent-originated verb with no master arm is a
   handshake that hangs forever, not a protocol extension.
"""

from __future__ import annotations

import ast
from collections.abc import Iterator

from oobleck_tpu.analysis import astutil
from oobleck_tpu.analysis.core import Finding, ModuleInfo, Project, Rule

MESSAGE_MODULE = "elastic/message.py"
AGENT_MODULE = "elastic/agent.py"
ENGINE_MODULE = "execution/engine.py"
MASTER_MODULE = "elastic/master.py"

# ResponseType member -> the pipe-kind literal the engine's listener
# (ReconfigurationEngine._listen) must dispatch on. Members absent here
# and not in CONTROL_PLANE_ONLY are NEW verbs: the rule fails until the
# engine arm exists and this map says so.
VERB_TO_ENGINE_KIND = {
    "RECONFIGURATION": "reconfigure",
    "DEGRADE": "degrade",
    "RESTORE": "restore",
    "GROW": "grow",
    # Pool-plane lease verbs reuse the proven drain/grow engine paths:
    # a grant is a proactive-drain-shaped DEGRADE, a reclaim is a GROW.
    "LEASE_GRANT": "degrade",
    "LEASE_RECLAIM": "grow",
}
# Verbs the worker/engine never sees (absorbed by the agent/master).
CONTROL_PLANE_ONLY = {"SUCCESS", "FAILURE", "PONG", "FORWARD_COORDINATOR"}

# Literal keys allowed in broadcast payload dicts; everything else goes
# through a named constant so legacy receivers can ignore it knowingly.
CORE_BROADCAST_KEYS = {"lost_ip", "kind"}
ENGINE_LISTENER_CLASS = "ReconfigurationEngine"


def _enum_members(module: ModuleInfo, enum_name: str) -> dict[str, ast.AST]:
    for node in ast.walk(module.tree):
        if isinstance(node, ast.ClassDef) and node.name == enum_name:
            members: dict[str, ast.AST] = {}
            for stmt in node.body:
                if isinstance(stmt, ast.Assign):
                    for tgt in stmt.targets:
                        if isinstance(tgt, ast.Name):
                            members[tgt.id] = stmt
            return members
    return {}


def _attr_accesses(module: ModuleInfo, base: str) -> set[str]:
    out: set[str] = set()
    for node in ast.walk(module.tree):
        if isinstance(node, ast.Attribute) \
                and isinstance(node.value, ast.Name) \
                and node.value.id == base:
            out.add(node.attr)
    return out


def _class_strings(module: ModuleInfo, class_name: str) -> set[str]:
    for node in ast.walk(module.tree):
        if isinstance(node, ast.ClassDef) and node.name == class_name:
            return {c.value for c in ast.walk(node)
                    if isinstance(c, ast.Constant)
                    and isinstance(c.value, str)}
    return set()


class ProtocolRule(Rule):
    code = "OBL004"
    name = "verb-exhaustiveness"
    rationale = ("every ResponseType verb dispatched in agent + engine; "
                 "broadcast keys via named constants (legacy tolerance)")

    def check_project(self, project: Project) -> Iterator[Finding]:
        msg_mods = project.modules_matching(MESSAGE_MODULE)
        if not msg_mods:
            return  # not analyzing the elastic plane (e.g. rule fixtures)
        msg = msg_mods[0]
        members = _enum_members(msg, "ResponseType")
        if not members:
            return

        agent_mods = project.modules_matching(AGENT_MODULE)
        if agent_mods:
            dispatched = _attr_accesses(agent_mods[0], "ResponseType")
            for name, node in members.items():
                if name not in dispatched:
                    yield msg.finding(
                        self, node,
                        f"ResponseType.{name} has no dispatch arm in "
                        f"{agent_mods[0].relpath} (response_loop must "
                        f"handle or explicitly absorb every verb)")

        engine_mods = project.modules_matching(ENGINE_MODULE)
        if engine_mods:
            kinds = _class_strings(engine_mods[0], ENGINE_LISTENER_CLASS)
            for name, node in members.items():
                expected = VERB_TO_ENGINE_KIND.get(name)
                if expected is not None:
                    if kinds and expected not in kinds:
                        yield msg.finding(
                            self, node,
                            f"ResponseType.{name} maps to pipe kind "
                            f"'{expected}' but {ENGINE_LISTENER_CLASS} in "
                            f"{engine_mods[0].relpath} never dispatches it")
                elif name not in CONTROL_PLANE_ONLY:
                    yield msg.finding(
                        self, node,
                        f"ResponseType.{name} is a new verb: add an engine "
                        f"dispatch arm and extend VERB_TO_ENGINE_KIND (or "
                        f"CONTROL_PLANE_ONLY) in analysis/rules/protocol.py "
                        f"— legacy receivers must have a declared fallback")

        for master in project.modules_matching(MASTER_MODULE):
            yield from self._check_broadcast_keys(master)
            requests = _enum_members(msg, "RequestType")
            handled = _attr_accesses(master, "RequestType")
            for name, node in requests.items():
                if name not in handled:
                    yield msg.finding(
                        self, node,
                        f"RequestType.{name} has no dispatch arm in "
                        f"{master.relpath} — an agent-originated verb the "
                        f"master never handles is a hung handshake")

    def _check_broadcast_keys(self, master: ModuleInfo) -> Iterator[Finding]:
        for fns in astutil.functions_of(master.tree).values():
            for fn in fns:
                if not fn.name.startswith("_broadcast"):
                    continue
                for node in ast.walk(fn):
                    # payload = {"literal": ...} — literal keys beyond the
                    # core set must be named constants.
                    if isinstance(node, ast.Assign) \
                            and isinstance(node.value, ast.Dict) \
                            and any(isinstance(t, ast.Name)
                                    and t.id == "payload"
                                    for t in node.targets):
                        for key in node.value.keys:
                            if isinstance(key, ast.Constant) \
                                    and isinstance(key.value, str) \
                                    and key.value not in CORE_BROADCAST_KEYS:
                                yield master.finding(
                                    self, key,
                                    f"broadcast payload key "
                                    f"'{key.value}' is a raw literal; new "
                                    f"keys ride named constants (the "
                                    f"TRACE_KEY/DECISION_KEY pattern) so "
                                    f"legacy receivers skip them knowingly")
                    # payload["literal"] = ... — same contract.
                    elif isinstance(node, ast.Assign) and any(
                            isinstance(t, ast.Subscript)
                            and isinstance(t.value, ast.Name)
                            and t.value.id == "payload"
                            and isinstance(t.slice, ast.Constant)
                            and isinstance(t.slice.value, str)
                            and t.slice.value not in CORE_BROADCAST_KEYS
                            for t in node.targets):
                        yield master.finding(
                            self, node,
                            "broadcast payload key assigned from a raw "
                            "string literal; use a named constant (the "
                            "TRACE_KEY/DECISION_KEY pattern)")
