"""OBL002 — host-sync leak in step-loop modules.

History: PR 5's overlap-everything hot path holds a zero-steady-state-
host-syncs contract — losses stay on device (``DeferredLoss``) and are
drained every ``loss_readback_every`` steps; the only sanctioned readback
funnel is ``_host_sync`` (which increments ``host_sync_counter``, the
contract's test hook). One stray ``float(loss)`` anywhere in the step
loop silently re-serializes dispatch and the 942-vs-805 tok/s win
evaporates without any test failing.

This rule flags host-synchronizing constructs in the step-loop modules —
``float(x)`` / ``x.item()`` on plausible device values, ``np.asarray``,
``jax.device_get``, ``block_until_ready`` — anywhere outside the funnel
(the ``DeferredLoss`` class, ``_host_sync`` itself, or any function that
touches ``host_sync_counter``). Cold paths that legitimately sync
(profiling, eval sweeps, reconfiguration) carry inline
``# oobleck: allow[OBL002] -- reason`` annotations: the rule is
fail-closed so NEW code in these modules is born compliant.
"""

from __future__ import annotations

import ast
from collections.abc import Iterator

from oobleck_tpu.analysis import astutil
from oobleck_tpu.analysis.core import Finding, ModuleInfo, Project, Rule

HOT_MODULES = (
    "oobleck_tpu/execution/engine.py",
    "oobleck_tpu/execution/pipeline.py",
    "oobleck_tpu/parallel/train.py",
    "oobleck_tpu/parallel/overlap.py",
    # The telemetry ring records once per step inside the loop: its
    # zero-host-syncs promise (obs/telemetry.py design constraint 1) is
    # the same contract, so it lives under the same fence.
    "oobleck_tpu/obs/telemetry.py",
)

FUNNEL_CLASSES = {"DeferredLoss"}
FUNNEL_FUNCTIONS = {"_host_sync"}
FUNNEL_MARKER = "host_sync_counter"

NP_RECEIVERS = {"np", "numpy"}


def _references_marker(fn: ast.AST) -> bool:
    for node in ast.walk(fn):
        if isinstance(node, ast.Name) and node.id == FUNNEL_MARKER:
            return True
        if isinstance(node, ast.Attribute) and node.attr == FUNNEL_MARKER:
            return True
    return False


def _in_funnel(node: ast.AST, marker_fns: set[int]) -> bool:
    fn = astutil.enclosing_function(node)
    if fn is not None and (fn.name in FUNNEL_FUNCTIONS
                           or id(fn) in marker_fns):
        return True
    cls = astutil.enclosing_class(node)
    return cls is not None and cls.name in FUNNEL_CLASSES


def _sync_kind(call: ast.Call) -> str | None:
    """Name of the host-sync construct, or None."""
    name = astutil.call_name(call)
    func = call.func
    if isinstance(func, ast.Name) and name == "float":
        # Only plausible device values: a bare name, attribute, or
        # subscript. float(literal) / float(a * b) / float(fn()) are
        # host arithmetic, not readbacks.
        if len(call.args) == 1 and isinstance(
                call.args[0], (ast.Name, ast.Attribute, ast.Subscript)):
            return "float()"
        return None
    if name == "item" and not call.args and not call.keywords \
            and isinstance(func, ast.Attribute):
        return ".item()"
    if name == "asarray" and astutil.receiver_name(call) in NP_RECEIVERS:
        return "np.asarray()"
    if name == "block_until_ready":
        return "block_until_ready()"
    if name == "device_get":
        return "device_get()"
    return None


class HotPathRule(Rule):
    code = "OBL002"
    name = "host-sync-leak"
    rationale = ("step-loop modules must route host syncs through the "
                 "DeferredLoss/_host_sync funnel — the PR-5 contract")

    def check_module(self, module: ModuleInfo,
                     project: Project) -> Iterator[Finding]:
        if not module.relpath.endswith(HOT_MODULES):
            return
        marker_fns = {
            id(fn) for fns in astutil.functions_of(module.tree).values()
            for fn in fns if _references_marker(fn)
        }
        for call in ast.walk(module.tree):
            if not isinstance(call, ast.Call):
                continue
            kind = _sync_kind(call)
            if kind is None or _in_funnel(call, marker_fns):
                continue
            yield module.finding(
                self, call,
                f"{kind} forces a host sync in a step-loop module outside "
                f"the DeferredLoss/_host_sync funnel; steady-state steps "
                f"must not read device values back")
