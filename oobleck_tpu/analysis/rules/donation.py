"""OBL003 — use-after-donation views.

History: the PR-3 checkpoint-corruption bug. The snapshot path captured
``np.asarray(...)`` views of train state; on the CPU backend asarray is
zero-copy, the train step donates its state buffers
(``donate_argnums``), and by the next step the "checkpoint" was reading
recycled memory — silent corruption, caught only by restore checksums
(``ckpt/snapshot.py`` documents the mandatory-copies rule).

This rule connects the two halves inside one function: if a variable is
passed at a donated position of a callable jitted with
``donate_argnums``, then capturing a view of that variable in the same
function — ``np.asarray(v)``, a slice (``v[...]``), or a bare aliasing
assignment (``w = v``) — is flagged. ``np.array`` / explicit ``.copy()``
are the sanctioned escape hatches (they materialize real copies).
"""

from __future__ import annotations

import ast
from collections.abc import Iterator

from oobleck_tpu.analysis import astutil
from oobleck_tpu.analysis.core import Finding, ModuleInfo, Project, Rule

NP_RECEIVERS = {"np", "numpy"}
JIT_NAMES = {"jit"}


def _donating_defs(tree: ast.AST) -> dict[str, tuple[int, ...] | None]:
    """{bare name: donated positions or None-for-unknown} for every
    assignment of a ``jit(..., donate_argnums=...)`` result — module
    globals, locals, and ``self._x`` attributes alike — plus functions
    decorated with a donating jit."""
    out: dict[str, tuple[int, ...] | None] = {}

    def positions(call: ast.Call) -> tuple[int, ...] | None:
        for kw in call.keywords:
            if kw.arg in ("donate_argnums", "donate_argnames"):
                v = kw.value
                if isinstance(v, ast.Constant) and isinstance(v.value, int):
                    return (v.value,)
                if isinstance(v, (ast.Tuple, ast.List)):
                    got = []
                    for el in v.elts:
                        if isinstance(el, ast.Constant) \
                                and isinstance(el.value, int):
                            got.append(el.value)
                        else:
                            return None
                    return tuple(got)
                return None  # dynamic → every position treated as donated
        return ()

    def is_donating_jit(call: ast.Call) -> bool:
        return astutil.call_name(call) in JIT_NAMES and positions(call) != ()

    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            call = node.value
            if is_donating_jit(call):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        out[tgt.id] = positions(call)
                    elif isinstance(tgt, ast.Attribute):
                        out[tgt.attr] = positions(call)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                if isinstance(dec, ast.Call) and (
                        is_donating_jit(dec)
                        or (astutil.call_name(dec) == "partial" and any(
                            isinstance(a, (ast.Name, ast.Attribute))
                            and astutil.dotted_name(a).endswith("jit")
                            for a in dec.args) and positions(dec) != ())):
                    out[node.name] = positions(dec)
    return out


def _identifier(node: ast.AST) -> str | None:
    """Bare identifier of a Name or self-attribute (self._cache -> _cache)."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    return None


class DonationRule(Rule):
    code = "OBL003"
    name = "use-after-donation"
    rationale = ("no zero-copy views of buffers donated to jit — the "
                 "PR-3 checkpoint-corruption bug")

    def check_module(self, module: ModuleInfo,
                     project: Project) -> Iterator[Finding]:
        donating = _donating_defs(module.tree)
        if not donating:
            return
        for fns in astutil.functions_of(module.tree).values():
            for fn in fns:
                yield from self._check_function(module, fn, donating)

    def _check_function(self, module: ModuleInfo, fn: ast.AST,
                        donating: dict[str, tuple[int, ...] | None],
                        ) -> Iterator[Finding]:
        donated_vars: dict[str, str] = {}  # identifier -> donating callee
        for call in ast.walk(fn):
            if not isinstance(call, ast.Call):
                continue
            callee = astutil.call_name(call)
            if callee not in donating:
                continue
            pos = donating[callee]
            args = call.args
            picked = (args if pos is None
                      else [args[i] for i in pos if i < len(args)])
            for arg in picked:
                ident = _identifier(arg)
                if ident is not None:
                    donated_vars[ident] = callee
        if not donated_vars:
            return

        for node in ast.walk(fn):
            # np.asarray(v) — zero-copy view, unless .copy()'d right away.
            if isinstance(node, ast.Call) \
                    and astutil.call_name(node) == "asarray" \
                    and astutil.receiver_name(node) in NP_RECEIVERS \
                    and node.args:
                ident = _identifier(node.args[0])
                if ident in donated_vars and not self._copied(node):
                    yield module.finding(
                        self, node,
                        f"np.asarray(`{ident}`) captures a zero-copy view "
                        f"of a buffer donated to `{donated_vars[ident]}` "
                        f"(donate_argnums); use np.array / .copy() — the "
                        f"buffer is recycled by the next step")
            # w = v  /  w = v[...] — aliasing capture of a donated buffer.
            elif isinstance(node, ast.Assign):
                src = node.value
                if isinstance(src, ast.Subscript):
                    ident = _identifier(src.value)
                    label = "a slice view"
                elif isinstance(src, (ast.Name, ast.Attribute)):
                    ident = _identifier(src)
                    label = "an alias"
                else:
                    continue
                if ident in donated_vars:
                    yield module.finding(
                        self, node,
                        f"assignment captures {label} of `{ident}`, which "
                        f"is donated to `{donated_vars[ident]}` "
                        f"(donate_argnums); copy before donating")

    @staticmethod
    def _copied(node: ast.AST) -> bool:
        """True for np.asarray(v).copy() — the immediate-copy idiom."""
        p = astutil.parent(node)
        return (isinstance(p, ast.Attribute) and p.attr == "copy"
                and isinstance(astutil.parent(p), ast.Call))
