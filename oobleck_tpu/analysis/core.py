"""Rule framework for oobleck-lint.

A run parses every target file once into a :class:`Project`, hands it to
each registered :class:`Rule`, then filters raw findings through inline
suppressions and the checked-in baseline. Only what survives — NEW
findings — fails the run. Design constraints:

- stdlib only, no imports of the analyzed code (parsing, never running);
- fingerprints are line-number independent (rule | path | scope |
  source-line hash) so unrelated edits above a grandfathered finding
  don't churn the baseline;
- suppressions carry their reason in the comment itself
  (``# oobleck: allow[OBL002] -- eval sweep, off the hot path``), the
  baseline carries one per entry, so every exemption is justified where
  a reviewer will read it.
"""

from __future__ import annotations

import ast
import dataclasses
import hashlib
import json
import re
from collections.abc import Iterable, Iterator
from pathlib import Path

from oobleck_tpu.analysis import astutil

# `# oobleck: allow[OBL001]` or `# oobleck: allow[OBL001,OBL005] -- why`.
_SUPPRESS_RE = re.compile(r"#\s*oobleck:\s*allow\[([A-Z0-9,\s]+)\]")
# A line that is only a suppression comment extends its scope to the
# next source line (for statements too long to annotate inline).
_COMMENT_ONLY_RE = re.compile(r"^\s*#")

SEVERITIES = ("error", "warning")


@dataclasses.dataclass
class Finding:
    rule: str
    path: str  # project-relative, forward slashes
    line: int
    col: int
    message: str
    severity: str = "error"
    scope: str = "<module>"
    snippet: str = ""

    def fingerprint(self) -> str:
        digest = hashlib.sha1(
            self.snippet.strip().encode("utf-8", "replace")).hexdigest()[:12]
        return f"{self.rule}|{self.path}|{self.scope}|{digest}"

    def render(self) -> str:
        return (f"{self.path}:{self.line}:{self.col}: {self.rule} "
                f"[{self.severity}] {self.message} (in {self.scope})")

    def as_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["fingerprint"] = self.fingerprint()
        return d


class ModuleInfo:
    """One parsed source file plus its suppression map."""

    def __init__(self, path: Path, relpath: str, source: str):
        self.path = path
        self.relpath = relpath
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=str(path))
        astutil.attach_parents(self.tree)
        self.suppressions = self._scan_suppressions()

    def _scan_suppressions(self) -> dict[int, set[str]]:
        out: dict[int, set[str]] = {}
        for i, line in enumerate(self.lines, start=1):
            m = _SUPPRESS_RE.search(line)
            if not m:
                continue
            rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
            out.setdefault(i, set()).update(rules)
            if _COMMENT_ONLY_RE.match(line):
                # Standalone comment line: covers the statement below it.
                out.setdefault(i + 1, set()).update(rules)
        return out

    def suppressed(self, rule: str, line: int) -> bool:
        return rule in self.suppressions.get(line, ())

    def line_text(self, line: int) -> str:
        if 1 <= line <= len(self.lines):
            return self.lines[line - 1]
        return ""

    def finding(self, rule: "Rule", node: ast.AST, message: str) -> Finding:
        line = getattr(node, "lineno", 1)
        return Finding(
            rule=rule.code,
            path=self.relpath,
            line=line,
            col=getattr(node, "col_offset", 0),
            message=message,
            severity=rule.severity,
            scope=astutil.scope_name(node),
            snippet=self.line_text(line),
        )


class Project:
    """Every parsed module of one run, plus lookup helpers for the
    cross-file rules (OBL004 reads message.py + agent.py + engine.py;
    OBL005 reads obs/registry.py)."""

    def __init__(self, root: Path, modules: list[ModuleInfo],
                 errors: list[str]):
        self.root = root
        self.modules = modules
        self.errors = errors
        self._by_rel = {m.relpath: m for m in modules}

    def module(self, relpath: str) -> ModuleInfo | None:
        return self._by_rel.get(relpath)

    def modules_matching(self, suffix: str) -> list[ModuleInfo]:
        return [m for m in self.modules if m.relpath.endswith(suffix)]


class Rule:
    """One named invariant. Subclasses override ``check_module`` (runs
    per file) and/or ``check_project`` (runs once, for cross-file
    rules)."""

    code = "OBL000"
    name = "unnamed"
    severity = "error"
    # One line shown by --explain and in the README table.
    rationale = ""

    def check_module(self, module: ModuleInfo,
                     project: Project) -> Iterator[Finding]:
        return iter(())

    def check_project(self, project: Project) -> Iterator[Finding]:
        return iter(())


def all_rules() -> list[Rule]:
    """The registered rule set, in code order."""
    from oobleck_tpu.analysis.rules import RULES

    return [cls() for cls in RULES]


# -------------------------------------------------------------------------
# baseline


def default_baseline_path(root: Path) -> Path:
    return root / "oobleck_tpu" / "analysis" / "baseline.json"


def load_baseline(path: Path) -> dict[str, str]:
    """{fingerprint: reason} — absent/empty file means empty baseline."""
    if not path.is_file():
        return {}
    data = json.loads(path.read_text())
    out: dict[str, str] = {}
    for entry in data.get("findings", []):
        out[entry["fingerprint"]] = entry.get("reason", "")
    return out


def write_baseline(path: Path, findings: Iterable[Finding],
                   reasons: dict[str, str] | None = None) -> None:
    reasons = reasons or {}
    entries = []
    for f in sorted(findings, key=lambda f: (f.path, f.line, f.rule)):
        fp = f.fingerprint()
        entries.append({
            "fingerprint": fp,
            "rule": f.rule,
            "path": f.path,
            "scope": f.scope,
            "reason": reasons.get(fp, "grandfathered at baseline creation"),
        })
    path.write_text(json.dumps(
        {"version": 1, "findings": entries}, indent=2) + "\n")


# -------------------------------------------------------------------------
# runner


DEFAULT_TARGETS = ("oobleck_tpu", "bench.py")
_SKIP_PARTS = {"__pycache__"}


def _collect_files(root: Path, targets: Iterable[str]) -> list[Path]:
    files: list[Path] = []
    for target in targets:
        p = (root / target) if not Path(target).is_absolute() else Path(target)
        if p.is_file() and p.suffix == ".py":
            files.append(p)
        elif p.is_dir():
            files.extend(sorted(
                f for f in p.rglob("*.py")
                if not (_SKIP_PARTS & set(f.parts))
            ))
    return files


@dataclasses.dataclass
class AnalysisResult:
    new: list[Finding]
    suppressed: list[Finding]
    baselined: list[Finding]
    unused_baseline: list[str]  # stale fingerprints (fixed findings)
    parse_errors: list[str]
    rules_run: int
    files_scanned: int

    @property
    def exit_code(self) -> int:
        return 1 if (self.new or self.parse_errors) else 0

    def summary(self) -> dict:
        return {
            "rules": self.rules_run,
            "files": self.files_scanned,
            "findings_new": len(self.new),
            "findings_suppressed": len(self.suppressed),
            "findings_baselined": len(self.baselined),
            "baseline_unused": len(self.unused_baseline),
            "parse_errors": len(self.parse_errors),
        }


def build_project(root: Path,
                  targets: Iterable[str] = DEFAULT_TARGETS) -> Project:
    modules: list[ModuleInfo] = []
    errors: list[str] = []
    for path in _collect_files(root, targets):
        rel = path.relative_to(root).as_posix() \
            if path.is_relative_to(root) else path.as_posix()
        try:
            modules.append(ModuleInfo(path, rel, path.read_text()))
        except (SyntaxError, UnicodeDecodeError, OSError) as e:
            errors.append(f"{rel}: {type(e).__name__}: {e}")
    return Project(root, modules, errors)


def run_analysis(root: Path,
                 targets: Iterable[str] = DEFAULT_TARGETS,
                 rules: list[Rule] | None = None,
                 baseline: dict[str, str] | None = None) -> AnalysisResult:
    """Parse, run every rule, split findings into new / suppressed /
    baselined. ``baseline=None`` loads the checked-in default."""
    project = build_project(root, targets)
    if rules is None:
        rules = all_rules()
    if baseline is None:
        baseline = load_baseline(default_baseline_path(root))

    raw: list[Finding] = []
    for rule in rules:
        for module in project.modules:
            raw.extend(rule.check_module(module, project))
        raw.extend(rule.check_project(project))

    new: list[Finding] = []
    suppressed: list[Finding] = []
    baselined: list[Finding] = []
    seen_fps: set[str] = set()
    for f in sorted(raw, key=lambda f: (f.path, f.line, f.col, f.rule)):
        module = project.module(f.path)
        if module is not None and module.suppressed(f.rule, f.line):
            suppressed.append(f)
        elif f.fingerprint() in baseline:
            seen_fps.add(f.fingerprint())
            baselined.append(f)
        else:
            new.append(f)
    unused = sorted(set(baseline) - seen_fps)
    return AnalysisResult(
        new=new, suppressed=suppressed, baselined=baselined,
        unused_baseline=unused, parse_errors=project.errors,
        rules_run=len(rules), files_scanned=len(project.modules),
    )
