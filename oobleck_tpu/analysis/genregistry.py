"""Generate ``oobleck_tpu/obs/registry.py`` from the source tree.

Usage: ``python -m oobleck_tpu.analysis.genregistry [--check]``

Scans every name-introducing call site (the same collection logic rule
OBL005 lints with — see ``rules/registry_names.py``) and writes the
three frozensets the observability plane treats as its schema:
``METRIC_FAMILIES``, ``FLIGHT_EVENT_KINDS``, ``SPAN_NAMES``. Output is
deterministic (sorted, no timestamps) so the file diffs cleanly and a
``--check`` run can assert freshness in CI.

The generated module is imported lazily by ``utils/metrics.py`` when
``OOBLECK_STRICT_REGISTRY=1``, turning the same schema into a runtime
assertion for debug/test runs.
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

from oobleck_tpu.analysis.core import DEFAULT_TARGETS, build_project
from oobleck_tpu.analysis.rules.registry_names import (
    CollectedNames,
    collect_names,
)

HEADER = '''\
"""Observability name registry — GENERATED, do not edit by hand.

Regenerate with ``make gen-registry`` (or
``python -m oobleck_tpu.analysis.genregistry``) after adding a metric
family, flight-event kind, or span name. Rule OBL005 fails the lint when
a literal name in the tree is missing here; ``OOBLECK_STRICT_REGISTRY=1``
makes ``utils/metrics.py`` enforce membership at runtime.
"""

from __future__ import annotations

'''


def _render_set(name: str, values: set[str]) -> str:
    lines = [f"{name} = frozenset({{"]
    lines.extend(f'    "{v}",' for v in sorted(values))
    lines.append("})")
    return "\n".join(lines)


def render(names: CollectedNames) -> str:
    return HEADER + "\n\n".join([
        _render_set("METRIC_FAMILIES", names.metrics),
        _render_set("FLIGHT_EVENT_KINDS", names.flight_events),
        _render_set("SPAN_NAMES", names.spans),
    ]) + "\n"


def registry_path(root: Path) -> Path:
    return root / "oobleck_tpu" / "obs" / "registry.py"


def generate(root: Path) -> str:
    project = build_project(root, DEFAULT_TARGETS)
    return render(collect_names(project))


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m oobleck_tpu.analysis.genregistry")
    parser.add_argument("--root", type=Path, default=None)
    parser.add_argument("--check", action="store_true",
                        help="exit 1 if the checked-in registry is stale "
                             "instead of rewriting it")
    args = parser.parse_args(argv)

    root = args.root
    if root is None:
        from oobleck_tpu.analysis.__main__ import _find_root
        root = _find_root(Path.cwd())
    root = root.resolve()

    content = generate(root)
    out = registry_path(root)
    if args.check:
        current = out.read_text() if out.is_file() else ""
        if current != content:
            print(f"{out} is stale — run `make gen-registry`")
            return 1
        print(f"{out} is up to date")
        return 0
    out.write_text(content)
    print(f"wrote {out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
