"""Degraded-mode execution plane: zero-reconfiguration failure recovery.

First line of defense when a host dies (ReCycle, arxiv 2405.14009):
classify the failure against the live DP topology (classify),
check the dead replica's microbatches fit the survivors' pipeline
bubbles and project the cost (planner), emit and validate the rerouted
instruction streams (emitter), and apply the reroute to the live engine
with no re-plan and no recompile (apply) — falling back to template
re-instantiation when infeasible. Every outcome is one DegradeDecision
(decision) in the flight recorder and the oobleck_degrade_* metrics
family. The decision seam (classify -> plan -> apply) is what the
future adaptive policy engine (ROADMAP item 2) will own.
"""

from oobleck_tpu.degrade.apply import specs_from_pipelines, try_degrade
from oobleck_tpu.degrade.classify import FailureReport, classify_failure
from oobleck_tpu.degrade.decision import (
    MECH_DISABLED,
    MECH_REINSTANTIATE,
    MECH_REROUTE,
    DegradeDecision,
)
from oobleck_tpu.degrade.emitter import (
    ReroutedSchedule,
    dataflow_edges,
    emit_rerouted,
    validate_reroute,
)
from oobleck_tpu.degrade.planner import (
    PipelineSpec,
    ReroutePlan,
    plan_reroute,
)

__all__ = [
    "DegradeDecision",
    "FailureReport",
    "MECH_DISABLED",
    "MECH_REINSTANTIATE",
    "MECH_REROUTE",
    "PipelineSpec",
    "ReroutePlan",
    "ReroutedSchedule",
    "classify_failure",
    "dataflow_edges",
    "emit_rerouted",
    "plan_reroute",
    "specs_from_pipelines",
    "try_degrade",
    "validate_reroute",
]
