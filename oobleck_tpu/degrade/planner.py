"""Reroute planner: do the dead replica's microbatches fit the bubbles?

Given a feasible FailureReport, the planner decides HOW to reroute: which
survivor absorbs how many of the dead replica's microbatches, and what the
step-time cost is. Both questions run through the same machinery the
scheduler itself uses — replay_schedule() dependency replay over
calibrated per-(stage, chunk, direction) durations — so the planner's
makespan estimate and a test-side replay of the emitted schedule are one
computation, not two models that can drift (ISSUE 7 pins this down with
a replayed-bubble == planner-estimate assertion).

The fit intuition (ReCycle, arxiv 2405.14009): a 1F1B pipeline at M
microbatches idles (S-1)/(M+S-1) of its time; raising M to M+extra fills
that bubble with borrowed forwards before stretching the steady state, so
small reroutes are nearly free. The planner does not use the closed form —
it replays the actual rerouted streams with the pipeline's own measured
op durations, because calibrated fwd/bwd asymmetry moves the break-even
point.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from oobleck_tpu.degrade.classify import FailureReport
from oobleck_tpu.execution.schedule import Op, replay_schedule


@dataclass(frozen=True)
class PipelineSpec:
    """What the planner needs to know about one pipeline: schedule shape
    plus calibrated op durations (pipe.last_op_times — (total_s, count)
    per (stage, chunk, 'f'|'b'|'cf'|'cb') — populated when sync_op_timing
    is on; 'cf'/'cb' are the cross-stage transfer times the same mode
    splits out of compute)."""

    num_stages: int
    num_microbatches: int
    virtual_stages: int = 1
    op_times: dict = field(default_factory=dict)
    # Measured fraction of cross-stage transfer time hidden under compute
    # (bench `overlap` key / oobleck_comm_hidden_fraction gauge). 0.0 keeps
    # the classic fully-serialized projection; 1.0 projects comm as free.
    comm_hidden_fraction: float = 0.0

    def duration_fn(self):
        """instruction -> seconds from calibrated means; falls back to the
        classic fwd=1/bwd=2 cost model for uncalibrated (stage, chunk)
        units, scaled to the calibrated mean when any calibration exists
        so mixed dictionaries stay on one time base. When the calibration
        carries comm entries ('cf'/'cb'), each compute op is charged its
        EFFECTIVE comm — max(0, comm - hidden_fraction * compute) — so an
        overlap-enabled deployment's degraded projection doesn't double-
        count latency the schedule already hides."""
        from oobleck_tpu.parallel.overlap import effective_comm

        means: dict[tuple[int, int, str], float] = {}
        for (stage, chunk, kind), (total, count) in self.op_times.items():
            if count > 0:
                means[(stage, chunk, kind)] = total / count
        if means:
            fallback_f = sum(v for (_, _, k), v in means.items()
                             if k == "f") or None
            n_f = sum(1 for (_, _, k) in means if k == "f")
            base_f = (fallback_f / n_f) if fallback_f else 1.0
        else:
            base_f = 1.0

        def dur(inst):
            kind = "b" if inst.op is Op.BACKWARD else "f"
            mean = means.get((inst.stage, inst.chunk, kind))
            base = mean if mean is not None else (
                base_f * (2.0 if kind == "b" else 1.0))
            comm = means.get((inst.stage, inst.chunk, "c" + kind))
            if comm is not None:
                base += effective_comm(comm, base,
                                       self.comm_hidden_fraction)
            return base

        return dur


@dataclass
class ReroutePlan:
    """The planner's answer: per-survivor absorbed microbatches plus the
    projected cost of running degraded.

    `new_microbatches` is keyed by pipeline list index (same index space
    as FailureReport.dead/surviving). `makespan_before` includes the dead
    pipelines — pipelines run concurrently, so the pre-failure step time
    is the max over ALL replicas and the global batch is preserved either
    way; throughput retention is therefore makespan_before /
    makespan_after, and slowdown its inverse.
    """

    report: FailureReport
    new_microbatches: dict[int, int] = field(default_factory=dict)
    extra_microbatches: int = 0
    makespan_before: float = 0.0
    makespan_after: float = 0.0
    reason: str = ""

    @property
    def feasible(self) -> bool:
        return not self.reason

    @property
    def slowdown(self) -> float:
        if self.makespan_before <= 0:
            return float("inf")
        return self.makespan_after / self.makespan_before

    @property
    def throughput_retention(self) -> float:
        s = self.slowdown
        return 0.0 if s in (0.0, float("inf")) else min(1.0, 1.0 / s)

    def as_record(self) -> dict:
        rec = self.report.as_record()
        rec.update({
            "new_microbatches": {str(k): v
                                 for k, v in sorted(
                                     self.new_microbatches.items())},
            "extra_microbatches": self.extra_microbatches,
            "makespan_before_s": self.makespan_before,
            "makespan_after_s": self.makespan_after,
            "projected_slowdown": self.slowdown
            if self.makespan_before > 0 else None,
            "projected_retention": self.throughput_retention,
        })
        if self.reason:
            rec["reason"] = self.reason
        return rec


def plan_reroute(report: FailureReport, specs: list[PipelineSpec],
                 max_slowdown: float = 4.0) -> ReroutePlan:
    """Distribute dead replicas' microbatches over survivors and project
    the degraded step time.

    specs is indexed like the engine's pipeline list (the same index
    space as report.dead/report.surviving). Infeasibility reasons beyond
    the classifier's: "indivisible_extra" (an interleaved survivor can
    only grow in multiples of its S, and the remainder cannot be placed)
    and "exceeds_max_slowdown" (the work fits but the projected step-time
    blowup crosses max_slowdown — re-instantiation with a rebalanced plan
    is the better deal).
    """
    plan = ReroutePlan(report=report)
    if not report.feasible:
        plan.reason = report.reason
        return plan

    extra = sum(specs[i].num_microbatches for i in report.dead)
    plan.extra_microbatches = extra
    assigned = {i: 0 for i in report.surviving}
    # Interleaved survivors grow in quanta of S (validate_interleaving);
    # canonical survivors in quanta of 1.
    quantum = {
        i: specs[i].num_stages if specs[i].virtual_stages > 1 else 1
        for i in report.surviving
    }
    remaining = extra
    while remaining > 0:
        candidates = [i for i in report.surviving
                      if quantum[i] <= remaining]
        if not candidates:
            plan.reason = "indivisible_extra"
            return plan
        # Least-loaded first keeps the post-reroute makespan (max over
        # survivors) minimal for homogeneous replicas.
        i = min(candidates,
                key=lambda j: (specs[j].num_microbatches + assigned[j], j))
        assigned[i] += quantum[i]
        remaining -= quantum[i]

    plan.new_microbatches = {
        i: specs[i].num_microbatches + assigned[i]
        for i in report.surviving
    }

    # One replay per distinct schedule shape, not per replica: homogeneous
    # DP fleets (the sim runs this planner at 1024 replicas) share one
    # op_times dict, and replay_schedule is pure in (S, M, v, durations),
    # so the memo changes nothing but the wall clock. Scoped to this call:
    # no cross-call staleness when calibration moves between incidents.
    memo: dict = {}

    def makespan(spec: PipelineSpec, microbatches: int) -> float:
        key = (spec.num_stages, microbatches, spec.virtual_stages,
               id(spec.op_times), spec.comm_hidden_fraction)
        if key not in memo:
            memo[key] = replay_schedule(spec.num_stages, microbatches,
                                        spec.virtual_stages,
                                        spec.duration_fn())[0]
        return memo[key]

    # Pre-failure step time: max over ALL replicas (they run concurrently).
    plan.makespan_before = max(
        makespan(s, s.num_microbatches) for s in specs)
    plan.makespan_after = max(
        makespan(specs[i], plan.new_microbatches[i])
        for i in report.surviving)
    if plan.makespan_before > 0 and plan.slowdown > max_slowdown:
        plan.reason = "exceeds_max_slowdown"
    return plan
