"""Degraded-mode fast path: mutate the live engine onto a rerouted plan.

try_degrade() is the one entry point engine.reconfigure() calls before
its template-re-instantiation fallback. On success the engine keeps its
EXACT topology — same pipelines, same stage executables, same compiled
programs — and only four things change: the dead replica's pipelines are
dropped, survivors adopt larger microbatch counts (bubble-absorbed, see
planner.py), dataloaders are rebuilt from the consumed position for the
new per-pipeline bucket slices, and the DP engine re-derives its owner
map over the survivors. No re-plan, no recompile: recovery is bounded by
~one step of bookkeeping (ReCycle, arxiv 2405.14009).

Data/grad exactness through the reroute: the sampler bucket size is
microbatch_size * sum(num_microbatches) and the reroute preserves that
sum, so the surviving pipelines collectively read the SAME shuffled
index bucket per iteration the full fleet would have — only the slice
boundaries move. Gradients stay exact because every stage pre-scales by
1/total_num_microbatches (unchanged) and the DP allreduce sums over
whichever owners remain, so the summed update is identical to the
no-failure run given identical data order (the parity test pins this).
"""

from __future__ import annotations

import logging
import time

from oobleck_tpu.degrade.classify import classify_failure
from oobleck_tpu.degrade.decision import (
    MECH_REINSTANTIATE,
    MECH_REROUTE,
    DegradeDecision,
)
from oobleck_tpu.degrade.emitter import emit_rerouted, validate_reroute
from oobleck_tpu.degrade.planner import PipelineSpec, plan_reroute
from oobleck_tpu.obs import spans
from oobleck_tpu.utils import metrics, recovery

logger = logging.getLogger(__name__)


def specs_from_pipelines(pipelines) -> list[PipelineSpec]:
    """Planner view of the engine's live pipeline list (calibrated op
    durations included when the interpreter has recorded any)."""
    return [
        PipelineSpec(
            num_stages=p.num_stages,
            num_microbatches=p.num_microbatches,
            virtual_stages=p.virtual_stages,
            op_times=dict(p.last_op_times),
        )
        for p in pipelines
    ]


def try_degrade(engine, lost_ip: str, lost_host: int,
                t0: float) -> DegradeDecision:
    """Classify, plan, and — when feasible — apply the reroute in place.

    Returns the DegradeDecision. mechanism == MECH_REROUTE means the
    engine was mutated and recovery is COMPLETE (decision already
    recorded, with measured latency). mechanism == MECH_REINSTANTIATE
    means nothing was mutated and the caller must run the fallback; it
    owns stamping measured_recovery_s and calling decision.record() once
    the fallback finishes, so one decision covers the whole failure.
    """
    # Spans parent onto the incident's ambient trace (engine.reconfigure
    # pins it), so the postmortem timeline shows where degrade time went.
    with spans.span("degrade.classify", lost_ip=lost_ip):
        report = classify_failure(
            lost_host, [p.ranks for p in engine.pipelines],
            engine.chips_per_host)
    specs = specs_from_pipelines(engine.pipelines)
    with spans.span("degrade.plan", survivors=len(report.surviving)):
        plan = plan_reroute(
            report, specs,
            max_slowdown=engine.args.execution.degrade_max_slowdown)
    decision = DegradeDecision(
        lost_ip=lost_ip,
        lost_host=lost_host,
        mechanism=MECH_REROUTE if plan.feasible else MECH_REINSTANTIATE,
        reason=plan.reason,
        plan_record=plan.as_record(),
        estimated_slowdown=(plan.slowdown
                            if plan.makespan_before > 0 else None),
        estimated_retention=plan.throughput_retention,
        extra_microbatches=plan.extra_microbatches,
    )
    if not plan.feasible:
        return decision

    # Structural safety net before touching engine state: emit + validate
    # the rerouted streams for every survivor. A violation here means a
    # scheduler regression, not a planning outcome — log it, then take the
    # always-correct fallback.
    try:
        for i in report.surviving:
            validate_reroute(emit_rerouted(
                specs[i].num_stages, specs[i].num_microbatches,
                plan.new_microbatches[i] - specs[i].num_microbatches,
                specs[i].virtual_stages))
    except (AssertionError, ValueError) as e:
        logger.error("rerouted schedule failed validation, falling back "
                     "to re-instantiation: %s", e)
        decision.mechanism = MECH_REINSTANTIATE
        decision.reason = "reroute_apply_failed"
        return decision

    with spans.span("degrade.apply",
                    extra_microbatches=plan.extra_microbatches):
        _apply_reroute(engine, lost_ip, report, plan)

    elapsed = time.perf_counter() - t0
    engine.recovery_times.append(elapsed)
    engine._recovering = True
    engine._recovered_at = time.monotonic()
    engine._m_reconfigs.inc(path="degrade")
    engine._set_template_gauge()
    recovery.observe_latency(elapsed, stage="degrade")
    decision.measured_recovery_s = elapsed
    decision.record()
    metrics.flight_recorder().record(
        "engine_degraded", lost_ip=lost_ip, path="degrade",
        elapsed_s=round(elapsed, 3), step=engine.step,
        extra_microbatches=plan.extra_microbatches,
        projected_retention=plan.throughput_retention)
    logger.warning(
        "degraded after losing %s in %.3fs: rerouted %d microbatches onto "
        "%d survivor(s), projected retention %.2f",
        lost_ip, elapsed, plan.extra_microbatches, len(report.surviving),
        plan.throughput_retention)
    if engine._precompiler is not None:
        # The NEXT failure predicts from the degraded topology.
        engine.start_recovery_precompile()
    return decision


def _apply_reroute(engine, lost_ip: str, report, plan) -> None:
    """The in-place mutation. Same bookkeeping order as
    engine._materialize_plan, minus everything that makes
    re-instantiation slow: no weight collection/re-placement, no stage
    rebuild, no optimizer-state re-placement — survivors keep their
    arrays and compiled programs untouched."""
    from oobleck_tpu.execution.engine import (
        DataParallelEngine,
        MultiHostDataParallelEngine,
    )
    from oobleck_tpu.execution.dataloader import (
        DeviceStager,
        OobleckDataLoader,
        OobleckSampler,
        PrefetchingLoader,
    )
    from oobleck_tpu.planning.instantiator import HeterogeneousPlan

    multihost = bool(getattr(engine, "multihost", False)
                     and engine.comm is not None)

    # Data position carries over — taken from the CONSUMED position, so a
    # prefetched-but-unconsumed iteration is replayed, not skipped.
    it_done = engine.dataloaders[0].num_iterations_done
    epoch = engine.dataloaders[0].epoch
    for dl in engine.dataloaders:
        if hasattr(dl, "close"):
            dl.close()

    survivors = [engine.pipelines[i] for i in report.surviving]
    for i in report.dead:
        engine.opt_states.pop(engine.pipelines[i].pipeline_id, None)
    engine.pipelines = survivors
    new_mb_list = [plan.new_microbatches[i] for i in report.surviving]
    for pipe, new_mb in zip(survivors, new_mb_list):
        pipe.adopt_microbatches(new_mb)

    # Every sampler changes (the bucket slice boundaries moved), so every
    # loader is rebuilt — positional pipeline_index over the survivor
    # list, same bucket total, same (iterations_done, epoch).
    train_samples = len(engine.dataset) - engine._eval_reserve()
    engine.dataloaders = []
    for pos, pipe in enumerate(survivors):
        sampler = OobleckSampler(
            num_samples=train_samples,
            microbatch_size=engine.args.job.microbatch_size,
            pipeline_index=pos,
            num_microbatches=new_mb_list,
            num_iterations_done=it_done,
            epoch=epoch,
        )
        loader = OobleckDataLoader(engine.dataset, sampler)
        # Multihost: non-participating pipelines only track position
        # (advance()), exactly as in engine._materialize_plan.
        if not multihost or pipe.participates_locally:
            if engine._prefetch_enabled():
                loader = DeviceStager(
                    loader,
                    lambda b, _p=pipe: _p._place_batch(
                        _p._as_batch_dict(b))[0],
                )
            else:
                loader = PrefetchingLoader(loader)
        engine.dataloaders.append(loader)

    if multihost:
        # Zero-respawn multihost reroute: the world object survives, but
        # the drained victim process leaves the collectives — shrink the
        # loss-psum membership (and the engine's consensus set) to the
        # survivors so nothing ever waits on the corpse.
        lost_proc = engine._host_index[lost_ip]
        live = [p for p in (engine._live_procs
                            if engine._live_procs is not None
                            else range(engine.comm.process_count))
                if p != lost_proc]
        engine._live_procs = live
        engine.dp_engine = MultiHostDataParallelEngine(
            survivors, engine.model, engine.comm, participants=live)
    else:
        engine.dp_engine = DataParallelEngine(survivors)
    engine.host_ips.remove(lost_ip)
    if engine.plan is not None:
        # Rebuild the plan descriptor so /status and the precompile
        # predictor describe the degraded layout honestly.
        engine.plan = HeterogeneousPlan(
            instances=[p.template for p in survivors],
            num_microbatches=list(new_mb_list),
            allreduce_across_hosts=engine.plan.allreduce_across_hosts,
        )
