"""Failure classifier: lost host vs live pipeline/DP topology.

The degraded-mode plane treats each PipelineInstance as one data-parallel
replica (its stages' layer ranges partition the whole model), so "does a
surviving DP peer stage exist?" reduces to: does at least one pipeline
survive with NO stage on the lost host? Every stage of a surviving
pipeline is a DP peer of the corresponding dead stage — same layer
ranges, same weights (modulo bounded replica drift) — which is what lets
the reroute planner hand the dead replica's microbatches to the
survivors' stages without touching topology (ReCycle, arxiv 2405.14009,
applied at the granularity our DP actually exists at).

The classifier is pure: it never reads engine state beyond what is passed
in, so the precompile predictor can run it ahead of failure on predicted
topologies and tests can table-drive it.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from oobleck_tpu.execution.reconfigure import split_pipelines_by_host


@dataclass
class FailureReport:
    """Which pipelines a lost host kills, and whether reroute is possible.

    `stranded_hosts` are LIVE hosts whose only pipeline died with the lost
    host (a dead pipeline spanning the victim plus healthy hosts): reroute
    would leave their chips idle, so the classifier reports them and the
    planner treats any stranding as infeasible — template re-instantiation
    re-folds those hosts into the new plan instead of wasting them.
    """

    lost_host: int
    dead: list[int] = field(default_factory=list)        # pipeline list indices
    surviving: list[int] = field(default_factory=list)   # pipeline list indices
    stranded_hosts: list[int] = field(default_factory=list)
    reason: str = ""

    @property
    def feasible(self) -> bool:
        return not self.reason

    def as_record(self) -> dict:
        """Flight-recorder-safe payload (plain JSON types only)."""
        return {
            "lost_host": self.lost_host,
            "dead_pipelines": list(self.dead),
            "surviving_pipelines": list(self.surviving),
            "stranded_hosts": list(self.stranded_hosts),
            "reason": self.reason or "peer_available",
        }


def classify_failure(lost_host: int, pipeline_ranks: list[list[int]],
                     chips_per_host: int) -> FailureReport:
    """Classify losing `lost_host` against the live pipeline set.

    pipeline_ranks[i] is pipeline i's global chip ranks (rank encodes the
    ORIGINAL host index: host = rank // chips_per_host — the engine's
    immutable mapping, never an index into the shrinking host_ips list).
    """
    dead, surviving = split_pipelines_by_host(
        pipeline_ranks, lost_host, chips_per_host)
    report = FailureReport(lost_host=lost_host, dead=dead, surviving=surviving)
    if not dead:
        report.reason = "lost_host_runs_no_pipeline"
        return report
    if not surviving:
        report.reason = "no_surviving_dp_peer"
        return report
    # Live hosts stranded by whole-replica reroute: every host of a dead
    # pipeline other than the victim itself.
    stranded = sorted({
        r // chips_per_host
        for i in dead
        for r in pipeline_ranks[i]
        if r // chips_per_host != lost_host
    })
    report.stranded_hosts = stranded
    if stranded:
        report.reason = "reroute_would_strand_hosts"
    return report
