"""Degraded-mode microbench: reroute vs re-instantiation on the CPU rig.

Two identical 2-stage, 2-replica engines (4 virtual CPU devices, one
single-host pipeline per host) lose the same host at the same step; the
recovery-to-next-step latency (failure injection until the NEXT train
step completes — the paper's recovery metric: how long until the job is
learning again) is measured for three mechanisms:

  * reroute — the degrade plane's fast path: the survivor absorbs the
    dead replica's microbatches on the same topology. No re-plan, no
    state movement, no recompile; the dominant cost is the next train
    step itself.
  * reinstantiate_respawn — the production template-re-instantiation
    path. On a real multi-host deployment a lost peer breaks the shared
    jax.distributed world, so the agent RESPAWNS the worker over the
    survivors (engine.reconfigure documents this; the degrade verb
    exists precisely so agents can skip it). Measured honestly as a
    fresh process that builds the survivor-topology engine and runs one
    step: interpreter + jax import, engine build, cold XLA compile,
    first step — each broken out in the output.
  * reinstantiate_inplace — the single-controller in-place replan
    (degrade disabled): re-plan + full parameter/optimizer readback and
    re-placement + pipeline rebuild. Reported transparently even though
    it is the fallback's BEST case — sharing the failed engine's
    process, its executables can hit a warm compile cache that a
    respawned worker never sees.

Also reported (reroute only): steady-state throughput retention and
survivor slowdown, measured next to the planner's dependency-replay
projection, so the simulate_bubble-calibrated estimate is accountable
to a measurement.

Run as `python -m oobleck_tpu.degrade.bench` under JAX_PLATFORMS=cpu
with XLA_FLAGS=--xla_force_host_platform_device_count=4 (bench.py and
`make degrade-bench` set this up). Prints ONE JSON line on stdout.
"""

from __future__ import annotations

import json
import os
import sys
import time

RESPAWN_TIMEOUT_S = 300

_MODEL_ARGS = {"hidden_size": 128, "num_layers": 8,
               "max_position_embeddings": 64}


def _make_engine(degrade_enabled: bool, hosts: list[str] | None = None):
    import jax

    from oobleck_tpu.config import (
        DistributedArguments,
        JobArguments,
        ModelArguments,
        OobleckArguments,
    )
    from oobleck_tpu.execution.engine import OobleckEngine

    hosts = hosts or ["10.0.0.0", "10.0.0.1"]
    args = OobleckArguments(
        dist=DistributedArguments(node_ips=hosts),
        job=JobArguments(
            microbatch_size=1,
            global_microbatch_size=8,
            steps=64,
            learning_rate=1e-3,
            warmup_steps=2,
        ),
        # Shaped compile-heavy / step-light (deep, narrow, short
        # sequences) so the respawn path's cold XLA compile is visible
        # against the step time — the compile is the cost the reroute
        # path avoids by keeping the live topology.
        model=ModelArguments(
            model_name="gpt2-tiny", dataset_path="synthetic",
            model_tag="degrade-bench",  # own profile cache: non-default args
            model_args=dict(_MODEL_ARGS),
        ),
    )
    args.execution.degrade_enabled = degrade_enabled
    args.execution.precompile_recovery_depth = 0  # mechanism cost, not warmth
    args.execution.eval_fraction = 0.0
    engine = OobleckEngine(args, devices=jax.devices()[:2 * len(hosts)])
    engine.initialize_distributed()
    engine.instantiate_pipelines(args.job.global_num_microbatch)
    return engine


def _steps(engine, n: int) -> float:
    """Mean wall-clock seconds per step over n steps."""
    t0 = time.perf_counter()
    for _ in range(n):
        engine._train_step()
    return (time.perf_counter() - t0) / n


def _recover_and_step(engine, lost_ip: str) -> float:
    """Failure-to-next-step latency: reconfigure (whichever path the
    engine takes) + the first post-recovery train step."""
    t0 = time.perf_counter()
    engine.reconfigure(lost_ip)
    engine._train_step()
    return time.perf_counter() - t0


def _respawn_arm() -> dict:
    """Time the production fallback: a fresh worker process built over
    the survivor topology, through its first completed train step. The
    parent's wall-clock from spawn to exit is the recovery latency; the
    child reports its internal phase split (see `--respawn`)."""
    import subprocess

    t0 = time.perf_counter()
    proc = subprocess.run(
        [sys.executable, "-m", "oobleck_tpu.degrade.bench", "--respawn"],
        capture_output=True, text=True, timeout=RESPAWN_TIMEOUT_S,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    total = time.perf_counter() - t0
    if proc.returncode != 0:
        return {"error": f"respawn worker exited {proc.returncode}",
                "stderr_tail": proc.stderr[-500:]}
    child = json.loads(proc.stdout.strip().splitlines()[-1])
    out = {"recovery_to_next_step_s": round(total, 3)}
    out["spawn_and_import_s"] = round(
        total - child["engine_build_s"] - child["first_step_s"], 3)
    out.update(child)
    return out


def _respawn_main() -> None:
    """Child side of the respawn arm: build the post-failure engine
    (survivor host only — checkpoint-free, as live mirrors make the
    production restart) and run ONE step. First step includes the cold
    compile a respawned worker always pays."""
    t0 = time.perf_counter()
    engine = _make_engine(degrade_enabled=False, hosts=["10.0.0.0"])
    t1 = time.perf_counter()
    engine._train_step()
    t2 = time.perf_counter()
    print(json.dumps({"engine_build_s": round(t1 - t0, 3),
                      "first_step_s": round(t2 - t1, 3)}))


def measure(warmup_steps: int = 2, measure_steps: int = 3) -> dict:
    out: dict = {
        "rig": "2 hosts x (2-stage pipeline on 2 virtual CPU chips), "
               "DP replicas, gpt2-tiny h128/L8/seq64",
        # The single-controller rig dispatches DP replicas sequentially, so
        # pre-failure wall-clock already includes both replicas' work and
        # measured retention can reach ~1.0; the projected figure models
        # replicas running concurrently (the real-cluster view). The
        # apples-to-apples check of the simulate_bubble fit is
        # survivor_slowdown: measured vs replay-projected cost of the
        # surviving pipeline absorbing the borrowed microbatches.
        "retention_note": "measured=wall-clock on serialized-replica rig; "
                          "projected=concurrent-replica model",
    }

    # -- reroute path -------------------------------------------------- #
    eng = _make_engine(degrade_enabled=True)
    assert len(eng.pipelines) == 2, [p.ranks for p in eng.pipelines]
    _steps(eng, warmup_steps)
    pre_step_s = _steps(eng, measure_steps)
    reroute_s = _recover_and_step(eng, "10.0.0.1")
    assert len(eng.pipelines) == 1 and eng.pipelines[0].num_microbatches == 8
    post_step_s = _steps(eng, measure_steps)
    from oobleck_tpu.utils import metrics

    retention_projected = metrics.registry().gauge(
        "oobleck_degrade_throughput_retention", "").value()
    out["reroute"] = {
        "recovery_to_next_step_s": round(reroute_s, 3),
        "reconfigure_s": round(eng.recovery_times[-1], 3),
        "pre_failure_step_s": round(pre_step_s, 3),
        "post_reroute_step_s": round(post_step_s, 3),
        "throughput_retention_measured": round(pre_step_s / post_step_s, 3)
        if post_step_s > 0 else None,
        "throughput_retention_projected": round(retention_projected, 3),
        # Survivor slowdown: the surviving pipeline's step cost after
        # absorbing the dead replica's microbatches vs its own pre-failure
        # share (half the serialized two-replica step on this homogeneous
        # rig), against the planner's replay projection (1/retention).
        "survivor_slowdown_measured": round(post_step_s / (pre_step_s / 2), 3)
        if pre_step_s > 0 else None,
        "survivor_slowdown_projected": round(1.0 / retention_projected, 3)
        if retention_projected > 0 else None,
        "extra_microbatches": int(metrics.registry().gauge(
            "oobleck_degrade_extra_microbatches", "").value()),
    }

    # -- re-instantiation: production respawn path ----------------------- #
    out["reinstantiate_respawn"] = _respawn_arm()

    # -- re-instantiation: single-controller in-place replan ------------- #
    eng2 = _make_engine(degrade_enabled=False)
    _steps(eng2, warmup_steps)
    _steps(eng2, measure_steps)  # same step history as the reroute engine
    reinst_s = _recover_and_step(eng2, "10.0.0.1")
    out["reinstantiate_inplace"] = {
        "recovery_to_next_step_s": round(reinst_s, 3),
        "reconfigure_s": round(eng2.recovery_times[-1], 3),
        "note": "best case: shares the failed engine's process, so the "
                "replanned layout can hit a warm compile cache",
    }

    respawn_s = out["reinstantiate_respawn"].get("recovery_to_next_step_s")
    out["reroute_speedup"] = (round(respawn_s / reroute_s, 2)
                              if respawn_s and reroute_s > 0 else None)
    out["reroute_speedup_vs_inplace"] = (round(reinst_s / reroute_s, 2)
                                         if reroute_s > 0 else None)
    out["reroute_at_least_5x_faster"] = bool(
        respawn_s is not None and respawn_s >= 5 * reroute_s)
    return out


def main() -> None:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    if "--respawn" in sys.argv:
        _respawn_main()
        return
    print(json.dumps(measure()))


if __name__ == "__main__":
    main()
