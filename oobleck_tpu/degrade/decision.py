"""DegradeDecision: one auditable record per failure-handling choice.

Every time the engine handles a lost host it produces exactly one
DegradeDecision — whether it rerouted, fell back to template
re-instantiation, or was configured off — carrying the classifier
verdict, the planner's projected cost, and (once known) the measured
recovery latency. record() writes it to the flight recorder and the
oobleck_degrade_* metrics family in one call so the two views can never
disagree about what happened.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field

from oobleck_tpu.utils import metrics


MECH_REROUTE = "reroute"
MECH_REINSTANTIATE = "reinstantiate"
MECH_DISABLED = "disabled"


@dataclass
class DegradeDecision:
    """What the degraded-mode plane decided for one failure, and why.

    `mechanism` is one of MECH_*; `reason` is "" for a successful reroute
    and otherwise names why the fast path was not taken (classifier or
    planner reason strings, or "degrade_disabled"/"reroute_apply_failed").
    Estimated fields come from the ReroutePlan; measured fields are filled
    in by whoever applied the mechanism.
    """

    lost_ip: str
    lost_host: int
    mechanism: str
    reason: str = ""
    plan_record: dict = field(default_factory=dict)
    estimated_slowdown: float | None = None
    estimated_retention: float | None = None
    extra_microbatches: int = 0
    measured_recovery_s: float | None = None
    decided_at: float = field(default_factory=time.time)

    def as_record(self) -> dict:
        rec = {
            "lost_ip": self.lost_ip,
            "lost_host": self.lost_host,
            "mechanism": self.mechanism,
            "reason": self.reason or "ok",
            "estimated_slowdown": self.estimated_slowdown,
            "estimated_retention": self.estimated_retention,
            "extra_microbatches": self.extra_microbatches,
            "measured_recovery_s": self.measured_recovery_s,
            "decided_at": self.decided_at,
        }
        if self.plan_record:
            rec["plan"] = self.plan_record
        return rec

    def record(self) -> None:
        """Flight-record the decision and bump the oobleck_degrade_*
        family. Safe to call from the engine thread mid-recovery."""
        metrics.flight_recorder().record("degrade_decision",
                                         **self.as_record())
        reg = metrics.registry()
        reg.counter(
            "oobleck_degrade_decisions_total",
            "Degraded-mode decisions by mechanism and reason",
        ).inc(mechanism=self.mechanism, reason=self.reason or "ok")
        if self.extra_microbatches:
            reg.gauge(
                "oobleck_degrade_extra_microbatches",
                "Microbatches rerouted onto survivors by the last degrade",
            ).set(self.extra_microbatches)
        if self.estimated_retention is not None:
            reg.gauge(
                "oobleck_degrade_throughput_retention",
                "Planner-projected throughput retention of the last reroute",
            ).set(self.estimated_retention)
        if self.measured_recovery_s is not None:
            reg.histogram(
                "oobleck_degrade_recovery_seconds",
                "Measured failure-to-resume latency by mechanism",
            ).observe(self.measured_recovery_s, mechanism=self.mechanism)
