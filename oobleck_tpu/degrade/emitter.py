"""Rerouted-schedule emitter: survivor streams carrying borrowed work.

A rerouted pipeline runs the SAME schedule family it already runs —
canonical 1F1B or interleaved 1F1B from execution/schedule.py — at a
larger microbatch count: its own base microbatches keep ids
[0, base_M) and the dead replica's borrowed microbatches take
[base_M, base_M + extra). Emitting through stage_instructions (rather
than splicing borrowed units into a frozen base stream) is what makes
send/recv matching and fwd-before-bwd correct BY CONSTRUCTION: the
borrowed units ride the exact dependency structure the interpreter
already honors, and the extra forwards land in the bubble slots the
1F1B steady state leaves open. validate_reroute() pins the invariants
down anyway — the tests drive it over every (S<=4, M<=8, v<=2)
drop-one-peer config so a schedule refactor cannot silently break the
degraded path.

Gradient accumulation across borrowed microbatches needs no emitter
support: the interpreter sums grads over whatever microbatch ids flow
through a stage, each pre-scaled by 1/total_num_microbatches — and the
global total is unchanged by rerouting (the borrowed microbatches exist
either way; only the pipeline running them changed).
"""

from __future__ import annotations

from dataclasses import dataclass

from oobleck_tpu.execution.schedule import (
    Instruction,
    Op,
    all_instructions,
    send_activation_dest,
    send_grad_dest,
    validate_interleaving,
)


@dataclass(frozen=True)
class ReroutedSchedule:
    """Per-stage instruction streams for one survivor absorbing `extra`
    borrowed microbatches on top of its `base_microbatches`."""

    num_stages: int
    base_microbatches: int
    extra_microbatches: int
    virtual_stages: int
    streams: tuple[tuple[Instruction, ...], ...]

    @property
    def num_microbatches(self) -> int:
        return self.base_microbatches + self.extra_microbatches

    def borrowed_units(self) -> list[Instruction]:
        """Every (chunk, microbatch) compute unit run on behalf of the dead
        replica, in stream order."""
        return [
            ins for stream in self.streams for ins in stream
            if ins.op in (Op.FORWARD, Op.BACKWARD)
            and ins.microbatch >= self.base_microbatches
        ]


def emit_rerouted(num_stages: int, base_microbatches: int,
                  extra_microbatches: int,
                  virtual_stages: int = 1) -> ReroutedSchedule:
    """Survivor streams at base+extra microbatches; raises ValueError when
    the rerouted count cannot run this survivor's schedule (interleaved
    survivors need (base+extra) % S == 0 — changing v instead would change
    chunk layouts and force a recompile, which the degraded path forbids)."""
    M = base_microbatches + extra_microbatches
    validate_interleaving(num_stages, M, virtual_stages)
    streams = tuple(
        tuple(stream)
        for stream in all_instructions(num_stages, M, virtual_stages)
    )
    return ReroutedSchedule(
        num_stages=num_stages,
        base_microbatches=base_microbatches,
        extra_microbatches=extra_microbatches,
        virtual_stages=virtual_stages,
        streams=streams,
    )


def dataflow_edges(streams) -> set[tuple[int, int]]:
    """The (src virtual stage, dst virtual stage) activation edges a stream
    set exercises — the pipeline's dataflow graph, microbatches erased."""
    edges: set[tuple[int, int]] = set()
    for stream in streams:
        for ins in stream:
            if ins.op is Op.SEND_ACTIVATION:
                S = len(streams)
                ds, dc = send_activation_dest(ins.stage, ins.chunk, S)
                edges.add((ins.chunk * S + ins.stage, dc * S + ds))
    return edges


def validate_reroute(sched: ReroutedSchedule) -> None:
    """Assert the rerouted streams' structural invariants; raises
    AssertionError with the offending unit on any violation.

    1. fwd-before-bwd per (virtual stage, microbatch) unit;
    2. send/recv matching: every RECV_ACTIVATION/RECV_GRAD has exactly one
       matching SEND on the producing stage, and vice versa;
    3. unchanged survivor dataflow: the virtual-stage edge set equals the
       base schedule's (borrowed microbatches add traffic on existing
       edges, never new edges), and every microbatch — base and borrowed —
       traverses all S*v virtual stages in order;
    4. completeness: every microbatch gets exactly one FORWARD and one
       BACKWARD per virtual stage.
    """
    S, v = sched.num_stages, sched.virtual_stages
    M = sched.num_microbatches
    last_vs = S * v - 1

    fwd_seen: dict[tuple[int, int], int] = {}
    bwd_seen: dict[tuple[int, int], int] = {}
    sends_a: dict[tuple[int, int, int], int] = {}
    recvs_a: dict[tuple[int, int, int], int] = {}
    sends_g: dict[tuple[int, int, int], int] = {}
    recvs_g: dict[tuple[int, int, int], int] = {}

    for stream in sched.streams:
        pos = {id(ins): k for k, ins in enumerate(stream)}
        for k, ins in enumerate(stream):
            vs = ins.chunk * S + ins.stage
            unit = (vs, ins.microbatch)
            if ins.op is Op.FORWARD:
                fwd_seen[unit] = fwd_seen.get(unit, 0) + 1
            elif ins.op is Op.BACKWARD:
                bwd_seen[unit] = bwd_seen.get(unit, 0) + 1
                # (1) the same physical stage must have run this unit's
                # forward EARLIER in its own stream.
                fwd_at = [j for j, other in enumerate(stream)
                          if other.op is Op.FORWARD
                          and other.microbatch == ins.microbatch
                          and other.chunk == ins.chunk]
                assert fwd_at and fwd_at[0] < k, (
                    f"backward before forward for unit {unit}")
            elif ins.op is Op.SEND_ACTIVATION:
                ds, dc = send_activation_dest(ins.stage, ins.chunk, S)
                key = (dc * S + ds, ins.microbatch, 0)
                sends_a[key] = sends_a.get(key, 0) + 1
            elif ins.op is Op.RECV_ACTIVATION:
                key = (vs, ins.microbatch, 0)
                recvs_a[key] = recvs_a.get(key, 0) + 1
            elif ins.op is Op.SEND_GRAD:
                ds, dc = send_grad_dest(ins.stage, ins.chunk, S)
                key = (dc * S + ds, ins.microbatch, 1)
                sends_g[key] = sends_g.get(key, 0) + 1
            elif ins.op is Op.RECV_GRAD:
                key = (vs, ins.microbatch, 1)
                recvs_g[key] = recvs_g.get(key, 0) + 1
        del pos

    # (4) completeness, base and borrowed alike.
    for m in range(M):
        for vs in range(S * v):
            assert fwd_seen.get((vs, m)) == 1, (
                f"unit (vs={vs}, mb={m}) forward count "
                f"{fwd_seen.get((vs, m), 0)} != 1")
            assert bwd_seen.get((vs, m)) == 1, (
                f"unit (vs={vs}, mb={m}) backward count "
                f"{bwd_seen.get((vs, m), 0)} != 1")

    # (2) send/recv matching, both directions.
    assert sends_a == recvs_a, (
        f"activation send/recv mismatch: "
        f"{set(sends_a.items()) ^ set(recvs_a.items())}")
    assert sends_g == recvs_g, (
        f"gradient send/recv mismatch: "
        f"{set(sends_g.items()) ^ set(recvs_g.items())}")
    # Every non-first virtual stage receives each microbatch's activation
    # exactly once; every non-last receives its gradient exactly once.
    for m in range(M):
        for vs in range(1, S * v):
            assert recvs_a.get((vs, m, 0)) == 1
        for vs in range(last_vs):
            assert recvs_g.get((vs, m, 1)) == 1

    # (3) dataflow graph unchanged vs the survivor's base schedule.
    if sched.base_microbatches > 0 and sched.extra_microbatches > 0:
        base_streams = all_instructions(S, sched.base_microbatches, v) \
            if (v == 1 or sched.base_microbatches % S == 0) else None
        if base_streams is not None:
            assert dataflow_edges(sched.streams) == dataflow_edges(
                base_streams), "reroute changed the dataflow graph"
