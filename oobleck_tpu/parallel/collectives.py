"""Manual-mode collective helpers for full-manual shard_map programs.

The fused train step runs with *every* mesh axis manual (scaling-book style):
tensor parallelism, fsdp parameter gathering, and the Megatron f/g conjugate
pair are written out explicitly here instead of relying on GSPMD propagation.

TPU-native replacement for the reference's NCCL primitive usage
(/root/reference/oobleck/execution/layer.py:127-217 — manual FSDP
all_gather/reduce-scatter hooks; engine.py:404-412 — DP allreduce): the same
operations expressed as XLA collectives over mesh axes.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P


# NOTE on Megatron `f` (identity forward / psum backward): the default fused
# path takes value_and_grad OUTSIDE shard_map, where the in/out-spec transposes
# insert the backward psum at each replicated->varying boundary themselves —
# an explicit custom_vjp psum there DOUBLE-counts the cotangent (verified
# numerically: grads off by ~2x with it, exact without), so that path writes
# only the forward reduction `g`. The OVERLAP path (parallel/overlap.py) is the
# opposite regime: value_and_grad runs INSIDE one check_rep=False shard_map, no
# spec transposes run, and the transpose of a bare lax.psum is psum (cotangents
# of axis-invariant values get multiplied by the axis size — measured 2e+01
# grad error). There every forward tensor-psum must be `psum_idbwd` and every
# replicated->column-parallel entry needs an explicit `megatron_f`; the
# `identity_bwd` flags below switch the shared building blocks between the two
# regimes.


def psum_idbwd(x, axis: str):
    """psum forward, identity backward (the stop_gradient trick).

    For explicit-backward bodies (grad taken inside shard_map) where the
    cotangent is already axis-invariant and a real psum transpose would
    multiply it by the axis size.
    """
    return x + lax.stop_gradient(lax.psum(x, axis) - x)


def megatron_f(x, axis: str):
    """Megatron `f`: identity forward, psum-over-`axis` backward.

    Placed at each replicated->column-parallel entry in explicit-backward
    bodies: each tensor rank's backward produces only its own partial input
    cotangent, and `f` sums them into the full one.
    """

    @jax.custom_vjp
    def f(v):
        return v

    def fwd(v):
        return v, None

    def bwd(_, g):
        return (lax.psum(g, axis),)

    f.defvjp(fwd, bwd)
    return f(x)


def reduce_from_tp(x, axis: str, *, identity_bwd: bool = False):
    """Megatron `g`: psum forward (row-parallel output), identity backward.

    identity_bwd=True makes the identity backward explicit (overlap path);
    False relies on the shard_map spec transpose (default path).
    """
    if identity_bwd:
        return psum_idbwd(x, axis)
    return lax.psum(x, axis)


def unshard_fsdp(param: jax.Array, axis: str, dim: int) -> jax.Array:
    """All-gather an fsdp-sharded parameter along `dim` for use.

    The AD transpose of all_gather is psum_scatter, so gradients come back
    already reduced *and* sharded — the ZeRO-3 reduce-scatter for free
    (cf. reference layer.py:213-217 doing this by hand with NCCL).
    """
    return lax.all_gather(param, axis, axis=dim, tiled=True)


def vocab_parallel_logits_loss(
    local_logits: jax.Array,
    targets: jax.Array,
    vocab_offset: jax.Array | int,
    tensor_axis: str | None,
    *,
    identity_bwd: bool = False,
) -> jax.Array:
    """Cross-entropy over vocab-sharded logits without materializing the full
    vocab dimension on any device (Megatron-style three-psum construction).

    local_logits: [..., seq, V_local] f32, this rank's vocab shard.
    targets:      [..., seq] global token ids.
    Returns per-position loss [..., seq].
    """
    local_logits = local_logits.astype(jnp.float32)
    vlocal = local_logits.shape[-1]
    # max for stability
    local_max = jnp.max(local_logits, axis=-1)
    if tensor_axis is not None:
        gmax = lax.pmax(lax.stop_gradient(local_max), tensor_axis)
    else:
        gmax = local_max
    # The max shift is for stability only; its gradient contribution cancels.
    gmax = lax.stop_gradient(gmax)
    shifted = local_logits - gmax[..., None]
    sumexp = jnp.sum(jnp.exp(shifted), axis=-1)
    # gold logit: only the owning rank contributes
    local_ids = targets - vocab_offset
    in_range = (local_ids >= 0) & (local_ids < vlocal)
    safe_ids = jnp.clip(local_ids, 0, vlocal - 1)
    gold = jnp.take_along_axis(shifted, safe_ids[..., None], axis=-1)[..., 0]
    gold = jnp.where(in_range, gold, 0.0)
    if tensor_axis is not None:
        reduce = psum_idbwd if identity_bwd else lax.psum
        sumexp = reduce(sumexp, tensor_axis)
        gold = reduce(gold, tensor_axis)
    return jnp.log(sumexp) - gold


def vocab_parallel_embed(
    wte_local: jax.Array,
    tokens: jax.Array,
    vocab_offset: jax.Array | int,
    tensor_axis: str | None,
    *,
    identity_bwd: bool = False,
) -> jax.Array:
    """Embedding lookup over a vocab-sharded table: masked local gather + psum.

    identity_bwd: the residual-stream cotangent arriving here in explicit-
    backward bodies is already tensor-summed (every downstream tensor-parallel
    branch is guarded by a `megatron_f`), so the psum's backward must be
    identity — each rank scatters the full row cotangent into only the rows
    its shard owns.
    """
    vlocal = wte_local.shape[0]
    local_ids = tokens - vocab_offset
    in_range = (local_ids >= 0) & (local_ids < vlocal)
    safe_ids = jnp.clip(local_ids, 0, vlocal - 1)
    out = wte_local[safe_ids]
    out = jnp.where(in_range[..., None], out, 0.0)
    if tensor_axis is not None:
        out = psum_idbwd(out, tensor_axis) if identity_bwd else lax.psum(out, tensor_axis)
    return out


def pvary_to(x, axes: tuple[str, ...]):
    """pcast `x` to be varying over exactly the axes in `axes` it isn't yet.

    lax.cond requires both branches to have identical varying-manual-axes
    types; this normalizes a branch output (or pytree) to a superset target.
    """
    if not hasattr(lax, "pcast"):
        # Pre-vma jax (no lax.pcast): shard_map carries no varying-manual-
        # axes types, so branch types already agree — nothing to normalize.
        return x

    def one(v):
        have = set(getattr(v.aval, "vma", ()) or ())
        missing = tuple(a for a in axes if a not in have)
        return lax.pcast(v, missing, to="varying") if missing else v

    return jax.tree.map(one, x)
