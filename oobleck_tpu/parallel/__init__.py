"""Parallelism layer: device meshes, sharding rules, and the SPMD pipeline.

TPU-native replacement for the reference's NCCL process-group machinery
(/root/reference/oobleck/execution/pipeline.py:565-617,
engine.py:363-412): instead of dynamically created process groups, parallelism
is expressed as a `jax.sharding.Mesh` with named axes

    data   — data parallelism (grad psum; batch split)
    stage  — pipeline parallelism (shard_map + ppermute)
    tensor — tensor parallelism (Megatron-style param sharding, GSPMD)
    fsdp   — parameter sharding within a stage (ZeRO-3 equivalent)

and reconfiguration maps to *rebuilding the mesh* over surviving devices and
re-lowering the step function (pre-compiled per template at startup).
"""

from oobleck_tpu.parallel.mesh import MeshShape, make_mesh

__all__ = ["MeshShape", "make_mesh", "TrainState", "build_train_step",
           "make_optimizer", "OverlapConfig"]


def __getattr__(name):
    # Lazy: parallel.train imports model code which imports parallel.collectives.
    if name in ("TrainState", "build_train_step", "make_optimizer", "StepMetrics"):
        from oobleck_tpu.parallel import train

        return getattr(train, name)
    if name == "OverlapConfig":
        from oobleck_tpu.parallel.overlap import OverlapConfig

        return OverlapConfig
    raise AttributeError(name)
