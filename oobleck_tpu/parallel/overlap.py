"""Collective/compute overlap on the fused hot path.

Three latency-hiding mechanisms for the fused SPMD step, each expressed as
explicit collectives so overlap is a *property of the program*, not a
scheduler accident:

  (a) bucketed gradient sync — grad leaves are grouped into size-targeted
      buckets and each bucket's data-axis all-reduce is written out as a
      reduce-scatter + all-gather ring of `ppermute` chunk steps. The per-
      bucket chains are data-independent and the steps are emitted
      interleaved (every bucket advances ring step s before any advances to
      s+1), so bucket k+1's chunk packing double-buffers behind bucket k's
      sends and the scheduler is free to slide the whole train under the
      tail of backward compute. Replaces the single terminal psum the
      default path gets from its shard_map in_spec transposes; numerically
      equal to it within f32 reduction-order noise (tested to 1e-6).
  (b) FSDP param-gather prefetch — in the per-stage block scan, layer L+1's
      fsdp all_gather is issued data-independently behind layer L's compute;
      the scan carry double-buffers exactly ONE gathered layer, and the
      mirrored release in backward falls out of the scan transpose (each
      gathered layer's cotangent is reduce-scattered as soon as its block's
      backward completes).
  (c) double-buffered cross-stage sends — an alternative circular-pipeline
      tick where the `ppermute` issued at tick t is consumed at tick t+2,
      so microbatch t's send rides under microbatch t+1's compute (costs
      S-1 extra warmup ticks).

The unified step that uses these lives in parallel/train.py (overlap mode):
value_and_grad runs INSIDE one check_rep=False shard_map, which is why the
models' `explicit_bwd` ShardCtx mode (Megatron f / identity-backward g, see
collectives.py) exists — a bare psum's transpose is psum on this jax, and
grads come out wrong by the axis size without it.

`grad_sync_axes` encodes the explicit per-leaf sync rule: psum over every
mesh axis of size > 1 that is neither in the leaf's PartitionSpec nor the
tensor axis. Never tensor — tensor-parallel grads are completed inside the
loss by the f/g pair; syncing them here would double-count. fsdp-sharded
dims are excluded via the spec: their reduction is the all_gather transpose
(psum_scatter), ZeRO-3 style.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, replace

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from oobleck_tpu.parallel.collectives import unshard_fsdp
from oobleck_tpu.parallel.mesh import (
    AXIS_DATA,
    AXIS_FSDP,
    AXIS_SEQ,
    AXIS_STAGE,
)

# Async-collective / latency-hiding scheduler flags for real TPU backends
# (MaxText-style set). Advisory on CPU; must be in XLA_FLAGS before backend
# init to take effect — apply_xla_overlap_flags() is for launcher scripts,
# not for mid-process toggling.
XLA_OVERLAP_FLAGS: tuple[str, ...] = (
    "--xla_tpu_enable_async_collective_fusion=true",
    "--xla_tpu_enable_async_collective_fusion_fuse_all_gather=true",
    "--xla_tpu_enable_async_collective_fusion_multiple_steps=true",
    "--xla_tpu_overlap_compute_collective_tc=true",
    "--xla_enable_async_all_gather=true",
)

_GRAD_SYNC_IMPLS = ("ring", "psum", "none")


@dataclass(frozen=True)
class OverlapConfig:
    """Knobs for the overlap-mode fused step.

    enabled=False keeps the default path byte-identical (grad sync via
    shard_map spec transposes). grad_sync="psum" is the unbucketed baseline
    arm (parity tests, serialized-time probes); "none" skips the data-axis
    sync entirely — timing probes ONLY, the grads are wrong.
    """

    enabled: bool = False
    bucket_bytes: int = 4 * 1024 * 1024
    prefetch_fsdp: bool = True
    double_buffer_sends: bool = False
    grad_sync: str = "ring"
    xla_flags: bool = True

    def __post_init__(self):
        if self.grad_sync not in _GRAD_SYNC_IMPLS:
            raise ValueError(
                f"grad_sync must be one of {_GRAD_SYNC_IMPLS}, got "
                f"{self.grad_sync!r}"
            )
        if self.bucket_bytes <= 0:
            raise ValueError(f"bucket_bytes must be > 0, got {self.bucket_bytes}")

    @classmethod
    def from_env(cls, base: "OverlapConfig | None" = None) -> "OverlapConfig":
        """Durable env overrides (same contract as ExecutionArguments'):
        OOBLECK_OVERLAP=1/0, OOBLECK_OVERLAP_BUCKET_MB=<float>,
        OOBLECK_OVERLAP_PREFETCH=1/0, OOBLECK_OVERLAP_DB_SENDS=1/0,
        OOBLECK_OVERLAP_GRAD_SYNC=ring|psum, OOBLECK_OVERLAP_XLA_FLAGS=1/0."""
        cfg = base or cls()
        flag = lambda v: v.strip().lower() not in ("0", "false", "no", "")  # noqa: E731
        v = os.environ.get("OOBLECK_OVERLAP")
        if v is not None:
            cfg = replace(cfg, enabled=flag(v))
        v = os.environ.get("OOBLECK_OVERLAP_BUCKET_MB")
        if v:
            # oobleck: allow[OBL002] -- env-string parse at config time, not a device readback
            cfg = replace(cfg, bucket_bytes=int(float(v) * 1024 * 1024))
        v = os.environ.get("OOBLECK_OVERLAP_PREFETCH")
        if v is not None:
            cfg = replace(cfg, prefetch_fsdp=flag(v))
        v = os.environ.get("OOBLECK_OVERLAP_DB_SENDS")
        if v is not None:
            cfg = replace(cfg, double_buffer_sends=flag(v))
        v = os.environ.get("OOBLECK_OVERLAP_GRAD_SYNC")
        if v:
            cfg = replace(cfg, grad_sync=v.strip())
        v = os.environ.get("OOBLECK_OVERLAP_XLA_FLAGS")
        if v is not None:
            cfg = replace(cfg, xla_flags=flag(v))
        return cfg


def apply_xla_overlap_flags(cfg: OverlapConfig | None = None,
                            env: dict | None = None) -> str:
    """Fold the async-collective flags into env['XLA_FLAGS'] (idempotent) and
    return the new value. Call BEFORE the jax backend initializes — from a
    launcher, or when building a subprocess env."""
    env = os.environ if env is None else env
    current = env.get("XLA_FLAGS", "")
    if cfg is not None and (not cfg.enabled or not cfg.xla_flags):
        return current
    missing = [f for f in XLA_OVERLAP_FLAGS if f not in current]
    if missing:
        current = (current + " " + " ".join(missing)).strip()
        env["XLA_FLAGS"] = current
    return current


# --------------------------------------------------------------------------
# per-leaf sync rule + bucketing


def spec_axes(spec) -> set:
    """Mesh axes named anywhere in a PartitionSpec (flattening tuples)."""
    out: set = set()
    for entry in spec:
        if entry is None:
            continue
        if isinstance(entry, (tuple, list)):
            out.update(entry)
        else:
            out.add(entry)
    return out


def spec_dim(spec, axis: str) -> int | None:
    """The dimension `axis` shards in `spec`, or None."""
    for d, entry in enumerate(spec):
        if entry == axis:
            return d
        if isinstance(entry, (tuple, list)) and axis in entry:
            return d
    return None


def grad_sync_axes(spec, axis_sizes: dict) -> tuple[str, ...]:
    """Explicit-sync axes for one grad leaf: every non-tensor mesh axis of
    size > 1 the leaf is NOT sharded over. Tensor is completed by the
    Megatron f/g pair inside the loss; sharded axes (stage layer-slices,
    fsdp dims) own disjoint shards or are reduced by the all_gather
    transpose."""
    present = spec_axes(spec)
    return tuple(
        a for a in (AXIS_STAGE, AXIS_DATA, AXIS_FSDP, AXIS_SEQ)
        if axis_sizes.get(a, 1) > 1 and a not in present
    )


def bucketize(nbytes: list[int], bucket_bytes: int,
              dtypes: list | None = None) -> list[list[int]]:
    """Greedy in-order grouping of leaf indices into ~bucket_bytes buckets.

    An oversized leaf rides alone; the last bucket may be under-full; when
    `dtypes` is given, a bucket never mixes dtypes (its leaves concatenate
    into one flat buffer)."""
    buckets: list[list[int]] = []
    cur: list[int] = []
    cur_bytes = 0
    cur_dtype = None
    for i, nb in enumerate(nbytes):
        dt = dtypes[i] if dtypes is not None else None
        if cur and (cur_bytes + nb > bucket_bytes or dt != cur_dtype):
            buckets.append(cur)
            cur, cur_bytes = [], 0
        cur.append(i)
        cur_bytes += nb
        cur_dtype = dt
    if cur:
        buckets.append(cur)
    return buckets


# --------------------------------------------------------------------------
# ring all-reduce (reduce-scatter + all-gather as explicit ppermute chunks)


def _ring_steps(bufs: list[jax.Array], axis_name: str, n: int) -> list[jax.Array]:
    """All-reduce each flat buffer over `axis_name` via a chunked ppermute
    ring, stepping every buffer per ring step (interleaved issue order: the
    chains are data-independent, so chunk packing of buffer b+1 double-
    buffers behind the in-flight send of buffer b)."""
    idx = lax.axis_index(axis_name)
    perm = [(i, (i + 1) % n) for i in range(n)]
    chunked = []
    accs = []
    for buf in bufs:
        pad = (-buf.size) % n
        flat = jnp.pad(buf, (0, pad))
        chunks = flat.reshape(n, -1)
        chunked.append(chunks)
        # reduce-scatter: at step s rank r holds the partial of chunk
        # (r+1-s)%n; after n-1 steps rank r fully owns chunk (r+2)%n.
        accs.append(chunks[(idx + 1) % n])
    for step in range(1, n):
        accs = [lax.ppermute(a, axis_name, perm) for a in accs]
        accs = [a + c[(idx + 1 - step) % n] for a, c in zip(accs, chunked)]
    own = (idx + 2) % n
    outs = [jnp.zeros_like(c).at[own].set(a) for c, a in zip(chunked, accs)]
    # all-gather: circulate the owned chunk n-1 hops; chunk ids decrement
    # per hop (receiver r gets the chunk rank r-1 held).
    curs = list(accs)
    cur_id = own
    for _ in range(n - 1):
        curs = [lax.ppermute(c, axis_name, perm) for c in curs]
        cur_id = (cur_id - 1) % n
        outs = [o.at[cur_id].set(c) for o, c in zip(outs, curs)]
    return [o.reshape(-1)[: b.size] for o, b in zip(outs, bufs)]


def ring_all_reduce(x: jax.Array, axis_name: str, axis_size: int) -> jax.Array:
    """Sum `x` over `axis_name` — equals lax.psum, written as ppermute chunks."""
    if axis_size <= 1:
        return x
    (flat,) = _ring_steps([x.reshape(-1)], axis_name, axis_size)
    return flat.reshape(x.shape)


def bucketed_ring_all_reduce(leaves: list[jax.Array], axis_name: str,
                             axis_size: int,
                             bucket_bytes: int) -> list[jax.Array]:
    """All-reduce a leaf list over `axis_name` in size-targeted buckets,
    each bucket one flat ring; returns leaves in the original order."""
    if axis_size <= 1 or not leaves:
        return list(leaves)
    dtypes = [jnp.dtype(l.dtype) for l in leaves]
    nbytes = [l.size * dt.itemsize for l, dt in zip(leaves, dtypes)]
    buckets = bucketize(nbytes, bucket_bytes, dtypes)
    bufs = [
        jnp.concatenate([leaves[i].reshape(-1) for i in b]) if len(b) > 1
        else leaves[b[0]].reshape(-1)
        for b in buckets
    ]
    reduced = _ring_steps(bufs, axis_name, axis_size)
    out: list[jax.Array | None] = [None] * len(leaves)
    for b, buf in zip(buckets, reduced):
        off = 0
        for i in b:
            n = leaves[i].size
            out[i] = lax.dynamic_slice_in_dim(buf, off, n).reshape(leaves[i].shape)
            off += n
    return out  # type: ignore[return-value]


def sync_grads(grads, specs, axis_sizes: dict, *, data_impl: str = "ring",
               bucket_bytes: int = 4 * 1024 * 1024):
    """Explicit per-leaf grad sync for the overlap-mode step.

    Non-data axes (stage/fsdp/seq not in the leaf's spec) sync with a plain
    psum — they are small, incidental reductions; the data axis (the pure
    DP all-reduce) goes through the bucketed ring ("ring"), a single psum
    per leaf ("psum", the parity baseline), or is skipped ("none", timing
    probes only)."""
    leaves, treedef = jax.tree.flatten(grads)
    spec_leaves = jax.tree.flatten(
        specs, is_leaf=lambda x: isinstance(x, P))[0]
    assert len(leaves) == len(spec_leaves), (len(leaves), len(spec_leaves))
    sync_axes = [grad_sync_axes(s, axis_sizes) for s in spec_leaves]
    out = list(leaves)
    for i, axes in enumerate(sync_axes):
        nondata = tuple(a for a in axes if a != AXIS_DATA)
        if nondata:
            out[i] = lax.psum(out[i], nondata)
    n_data = axis_sizes.get(AXIS_DATA, 1)
    data_idx = [i for i, axes in enumerate(sync_axes) if AXIS_DATA in axes]
    if data_idx and n_data > 1 and data_impl != "none":
        if data_impl == "psum":
            for i in data_idx:
                out[i] = lax.psum(out[i], AXIS_DATA)
        else:
            synced = bucketed_ring_all_reduce(
                [out[i] for i in data_idx], AXIS_DATA, n_data, bucket_bytes)
            for i, v in zip(data_idx, synced):
                out[i] = v
    return jax.tree.unflatten(treedef, out)


# --------------------------------------------------------------------------
# FSDP gather prefetch


def unstacked_specs(stacked_specs):
    """Drop the leading (layer-stack) dim from a stacked-block spec tree."""
    return jax.tree.map(lambda s: P(*tuple(s)[1:]), stacked_specs,
                        is_leaf=lambda x: isinstance(x, P))


def fsdp_gather_block(block_params, block_specs, axis: str):
    """All-gather every fsdp-sharded leaf of ONE (unstacked) block; leaves
    without the axis pass through. The transpose reduce-scatters the
    cotangent, so the release in backward mirrors the gather in forward."""

    def one(p, spec):
        d = spec_dim(spec, axis)
        return unshard_fsdp(p, axis, d) if d is not None else p

    return jax.tree.map(one, block_params, block_specs)


def prefetched_block_scan(apply_block, gather_block, stacked_params, h,
                          num_layers: int):
    """Scan blocks with layer L+1's gather issued behind layer L's compute.

    The carry double-buffers exactly ONE gathered layer: iteration i applies
    the already-gathered layer i (from the carry) and issues the gather of
    layer i+1 — the two are data-independent, so the gather's collective can
    run under the block compute. The last iteration prefetches layer
    num_layers-1 again (index clamp); its result is dead and DCE-able, the
    price of a structurally uniform carry."""

    def slice_layer(i):
        return jax.tree.map(
            lambda x: lax.dynamic_index_in_dim(x, i, 0, keepdims=False),
            stacked_params)

    def body(carry, i):
        h, cur_gathered = carry
        nxt = gather_block(slice_layer(jnp.minimum(i + 1, num_layers - 1)))
        h = apply_block(cur_gathered, h)
        return (h, nxt), None

    carry0 = (h, gather_block(slice_layer(0)))
    (h, _dead), _ = lax.scan(body, carry0, jnp.arange(num_layers))
    return h


def prefetch_carry_shapes(gather_block, stacked_params, h):
    """eval_shape of the prefetched-scan carry — the double-buffer window
    invariant (exactly one gathered layer resident beyond the activation)
    is testable from this without running the scan."""

    def carry0(stacked, h):
        one = jax.tree.map(
            lambda x: lax.dynamic_index_in_dim(x, 0, 0, keepdims=False),
            stacked)
        return (h, gather_block(one))

    return jax.eval_shape(carry0, stacked_params, h)


# --------------------------------------------------------------------------
# measurement


def comm_hidden_fraction(t_overlapped: float, t_compute_only: float,
                         t_comm_only: float) -> float:
    """Fraction of the standalone comm cost hidden by the overlapped step:
    (P + C - T) / C clamped to [0, 1], where T is the overlapped step time,
    P the step with the data sync removed, C the sync alone."""
    if t_comm_only <= 0.0:
        return 0.0
    frac = (t_compute_only + t_comm_only - t_overlapped) / t_comm_only
    return max(0.0, min(1.0, frac))


def effective_comm(comm: float, overlappable_compute: float,
                   hidden_fraction: float) -> float:
    """Comm cost a planner should charge once overlap hides what it can:
    max(0, comm - hidden_fraction * overlappable_compute)."""
    return max(0.0, comm - hidden_fraction * overlappable_compute)
