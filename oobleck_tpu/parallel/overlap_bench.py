"""Collective/compute overlap microbench: measured comm-hidden fraction.

Measures, per mesh shape on the 8-virtual-CPU rig, the three arms that
define the hidden fraction (parallel/overlap.py):

  T_ovl — the overlapped (loss, grads) step: unified shard_map with the
          bucketed ring grad sync, FSDP gather prefetch, and
          double-buffered cross-stage sends.
  P     — the same step with grad_sync="none": compute without the data-
          parallel gradient collective (the overlappable comm).
  C     — the bucketed ring all-reduce alone, jitted over grad-shaped
          inputs on the same mesh.

  comm_hidden_fraction = clamp((P + C - T_ovl) / C, 0, 1)

Also reported: serialized (default three-phase path) vs overlapped
tokens/sec, the ring-vs-psum grad parity (max abs leaf diff — the
correctness gate for the bucketed sync), and a flash-vs-XLA attention
sub-key (forward + grad parity and times under pallas-interpret).

CPU numbers are a *scheduling proxy*: XLA:CPU runs one stream, so the
hidden fraction here reflects dispatch/fusion interleaving, not DMA
engines — on-device numbers must be re-measured on TPU (bench.py stamps
device-only figures stale). Sets the oobleck_comm_hidden_fraction gauge
to the best measured fraction.

Run as `python -m oobleck_tpu.parallel.overlap_bench` under
JAX_PLATFORMS=cpu with XLA_FLAGS=--xla_force_host_platform_device_count=8
(bench.py and `make overlap-bench` set this up). Prints ONE JSON line.
"""

from __future__ import annotations

import json
import os
import statistics
import time

# Workload sized so compute dominates dispatch overhead: at seq/batch 32
# the step is compile-structure-bound on CPU and the hidden fraction
# reads as noise; at 64/64 the ring's cost is resolvable against P.
_SEQ = 64
_BATCH = 64
_NUM_MB = 4
_REPS = 5


def _median_s(fn, *args, reps: int = _REPS) -> float:
    import jax

    jax.block_until_ready(fn(*args))  # warmup / compile
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        jax.block_until_ready(fn(*args))
        ts.append(time.perf_counter() - t0)
    return statistics.median(ts)


def _build(shape, overlap):
    import jax
    import jax.numpy as jnp

    from oobleck_tpu.models import build_model
    from oobleck_tpu.parallel import build_train_step, make_mesh, make_optimizer

    model = build_model("gpt2-tiny", {"remat": True, "dtype": jnp.float32})
    mesh = make_mesh(shape)
    init_fn, step = build_train_step(
        model, mesh, num_microbatches=_NUM_MB,
        optimizer=make_optimizer(learning_rate=1e-3, warmup_steps=2),
        overlap=overlap)
    state = init_fn(jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (_BATCH, _SEQ), 0,
                                model.config.vocab_size, dtype=jnp.int32)
    prepared = step.prepare(tokens)
    return model, mesh, state, step, prepared


def _comm_only_s(model, mesh, params, cfg) -> float:
    """Median time of the bucketed ring grad sync alone (arm C)."""
    import jax

    from oobleck_tpu.parallel import overlap as ovl
    from oobleck_tpu.parallel.mesh import ALL_AXES
    from jax.sharding import PartitionSpec as P

    specs = model.param_specs(stacked=True)
    axis_sizes = dict(mesh.shape)

    def body(grads):
        return ovl.sync_grads(grads, specs, axis_sizes,
                              data_impl=cfg.grad_sync,
                              bucket_bytes=cfg.bucket_bytes)

    sm = jax.jit(jax.shard_map(
        body, mesh=mesh, in_specs=(specs,), out_specs=specs,
        axis_names=set(ALL_AXES), check_vma=False))
    return _median_s(sm, params)


def _grad_diff(ga, gb) -> float:
    import jax
    import numpy as np

    return max(float(np.max(np.abs(np.asarray(a) - np.asarray(b))))
               for a, b in zip(jax.tree.leaves(jax.device_get(ga)),
                               jax.tree.leaves(jax.device_get(gb))))


def _measure_shape(name: str, shape, cfg) -> dict:
    from oobleck_tpu.parallel.overlap import comm_hidden_fraction

    model, mesh, state, step_ovl, prepared = _build(shape, cfg)
    _, _, _, step_ser, _ = _build(shape, None)
    from dataclasses import replace

    _, _, _, step_nosync, _ = _build(shape, replace(cfg, grad_sync="none"))

    t_ovl = _median_s(step_ovl.loss_and_grads, state.params, *prepared)
    t_ser = _median_s(step_ser.loss_and_grads, state.params, *prepared)
    t_p = _median_s(step_nosync.loss_and_grads, state.params, *prepared)
    t_c = _comm_only_s(model, mesh, state.params, cfg)
    hf = comm_hidden_fraction(t_ovl, t_p, t_c)
    tokens = _BATCH * _SEQ
    return {
        "mesh": name,
        "overlapped_step_s": round(t_ovl, 5),
        "serialized_step_s": round(t_ser, 5),
        "compute_only_s": round(t_p, 5),
        "comm_only_s": round(t_c, 5),
        "comm_hidden_fraction": round(hf, 4),
        "tokens_per_sec_overlapped": round(tokens / t_ovl, 1),
        "tokens_per_sec_serialized": round(tokens / t_ser, 1),
    }


def _parity(shape, cfg) -> dict:
    """Ring-vs-psum grad parity on one shape — the bucketed sync's
    correctness gate (must stay <= 1e-6 per leaf in f32)."""
    from dataclasses import replace

    _, _, state, step_ring, prepared = _build(shape, cfg)
    _, _, _, step_psum, _ = _build(shape, replace(cfg, grad_sync="psum"))
    _, _, _, step_ser, _ = _build(shape, None)
    loss_r, g_ring = step_ring.loss_and_grads(state.params, *prepared)
    loss_p, g_psum = step_psum.loss_and_grads(state.params, *prepared)
    loss_s, g_ser = step_ser.loss_and_grads(state.params, *prepared)
    return {
        "ring_vs_psum_max_abs_diff": _grad_diff(g_ring, g_psum),
        "overlap_vs_default_max_abs_diff": _grad_diff(g_ring, g_ser),
        "loss_ring_vs_default_abs_diff": abs(float(loss_r) - float(loss_s)),
    }


def _flash_subkey() -> dict:
    """Flash (pallas-interpret) vs XLA attention: fwd + grad parity and
    per-call times on a tiny shape. CPU interpret times are a correctness
    proxy only — the compiled-kernel speedup exists on TPU."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from oobleck_tpu.ops.attention import _xla_causal_attention
    from oobleck_tpu.ops.flash import flash_attention

    q, k, v = (jax.random.normal(jax.random.PRNGKey(i), (1, 2, 128, 16),
                                 jnp.float32) for i in range(3))

    def loss_flash(q):
        return jnp.sum(flash_attention(q, k, v) ** 2)

    def loss_xla(q):
        return jnp.sum(_xla_causal_attention(q, k, v) ** 2)

    fwd_f = jax.jit(flash_attention)
    fwd_x = jax.jit(_xla_causal_attention)
    out_f, out_x = fwd_f(q, k, v), fwd_x(q, k, v)
    g_f = jax.jit(jax.grad(loss_flash))(q)
    g_x = jax.jit(jax.grad(loss_xla))(q)
    return {
        "shape": "b1 h2 s128 d16 f32 causal",
        "fwd_max_abs_diff": float(np.max(np.abs(out_f - out_x))),
        "grad_max_abs_diff": float(np.max(np.abs(g_f - g_x))),
        "flash_interpret_fwd_s": round(_median_s(fwd_f, q, k, v), 5),
        "xla_fwd_s": round(_median_s(fwd_x, q, k, v), 5),
        "note": "pallas-interpret on CPU: parity gate only; compiled "
                "kernel timing is TPU-only",
    }


def measure() -> dict:
    from oobleck_tpu.parallel import OverlapConfig
    from oobleck_tpu.parallel.mesh import MeshShape
    from oobleck_tpu.utils import metrics

    cfg = OverlapConfig(enabled=True, grad_sync="ring",
                        bucket_bytes=1 << 16, prefetch_fsdp=True,
                        double_buffer_sends=True)
    shapes = {
        "d8": MeshShape(data=8),
        "f2d4": MeshShape(fsdp=2, data=4),
        "s2f2t2": MeshShape(stage=2, fsdp=2, tensor=2),
    }
    rows = [_measure_shape(name, sh, cfg) for name, sh in shapes.items()]
    best_hf = max(r["comm_hidden_fraction"] for r in rows)
    metrics.registry().gauge(
        "oobleck_comm_hidden_fraction",
        "measured fraction of grad-sync comm hidden under compute",
    ).set(best_hf)
    return {
        "rig": "8 virtual CPU devices, gpt2-tiny f32 remat, "
               f"batch={_BATCH} seq={_SEQ} num_mb={_NUM_MB}",
        "config": {"grad_sync": cfg.grad_sync,
                   "bucket_bytes": cfg.bucket_bytes,
                   "prefetch_fsdp": cfg.prefetch_fsdp,
                   "double_buffer_sends": cfg.double_buffer_sends},
        "shapes": rows,
        "comm_hidden_fraction": best_hf,
        "parity": _parity(MeshShape(stage=2, fsdp=2, tensor=2), cfg),
        "flash_vs_xla": _flash_subkey(),
        "note": "CPU scheduling proxy — single XLA:CPU stream; re-measure "
                "hidden fraction on TPU for device truth",
    }


def main() -> None:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    print(json.dumps(measure()))


if __name__ == "__main__":
    main()
