"""Cross-process device collectives for the multi-host MPMD path.

The reference syncs heterogeneous pipelines across nodes with NCCL process
groups (/root/reference/oobleck/execution/engine.py:363-412, per-(layer,
shard) allreduce; pipeline.py:582-617, node-spanning p2p). The TPU-native
equivalent here: every worker joins ONE jax.distributed world, and all
cross-host data-plane traffic rides XLA collectives compiled over small
"process meshes" — one device per participating process — so on real
hardware the bytes move over ICI/DCN, never through the control plane
(which the round-3 GRAD_SYNC TCP relay violated; deleted in favor of this).

Three primitives, all built on the same mechanism
(`jax.make_array_from_single_device_arrays` over a process mesh + a jitted
reduction with replicated out_sharding):

  * `group_sum`:   sum of per-process f32 vectors over any process subset —
                   the grand DP gradient allreduce (all processes) and
                   point-to-point activation transfer (2 processes, receiver
                   contributes zeros) are both this;
  * `group_min`:   element-wise min — used as a "lowest owner" election for
                   layer-state recovery (each process votes its process
                   index where it holds a layer, +inf elsewhere);
  * flat pack/unpack helpers with a deterministic per-layer layout shared by
    every process (layouts derive from model avals, so no metadata protocol
    is needed — shapes are static, as everywhere else on TPU).

Every participating process MUST call the same primitive with the same
(participants, length) in the same relative order; the engine guarantees
this by having every process interpret the same global schedule.
"""

from __future__ import annotations

from typing import Any, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


class ProcessComm:
    """Collectives over jax.distributed processes (cached meshes + jits)."""

    def __init__(self):
        self._mesh_cache: dict[tuple[int, ...], Mesh] = {}
        self._jit_cache: dict[tuple, Any] = {}
        self._local_device = jax.local_devices()[0]
        self.process_index = jax.process_index()
        self.process_count = jax.process_count()

    # -- process meshes ------------------------------------------------- #

    def _mesh(self, participants: tuple[int, ...]) -> Mesh:
        if participants not in self._mesh_cache:
            devs = jax.devices()
            picked = [
                min((d for d in devs if d.process_index == p),
                    key=lambda d: d.id)
                for p in participants
            ]
            self._mesh_cache[participants] = Mesh(np.array(picked), ("proc",))
        return self._mesh_cache[participants]

    def _reduce_device(self, local_vec, length: int,
                       participants: Sequence[int], op: str):
        """Shared machinery: stack per-process rows, reduce over `proc`.
        Accepts a host OR device f32 vector; returns the reduced vector as
        a DEVICE array on this process's local device (no host round-trip
        on the receive side)."""
        participants = tuple(sorted(participants))
        assert self.process_index in participants, (
            f"process {self.process_index} is not in {participants}"
        )
        if len(participants) == 1:
            return jax.device_put(
                jnp.asarray(local_vec, jnp.float32), self._local_device
            )
        mesh = self._mesh(participants)
        n = len(participants)
        sharding = NamedSharding(mesh, P("proc"))
        row = jax.device_put(
            jnp.asarray(local_vec, jnp.float32)[None, :], self._local_device
        )
        garr = jax.make_array_from_single_device_arrays(
            (n, length), sharding, [row]
        )
        key = (participants, n, length, op)
        if key not in self._jit_cache:
            fn = {"sum": lambda a: a.sum(0), "min": lambda a: a.min(0)}[op]
            self._jit_cache[key] = jax.jit(
                fn, out_shardings=NamedSharding(mesh, P())
            )
        out = self._jit_cache[key](garr)
        return out.addressable_data(0)

    # -- public primitives ---------------------------------------------- #

    def group_sum(self, local_vec, length: int,
                  participants: Sequence[int]) -> np.ndarray:
        """Element-wise sum of each participant's f32 vector (all get it)."""
        return np.asarray(
            self._reduce_device(local_vec, length, participants, "sum")
        )

    def group_min(self, local_vec, length: int,
                  participants: Sequence[int]) -> np.ndarray:
        return np.asarray(
            self._reduce_device(local_vec, length, participants, "min")
        )

    def group_sum_device(self, local_vec, length: int,
                         participants: Sequence[int]):
        """group_sum whose input AND output stay device arrays on this
        process's local device — the hot-path form (per-step gradient
        allreduce) with no host staging on either side."""
        return self._reduce_device(local_vec, length, participants, "sum")

    @property
    def local_device_sharding(self):
        return jax.sharding.SingleDeviceSharding(self._local_device)

    def send(self, value, src: int, dst: int, aval):
        """Point-to-point: move the pytree `value` (on src) to dst; returns
        it on dst (leaves on this process's local device), None on src.
        Compiles to a 2-process collective — the multi-host analog of the
        reference's stage-to-stage NCCL p2p (pipeline.py:288-333). `aval`
        is the static pytree of ShapeDtypeStructs (tuple carries — T5
        bridge, CLIP towers — flatten like any pytree); pack/unpack run on
        device, so the bytes never stage through host numpy."""
        leaf_avals = jax.tree.leaves(aval)
        struct = jax.tree.structure(aval)
        sizes = [int(np.prod(l.shape)) if l.shape else 1 for l in leaf_avals]
        size = sum(sizes)
        if self.process_index == src:
            # Consolidate onto the local proc-mesh device (D2D within the
            # host), then fuse ravel/cast/concat in one jitted program.
            leaves = jax.device_put(
                jax.tree.leaves(value),
                jax.sharding.SingleDeviceSharding(self._local_device),
            )
            key = ("pack", tuple((l.shape, str(l.dtype)) for l in leaf_avals))
            if key not in self._jit_cache:
                self._jit_cache[key] = jax.jit(lambda ls: jnp.concatenate(
                    [l.ravel().astype(jnp.float32) for l in ls]
                ))
            flat = self._jit_cache[key](leaves)
        else:
            flat = jnp.zeros(size, jnp.float32)
        total = self._reduce_device(flat, size, (src, dst), "sum")
        if self.process_index == src:
            return None
        key = ("unpack", tuple((l.shape, str(l.dtype)) for l in leaf_avals))
        if key not in self._jit_cache:
            def unpack(f):
                out, off = [], 0
                for l, n in zip(leaf_avals, sizes):
                    out.append(f[off:off + n].reshape(l.shape)
                               .astype(l.dtype))
                    off += n
                return out
            self._jit_cache[key] = jax.jit(unpack)
        return jax.tree.unflatten(struct, self._jit_cache[key](total))


# ---------------------------------------------------------------------- #
# Flat layouts for layer-keyed pytrees.


class FlatLayout:
    """Deterministic f32 flat layout for a {layer_index: pytree} mapping,
    derived from abstract shapes only — every process computes the identical
    layout without communicating (static shapes, the TPU discipline)."""

    def __init__(self, avals_by_layer: dict[int, Any], extra: int = 0):
        self.layers = sorted(avals_by_layer)
        self.slices: dict[int, tuple[int, int]] = {}
        self.structs: dict[int, Any] = {}
        self.leaf_metas: dict[int, list] = {}
        off = 0
        for li in self.layers:
            leaves, struct = jax.tree.flatten(avals_by_layer[li])
            metas = [(tuple(l.shape), l.dtype) for l in leaves]
            size = sum(int(np.prod(s)) if s else 1 for s, _ in metas)
            self.slices[li] = (off, size)
            self.structs[li] = struct
            self.leaf_metas[li] = metas
            off += size
        self.param_length = off
        self.extra = extra
        self.length = off + extra

    def pack_into(self, buf: np.ndarray, li: int, tree) -> None:
        off, size = self.slices[li]
        flat = np.concatenate([
            np.asarray(jax.device_get(l), np.float32).reshape(-1)
            for l in jax.tree.leaves(tree)
        ]) if jax.tree.leaves(tree) else np.zeros(0, np.float32)
        assert flat.shape[0] == size, (li, flat.shape, size)
        buf[off:off + size] += flat

    def unpack(self, buf, li: int):
        """Slice layer li's tree out of a flat buffer. Works on host numpy
        AND under jit tracing (pure slicing/reshape/cast) — the device-side
        unpack paths jit this same function."""
        off, _ = self.slices[li]
        leaves = []
        for shape, dtype in self.leaf_metas[li]:
            n = int(np.prod(shape)) if shape else 1
            leaves.append(buf[off:off + n].reshape(shape).astype(dtype))
            off += n
        return jax.tree.unflatten(self.structs[li], leaves)


def layer_avals(model) -> dict[int, Any]:
    """Abstract param trees per pipeline layer (no device use)."""
    rng = jax.random.PRNGKey(0)
    return {
        li: jax.eval_shape(lambda r, _li=li: model.init_layer(r, _li), rng)
        for li in range(model.num_pipeline_layers)
    }


def activation_avals(model, microbatch_size: int, seq_len: int) -> list:
    """Abstract activation (carry) aval AFTER each non-final layer, chained
    through jax.eval_shape — the static shape contract for cross-host
    stage-to-stage transfers (no metadata handshake, unlike the reference's
    first-transfer header protocol, pipeline.py:288-333)."""
    avals = layer_avals(model)
    batch = jax.tree.map(
        lambda a: jax.ShapeDtypeStruct(np.shape(a), np.asarray(a).dtype),
        model.sample_batch(microbatch_size, seq_len),
    )
    out: list = []

    def step(li, carry):
        return jax.eval_shape(
            lambda p, c, b: model.apply_layer(li, p, c, b),
            avals[li], carry, batch,
        )

    carry = None
    for li in range(model.num_pipeline_layers - 1):
        carry = step(li, carry)
        out.append(carry)
    return out
