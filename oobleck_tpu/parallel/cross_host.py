"""Cross-process device collectives for the multi-host MPMD path.

The reference syncs heterogeneous pipelines across nodes with NCCL process
groups (/root/reference/oobleck/execution/engine.py:363-412, per-(layer,
shard) allreduce; pipeline.py:582-617, node-spanning p2p). The TPU-native
equivalent here: every worker joins ONE jax.distributed world, and all
cross-host data-plane traffic rides XLA collectives compiled over small
"process meshes" — one device per participating process — so on real
hardware the bytes move over ICI/DCN, never through the control plane
(which the round-3 GRAD_SYNC TCP relay violated; deleted in favor of this).

Three primitives, all built on the same mechanism
(`jax.make_array_from_single_device_arrays` over a process mesh + a jitted
reduction with replicated out_sharding):

  * `group_sum`:   sum of per-process f32 vectors over any process subset —
                   the grand DP gradient allreduce (all processes) and
                   point-to-point activation transfer (2 processes, receiver
                   contributes zeros) are both this;
  * `group_min`:   element-wise min — used as a "lowest owner" election for
                   layer-state recovery (each process votes its process
                   index where it holds a layer, +inf elsewhere);
  * flat pack/unpack helpers with a deterministic per-layer layout shared by
    every process (layouts derive from model avals, so no metadata protocol
    is needed — shapes are static, as everywhere else on TPU).

Every participating process MUST call the same primitive with the same
(participants, length) in the same relative order; the engine guarantees
this by having every process interpret the same global schedule.
"""

from __future__ import annotations

from typing import Any, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


class ProcessComm:
    """Collectives over jax.distributed processes (cached meshes + jits)."""

    def __init__(self):
        self._mesh_cache: dict[tuple[int, ...], Mesh] = {}
        self._jit_cache: dict[tuple, Any] = {}
        self._layout_cache: dict[tuple, "TypedFlatLayout"] = {}
        self._local_device = jax.local_devices()[0]
        self.process_index = jax.process_index()
        self.process_count = jax.process_count()
        # Observability: bytes THIS process contributed to cross-process
        # collectives (its row of each reduce; single-participant calls are
        # local and count zero). The DP engine snapshots deltas per step so
        # tests can assert the wire carries only what DP actually requires.
        self.wire_bytes = 0

    # -- process meshes ------------------------------------------------- #

    def _mesh(self, participants: tuple[int, ...]) -> Mesh:
        if participants not in self._mesh_cache:
            devs = jax.devices()
            picked = [
                min((d for d in devs if d.process_index == p),
                    key=lambda d: d.id)
                for p in participants
            ]
            self._mesh_cache[participants] = Mesh(np.array(picked), ("proc",))
        return self._mesh_cache[participants]

    def _reduce_device(self, local_vec, length: int,
                       participants: Sequence[int], op: str,
                       dtype=jnp.float32):
        """Shared machinery: stack per-process rows, reduce over `proc`.
        Accepts a host OR device vector (cast to `dtype` — the WIRE dtype:
        bf16 edges ride as bf16, f32 grads as f32); returns the reduced
        vector as a DEVICE array on this process's local device (no host
        round-trip on the receive side)."""
        participants = tuple(sorted(participants))
        assert self.process_index in participants, (
            f"process {self.process_index} is not in {participants}"
        )
        if len(participants) == 1:
            return jax.device_put(
                jnp.asarray(local_vec, dtype), self._local_device
            )
        self.wire_bytes += length * np.dtype(dtype).itemsize
        mesh = self._mesh(participants)
        n = len(participants)
        sharding = NamedSharding(mesh, P("proc"))
        row = jax.device_put(
            jnp.asarray(local_vec, dtype)[None, :], self._local_device
        )
        garr = jax.make_array_from_single_device_arrays(
            (n, length), sharding, [row]
        )
        key = (participants, n, length, op, np.dtype(dtype).name)
        if key not in self._jit_cache:
            fn = {"sum": lambda a: a.sum(0), "min": lambda a: a.min(0)}[op]
            self._jit_cache[key] = jax.jit(
                fn, out_shardings=NamedSharding(mesh, P())
            )
        out = self._jit_cache[key](garr)
        return out.addressable_data(0)

    # -- public primitives ---------------------------------------------- #

    def group_sum(self, local_vec, length: int,
                  participants: Sequence[int],
                  dtype=jnp.float32) -> np.ndarray:
        """Element-wise sum of each participant's vector (all get it).
        `dtype` is the wire dtype — int32 lanes keep integer meta (step
        counts, byte counts) exact where f32 would round past 2**24."""
        return np.asarray(
            self._reduce_device(local_vec, length, participants, "sum",
                                dtype)
        )

    def group_min(self, local_vec, length: int,
                  participants: Sequence[int]) -> np.ndarray:
        return np.asarray(
            self._reduce_device(local_vec, length, participants, "min")
        )

    def group_sum_device(self, local_vec, length: int,
                         participants: Sequence[int], dtype=jnp.float32):
        """group_sum whose input AND output stay device arrays on this
        process's local device — the hot-path form (per-step gradient
        allreduce) with no host staging on either side. `dtype` is the
        wire dtype (native grad/activation width, not forced f32)."""
        return self._reduce_device(local_vec, length, participants, "sum",
                                   dtype)

    @property
    def local_device_sharding(self):
        return jax.sharding.SingleDeviceSharding(self._local_device)

    def send(self, value, src: int, dst: int, aval):
        """Point-to-point: move the pytree `value` (on src) to dst; returns
        it on dst (leaves on this process's local device), None on src.
        Compiles to a 2-process collective — the multi-host analog of the
        reference's stage-to-stage NCCL p2p (pipeline.py:288-333). `aval`
        is the static pytree of ShapeDtypeStructs (tuple carries — T5
        bridge, CLIP towers — flatten like any pytree); pack/unpack run on
        device, so the bytes never stage through host numpy. The wire
        carries NATIVE dtypes (one flat vector per distinct leaf dtype):
        bf16 activations cost bf16 bytes, and the receiver's zero
        contribution keeps the sum bit-exact."""
        sig = tuple((tuple(l.shape), str(l.dtype))
                    for l in jax.tree.leaves(aval))
        if sig not in self._layout_cache:
            self._layout_cache[sig] = TypedFlatLayout({0: aval})
        layout = self._layout_cache[sig]
        struct = jax.tree.structure(aval)
        if self.process_index == src:
            # Consolidate onto the local proc-mesh device (D2D within the
            # host), then fuse ravel/cast/concat in one jitted program.
            leaves = jax.device_put(
                jax.tree.leaves(value),
                jax.sharding.SingleDeviceSharding(self._local_device),
            )
            key = ("pack", sig)
            if key not in self._jit_cache:
                self._jit_cache[key] = jax.jit(
                    lambda ls: layout.pack_leaves(0, ls)
                )
            vecs = self._jit_cache[key](leaves)
        else:
            vecs = tuple(jnp.zeros(layout.lengths[dt], dt)
                         for dt in layout.dtypes)
        totals = tuple(
            self._reduce_device(v, layout.lengths[dt], (src, dst), "sum", dt)
            for v, dt in zip(vecs, layout.dtypes)
        )
        if self.process_index == src:
            return None
        key = ("unpack", sig)
        if key not in self._jit_cache:
            self._jit_cache[key] = jax.jit(
                lambda vs: jax.tree.leaves(layout.unpack(vs, 0))
            )
        return jax.tree.unflatten(struct, self._jit_cache[key](totals))


# ---------------------------------------------------------------------- #
# Flat layouts for layer-keyed pytrees.


class TypedFlatLayout:
    """Native-dtype flat layout for a {layer_index: pytree} mapping: ONE
    flat vector per distinct leaf dtype (bf16 leaves ride a bf16 vector,
    f32 an f32 one — no f32 widening on the wire).
    Derived from abstract shapes only, so every process computes the
    identical layout without communicating. Non-arithmetic leaves (bool)
    map to an int32 wire lane and cast back on unpack.

    The reference keeps native dtypes trivially — NCCL allreduces each
    tensor in place (engine.py:404-412); this is the packed-wire
    equivalent for the flat process-mesh collectives."""

    _WIRE = {np.dtype(np.bool_): np.dtype(np.int32)}

    def __init__(self, avals_by_layer: dict[int, Any]):
        self.layers = sorted(avals_by_layer)
        self.structs: dict[int, Any] = {}
        # li -> [(shape, dtype, wire_dtype, offset_in_wire_vec, size)]
        self.leaf_metas: dict[int, list] = {}
        lengths: dict[Any, int] = {}
        for li in self.layers:
            leaves, struct = jax.tree.flatten(avals_by_layer[li])
            metas = []
            for l in leaves:
                dt = np.dtype(l.dtype)
                wdt = self._WIRE.get(dt, dt)
                n = int(np.prod(l.shape)) if l.shape else 1
                off = lengths.get(wdt, 0)
                metas.append((tuple(l.shape), l.dtype, wdt, off, n))
                lengths[wdt] = off + n
            self.structs[li] = struct
            self.leaf_metas[li] = metas
        self.dtypes = tuple(sorted(lengths, key=lambda d: d.name))
        self.lengths = lengths

    @property
    def wire_bytes(self) -> int:
        """Bytes one process's full contribution occupies on the wire."""
        return sum(n * dt.itemsize for dt, n in self.lengths.items())

    def pack_leaves(self, li: int, leaves: list):
        """Trace-pure: layer li's leaves -> per-dtype flat vectors (tuple
        aligned with self.dtypes). Leaves must be full layers in layout
        order; partial packing is not supported (offsets are cumulative)."""
        segs: dict[Any, list] = {dt: [] for dt in self.dtypes}
        for leaf, (shape, dtype, wdt, off, n) in zip(
            leaves, self.leaf_metas[li], strict=True
        ):
            segs[wdt].append(jnp.ravel(leaf).astype(wdt))
        return tuple(
            jnp.concatenate(segs[dt]) if segs[dt]
            else jnp.zeros(0, dt)
            for dt in self.dtypes
        )

    def unpack(self, vecs, li: int):
        """Layer li's tree out of per-dtype flat vectors (tuple aligned
        with self.dtypes). Trace-pure (works on numpy and under jit)."""
        by_dt = dict(zip(self.dtypes, vecs, strict=True))
        leaves = []
        for shape, dtype, wdt, off, n in self.leaf_metas[li]:
            leaves.append(
                by_dt[wdt][off:off + n].reshape(shape).astype(dtype)
            )
        return jax.tree.unflatten(self.structs[li], leaves)

    def pack_into(self, bufs: dict, li: int, tree) -> None:
        """Host-side: write layer li's leaves into per-dtype numpy buffers
        (keyed by wire dtype, sized self.lengths). Winner-unique packing —
        assignment, not accumulation."""
        for leaf, (shape, dtype, wdt, off, n) in zip(
            jax.tree.leaves(tree), self.leaf_metas[li], strict=True
        ):
            bufs[wdt][off:off + n] = np.asarray(
                jax.device_get(leaf)
            ).ravel().astype(wdt)


def layer_avals(model) -> dict[int, Any]:
    """Abstract param trees per pipeline layer (no device use)."""
    rng = jax.random.PRNGKey(0)
    return {
        li: jax.eval_shape(lambda r, _li=li: model.init_layer(r, _li), rng)
        for li in range(model.num_pipeline_layers)
    }


def activation_avals(model, microbatch_size: int, seq_len: int) -> list:
    """Abstract activation (carry) aval AFTER each non-final layer, chained
    through jax.eval_shape — the static shape contract for cross-host
    stage-to-stage transfers (no metadata handshake, unlike the reference's
    first-transfer header protocol, pipeline.py:288-333)."""
    avals = layer_avals(model)
    batch = jax.tree.map(
        lambda a: jax.ShapeDtypeStruct(np.shape(a), np.asarray(a).dtype),
        model.sample_batch(microbatch_size, seq_len),
    )
    out: list = []

    def step(li, carry):
        return jax.eval_shape(
            lambda p, c, b: model.apply_layer(li, p, c, b),
            avals[li], carry, batch,
        )

    carry = None
    for li in range(model.num_pipeline_layers - 1):
        carry = step(li, carry)
        out.append(carry)
    return out
