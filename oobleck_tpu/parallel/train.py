"""The fused SPMD train step: pipeline + tensor + fsdp + data parallelism in
one jitted program built from three full-manual shard_map phases.

TPU-native replacement for the reference's hot loop
(/root/reference/oobleck/execution/pipeline.py:458-487 — a Python interpreter
dispatching per-instruction NCCL ops): here the whole schedule is *compiled*.

  Phase A  embed: vocab-parallel lookup, microbatches sharded over `stage`
           (every device embeds a distinct slice — no redundant work).
  Phase B  pipeline: circular collective-permute schedule over `stage` —
           each tick, stage 0 ingests a microbatch, every stage applies its
           block slice (Megatron-TP + fsdp gathers inside), `lax.ppermute`
           shifts activations to the next stage. XLA differentiates through
           the permute, so the backward pipeline comes from `jax.grad`, with
           `jax.checkpoint` standing in for 1F1B's memory discipline.
  Phase C  head/loss: vocab-parallel cross-entropy, microbatches again
           sharded over `stage` so the lm-head matmul uses all devices.

Design rules learned the hard way (enforced throughout):
  * every mesh axis is manual — no GSPMD/auto axes inside shard_map;
  * collectives are issued unconditionally and identically on all devices —
    never inside a `lax.cond` on a device-varying predicate (XLA matches
    collectives by program position; divergence deadlocks the rendezvous);
  * gradient cross-device reductions are not hand-written on the DEFAULT
    path: they fall out of the shard_map in_spec transposes (replicated
    input -> psum of cotangents, all_gather -> psum_scatter), which is
    exactly the DP/fsdp/TP grad sync the reference builds NCCL process-group
    grids for (engine.py:363-412).
  * the OVERLAP path (build_train_step(..., overlap=OverlapConfig(enabled=
    True))) inverts that last rule: the whole step is ONE check_rep=False
    shard_map with value_and_grad INSIDE and the grad sync written out —
    bucketed ppermute rings over the data axis, psums over the other
    non-spec axes, Megatron f / identity-backward g inside the model
    (ShardCtx.explicit_bwd) for the tensor axis — so collectives can be
    bucketed, interleaved, and latency-hidden behind compute. See
    parallel/overlap.py.
"""

from __future__ import annotations

from dataclasses import replace as _dc_replace
from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from oobleck_tpu.models.gpt import ShardCtx
from oobleck_tpu.parallel import overlap as ovl
from oobleck_tpu.parallel.collectives import pvary_to
from oobleck_tpu.parallel.mesh import (
    ALL_AXES,
    AXIS_DATA,
    AXIS_FSDP,
    AXIS_SEQ,
    AXIS_STAGE,
    AXIS_TENSOR,
)


class TrainState(NamedTuple):
    params: Any
    opt_state: Any
    step: jax.Array


class StepMetrics(NamedTuple):
    loss: jax.Array
    grad_norm: jax.Array


def make_optimizer(
    *,
    learning_rate: float = 1e-4,
    warmup_steps: int = 10,
    weight_decay: float = 0.01,
    max_grad_norm: float = 1.0,
) -> optax.GradientTransformation:
    """AdamW + linear-warmup LR + global-norm clipping.

    Matches the reference's optimizer stack (fused AdamW + WarmupLR,
    /root/reference/oobleck/execution/pipeline.py:117-127) with clipping
    added (reference leaves grads unclipped).
    """
    def schedule(step):
        return learning_rate * jnp.minimum(1.0, (step + 1) / max(warmup_steps, 1))

    return optax.chain(
        optax.clip_by_global_norm(max_grad_norm),
        optax.adamw(schedule, b1=0.9, b2=0.999, weight_decay=weight_decay),
    )


def state_partition_specs(model, optimizer) -> TrainState:
    """PartitionSpec pytree for the full TrainState (params + opt mirrors)."""
    param_specs = model.param_specs(stacked=True)
    params_shape = jax.eval_shape(lambda: model.init_params(jax.random.PRNGKey(0)))
    opt_shape = jax.eval_shape(optimizer.init, params_shape)
    opt_specs = optax.tree_map_params(
        optimizer,
        lambda _leaf, spec: spec,
        opt_shape,
        param_specs,
        transform_non_params=lambda _leaf: P(),
        is_leaf=lambda x: isinstance(x, P),
    )
    return TrainState(params=param_specs, opt_state=opt_specs, step=P())


def _to_shardings(mesh, spec_tree):
    return jax.tree.map(
        lambda spec: NamedSharding(mesh, spec),
        spec_tree,
        is_leaf=lambda x: isinstance(x, P),
    )


def shift_targets(tokens_mb: np.ndarray) -> np.ndarray:
    """Next-token targets for [num_mb, mb, seq] tokens, shifted on the host.

    The shift must see the GLOBAL sequence (targets[t] = token[t+1] crosses
    seq-shard boundaries), so it happens here on the unsharded numpy batch
    rather than inside the jitted step — see the note in loss_fn."""
    return np.concatenate(
        [tokens_mb[:, :, 1:], np.zeros_like(tokens_mb[:, :, :1])], axis=-1
    )


def count_params(model) -> int:
    """Total parameter count via eval_shape (no device allocation)."""
    shapes = jax.eval_shape(lambda: model.init_params(jax.random.PRNGKey(0)))
    return sum(int(np.prod(l.shape)) for l in jax.tree.leaves(shapes))


def estimate_flops_per_token(n_params: int, seq_len: int, *,
                             num_layers: int = 0,
                             hidden_size: int = 0) -> float:
    """Training FLOPs per token: 6N for the matmuls (fwd+bwd) plus the
    causal-attention term. Shared by bench.py's headline MFU and the
    engine's per-step MFU gauge so the two can never diverge."""
    return 6.0 * n_params + 6.0 * (num_layers * hidden_size * seq_len)


def peak_flops(device_kind: str) -> float | None:
    """Peak bf16 FLOP/s per chip by TPU generation (public specs);
    None for unknown kinds (CPU, GPU) — MFU is then unreported."""
    kind = device_kind.lower()
    for tag, peak in (("v5 lite", 197e12), ("v5e", 197e12),
                      ("v5p", 459e12), ("v6", 918e12), ("v4", 275e12)):
        if tag in kind:
            return peak
    return None


def mfu_estimate(tokens_per_sec: float, flops_per_token: float,
                 n_chips: int, peak_flops_per_chip: float | None
                 ) -> float | None:
    """Model FLOPs utilization from the planner's FLOPs model: achieved
    training FLOP/s over the fleet's peak. One definition shared by the
    engine's per-step gauge, the goodput ledger, and bench.py — so the
    MFU in /status.fleet_health and the MFU in a bench record can never
    be computed two different ways. None when peak is unknown (CPU) or
    the inputs are degenerate."""
    if (peak_flops_per_chip is None or peak_flops_per_chip <= 0
            or n_chips <= 0 or tokens_per_sec <= 0):
        return None
    return (flops_per_token * tokens_per_sec) / (
        n_chips * peak_flops_per_chip)


def _overlap_loss_and_grads(model, mesh, specs, ctx: ShardCtx, cfg,
                            *, num_mb: int, remat: bool):
    """Overlap-mode core: ONE check_rep=False shard_map over every mesh axis
    computing (loss, synced grads) with value_and_grad INSIDE.

    Boundary collectives that the three-phase default path gets from its
    in/out specs are written out: an all_gather over `stage` reconstructs
    the stage-replicated activation block after the stage-sharded embed, a
    psum over `stage` broadcasts the last stage's pipeline outputs (zeros
    elsewhere — each stage then slices its own head chunk, so the psum
    transpose correctly accumulates every stage's head cotangent), and the
    per-leaf grad sync goes through overlap.sync_grads (bucketed ppermute
    rings over data; psums over the other non-spec axes; tensor completed
    by the model's explicit_bwd f/g — see the regime note in collectives.py).
    """
    S = mesh.shape[AXIS_STAGE]
    axis_sizes = dict(mesh.shape)
    ctx_u = _dc_replace(ctx, explicit_bwd=True)
    ctx_nofsdp = _dc_replace(ctx_u, fsdp=None)
    tok_stage = P(AXIS_STAGE, (AXIS_DATA, AXIS_FSDP), AXIS_SEQ)
    chunk = num_mb // S
    block_specs_1 = ovl.unstacked_specs(specs["blocks"])
    prefetch = cfg.prefetch_fsdp and axis_sizes[AXIS_FSDP] > 1
    db_sends = cfg.double_buffer_sends and S > 1
    lead = 2 * (S - 1) if db_sends else S - 1
    n_ticks = num_mb + lead
    perm = [(i, (i + 1) % S) for i in range(S)]

    def body(params, tokens_loc, targets_loc):
        stage_idx = lax.axis_index(AXIS_STAGE)
        is_first = stage_idx == 0
        is_last = stage_idx == S - 1
        mb_local, seq_local = tokens_loc.shape[1], tokens_loc.shape[2]
        seq_global = seq_local * axis_sizes[AXIS_SEQ]
        valid = num_mb * (mb_local * axis_sizes[AXIS_DATA]
                          * axis_sizes[AXIS_FSDP]) * (seq_global - 1)
        # Local shard of the next-token mask (last global position invalid).
        pos = lax.axis_index(AXIS_SEQ) * seq_local + jnp.arange(seq_local)
        mask_loc = jnp.broadcast_to(
            (pos < seq_global - 1).astype(jnp.float32), tokens_loc.shape)

        def apply_stage(blocks_local, h):
            if prefetch:
                return ovl.prefetched_block_scan(
                    lambda bp, hh: model.apply_block(bp, hh, ctx_nofsdp),
                    lambda bp: ovl.fsdp_gather_block(
                        bp, block_specs_1, AXIS_FSDP),
                    blocks_local, h, model.config.num_layers // S)

            def bodyb(h, bp):
                return model.apply_block(bp, h, ctx_u), None

            h, _ = lax.scan(bodyb, h, blocks_local)
            return h

        def local_loss(params):
            x_loc = model.embed(params["embed"], tokens_loc, ctx_u)
            x = (lax.all_gather(x_loc, AXIS_STAGE, axis=0, tiled=True)
                 if S > 1 else x_loc)
            blocks_local = params["blocks"]

            def tick_plain(carry, t):
                state, outputs = carry
                inp = lax.dynamic_index_in_dim(
                    x, jnp.minimum(t, num_mb - 1), 0, keepdims=False)
                cur = jnp.where(is_first, inp, state)
                out = apply_stage(blocks_local, cur)
                out_idx = t - lead
                upd = lax.dynamic_update_index_in_dim(
                    outputs, out, jnp.maximum(out_idx, 0), 0)
                outputs = jnp.where(is_last & (out_idx >= 0), upd, outputs)
                state = lax.ppermute(out, AXIS_STAGE, perm)
                return (state, outputs), None

            def tick_db(carry, t):
                # The ppermute issued at tick t is consumed at tick t+2:
                # microbatch m reaches stage s at tick m + 2s, and the send
                # of m rides under the compute of m+1 (one extra in-flight
                # buffer, S-1 extra warmup ticks).
                ready, in_flight, outputs = carry
                inp = lax.dynamic_index_in_dim(
                    x, jnp.minimum(t, num_mb - 1), 0, keepdims=False)
                cur = jnp.where(is_first, inp, ready)
                out = apply_stage(blocks_local, cur)
                out_idx = t - lead
                upd = lax.dynamic_update_index_in_dim(
                    outputs, out, jnp.maximum(out_idx, 0), 0)
                outputs = jnp.where(is_last & (out_idx >= 0), upd, outputs)
                return (in_flight, lax.ppermute(out, AXIS_STAGE, perm),
                        outputs), None

            tick_fn = tick_db if db_sends else tick_plain
            tick = jax.checkpoint(tick_fn) if remat else tick_fn
            zero = jnp.zeros_like(x[0])
            init = ((zero, zero, jnp.zeros_like(x)) if db_sends
                    else (zero, jnp.zeros_like(x)))
            carry, _ = lax.scan(tick, init, jnp.arange(n_ticks))
            outputs = carry[-1]
            ys = lax.psum(outputs, AXIS_STAGE) if S > 1 else outputs
            ys_chunk = lax.dynamic_slice_in_dim(
                ys, stage_idx * chunk, chunk, axis=0)
            loss_sum = model.head_loss_shifted(
                params["head"], ys_chunk, targets_loc, mask_loc, ctx_u)
            return loss_sum / valid

        loss_local, grads = jax.value_and_grad(local_loss)(params)
        grads = ovl.sync_grads(
            grads, specs, axis_sizes,
            data_impl=cfg.grad_sync, bucket_bytes=cfg.bucket_bytes)
        loss = lax.psum(
            loss_local, (AXIS_STAGE, AXIS_DATA, AXIS_FSDP, AXIS_SEQ))
        return loss, grads

    return jax.shard_map(
        body, mesh=mesh, in_specs=(specs, tok_stage, tok_stage),
        out_specs=(P(), specs), axis_names=set(ALL_AXES), check_vma=False,
    )


def build_train_step(model, mesh, *, num_microbatches: int, optimizer=None,
                     remat: bool | None = None,
                     overlap: "ovl.OverlapConfig | None" = None):
    """Build (init_fn, step_fn) for the fused SPMD path.

    init_fn(rng) -> TrainState, sharded over `mesh`.
    step_fn(state, tokens) -> (TrainState, StepMetrics); tokens [batch, seq]
    with batch = num_microbatches * microbatch_size (microbatch split is
    internal). Fully jit-compiled, state donated.

    overlap: an enabled OverlapConfig switches grad computation to the
    explicit-collective overlap path (see _overlap_loss_and_grads);
    None/disabled keeps the default three-phase path unchanged.
    """
    if optimizer is None:
        optimizer = make_optimizer()
    if remat is None:
        remat = model.config.remat
    S = mesh.shape[AXIS_STAGE]
    tp = mesh.shape[AXIS_TENSOR]
    sp = mesh.shape[AXIS_SEQ]
    num_mb = num_microbatches
    if model.config.num_layers % S != 0:
        raise ValueError(
            f"num_layers={model.config.num_layers} not divisible by stage={S}"
        )
    if model.config.num_heads % tp != 0:
        raise ValueError(
            f"num_heads={model.config.num_heads} not divisible by tensor={tp}"
        )
    if num_mb % S != 0:
        raise ValueError(
            f"num_microbatches={num_mb} not divisible by stage={S}: the embed "
            "and head phases shard microbatches over the stage axis"
        )
    ctx = ShardCtx(tensor=AXIS_TENSOR, fsdp=AXIS_FSDP,
                   seq=AXIS_SEQ if sp > 1 else None)
    specs = model.param_specs(stacked=True)
    batch_shards = mesh.shape[AXIS_DATA] * mesh.shape[AXIS_FSDP]

    # Batch layouts: microbatch index over `stage` (phases A/C) or replicated
    # (phase B input); sample dim over (data, fsdp) and sequence dim over
    # `seq` (ring attention) everywhere.
    tok_stage = P(AXIS_STAGE, (AXIS_DATA, AXIS_FSDP), AXIS_SEQ)
    x_stage = P(AXIS_STAGE, (AXIS_DATA, AXIS_FSDP), AXIS_SEQ, None)
    x_repl = P(None, (AXIS_DATA, AXIS_FSDP), AXIS_SEQ, None)

    def embed_fn(embed_params, tokens_loc):
        return model.embed(embed_params, tokens_loc, ctx)

    def pipeline_fn(blocks_local, x):
        """Circular pipeline over the stage axis. x: [num_mb, mb, seq, E]
        (stage-replicated); returns [1, num_mb, mb, seq, E] whose global
        stage-stacked form is sliced at S-1 by the caller."""
        stage_idx = lax.axis_index(AXIS_STAGE)
        is_first = stage_idx == 0
        is_last = stage_idx == S - 1
        perm = [(i, (i + 1) % S) for i in range(S)]

        def apply_stage(h):
            def body(h, bp):
                return model.apply_block(bp, h, ctx), None

            h, _ = lax.scan(body, h, blocks_local)
            return h

        def tick_fn(carry, t):
            state, outputs = carry
            inp = lax.dynamic_index_in_dim(
                x, jnp.minimum(t, num_mb - 1), 0, keepdims=False
            )
            cur = jnp.where(is_first, inp, state)
            out = apply_stage(cur)
            out_idx = t - (S - 1)
            upd = lax.dynamic_update_index_in_dim(
                outputs, out, jnp.maximum(out_idx, 0), 0
            )
            outputs = jnp.where(is_last & (out_idx >= 0), upd, outputs)
            state = lax.ppermute(out, AXIS_STAGE, perm)
            return (state, outputs), None

        tick = jax.checkpoint(tick_fn) if remat else tick_fn
        vary = (AXIS_DATA, AXIS_FSDP, AXIS_STAGE)
        state0 = pvary_to(jnp.zeros_like(x[0]), vary)
        outputs0 = pvary_to(jnp.zeros_like(x), vary)
        (_, outputs), _ = lax.scan(
            tick, (state0, outputs0), jnp.arange(num_mb + S - 1)
        )
        return outputs[None]

    def head_fn(head_params, ys_loc, targets_loc, mask_loc):
        # Pre-shifted targets: the next-token shift crosses seq-shard
        # boundaries, so the caller shifts globally (see wrapped_step).
        loss_sum = model.head_loss_shifted(
            head_params, ys_loc, targets_loc, mask_loc, ctx
        )
        return lax.psum(loss_sum, (AXIS_STAGE, AXIS_DATA, AXIS_FSDP, AXIS_SEQ))

    embed_sm = jax.shard_map(
        embed_fn, mesh=mesh, in_specs=(specs["embed"], tok_stage),
        out_specs=x_stage, axis_names=set(ALL_AXES),
    )
    pipe_sm = jax.shard_map(
        pipeline_fn, mesh=mesh, in_specs=(specs["blocks"], x_repl),
        out_specs=P(AXIS_STAGE, None, (AXIS_DATA, AXIS_FSDP), AXIS_SEQ, None),
        axis_names=set(ALL_AXES),
    )
    head_sm = jax.shard_map(
        head_fn, mesh=mesh,
        in_specs=(specs["head"], x_stage, tok_stage, tok_stage),
        out_specs=P(), axis_names=set(ALL_AXES),
    )

    def loss_fn(params, tokens_mb, targets_mb):
        # targets_mb is the globally next-token-shifted copy of tokens_mb,
        # computed on the HOST (see _shift_targets).  Computing the shift
        # inside jit looks equivalent — tokens are still logically global —
        # but when the shifted array then feeds a shard_map in_spec that
        # shards the sequence dim, the GSPMD partitioner on older jax
        # (0.4.x) shifts each seq shard locally without the cross-shard
        # halo exchange, silently corrupting the target at every shard
        # boundary.  The host shift is equally global and version-proof.
        seq = tokens_mb.shape[2]
        mask_mb = jnp.broadcast_to(
            (jnp.arange(seq) < seq - 1).astype(jnp.float32), tokens_mb.shape
        )
        x = embed_sm(params["embed"], tokens_mb)
        ys = pipe_sm(params["blocks"], x)[S - 1]
        loss_sum = head_sm(params["head"], ys, targets_mb, mask_mb)
        valid = num_mb * tokens_mb.shape[1] * (seq - 1)
        return loss_sum / valid

    overlap = overlap if (overlap is not None and overlap.enabled) else None
    if overlap is not None:
        ovl_sm = _overlap_loss_and_grads(
            model, mesh, specs, ctx, overlap, num_mb=num_mb, remat=remat)

        def loss_and_grads(params, tokens_mb, targets_mb):
            return ovl_sm(params, tokens_mb, targets_mb)
    else:
        def loss_and_grads(params, tokens_mb, targets_mb):
            return jax.value_and_grad(loss_fn)(params, tokens_mb, targets_mb)

    def step_fn(state: TrainState, tokens_mb, targets_mb):
        loss, grads = loss_and_grads(state.params, tokens_mb, targets_mb)
        updates, new_opt = optimizer.update(grads, state.opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)
        metrics = StepMetrics(loss=loss, grad_norm=optax.global_norm(grads))
        return TrainState(new_params, new_opt, state.step + 1), metrics

    def init_fn(rng):
        params = model.init_params(rng)
        return TrainState(params, optimizer.init(params), jnp.zeros((), jnp.int32))

    state_specs = state_partition_specs(model, optimizer)
    state_shardings = _to_shardings(mesh, state_specs)
    token_sharding = NamedSharding(mesh, P(None, (AXIS_DATA, AXIS_FSDP), AXIS_SEQ))

    jit_init = jax.jit(init_fn, out_shardings=state_shardings)
    jit_step = jax.jit(
        step_fn,
        in_shardings=(state_shardings, token_sharding, token_sharding),
        out_shardings=(state_shardings, None),
        donate_argnums=(0,),
    )

    def _global_arrays(*host_arrays):
        if jax.process_count() > 1:
            # Multi-process SPMD: every host computes the same global batch
            # (same dataset + sampler seed); build the global array from the
            # host-local copy — numpy inputs cannot carry non-trivial
            # shardings across processes.
            return tuple(
                jax.make_array_from_callback(
                    a.shape, token_sharding, lambda idx, a=a: a[idx]
                )
                for a in host_arrays
            )
        return host_arrays

    def prepare_tokens(tokens):
        """Everything wrapped_step does before dispatching the compiled
        program: reshape, host-side target shift, globalize, and (single
        process) an async device_put onto the token sharding. Safe to run
        on a background thread (the DeviceStager), so by the time the
        train loop calls the step the inputs are already in flight to the
        devices."""
        tokens = np.asarray(tokens)  # oobleck: allow[OBL002] -- input is host memory already
        b, seq = tokens.shape
        assert b % num_mb == 0, f"batch {b} not divisible by {num_mb} microbatches"
        assert seq % sp == 0, f"seq {seq} not divisible by seq-parallel {sp}"
        tokens_mb = tokens.reshape(num_mb, b // num_mb, seq)
        tokens_mb, targets_mb = _global_arrays(tokens_mb,
                                               shift_targets(tokens_mb))
        if jax.process_count() == 1:
            # numpy inputs would otherwise be copied host->device inside
            # the jit dispatch; device_put here starts the transfer early
            # and does not block on its completion.
            tokens_mb, targets_mb = jax.device_put(
                [tokens_mb, targets_mb], [token_sharding, token_sharding]
            )
        return tokens_mb, targets_mb

    def wrapped_step(state, tokens=None, prepared=None):
        if prepared is None:
            prepared = prepare_tokens(tokens)
        tokens_mb, targets_mb = prepared
        return jit_step(state, tokens_mb, targets_mb)

    wrapped_step.jitted = jit_step
    wrapped_step.loss_fn = loss_fn
    wrapped_step.globalize = _global_arrays
    wrapped_step.prepare = prepare_tokens
    wrapped_step.state_shardings = state_shardings
    wrapped_step.token_sharding = token_sharding
    wrapped_step.overlap = overlap
    # (loss, grads) probe for parity tests and the overlap bench — the same
    # core the step uses, without the optimizer update or donation.
    wrapped_step.loss_and_grads = jax.jit(
        loss_and_grads,
        in_shardings=(state_shardings.params, token_sharding, token_sharding),
    )
    return jit_init, wrapped_step
