"""Device mesh construction.

The mesh is the TPU equivalent of the reference's rank grid
(/root/reference/oobleck/csrc/planning/pipeline_template.h:57-84): a pipeline
template's stage→device assignment becomes the `stage` axis of a Mesh, and
FSDP/TP degrees within a stage become the `fsdp`/`tensor` axes.

Axis order is chosen so that the innermost axes (tensor, fsdp) map to
physically adjacent devices — on a real TPU slice, JAX's default device order
follows the torus coordinates, so keeping high-bandwidth collectives (TP
all-reduce, FSDP all-gather) on the fastest-varying axes rides ICI neighbor
links, while `data` (pure grad allreduce, once per step) takes the outermost.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np
import jax
from jax.sharding import Mesh

AXIS_DATA = "data"
AXIS_STAGE = "stage"
AXIS_FSDP = "fsdp"
AXIS_SEQ = "seq"
AXIS_TENSOR = "tensor"

ALL_AXES = (AXIS_DATA, AXIS_STAGE, AXIS_FSDP, AXIS_SEQ, AXIS_TENSOR)


@dataclass(frozen=True)
class MeshShape:
    data: int = 1
    stage: int = 1
    fsdp: int = 1
    seq: int = 1      # sequence/context parallelism (ring attention)
    tensor: int = 1

    @property
    def num_devices(self) -> int:
        return self.data * self.stage * self.fsdp * self.seq * self.tensor

    @classmethod
    def infer(
        cls,
        num_devices: int,
        *,
        stage: int = 1,
        tensor: int = 1,
        fsdp: int = 1,
        seq: int = 1,
        data: int = -1,
    ) -> "MeshShape":
        """Fill in data=-1 from the device count."""
        denom = stage * tensor * fsdp * seq
        if data == -1:
            if num_devices % denom != 0:
                raise ValueError(
                    f"{num_devices} devices not divisible by "
                    f"stage*tensor*fsdp*seq={denom}"
                )
            data = num_devices // denom
        shape = cls(data=data, stage=stage, fsdp=fsdp, seq=seq, tensor=tensor)
        if shape.num_devices != num_devices:
            raise ValueError(f"{shape} does not cover {num_devices} devices")
        return shape


def make_mesh(shape: MeshShape, devices: list | None = None) -> Mesh:
    """Build a Mesh with axes (data, stage, fsdp, seq, tensor) over `devices`.

    `devices` defaults to all local devices; pipelines over device *subsets*
    (heterogeneous instances) pass their own slice.
    """
    if devices is None:
        devices = jax.devices()
    if len(devices) < shape.num_devices:
        raise ValueError(f"need {shape.num_devices} devices, have {len(devices)}")
    grid = np.array(devices[: shape.num_devices]).reshape(
        shape.data, shape.stage, shape.fsdp, shape.seq, shape.tensor
    )
    return Mesh(grid, ALL_AXES)
