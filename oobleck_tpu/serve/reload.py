"""Zero-downtime checkpoint hot-reload for the serving plane.

The watcher polls the checkpoint root for a newer COMMITTED step
(`complete_step_dirs` — presence of the atomically-renamed MANIFEST.json
is the commit marker, so a torn write is invisible here by
construction), validates and assembles it off the decode path, stages
the weights to device, and posts the swap to the batcher, which applies
it between decode steps. In-flight requests are never dropped.

Quarantine-awareness: the watcher is a READ-ONLY consumer of a root a
live trainer owns. It never renames/quarantines dirs (that is the
trainer's startup job) — a dir that fails validation here is simply
skipped and retried never (the trainer's GC or quarantine will handle
it); dirs the trainer has already quarantined live under `quarantine/`
and are structurally invisible to the step-dir walk.

Chaos: the `serve_reload` barrier fires on every reload attempt —
`OOBLECK_CHAOS=delay_at=serve_reload:0.5` injects a slow reload (cold
storage, NFS stall) and `kill_at=serve_reload` a torn one.
"""

from __future__ import annotations

import logging
import threading

import jax
import numpy as np

from oobleck_tpu.ckpt import manifest as mf
from oobleck_tpu.ckpt import restore
from oobleck_tpu.utils import background, metrics
from oobleck_tpu.utils.chaos import chaos

logger = logging.getLogger("oobleck.serve")

CHAOS_BARRIER_RELOAD = "serve_reload"


def params_from_payload(model, payload: dict):
    """Checkpoint payload (either kind) -> fused host params tree.

    kind=layers assembles {0: embed, 1..L: block, L+1: head} through the
    fused path's own converter; kind=fused_stacked already IS the fused
    tree."""
    if payload.get("kind") == mf.KIND_FUSED_STACKED:
        return payload["params"]
    from oobleck_tpu.execution.fused import layers_to_params

    return layers_to_params(model, payload["params"])


def load_latest_params(root, model) -> tuple[int, object] | None:
    """Newest committed checkpoint -> (step, fused host params), or None.

    Read-only (`quarantine_bad=False`): shares step selection with the
    engine restore via ckpt.load_latest."""
    res = restore.load_latest(root, quarantine_bad=False)
    if res is None:
        return None
    step, payload = res
    return step, params_from_payload(model, payload)


def publish_params(root, model, params, *, step: int,
                   model_name: str | None = None,
                   model_args: dict | None = None) -> None:
    """Write a fused params tree as one committed checkpoint step (no
    optimizer state) — the minimal trainer->server handoff, used by the
    serve bench and tests. Training jobs publish through the engine's
    durable-state plane instead."""
    from oobleck_tpu.ckpt import DurableStatePlane
    from oobleck_tpu.execution.fused import params_to_layers

    extra: dict = {}
    if model_name:
        extra["model_name"] = model_name
    if model_args:
        extra["model_args"] = model_args
    layers = params_to_layers(model, jax.tree.map(np.asarray, params))
    plane = DurableStatePlane(root, asynchronous=False)
    try:
        plane.save(step=step, params=layers,
                   opt_state={li: [] for li in layers}, extra=extra)
    finally:
        plane.close()


class CheckpointWatcher:
    """Polls a checkpoint root and feeds newer committed steps to the
    batcher as staged weight swaps."""

    def __init__(self, root, model, engine, batcher, *,
                 poll_secs: float = 5.0, current_step: int = -1,
                 ip: str | None = None):
        self.root = root
        self.model = model
        self.engine = engine
        self.batcher = batcher
        self.poll_secs = float(poll_secs)
        self.current_step = int(current_step)
        self.ip = ip
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name="oobleck-serve-reload", daemon=True)
        reg = metrics.registry()
        self.m_failures = reg.counter(
            "oobleck_serve_reload_failures_total",
            "Reload attempts that failed validation/assembly")
        self.m_step = reg.gauge(
            "oobleck_serve_weights_step", "Checkpoint step currently served")
        if self.current_step >= 0:
            self.m_step.set(self.current_step)

    def start(self) -> "CheckpointWatcher":
        self._thread.start()
        return self

    def stop(self, timeout: float = 10.0) -> None:
        self._stop.set()
        self._thread.join(timeout)

    def _run(self) -> None:
        while not self._stop.wait(self.poll_secs):
            try:
                self.poll_once()
            except Exception:  # noqa: BLE001
                # The watcher must outlive any single bad poll: serving
                # the current weights beats dying on a reload error.
                logger.exception("reload poll failed")
                self.m_failures.inc()

    def poll_once(self) -> int | None:
        """One poll: load the newest committed step newer than what we
        serve, stage it, and post the swap. Returns the new step, or None
        when there is nothing newer (or nothing valid)."""
        steps = restore.complete_step_dirs(self.root)
        if not steps or steps[0][0] <= self.current_step:
            return None
        chaos().barrier(CHAOS_BARRIER_RELOAD, ip=self.ip)
        for step, d in steps:
            if step <= self.current_step:
                break
            try:
                payload = restore.load_step_dir(d)
            except restore.CheckpointCorrupt as e:
                # Skip, never quarantine (the trainer owns the root); the
                # next-newest complete step still wins this poll.
                logger.warning("reload: %s failed validation (%s); "
                               "keeping step %d", d.name, e,
                               self.current_step)
                self.m_failures.inc()
                continue
            params = params_from_payload(self.model, payload)
            # Staging device_puts run on the watcher thread while the
            # batcher decodes — fence them (utils/background.py) so the
            # two can't interleave inside the XLA runtime.
            with background.device_work("serve_stage"):
                staged = self.engine.stage_params(params)
            self.batcher.post_swap(step, staged)
            self.current_step = step
            self.m_step.set(step)
            logger.info("reload: staged step %d for swap", step)
            return step
        return None
