"""Serving microbenchmark: open-loop load, paged-KV capacity, reload pause.

Two measurements, both CPU-friendly on a tiny model:

  1. Full-plane open-loop load: stands up the serving plane (checkpoint
     root -> PagedDecodeEngine -> batcher -> HTTP), drives /v1/generate
     with Poisson arrivals, mixed prompt lengths, and a shared-prefix
     fraction, triggers one hot-reload mid-traffic, and reports
     tokens/sec, TTFT p50/p99, reload pause vs full restore, and the
     prefix cache hit rate the shared-prefix mix earned.

  2. Equal-HBM capacity probe: a dense slot cache and a paged pool of the
     SAME byte budget (slots * max_seq tokens == num_pages * page_size
     tokens) each take a burst of short requests; the peak concurrent
     in-flight count after one admission pass is what that budget
     sustains. Dense reserves max_seq per request, paged reserves the
     request's true span — the gap is the paged-KV claim, reported as
     `concurrent_requests_sustained` and `kv_bytes_per_token`.

Standalone:  python -m oobleck_tpu.serve.bench
Embedded:    bench.py folds the result under its "serve" key.
"""

from __future__ import annotations

import json
import shutil
import tempfile
import threading
import time

import jax
import numpy as np

from oobleck_tpu.utils import metrics


def _percentiles(hist, q50=0.5, q99=0.99) -> dict:
    series = hist.series()
    merged = metrics.merge_histogram_series(series)
    if not merged:
        return {"p50": None, "p99": None}
    return {
        "p50": round(metrics.histogram_percentile(merged, q50) or 0.0, 6),
        "p99": round(metrics.histogram_percentile(merged, q99) or 0.0, 6),
    }


def _cache_nbytes(cache) -> int:
    return sum(int(x.nbytes) for x in jax.tree.leaves(cache))


def _burst_capacity(engine, *, n_requests: int, prompt_len: int,
                    gen_tokens: int) -> tuple[int, int]:
    """(peak concurrent in-flight, completed) for a burst of short
    requests against one engine. The batcher's scheduler thread is never
    started — `_admit`/`_decode_step` are driven directly, so the peak
    after the first admission pass is deterministic, not a sampling
    artifact."""
    from oobleck_tpu.serve.batcher import ContinuousBatcher, GenRequest

    b = ContinuousBatcher(engine, max_queue=n_requests)
    reqs = [b.submit(GenRequest(
        [1 + (i * prompt_len + j) % 97 for j in range(prompt_len)],
        max_tokens=gen_tokens)) for i in range(n_requests)]
    b._admit()
    peak = b.slots_active
    for _ in range(50 * n_requests):
        if all(r.done.is_set() for r in reqs):
            break
        b._admit()
        if b.slots_active:
            b._decode_step()
        peak = max(peak, b.slots_active)
    done = sum(1 for r in reqs if r.finish_reason == "length")
    b.stop()
    return peak, done


def measure_kv_capacity(model_name: str = "gpt2-tiny", *,
                        dense_slots: int = 2, max_seq: int = 64,
                        page_size: int = 8) -> dict:
    """Equal-HBM concurrency: dense `slots x max_seq` vs a paged pool of
    the same token count (`num_pages * page_size`), loaded with requests
    whose true span is one page (prompt 4 + 4 generated)."""
    from oobleck_tpu.models import build_model
    from oobleck_tpu.serve.engine import DecodeEngine, PagedDecodeEngine

    model = build_model(model_name, {"num_layers": 2})
    params = model.init_params(jax.random.PRNGKey(0))

    num_pages = dense_slots * max_seq // page_size   # same token budget
    span = 8                                          # 4 prompt + 4 generated
    burst = num_pages + 4                             # oversubscribe both

    dense = DecodeEngine(model, slots=dense_slots, max_seq=max_seq)
    dense.set_params(dense.stage_params(params), 1)
    dense_peak, dense_done = _burst_capacity(
        dense, n_requests=burst, prompt_len=4, gen_tokens=4)
    dense_bytes = _cache_nbytes(dense.cache)

    paged = PagedDecodeEngine(model, lanes=num_pages - 1, max_seq=max_seq,
                              page_size=page_size, num_pages=num_pages)
    paged.set_params(paged.stage_params(params), 1)
    paged_peak, paged_done = _burst_capacity(
        paged, n_requests=burst, prompt_len=4, gen_tokens=4)
    paged_bytes = _cache_nbytes(paged.cache)

    return {
        "budget_tokens": dense_slots * max_seq,
        "request_span_tokens": span,
        "burst_requests": burst,
        "completed_dense": dense_done,
        "completed_paged": paged_done,
        # Peak concurrent in-flight requests the budget sustains.
        "concurrent_requests_sustained": paged_peak,
        "concurrent_requests_sustained_dense": dense_peak,
        "concurrency_gain": round(paged_peak / max(dense_peak, 1), 2),
        # Cache HBM per concurrently-LIVE token at that peak: dense pays
        # for max_seq reservations, paged for true spans.
        "kv_bytes_per_token": round(paged_bytes / (paged_peak * span), 1),
        "kv_bytes_per_token_dense": round(
            dense_bytes / (max(dense_peak, 1) * span), 1),
    }


def _open_loop(port: int, *, n_requests: int, rate_hz: float,
               shared_frac: float, gen_tokens: int, seed: int = 0) -> dict:
    """Open-loop Poisson arrivals against /v1/generate: each request fires
    at its arrival time regardless of completions (no closed-loop
    self-throttling). A `shared_frac` fraction of prompts opens with a
    fixed 20-token head (> one 16-token page) so the prefix cache has
    something to earn; lengths are otherwise mixed."""
    import http.client

    rng = np.random.default_rng(seed)
    shared_head = [7 + i for i in range(20)]
    outcomes: list[int] = []

    def one_request(tokens: list[int]) -> None:
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=60)
        body = json.dumps({"tokens": tokens, "max_tokens": gen_tokens})
        conn.request("POST", "/v1/generate", body,
                     {"Content-Type": "application/json"})
        resp = conn.getresponse()
        out = json.loads(resp.read())
        conn.close()
        if resp.status != 200:
            raise RuntimeError(f"generate failed: {resp.status} {out}")
        outcomes.append(len(out["tokens"]))

    threads = []
    for _ in range(n_requests):
        if rng.random() < shared_frac:
            tail = [int(t) for t in rng.integers(1, 90, rng.integers(2, 9))]
            tokens = shared_head + tail
        else:
            tokens = [int(t) for t in rng.integers(1, 90, rng.integers(4, 25))]
        t = threading.Thread(target=one_request, args=(tokens,))
        t.start()
        threads.append(t)
        time.sleep(float(rng.exponential(1.0 / rate_hz)))
    return {"threads": threads, "outcomes": outcomes}


def measure_serve(root: str | None = None, *, model_name: str = "gpt2-tiny",
                  slots: int = 2, max_seq: int = 64, requests: int = 12,
                  gen_tokens: int = 12, shared_frac: float = 0.5,
                  rate_hz: float = 40.0) -> dict:
    """End-to-end serve numbers on a tiny model (CPU-friendly)."""
    from oobleck_tpu.models import build_model
    from oobleck_tpu.serve import (
        ServeArguments,
        ServingPlane,
        load_latest_params,
        publish_params,
    )

    tmp = root or tempfile.mkdtemp(prefix="oobleck_serve_bench_")
    plane = None
    try:
        model = build_model(model_name, {"num_layers": 2})
        params = model.init_params(jax.random.PRNGKey(0))
        publish_params(tmp, model, params, step=1, model_name=model_name)

        # The comparison baseline: one full restore (validate + assemble)
        # of the same checkpoint — what a swap WOULD cost if the server
        # reloaded synchronously on the decode path.
        t0 = time.perf_counter()
        load_latest_params(tmp, model)
        restore_s = time.perf_counter() - t0

        # Pool sized with headroom so the prefix-hit measurement reflects
        # the cache, not allocation churn evicting the shared head.
        plane = ServingPlane(
            tmp, model=model,
            args=ServeArguments(port=0, slots=slots, max_seq=max_seq,
                                reload_secs=0.1, page_size=16, kv_pages=32,
                                lanes=8)).start()
        port = plane.server.port
        eng = plane.engine
        hits0 = eng.m_prefix_hits.value() if hasattr(eng, "m_prefix_hits") \
            else None
        cached0 = eng.m_cached_tokens.value() if hits0 is not None else None

        t0 = time.perf_counter()
        load = _open_loop(port, n_requests=requests, rate_hz=rate_hz,
                          shared_frac=shared_frac, gen_tokens=gen_tokens)
        # Trigger a hot-reload mid-traffic.
        publish_params(tmp, model, params, step=2, model_name=model_name)
        for t in load["threads"]:
            t.join()
        wall = time.perf_counter() - t0
        counts = load["outcomes"]
        deadline = time.monotonic() + 30
        while plane.batcher.m_reloads.value() < 1 \
                and time.monotonic() < deadline:
            time.sleep(0.02)

        b = plane.batcher
        out = {
            "model": model_name,
            "kv_cache": plane.args.kv_cache,
            "slots": slots,
            "requests": requests,
            "shared_prefix_frac": shared_frac,
            "tokens": int(sum(counts)),
            "tokens_per_sec": round(sum(counts) / max(wall, 1e-9), 2),
            "ttft_s": _percentiles(b.m_ttft),
            "token_latency_s": _percentiles(b.m_step),
            "reloads": int(b.m_reloads.value()),
            "reload_pause_s": _percentiles(b.m_reload_pause),
            "full_restore_s": round(restore_s, 6),
        }
        if hits0 is not None:
            done = max(len(counts), 1)
            out["prefix_hit_rate"] = round(
                (eng.m_prefix_hits.value() - hits0) / done, 4)
            out["prefix_cached_tokens"] = int(
                eng.m_cached_tokens.value() - cached0)
        pause_p99 = out["reload_pause_s"]["p99"]
        if pause_p99 is not None and restore_s > 0:
            out["reload_pause_vs_restore"] = round(pause_p99 / restore_s, 4)
        out.update(measure_kv_capacity(model_name))
        return out
    finally:
        if plane is not None:
            plane.stop()
        if root is None:
            shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    print(json.dumps(measure_serve(), indent=2))
