"""Serving microbenchmark: tokens/sec, TTFT, and hot-reload pause.

Stands up the full serving plane (checkpoint root -> DecodeEngine ->
batcher -> HTTP) on a tiny model, drives concurrent /v1/generate
requests, triggers one hot-reload mid-traffic, and reports:

  * tokens/sec and TTFT p50/p99 from the registry histograms,
  * reload pause p99 (the decode-loop stall taken to swap weights)
    against a full checkpoint-restore latency — the zero-downtime claim
    is that the pause is the pointer swap, not the restore.

Standalone:  python -m oobleck_tpu.serve.bench
Embedded:    bench.py folds the result under its "serve" key.
"""

from __future__ import annotations

import json
import shutil
import tempfile
import threading
import time

import jax

from oobleck_tpu.utils import metrics


def _percentiles(hist, q50=0.5, q99=0.99) -> dict:
    series = hist.series()
    merged = metrics.merge_histogram_series(series)
    if not merged:
        return {"p50": None, "p99": None}
    return {
        "p50": round(metrics.histogram_percentile(merged, q50) or 0.0, 6),
        "p99": round(metrics.histogram_percentile(merged, q99) or 0.0, 6),
    }


def measure_serve(root: str | None = None, *, model_name: str = "gpt2-tiny",
                  slots: int = 2, max_seq: int = 64, requests: int = 8,
                  gen_tokens: int = 12) -> dict:
    """End-to-end serve numbers on a tiny model (CPU-friendly)."""
    import http.client

    from oobleck_tpu.models import build_model
    from oobleck_tpu.serve import (
        ServeArguments,
        ServingPlane,
        load_latest_params,
        publish_params,
    )

    tmp = root or tempfile.mkdtemp(prefix="oobleck_serve_bench_")
    plane = None
    try:
        model = build_model(model_name, {"num_layers": 2})
        params = model.init_params(jax.random.PRNGKey(0))
        publish_params(tmp, model, params, step=1, model_name=model_name)

        # The comparison baseline: one full restore (validate + assemble)
        # of the same checkpoint — what a swap WOULD cost if the server
        # reloaded synchronously on the decode path.
        t0 = time.perf_counter()
        load_latest_params(tmp, model)
        restore_s = time.perf_counter() - t0

        plane = ServingPlane(
            tmp, model=model,
            args=ServeArguments(port=0, slots=slots, max_seq=max_seq,
                                reload_secs=0.1)).start()
        port = plane.server.port

        def one_request(prompt_len: int) -> int:
            conn = http.client.HTTPConnection("127.0.0.1", port, timeout=60)
            body = json.dumps({
                "tokens": list(range(1, prompt_len + 1)),
                "max_tokens": gen_tokens,
            })
            conn.request("POST", "/v1/generate", body,
                         {"Content-Type": "application/json"})
            resp = conn.getresponse()
            out = json.loads(resp.read())
            conn.close()
            if resp.status != 200:
                raise RuntimeError(f"generate failed: {resp.status} {out}")
            return len(out["tokens"])

        t0 = time.perf_counter()
        counts: list[int] = []
        threads = [threading.Thread(
            target=lambda i=i: counts.append(one_request(4 + (i % 5))))
            for i in range(requests)]
        for t in threads:
            t.start()
        # Trigger a hot-reload mid-traffic.
        publish_params(tmp, model, params, step=2, model_name=model_name)
        for t in threads:
            t.join()
        wall = time.perf_counter() - t0
        deadline = time.monotonic() + 30
        while plane.batcher.m_reloads.value() < 1 \
                and time.monotonic() < deadline:
            time.sleep(0.02)

        b = plane.batcher
        out = {
            "model": model_name,
            "slots": slots,
            "requests": requests,
            "tokens": int(sum(counts)),
            "tokens_per_sec": round(sum(counts) / max(wall, 1e-9), 2),
            "ttft_s": _percentiles(b.m_ttft),
            "token_latency_s": _percentiles(b.m_step),
            "reloads": int(b.m_reloads.value()),
            "reload_pause_s": _percentiles(b.m_reload_pause),
            "full_restore_s": round(restore_s, 6),
        }
        pause_p99 = out["reload_pause_s"]["p99"]
        if pause_p99 is not None and restore_s > 0:
            out["reload_pause_vs_restore"] = round(pause_p99 / restore_s, 4)
        return out
    finally:
        if plane is not None:
            plane.stop()
        if root is None:
            shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    print(json.dumps(measure_serve(), indent=2))
