"""Block/paged KV allocation for the serving plane.

Host-side bookkeeping for the device page pool (`[L, N_pages, Hkv, page,
D]`, see ops/paged_attention.py): a free list of fixed-size pages,
per-page refcounts, and per-request block tables. HBM per request is
`ceil((prompt + max_tokens) / page)` pages instead of a dense max_seq
slot, so concurrency is bounded by TOTAL live tokens, not request count.

Prefix caching: every FULL page a prompt fills is registered under the
rolling hash of the token chain it closes (h_i = hash(h_{i-1}, page_i's
tokens) — position-dependent by construction, so equal page content at
different depths never collides). A later prompt sharing that head walks
the chain, pins the matched pages (refcount++), and skips prefill
compute for them. Freed pages KEEP their registration until the free
list hands them out again (FIFO ≈ LRU eviction), so a popular prefix
survives its first requester.

Copy-on-write: shared pages are read-only; `make_writable` gives a
request a private copy of a page it must write (the device-side copy is
the caller's job — the allocator only manages identity/refcounts and
reports whether a copy is needed).

Page 0 is RESERVED as the garbage page: inactive decode lanes park their
block tables on it, bucket-padding writes land on it, and it is never
allocated — so stray writes can never corrupt a live request's KV.
"""

from __future__ import annotations

from collections import OrderedDict, deque
from collections.abc import Sequence

GARBAGE_PAGE = 0


class PagesExhausted(Exception):
    """No free pages for the requested allocation; callers queue or shed."""


def pages_for(tokens: int, page_size: int) -> int:
    """Pages needed to hold `tokens` positions."""
    return -(-tokens // page_size)


def chain_hashes(tokens: Sequence[int], page_size: int) -> list[int]:
    """Rolling hash per FULL page boundary of `tokens` (h_i = hash(h_{i-1},
    page_i's tokens) — position-dependent by construction). The prefix
    cache keys pages with it; the serving router reuses the SAME chain to
    map a request's prompt head to the replica most likely to hold its
    prefix pages. Deterministic within a process for integer tokens
    (PYTHONHASHSEED only salts str/bytes)."""
    out = []
    h = 0
    for i in range(len(tokens) // page_size):
        h = hash((h, tuple(tokens[i * page_size:(i + 1) * page_size])))
        out.append(h)
    return out


class BlockAllocator:
    """Free-list page allocator with refcounts and prefix-chain cache."""

    def __init__(self, num_pages: int, page_size: int):
        if num_pages < 2:
            raise ValueError("need at least 2 pages (page 0 is reserved)")
        if page_size < 1:
            raise ValueError("page_size must be >= 1")
        self.num_pages = int(num_pages)
        self.page_size = int(page_size)
        # Insertion-ordered: oldest-freed first, so reallocating evicts
        # the least-recently-used cached prefix pages.
        self._free: OrderedDict[int, None] = OrderedDict(
            (p, None) for p in range(1, num_pages))
        self._ref = [0] * num_pages
        self._chain_to_page: dict[int, int] = {}   # chain hash -> page id
        self._page_to_chain: dict[int, int] = {}   # reverse, for eviction
        self.cow_copies = 0

    # -- introspection --------------------------------------------------- #

    @property
    def free_pages(self) -> int:
        return len(self._free)

    @property
    def pages_in_use(self) -> int:
        return (self.num_pages - 1) - len(self._free)

    def refcount(self, page: int) -> int:
        return self._ref[page]

    # -- allocation ------------------------------------------------------ #

    def can_allocate(self, n_pages: int) -> bool:
        return n_pages <= len(self._free)

    def allocate(self, n_pages: int) -> list[int]:
        """Take `n_pages` off the free list (oldest-freed first); raises
        PagesExhausted without side effects when short."""
        if n_pages > len(self._free):
            raise PagesExhausted(
                f"need {n_pages} pages, {len(self._free)} free "
                f"(pool {self.num_pages - 1})")
        out = []
        for _ in range(n_pages):
            page, _ = self._free.popitem(last=False)
            self._evict_registration(page)
            self._ref[page] = 1
            out.append(page)
        return out

    def ref(self, pages: Sequence[int]) -> None:
        for p in pages:
            if p == GARBAGE_PAGE:
                continue
            assert self._ref[p] > 0, f"ref of unowned page {p}"
            self._ref[p] += 1

    def release(self, pages: Sequence[int]) -> None:
        """Drop one reference per page; refcount 0 returns the page to the
        free list (registration kept — it may be rediscovered as a cached
        prefix until the free list recycles the page)."""
        for p in pages:
            if p == GARBAGE_PAGE:
                continue
            assert self._ref[p] > 0, f"release of unowned page {p}"
            self._ref[p] -= 1
            if self._ref[p] == 0:
                self._free[p] = None

    # -- copy-on-write --------------------------------------------------- #

    def make_writable(self, pages: list[int], idx: int) -> tuple[int, int] | None:
        """Ensure `pages[idx]` is privately owned before a write.

        Shared (refcount > 1) -> allocate a fresh page, swap it into the
        table at `idx`, drop one ref on the original, and return
        (src_page, dst_page) so the caller copies the device bytes.
        Already-private -> None (no copy needed). The fresh page is NOT
        registered in the prefix cache: its content diverges at the next
        write, and a stale registration would hand later prompts wrong
        keys."""
        src = pages[idx]
        if src == GARBAGE_PAGE or self._ref[src] <= 1:
            return None
        dst = self.allocate(1)[0]
        self._ref[src] -= 1          # shared, so never reaches 0 here
        pages[idx] = dst
        self.cow_copies += 1
        return src, dst

    def rewind_span(self, pages: list[int],
                    first_pos: int, last_pos: int) -> list[tuple[int, int]]:
        """Tail-page write-cursor rewind after speculative rejection.

        Positions [first_pos, last_pos] of this chain hold KV written for
        draft tokens the verify step rejected. The bytes themselves need
        no device work for the OWNING request — they sit past its write
        cursor, are masked by every ragged-attention length, and the next
        accepted token overwrites position first_pos — but the pages they
        landed on must never be SERVED to anyone else:

          * any prefix registration on a touched page is evicted (in the
            natural flow generated-token pages are never registered —
            register_chain covers full PROMPT pages only — so this is a
            defensive invariant, not a hot path);
          * a touched page shared with another chain (refcount > 1) is
            copied out via `make_writable`, exactly like prefill's
            defensive CoW, so the neighbor keeps the clean bytes.

        Returns the (src, dst) device copies the caller owes, in table
        order. No-op (empty list) when the span is empty."""
        out: list[tuple[int, int]] = []
        if last_pos < first_pos:
            return out
        for idx in range(first_pos // self.page_size,
                         last_pos // self.page_size + 1):
            if idx >= len(pages):
                break
            page = pages[idx]
            if page == GARBAGE_PAGE:
                continue
            self._evict_registration(page)
            moved = self.make_writable(pages, idx)
            if moved is not None:
                out.append(moved)
        return out

    # -- prefix cache ---------------------------------------------------- #

    def _chain_hashes(self, tokens: Sequence[int]) -> list[int]:
        """Rolling hash per FULL page boundary of `tokens`."""
        return chain_hashes(tokens, self.page_size)

    def _evict_registration(self, page: int) -> None:
        h = self._page_to_chain.pop(page, None)
        if h is not None and self._chain_to_page.get(h) == page:
            del self._chain_to_page[h]

    def match_prefix(self, tokens: Sequence[int]) -> tuple[list[int], int]:
        """Longest registered full-page chain covering a head of `tokens`,
        capped at len(tokens) - 1 so at least one live token always
        prefills (the tail prefill is what produces next-token logits).

        Returns (pages, cached_tokens); the matched pages are PINNED
        (refcount++ / pulled off the free list) — the caller must
        `release` them when the request finishes."""
        limit = (len(tokens) - 1) // self.page_size
        pages: list[int] = []
        for h in self._chain_hashes(tokens)[:limit]:
            page = self._chain_to_page.get(h)
            if page is None:
                break
            pages.append(page)
        for p in pages:
            if self._ref[p] == 0:
                self._free.pop(p, None)
            self._ref[p] += 1
        return pages, len(pages) * self.page_size

    def peek_prefix(self, tokens: Sequence[int]) -> int:
        """Non-mutating `match_prefix`: the cached token count a request
        would reuse, without pinning anything. Admission-capacity math."""
        limit = (len(tokens) - 1) // self.page_size
        n = 0
        for h in self._chain_hashes(tokens)[:limit]:
            if h not in self._chain_to_page:
                break
            n += 1
        return n * self.page_size

    def register_chain(self, tokens: Sequence[int], pages: Sequence[int]) -> None:
        """Register every full page of `tokens` held in `pages` (the
        request's block table, cached head included) for future prefix
        reuse. Last writer wins on hash collisions between live pages —
        both registrations are valid content, so either is safe to hand
        out."""
        for h, page in zip(self._chain_hashes(tokens), pages):
            if page == GARBAGE_PAGE:
                break
            # One registration per page: a page closing chain h holds
            # exactly the tokens hashing to h.
            self._evict_registration(page)
            self._chain_to_page[h] = page
            self._page_to_chain[page] = h
