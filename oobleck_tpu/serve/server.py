"""Stdlib HTTP front end for the serving plane.

Same dependency-free pattern as the master's /metrics endpoint
(utils/metrics.py): ThreadingHTTPServer on a daemon thread, port 0 binds
an ephemeral port (read `.port` after start).

Endpoints:
  POST /v1/generate   {"tokens": [..]} or {"prompt": ".."} (byte-level
                      stand-in tokenizer), optional "max_tokens",
                      "temperature", "deadline_ms", "eos_token".
                      -> {"tokens", "text", "finish_reason", "step",
                          "ttft_ms", "latency_ms", "trace_id"}
                      Optional "trace_id" in the body joins server-side
                      spans to the caller's trace (obs/spans).
                      429 when the admission queue is full (backpressure),
                      400 on malformed input.
  GET  /healthz       {"ok", "step", "slots_active", "queue_depth"}
  GET  /metrics       Prometheus text for this process's registry
                      (TTFT/per-token histograms, queue/slot gauges,
                      reload counters).

Run standalone against a training job's checkpoint root:

    OOBLECK_CKPT_DIR=/ckpt OOBLECK_SERVE_PORT=8000 \
        python -m oobleck_tpu.serve.server
"""

from __future__ import annotations

import json
import logging
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from oobleck_tpu.serve.batcher import GenRequest, QueueFull
from oobleck_tpu.utils import metrics

logger = logging.getLogger("oobleck.serve")


def tokens_from_body(body: dict, vocab_size: int) -> list[int]:
    """Request tokens: explicit id list, or a byte-level stand-in
    tokenization of "prompt" (this repo trains on synthetic data — a real
    deployment drops its tokenizer in here)."""
    if "tokens" in body:
        tokens = body["tokens"]
        if (not isinstance(tokens, list) or not tokens
                or not all(isinstance(t, int) and not isinstance(t, bool)
                           and 0 <= t < vocab_size for t in tokens)):
            raise ValueError(
                f"tokens must be a non-empty list of ints in [0, {vocab_size})")
        return tokens
    if "prompt" in body:
        raw = str(body["prompt"]).encode("utf-8")
        if not raw:
            raise ValueError("empty prompt")
        return [b % vocab_size for b in raw]
    raise ValueError("body needs 'tokens' or 'prompt'")


def text_from_tokens(tokens: list[int]) -> str:
    """Inverse of the byte-level stand-in (lossy for ids >= 256)."""
    return bytes(t for t in tokens if t < 256).decode("utf-8", "replace")


class ServeHTTPServer:
    """HTTP front end over a ContinuousBatcher."""

    def __init__(self, batcher, *, port: int = 0, host: str = "0.0.0.0",
                 request_timeout: float = 120.0):
        self.batcher = batcher
        self.request_timeout = request_timeout
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # keep test logs quiet
                logger.debug("serve http: " + fmt, *args)

            def _reply(self, code: int, payload: dict,
                       ctype: str = "application/json") -> None:
                body = json.dumps(payload).encode() \
                    if ctype == "application/json" else payload
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                try:
                    path = self.path.split("?")[0]
                    if path == "/healthz":
                        self._reply(200, outer._health())
                    elif path == "/metrics":
                        text = metrics.render_prometheus(
                            [metrics.registry().snapshot()]).encode()
                        self._reply(
                            200, text,
                            "text/plain; version=0.0.4; charset=utf-8")
                    else:
                        self.send_error(404)
                except Exception:  # noqa: BLE001 — endpoint must never kill the server
                    logger.exception("serve GET failed")
                    self.send_error(500)

            def do_POST(self):
                try:
                    if self.path.split("?")[0] != "/v1/generate":
                        self.send_error(404)
                        return
                    length = int(self.headers.get("Content-Length") or 0)
                    try:
                        body = json.loads(self.rfile.read(length) or b"{}")
                        if not isinstance(body, dict):
                            raise ValueError("body must be a JSON object")
                        code, payload = outer._generate(body)
                    except ValueError as e:
                        code, payload = 400, {"error": str(e)}
                    self._reply(code, payload)
                except Exception:  # noqa: BLE001 — endpoint must never kill the server
                    logger.exception("serve POST failed")
                    self.send_error(500)

        self._server = ThreadingHTTPServer((host, port), Handler)
        self._server.daemon_threads = True
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="oobleck-serve-http",
            daemon=True)

    def _health(self) -> dict:
        eng = self.batcher.engine
        return {"ok": eng.params is not None,
                "step": eng.params_step,
                "slots_active": self.batcher.slots_active,
                "queue_depth": self.batcher.queue_depth}

    def _generate(self, body: dict) -> tuple[int, dict]:
        vocab = self.batcher.engine.model.config.vocab_size
        tokens = tokens_from_body(body, vocab)
        max_tokens = int(body.get("max_tokens",
                                  self.batcher.default_max_tokens))
        if max_tokens < 1:
            raise ValueError("max_tokens must be >= 1")
        deadline_ms = body.get("deadline_ms")
        eos = body.get("eos_token")
        if eos is not None and not isinstance(eos, int):
            raise ValueError("eos_token must be an int")
        # Client-supplied trace id (distributed tracing across the caller's
        # own spans) or a fresh one; returned in the response either way so
        # the caller can join server-side spans to its request.
        trace_id = body.get("trace_id")
        if trace_id is not None and not isinstance(trace_id, str):
            raise ValueError("trace_id must be a string")
        req = GenRequest(
            tokens, max_tokens=max_tokens,
            temperature=float(body.get("temperature", 0.0)),
            deadline_s=(float(deadline_ms) / 1e3) if deadline_ms else None,
            eos_token=eos, trace_id=trace_id)
        try:
            self.batcher.submit(req)
        except QueueFull as e:
            return 429, {"error": str(e)}
        if not req.wait(self.request_timeout):
            return 504, {"error": "generation timed out"}
        if req.finish_reason in ("error", "shutdown"):
            return 500, {"error": req.finish_reason}
        if req.finish_reason == "too_long":
            return 400, {"error": "prompt + max_tokens exceed max_seq"}
        return 200, {
            "tokens": req.out_tokens,
            "text": text_from_tokens(req.out_tokens),
            "finish_reason": req.finish_reason,
            "step": req.step,
            "ttft_ms": round((req.ttft_s or 0.0) * 1e3, 3),
            "latency_ms": round((req.total_s or 0.0) * 1e3, 3),
            "trace_id": req.trace_id,
        }

    def start(self) -> "ServeHTTPServer":
        self._thread.start()
        logger.info("serve http listening on :%d", self.port)
        return self

    def close(self) -> None:
        self._server.shutdown()
        self._server.server_close()


def main() -> None:  # pragma: no cover - exercised via ServingPlane in tests
    import os

    from oobleck_tpu.serve import ServingPlane

    logging.basicConfig(level=logging.INFO)
    root = os.environ.get("OOBLECK_CKPT_DIR")
    if not root:
        raise SystemExit("set OOBLECK_CKPT_DIR to the checkpoint root")
    plane = ServingPlane(
        root, model_name=os.environ.get("OOBLECK_SERVE_MODEL"),
        model_args=json.loads(os.environ.get("OOBLECK_SERVE_MODEL_ARGS", "{}")))
    plane.start()
    print(f"serving on :{plane.server.port} from {root}")
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        plane.stop()


if __name__ == "__main__":  # pragma: no cover
    main()
