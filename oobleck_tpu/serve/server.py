"""Stdlib HTTP front end for the serving plane.

Same dependency-free pattern as the master's /metrics endpoint
(utils/metrics.py): ThreadingHTTPServer on a daemon thread, port 0 binds
an ephemeral port (read `.port` after start).

Endpoints:
  POST /v1/generate   {"tokens": [..]} or {"prompt": ".."} (byte-level
                      stand-in tokenizer), optional "max_tokens",
                      "temperature", "deadline_ms", "eos_token".
                      -> {"tokens", "text", "finish_reason", "step",
                          "ttft_ms", "latency_ms", "trace_id"}
                      Optional "trace_id" in the body joins server-side
                      spans to the caller's trace (obs/spans).
                      429 when the admission queue is full (backpressure)
                      with an honest Retry-After header derived from the
                      measured queue drain rate, 400 on malformed input.
  GET  /healthz       {"ok", "step", "slots_active", "queue_depth"} plus
                      the router-facing replica state: "v" (wire
                      version), "weights_step", "lanes",
                      "lane_occupancy", "page_size", "retry_after_s" —
                      so the router (and humans) read replica state
                      without scraping /metrics.
  GET  /metrics       Prometheus text for this process's registry
                      (TTFT/per-token histograms, queue/slot gauges,
                      reload counters).

Chaos (`kill_replica=<port>[@<req>]`, `hang_replica=<port>:<secs>`): the
generate path checks both directives per request — a killed replica
aborts the in-flight connection with no response and stops accepting,
a hung one sleeps before answering. Both one-shot, flight-recorded.

Run standalone against a training job's checkpoint root:

    OOBLECK_CKPT_DIR=/ckpt OOBLECK_SERVE_PORT=8000 \
        python -m oobleck_tpu.serve.server
"""

from __future__ import annotations

import json
import logging
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from oobleck_tpu.serve.batcher import GenRequest, QueueFull
from oobleck_tpu.utils import metrics
from oobleck_tpu.utils.chaos import chaos

logger = logging.getLogger("oobleck.serve")

# Replica wire version advertised in /healthz and the router-registration
# handshake. Routers accept replicas WITHOUT it (legacy wire compat) but
# can only trust the richer keys when it is present.
REPLICA_WIRE_V = 1


class _QuietThreadingHTTPServer(ThreadingHTTPServer):
    """ThreadingHTTPServer that logs handler crashes instead of printing
    tracebacks — a chaos-killed connection aborts mid-response by design
    and must not spray stderr."""

    def handle_error(self, request, client_address):  # noqa: D102
        logger.debug("serve http handler error from %s", client_address,
                     exc_info=True)


def tokens_from_body(body: dict, vocab_size: int) -> list[int]:
    """Request tokens: explicit id list, or a byte-level stand-in
    tokenization of "prompt" (this repo trains on synthetic data — a real
    deployment drops its tokenizer in here)."""
    if "tokens" in body:
        tokens = body["tokens"]
        if (not isinstance(tokens, list) or not tokens
                or not all(isinstance(t, int) and not isinstance(t, bool)
                           and 0 <= t < vocab_size for t in tokens)):
            raise ValueError(
                f"tokens must be a non-empty list of ints in [0, {vocab_size})")
        return tokens
    if "prompt" in body:
        raw = str(body["prompt"]).encode("utf-8")
        if not raw:
            raise ValueError("empty prompt")
        return [b % vocab_size for b in raw]
    raise ValueError("body needs 'tokens' or 'prompt'")


def text_from_tokens(tokens: list[int]) -> str:
    """Inverse of the byte-level stand-in (lossy for ids >= 256)."""
    return bytes(t for t in tokens if t < 256).decode("utf-8", "replace")


class ServeHTTPServer:
    """HTTP front end over a ContinuousBatcher."""

    def __init__(self, batcher, *, port: int = 0, host: str = "0.0.0.0",
                 request_timeout: float = 120.0):
        self.batcher = batcher
        self.request_timeout = request_timeout
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # keep test logs quiet
                logger.debug("serve http: " + fmt, *args)

            def _reply(self, code: int, payload: dict,
                       ctype: str = "application/json",
                       headers: dict | None = None) -> None:
                body = json.dumps(payload).encode() \
                    if ctype == "application/json" else payload
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                for k, v in (headers or {}).items():
                    self.send_header(k, str(v))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                try:
                    path = self.path.split("?")[0]
                    if path == "/healthz":
                        self._reply(200, outer._health())
                    elif path == "/metrics":
                        text = metrics.render_prometheus(
                            [metrics.registry().snapshot()]).encode()
                        self._reply(
                            200, text,
                            "text/plain; version=0.0.4; charset=utf-8")
                    else:
                        self.send_error(404)
                except Exception:  # noqa: BLE001 — endpoint must never kill the server
                    logger.exception("serve GET failed")
                    self.send_error(500)

            def do_POST(self):
                try:
                    if self.path.split("?")[0] != "/v1/generate":
                        self.send_error(404)
                        return
                    if outer._chaos_hooks(self):
                        return  # replica died mid-request (no response)
                    length = int(self.headers.get("Content-Length") or 0)
                    try:
                        body = json.loads(self.rfile.read(length) or b"{}")
                        if not isinstance(body, dict):
                            raise ValueError("body must be a JSON object")
                        code, payload, headers = outer._generate(body)
                    except ValueError as e:
                        code, payload, headers = 400, {"error": str(e)}, None
                    self._reply(code, payload, headers=headers)
                except Exception:  # noqa: BLE001 — endpoint must never kill the server
                    logger.exception("serve POST failed")
                    self.send_error(500)

        self._server = _QuietThreadingHTTPServer((host, port), Handler)
        self._server.daemon_threads = True
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="oobleck-serve-http",
            daemon=True)

    def _chaos_hooks(self, handler) -> bool:
        """Per-request replica fault injection; True when the replica just
        died (the handler must return without replying)."""
        c = chaos()
        if not c.active:
            return False
        secs = c.hang_replica_secs(self.port)
        if secs:
            time.sleep(secs)
        if c.kill_replica_now(self.port):
            # Die like a process, not like an endpoint: abort this
            # connection with no response bytes and stop accepting. The
            # shutdown runs on its own thread (shutdown() blocks until
            # the accept loop notices, and this handler thread must not
            # wait on that).
            threading.Thread(target=self.close, daemon=True).start()
            try:
                handler.connection.close()
            except OSError:
                pass
            return True
        return False

    def _health(self) -> dict:
        eng = self.batcher.engine
        lanes = getattr(eng, "slots", 0) or 0
        active = self.batcher.slots_active
        return {"ok": eng.params is not None,
                "step": eng.params_step,
                "slots_active": active,
                "queue_depth": self.batcher.queue_depth,
                # Router-facing replica state (versioned; routers fall
                # back to the legacy keys above when "v" is absent).
                "v": REPLICA_WIRE_V,
                "weights_step": eng.params_step,
                "lanes": lanes,
                "lane_occupancy": round(active / lanes, 4) if lanes else 1.0,
                "page_size": int(getattr(eng, "page_size", 0) or 0),
                "retry_after_s": self.batcher.retry_after_s()}

    def _generate(self, body: dict) -> tuple[int, dict, dict | None]:
        vocab = self.batcher.engine.model.config.vocab_size
        tokens = tokens_from_body(body, vocab)
        max_tokens = int(body.get("max_tokens",
                                  self.batcher.default_max_tokens))
        if max_tokens < 1:
            raise ValueError("max_tokens must be >= 1")
        deadline_ms = body.get("deadline_ms")
        eos = body.get("eos_token")
        if eos is not None and not isinstance(eos, int):
            raise ValueError("eos_token must be an int")
        # Client-supplied trace id (distributed tracing across the caller's
        # own spans) or a fresh one; returned in the response either way so
        # the caller can join server-side spans to its request.
        trace_id = body.get("trace_id")
        if trace_id is not None and not isinstance(trace_id, str):
            raise ValueError("trace_id must be a string")
        # Per-request speculative-decode mode; None defers to the serving
        # plane's default, and a request can only narrow (off) or pick
        # among the drafters the plane enabled.
        speculation = body.get("speculation")
        if speculation is not None and speculation not in ("off", "lookup",
                                                           "draft"):
            raise ValueError("speculation must be one of off|lookup|draft")
        req = GenRequest(
            tokens, max_tokens=max_tokens,
            temperature=float(body.get("temperature", 0.0)),
            deadline_s=(float(deadline_ms) / 1e3) if deadline_ms else None,
            eos_token=eos, trace_id=trace_id, speculation=speculation)
        try:
            self.batcher.submit(req)
        except QueueFull as e:
            # Honest backpressure: when the queue will drain is derivable
            # from how fast it HAS been draining — advertise that, not a
            # constant, so clients (and the router's spill logic) back
            # off proportionally to the actual overload.
            retry_after = self.batcher.retry_after_s()
            return 429, {"error": str(e), "retry_after_s": retry_after}, \
                {"Retry-After": retry_after}
        if not req.wait(self.request_timeout):
            return 504, {"error": "generation timed out"}, None
        if req.finish_reason in ("error", "shutdown"):
            return 500, {"error": req.finish_reason}, None
        if req.finish_reason == "too_long":
            return 400, {"error": "prompt + max_tokens exceed max_seq"}, None
        return 200, {
            "tokens": req.out_tokens,
            "text": text_from_tokens(req.out_tokens),
            "finish_reason": req.finish_reason,
            "step": req.step,
            "ttft_ms": round((req.ttft_s or 0.0) * 1e3, 3),
            "latency_ms": round((req.total_s or 0.0) * 1e3, 3),
            "trace_id": req.trace_id,
        }, None

    def start(self) -> "ServeHTTPServer":
        self._thread.start()
        logger.info("serve http listening on :%d", self.port)
        return self

    def close(self) -> None:
        self._server.shutdown()
        self._server.server_close()


def main() -> None:  # pragma: no cover - exercised via ServingPlane in tests
    import os

    from oobleck_tpu.serve import ServingPlane

    logging.basicConfig(level=logging.INFO)
    root = os.environ.get("OOBLECK_CKPT_DIR")
    if not root:
        raise SystemExit("set OOBLECK_CKPT_DIR to the checkpoint root")
    plane = ServingPlane(
        root, model_name=os.environ.get("OOBLECK_SERVE_MODEL"),
        model_args=json.loads(os.environ.get("OOBLECK_SERVE_MODEL_ARGS", "{}")))
    plane.start()
    print(f"serving on :{plane.server.port} from {root}")
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        plane.stop()


if __name__ == "__main__":  # pragma: no cover
    main()
