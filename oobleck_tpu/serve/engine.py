"""Decode engines: jitted prefill/decode over dense-slot or paged KV state.

An engine owns the device-side serving state for one model: the current
weights (swappable between decode steps), the KV cache, and the compiled
prefill/decode executables. Two cache disciplines:

  DecodeEngine       dense slots — `[L, slots, H, max_seq, D]`, HBM per
                     slot scales with max_seq regardless of actual
                     lengths. Kept as the baseline the paged bench gate
                     compares against.
  PagedDecodeEngine  block/paged — `[L, N_pages, Hkv, page, D]` pool,
                     per-request page chains (serve/kv_blocks.py), ragged
                     paged attention (ops/paged_attention.py), prefix
                     reuse. HBM per request is its true token span, so
                     concurrency is bounded by total live tokens, not by
                     a handful of max_seq reservations.

Prompt lengths are padded to a small set of power-of-two buckets so the
number of distinct prefill programs is O(log max_seq) instead of one per
prompt length; both program families route through the PR 1 persistent
compilation cache (`utils/compile_cache.ensure_persistent_cache`) so a
server cold-start deserializes instead of recompiling. The paged engine
additionally buckets cached-head page counts (prefix hits) the same way;
head-bucket programs compile lazily on first hit and persist like the
rest.

All engine methods must be called from ONE thread (the batcher's): the
jitted calls donate the cache buffers, so a concurrent caller would race
on an invalidated buffer. Weight STAGING (host->device) is the exception
— `stage_params` is thread-safe and runs on the reload watcher so the
batcher-side swap is a pointer assignment.
"""

from __future__ import annotations

import logging
import os
import threading

import jax
import jax.numpy as jnp
import numpy as np

from oobleck_tpu.serve.kv_blocks import (
    GARBAGE_PAGE,
    BlockAllocator,
    PagesExhausted,
    pages_for,
)
from oobleck_tpu.utils import metrics
from oobleck_tpu.utils.compile_cache import (
    cache_event,
    ensure_persistent_cache,
)

logger = logging.getLogger("oobleck.serve")


def default_prefill_buckets(max_seq: int, smallest: int = 16) -> tuple[int, ...]:
    """Power-of-two prompt-length buckets up to max_seq."""
    out = []
    b = min(smallest, max_seq)
    while b < max_seq:
        out.append(b)
        b *= 2
    out.append(max_seq)
    return tuple(out)


class _EngineBase:
    """Weights + compile-cache plumbing shared by both cache disciplines."""

    def __init__(self, model, *, max_seq: int,
                 prefill_buckets: tuple[int, ...] | None = None):
        self.model = model
        self.max_seq = int(max_seq)
        if max_seq > model.config.max_position_embeddings:
            raise ValueError(
                f"max_seq {max_seq} exceeds the model's "
                f"max_position_embeddings {model.config.max_position_embeddings}")
        self.prefill_buckets = tuple(sorted(
            prefill_buckets or default_prefill_buckets(self.max_seq)))
        if self.prefill_buckets[-1] > self.max_seq:
            raise ValueError("prefill bucket exceeds max_seq")

        self.compile_cache_dir = ensure_persistent_cache()
        if self.compile_cache_dir is not None:
            # JAX creates the dir lazily on first write; hit/miss
            # classification (entry-count deltas) needs it to exist now.
            try:
                os.makedirs(self.compile_cache_dir, exist_ok=True)
            except OSError:
                self.compile_cache_dir = None
        if self.compile_cache_dir is not None:
            # Decode programs are tiny and compile fast; the default
            # min-compile-time threshold would skip persisting them, and a
            # server cold-start wants ALL its programs served from cache.
            try:
                jax.config.update(
                    "jax_persistent_cache_min_compile_time_secs", 0.0)
            except AttributeError:
                pass

        self.params = None          # device-resident fused tree
        self.params_step: int = -1  # checkpoint step the weights came from
        self._stage_lock = threading.Lock()

    # -- weights -------------------------------------------------------- #

    def stage_params(self, host_params):
        """Host checkpoint tree -> device tree, blocking until resident.

        Thread-safe; called by the reload watcher so the expensive
        host->device copy happens OFF the decode thread and the batcher's
        swap is a reference assignment."""
        with self._stage_lock:
            staged = jax.device_put(
                jax.tree.map(jnp.asarray, host_params))
            jax.block_until_ready(staged)
            return staged

    def set_params(self, device_params, step: int) -> None:
        """Swap the served weights (decode-step barrier: the batcher calls
        this between decode steps, never mid-step). In-flight requests
        keep their KV cache — entries computed under the old weights mix
        with new-weight queries, the standard continuous-serving
        tradeoff; the alternative (drop + re-prefill) violates the
        zero-dropped-requests contract."""
        self.params = device_params
        self.params_step = int(step)

    # -- compile accounting --------------------------------------------- #

    def _cache_entries(self) -> int | None:
        d = self.compile_cache_dir
        if not d or not os.path.isdir(d):
            return None
        try:
            return sum(1 for n in os.listdir(d) if not n.startswith("."))
        except OSError:
            return None

    def _classified(self, fn):
        """Run one first-compile call, classifying it as a persistent-cache
        hit (no new entry appeared in the cache dir) or miss."""
        before = self._cache_entries()
        out = fn()
        jax.block_until_ready(out)
        after = self._cache_entries()
        if before is not None and after is not None:
            cache_event("serve_hit" if after == before else "serve_miss")
        return out

    def bucket_for(self, n: int) -> int | None:
        for b in self.prefill_buckets:
            if n <= b:
                return b
        return None


class DecodeEngine(_EngineBase):
    """Dense-slot serving state: weights + slot KV cache + compiled steps."""

    def __init__(self, model, *, slots: int, max_seq: int,
                 prefill_buckets: tuple[int, ...] | None = None):
        super().__init__(model, max_seq=max_seq,
                         prefill_buckets=prefill_buckets)
        self.slots = int(slots)
        self.cache = model.init_kv_cache(self.slots, self.max_seq)

        # argnums: 0=params, 1=cache (donated), rest per call.
        self._decode_fn = jax.jit(
            lambda p, cache, token, pos:
                model.forward_decode(p, token, cache, pos),
            donate_argnums=(1,))
        self._prefill_fn = jax.jit(
            lambda p, cache, tokens, slot, length:
                model.forward_prefill(p, tokens, cache, slot, length),
            donate_argnums=(1,))

    def warmup(self) -> int:
        """Compile the decode step and every prefill bucket up front (cold
        starts pay compiles at startup, not on the first request). Returns
        the number of programs compiled. Requires weights."""
        assert self.params is not None, "set_params before warmup"
        n = 0
        for b in self.prefill_buckets:
            tokens = jnp.zeros((1, b), jnp.int32)
            logits, self.cache = self._classified(
                lambda t=tokens: self._prefill_fn(
                    self.params, self.cache, t, jnp.int32(0), jnp.int32(1)))
            n += 1
        token = jnp.zeros((self.slots,), jnp.int32)
        pos = jnp.zeros((self.slots,), jnp.int32)
        (logits, self.cache) = self._classified(
            lambda: self._decode_fn(self.params, self.cache, token, pos))
        n += 1
        logger.info("serve warmup: %d programs (buckets %s), cache dir %s",
                    n, self.prefill_buckets, self.compile_cache_dir)
        return n

    # -- steps (batcher thread only) ------------------------------------ #

    def prefill(self, tokens: list[int], slot: int) -> np.ndarray:
        """Run one request's prompt into `slot`; returns next-token logits
        [V] as a host array."""
        n = len(tokens)
        b = self.bucket_for(n)
        if b is None:
            raise ValueError(f"prompt length {n} exceeds max_seq {self.max_seq}")
        padded = np.zeros((1, b), np.int32)
        padded[0, :n] = tokens
        logits, self.cache = self._prefill_fn(
            self.params, self.cache, jnp.asarray(padded),
            jnp.int32(slot), jnp.int32(n))
        return np.asarray(logits)

    def decode(self, token: np.ndarray, pos: np.ndarray) -> np.ndarray:
        """One decode step over ALL slots (inactive slots compute garbage
        harmlessly); returns logits [slots, V] on host."""
        logits, self.cache = self._decode_fn(
            self.params, self.cache,
            jnp.asarray(token, jnp.int32), jnp.asarray(pos, jnp.int32))
        return np.asarray(logits)


def default_head_buckets(max_pages: int) -> tuple[int, ...]:
    """Power-of-two cached-head page-count buckets: one jitted tail-prefill
    program per (tail bucket, head bucket) pair actually seen."""
    out = [1]
    while out[-1] < max_pages:
        out.append(min(out[-1] * 2, max_pages))
    return tuple(dict.fromkeys(out))


class PagedDecodeEngine(_EngineBase):
    """Paged serving state: page pool + block tables + prefix reuse.

    `lanes` is the decode batch width (the analogue of dense `slots`, but
    cheap: a lane is two int arrays, not a max_seq KV reservation), exposed
    as `.slots` so the batcher drives both engines identically. Admission
    capacity is PAGES: `can_admit` answers whether a request's full token
    span (prompt + max_tokens, minus its cached prefix) fits the pool, and
    `release` returns a finished request's pages immediately."""

    def __init__(self, model, *, lanes: int, max_seq: int,
                 page_size: int = 16, num_pages: int = 0,
                 prefill_buckets: tuple[int, ...] | None = None):
        super().__init__(model, max_seq=max_seq,
                         prefill_buckets=prefill_buckets)
        self.page_size = int(page_size)
        if num_pages <= 0:
            raise ValueError("num_pages must be explicit and positive")
        self.num_pages = int(num_pages)
        self.slots = self.lanes = int(lanes)
        self.table_pages = pages_for(self.max_seq, self.page_size)
        self.head_buckets = default_head_buckets(self.table_pages)

        self.allocator = BlockAllocator(self.num_pages, self.page_size)
        self.cache = model.init_paged_kv_cache(self.num_pages, self.page_size)
        # Host-side lane state; device tables rebuilt per call (tiny int32).
        self.tables = np.full((self.lanes, self.table_pages), GARBAGE_PAGE,
                              np.int32)
        self._lane_pages: list[list[int]] = [[] for _ in range(self.lanes)]

        self._decode_fn = jax.jit(
            lambda p, cache, token, tables, pos:
                model.forward_decode_paged(p, token, cache, tables, pos),
            donate_argnums=(1,))
        # One callable; jit retraces per (tail bucket, head bucket) shape
        # pair. head_tables=None (shape-free) is the no-hit fast path.
        self._prefill_fn = jax.jit(
            lambda p, cache, tokens, tables, length:
                model.forward_prefill_paged(p, tokens, cache, tables, length),
            donate_argnums=(1,))
        self._prefill_head_fn = jax.jit(
            lambda p, cache, tokens, tables, length, head, prior:
                model.forward_prefill_paged(
                    p, tokens, cache, tables, length,
                    head_tables=head, prior_len=prior),
            donate_argnums=(1,))

        reg = metrics.registry()
        self.m_pages_in_use = reg.gauge(
            "oobleck_serve_kv_pages_in_use", "KV pool pages owned by requests")
        self.m_pages_free = reg.gauge(
            "oobleck_serve_kv_pages_free", "KV pool pages on the free list")
        self.m_prefix_hits = reg.counter(
            "oobleck_serve_prefix_hits_total",
            "Prefills that reused at least one cached prefix page")
        self.m_prompt_tokens = reg.counter(
            "oobleck_serve_prompt_tokens_total", "Prompt tokens admitted")
        self.m_cached_tokens = reg.counter(
            "oobleck_serve_prefix_cached_tokens_total",
            "Prompt tokens served from cached prefix pages (prefill skipped)")
        self._set_page_gauges()

    def _set_page_gauges(self) -> None:
        self.m_pages_in_use.set(self.allocator.pages_in_use)
        self.m_pages_free.set(self.allocator.free_pages)

    # -- admission capacity (batcher thread only) ------------------------ #

    def can_admit(self, tokens: list[int], max_tokens: int) -> bool:
        """Whether prompt + max_tokens fits the pool right now, net of the
        request's cached prefix. Single-threaded with prefill, so a True
        answer cannot be raced stale."""
        need = pages_for(len(tokens) + max_tokens, self.page_size)
        need -= self.allocator.peek_prefix(tokens) // self.page_size
        return self.allocator.can_allocate(need)

    def release(self, lane: int) -> None:
        """Return a finished request's pages (refcounted: pages shared with
        a live prefix stay resident). Incremental — runs per finish, not
        per batch."""
        if self._lane_pages[lane]:
            self.allocator.release(self._lane_pages[lane])
            self._lane_pages[lane] = []
        self.tables[lane] = GARBAGE_PAGE
        self._set_page_gauges()

    def _head_bucket(self, n: int) -> int:
        for b in self.head_buckets:
            if n <= b:
                return b
        raise ValueError(f"cached head of {n} pages exceeds table "
                         f"{self.table_pages}")

    # -- steps (batcher thread only) ------------------------------------ #

    def warmup(self) -> int:
        """Compile the decode step, every no-hit prefill bucket, and the
        smallest prefix-hit variant. Remaining (tail, head) pairs compile
        lazily on first hit and persist like the rest. Requires weights."""
        assert self.params is not None, "set_params before warmup"
        n = 0
        tables = jnp.zeros((self.table_pages,), jnp.int32)
        for b in self.prefill_buckets:
            tokens = jnp.zeros((1, b), jnp.int32)
            logits, self.cache = self._classified(
                lambda t=tokens: self._prefill_fn(
                    self.params, self.cache, t, tables, jnp.int32(1)))
            n += 1
        head = jnp.zeros((self.head_buckets[0],), jnp.int32)
        tokens = jnp.zeros((1, self.prefill_buckets[0]), jnp.int32)
        logits, self.cache = self._classified(
            lambda: self._prefill_head_fn(
                self.params, self.cache, tokens, tables, jnp.int32(1),
                head, jnp.int32(0)))
        n += 1
        token = np.zeros((self.lanes,), np.int32)
        pos = np.zeros((self.lanes,), np.int32)
        (logits, self.cache) = self._classified(
            lambda: self._decode_fn(
                self.params, self.cache, jnp.asarray(token),
                jnp.asarray(self.tables), jnp.asarray(pos)))
        n += 1
        logger.info(
            "paged serve warmup: %d programs (buckets %s, head buckets %s, "
            "%d pages x %d), cache dir %s", n, self.prefill_buckets,
            self.head_buckets, self.num_pages, self.page_size,
            self.compile_cache_dir)
        return n

    def prefill(self, tokens: list[int], lane: int, *,
                max_tokens: int = 0) -> np.ndarray:
        """Admit one request into `lane`: match its cached prefix, reserve
        pages for its full span, prefill only the uncached tail, and
        register the prompt's full pages for future reuse. Returns
        next-token logits [V] on host. Raises PagesExhausted (allocation
        untouched) when the pool cannot hold the span — callers gate on
        `can_admit` so this is a defensive backstop."""
        n = len(tokens)
        head_pages, cached_len = self.allocator.match_prefix(tokens)
        tail = tokens[cached_len:]
        b = self.bucket_for(len(tail))
        if b is None:
            self.allocator.release(head_pages)
            raise ValueError(f"prompt length {n} exceeds max_seq {self.max_seq}")
        try:
            fresh = self.allocator.allocate(
                pages_for(n + max_tokens, self.page_size) - len(head_pages))
        except PagesExhausted:
            self.allocator.release(head_pages)
            raise
        table = head_pages + fresh

        self.m_prompt_tokens.inc(n)
        if cached_len:
            self.m_prefix_hits.inc()
            self.m_cached_tokens.inc(cached_len)
        # Defensive CoW: the first tail write lands on the first fresh page
        # (cached_len is page-aligned), so shared pages are never written in
        # the natural flow — but if that invariant ever breaks, copy rather
        # than corrupt a neighbor's prefix.
        moved = self.allocator.make_writable(
            table, cached_len // self.page_size)
        if moved is not None:
            src, dst = moved
            self.cache = {
                "k": self.cache["k"].at[:, dst].set(self.cache["k"][:, src]),
                "v": self.cache["v"].at[:, dst].set(self.cache["v"][:, src]),
            }

        padded = np.zeros((1, b), np.int32)
        padded[0, :len(tail)] = tail
        dev_table = np.full((self.table_pages,), GARBAGE_PAGE, np.int32)
        dev_table[:len(table)] = table
        if cached_len:
            hb = self._head_bucket(len(head_pages))
            head = np.full((hb,), GARBAGE_PAGE, np.int32)
            head[:len(head_pages)] = head_pages
            logits, self.cache = self._prefill_head_fn(
                self.params, self.cache, jnp.asarray(padded),
                jnp.asarray(dev_table), jnp.int32(len(tail)),
                jnp.asarray(head), jnp.int32(cached_len))
        else:
            logits, self.cache = self._prefill_fn(
                self.params, self.cache, jnp.asarray(padded),
                jnp.asarray(dev_table), jnp.int32(len(tail)))

        self.allocator.register_chain(tokens, table)
        self._lane_pages[lane] = table
        self.tables[lane] = dev_table
        self._set_page_gauges()
        return np.asarray(logits)

    def decode(self, token: np.ndarray, pos: np.ndarray) -> np.ndarray:
        """One ragged decode step over ALL lanes (inactive lanes ride the
        garbage page harmlessly); returns logits [lanes, V] on host."""
        logits, self.cache = self._decode_fn(
            self.params, self.cache, jnp.asarray(token, jnp.int32),
            jnp.asarray(self.tables), jnp.asarray(pos, jnp.int32))
        return np.asarray(logits)

    # -- speculative multi-token verify (batcher thread only) ------------- #

    @property
    def supports_verify(self) -> bool:
        return hasattr(self.model, "forward_verify_paged")

    def _get_verify_fn(self):
        fn = getattr(self, "_verify_fn", None)
        if fn is None:
            fn = self._verify_fn = jax.jit(
                lambda p, cache, tokens, tables, pos, live:
                    self.model.forward_verify_paged(
                        p, tokens, cache, tables, pos, live),
                donate_argnums=(1,))
        return fn

    def warmup_verify(self, t: int) -> None:
        """Compile the T-wide verify program up front (one program per
        distinct T; the batcher uses a fixed T = k_max + 1, so this is
        one compile). No-op for T <= 1 — that's the plain decode path."""
        if t <= 1 or not self.supports_verify:
            return
        assert self.params is not None, "set_params before warmup"
        tokens = np.zeros((self.lanes, t), np.int32)
        pos = np.zeros((self.lanes,), np.int32)
        live = np.zeros((self.lanes,), np.int32)
        (logits, self.cache) = self._classified(
            lambda: self._get_verify_fn()(
                self.params, self.cache, jnp.asarray(tokens),
                jnp.asarray(self.tables), jnp.asarray(pos),
                jnp.asarray(live)))
        logger.info("paged serve warmup: verify program T=%d compiled", t)

    def verify(self, tokens: np.ndarray, pos: np.ndarray,
               n_live: np.ndarray) -> np.ndarray:
        """One multi-token verify step over ALL lanes.

        `tokens[b]` is [last emitted token, draft_1..draft_{T-1}] fed at
        absolute positions pos[b]..pos[b]+T-1; only the first n_live[b]
        columns are real — the rest scatter their KV to the garbage page
        and compute junk logits the caller ignores. Returns logits
        [lanes, T, V] on host; row j of lane b is exactly what
        sequential decode would produce after emitting tokens[b, :j+1],
        which is what makes greedy acceptance byte-exact."""
        logits, self.cache = self._get_verify_fn()(
            self.params, self.cache, jnp.asarray(tokens, jnp.int32),
            jnp.asarray(self.tables), jnp.asarray(pos, jnp.int32),
            jnp.asarray(n_live, jnp.int32))
        return np.asarray(logits)

    def rollback(self, lane: int, first_pos: int, last_pos: int) -> None:
        """Rewind a lane's KV write cursor after verify rejected the draft
        suffix at positions [first_pos, last_pos]. The allocator evicts
        any prefix registration on the touched pages and CoWs shared ones
        (serve/kv_blocks.rewind_span); the device copies owed for a CoW
        use the same .at[].set pattern as prefill's defensive copy. The
        rejected bytes themselves stay in place for the OWNING lane —
        masked by every ragged length until the next accepted token
        overwrites them."""
        copies = self.allocator.rewind_span(
            self._lane_pages[lane], first_pos, last_pos)
        for src, dst in copies:
            self.cache = {
                "k": self.cache["k"].at[:, dst].set(self.cache["k"][:, src]),
                "v": self.cache["v"].at[:, dst].set(self.cache["v"][:, src]),
            }
        if copies:
            table = self._lane_pages[lane]
            self.tables[lane, :len(table)] = table
        self._set_page_gauges()
