"""Decode engine: jitted prefill/decode over a slot-structured KV cache.

One DecodeEngine owns the device-side serving state for one model: the
current weights (swappable between decode steps), the preallocated KV
cache (`[L, slots, H, max_seq, D]`, donated through every jitted call so
XLA updates it in place), and the compiled prefill/decode executables.

Prompt lengths are padded to a small set of power-of-two buckets so the
number of distinct prefill programs is O(log max_seq) instead of one per
prompt length; both program families route through the PR 1 persistent
compilation cache (`utils/compile_cache.ensure_persistent_cache`) so a
server cold-start deserializes instead of recompiling.

All engine methods must be called from ONE thread (the batcher's): the
jitted calls donate the cache buffers, so a concurrent caller would race
on an invalidated buffer. Weight STAGING (host->device) is the exception
— `stage_params` is thread-safe and runs on the reload watcher so the
batcher-side swap is a pointer assignment.
"""

from __future__ import annotations

import logging
import os
import threading

import jax
import jax.numpy as jnp
import numpy as np

from oobleck_tpu.utils.compile_cache import (
    cache_event,
    ensure_persistent_cache,
)

logger = logging.getLogger("oobleck.serve")


def default_prefill_buckets(max_seq: int, smallest: int = 16) -> tuple[int, ...]:
    """Power-of-two prompt-length buckets up to max_seq."""
    out = []
    b = min(smallest, max_seq)
    while b < max_seq:
        out.append(b)
        b *= 2
    out.append(max_seq)
    return tuple(out)


class DecodeEngine:
    """Device-side serving state: weights + KV cache + compiled steps."""

    def __init__(self, model, *, slots: int, max_seq: int,
                 prefill_buckets: tuple[int, ...] | None = None):
        self.model = model
        self.slots = int(slots)
        self.max_seq = int(max_seq)
        if max_seq > model.config.max_position_embeddings:
            raise ValueError(
                f"max_seq {max_seq} exceeds the model's "
                f"max_position_embeddings {model.config.max_position_embeddings}")
        self.prefill_buckets = tuple(sorted(
            prefill_buckets or default_prefill_buckets(self.max_seq)))
        if self.prefill_buckets[-1] > self.max_seq:
            raise ValueError("prefill bucket exceeds max_seq")

        self.compile_cache_dir = ensure_persistent_cache()
        if self.compile_cache_dir is not None:
            # JAX creates the dir lazily on first write; hit/miss
            # classification (entry-count deltas) needs it to exist now.
            try:
                os.makedirs(self.compile_cache_dir, exist_ok=True)
            except OSError:
                self.compile_cache_dir = None
        if self.compile_cache_dir is not None:
            # Decode programs are tiny and compile fast; the default
            # min-compile-time threshold would skip persisting them, and a
            # server cold-start wants ALL its programs served from cache.
            try:
                jax.config.update(
                    "jax_persistent_cache_min_compile_time_secs", 0.0)
            except AttributeError:
                pass

        self.params = None          # device-resident fused tree
        self.params_step: int = -1  # checkpoint step the weights came from
        self.cache = model.init_kv_cache(self.slots, self.max_seq)
        self._stage_lock = threading.Lock()

        # argnums: 0=params, 1=cache (donated), rest per call.
        self._decode_fn = jax.jit(
            lambda p, cache, token, pos:
                model.forward_decode(p, token, cache, pos),
            donate_argnums=(1,))
        self._prefill_fn = jax.jit(
            lambda p, cache, tokens, slot, length:
                model.forward_prefill(p, tokens, cache, slot, length),
            donate_argnums=(1,))

    # -- weights -------------------------------------------------------- #

    def stage_params(self, host_params):
        """Host checkpoint tree -> device tree, blocking until resident.

        Thread-safe; called by the reload watcher so the expensive
        host->device copy happens OFF the decode thread and the batcher's
        swap is a reference assignment."""
        with self._stage_lock:
            staged = jax.device_put(
                jax.tree.map(jnp.asarray, host_params))
            jax.block_until_ready(staged)
            return staged

    def set_params(self, device_params, step: int) -> None:
        """Swap the served weights (decode-step barrier: the batcher calls
        this between decode steps, never mid-step). In-flight requests
        keep their KV cache — entries computed under the old weights mix
        with new-weight queries, the standard continuous-serving
        tradeoff; the alternative (drop + re-prefill) violates the
        zero-dropped-requests contract."""
        self.params = device_params
        self.params_step = int(step)

    # -- compile accounting --------------------------------------------- #

    def _cache_entries(self) -> int | None:
        d = self.compile_cache_dir
        if not d or not os.path.isdir(d):
            return None
        try:
            return sum(1 for n in os.listdir(d) if not n.startswith("."))
        except OSError:
            return None

    def _classified(self, fn):
        """Run one first-compile call, classifying it as a persistent-cache
        hit (no new entry appeared in the cache dir) or miss."""
        before = self._cache_entries()
        out = fn()
        jax.block_until_ready(out)
        after = self._cache_entries()
        if before is not None and after is not None:
            cache_event("serve_hit" if after == before else "serve_miss")
        return out

    def warmup(self) -> int:
        """Compile the decode step and every prefill bucket up front (cold
        starts pay compiles at startup, not on the first request). Returns
        the number of programs compiled. Requires weights."""
        assert self.params is not None, "set_params before warmup"
        n = 0
        for b in self.prefill_buckets:
            tokens = jnp.zeros((1, b), jnp.int32)
            logits, self.cache = self._classified(
                lambda t=tokens: self._prefill_fn(
                    self.params, self.cache, t, jnp.int32(0), jnp.int32(1)))
            n += 1
        token = jnp.zeros((self.slots,), jnp.int32)
        pos = jnp.zeros((self.slots,), jnp.int32)
        (logits, self.cache) = self._classified(
            lambda: self._decode_fn(self.params, self.cache, token, pos))
        n += 1
        logger.info("serve warmup: %d programs (buckets %s), cache dir %s",
                    n, self.prefill_buckets, self.compile_cache_dir)
        return n

    # -- steps (batcher thread only) ------------------------------------ #

    def bucket_for(self, n: int) -> int | None:
        for b in self.prefill_buckets:
            if n <= b:
                return b
        return None

    def prefill(self, tokens: list[int], slot: int) -> np.ndarray:
        """Run one request's prompt into `slot`; returns next-token logits
        [V] as a host array."""
        n = len(tokens)
        b = self.bucket_for(n)
        if b is None:
            raise ValueError(f"prompt length {n} exceeds max_seq {self.max_seq}")
        padded = np.zeros((1, b), np.int32)
        padded[0, :n] = tokens
        logits, self.cache = self._prefill_fn(
            self.params, self.cache, jnp.asarray(padded),
            jnp.int32(slot), jnp.int32(n))
        return np.asarray(logits)

    def decode(self, token: np.ndarray, pos: np.ndarray) -> np.ndarray:
        """One decode step over ALL slots (inactive slots compute garbage
        harmlessly); returns logits [slots, V] on host."""
        logits, self.cache = self._decode_fn(
            self.params, self.cache,
            jnp.asarray(token, jnp.int32), jnp.asarray(pos, jnp.int32))
        return np.asarray(logits)
