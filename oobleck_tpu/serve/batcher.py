"""Continuous batcher: slot scheduling, admission control, weight swaps.

One scheduler thread owns the DecodeEngine. Each loop iteration:

  1. applies a pending weight swap (the decode-step barrier for
     zero-downtime hot-reload: in-flight requests keep their slots and
     KV state, nothing is dropped);
  2. admits queued requests into free slots (one prefill each);
  3. runs ONE decode step over all slots and feeds each active slot its
     sampled token — or, with a speculative controller attached
     (serve/speculative.py), ONE draft->verify->accept/rollback step
     that can advance a lane by up to k+1 tokens while keeping greedy
     output byte-identical to the one-token path.

Admission is a bounded queue — when it is full `submit` rejects
immediately (backpressure to the client as HTTP 429) instead of
buffering unboundedly. Each request carries `max_tokens` and an optional
wall-clock deadline; deadline-expired requests finish with what they
have rather than starving the batch, and requests that expire while
still QUEUED are swept at enqueue/admit time under the distinct
`outcome=deadline_queued` — dead work never consumes a prefill.

Paged engines (PagedDecodeEngine) admit by PAGES available, not lanes
free: `can_admit` gates each admission on the request's full token span
fitting the pool (net of its cached prefix), a small FIFO waiting line
preserves arrival order while capacity frees up (no head-of-line skip),
and `release` returns a finished request's pages immediately. Dense
engines lack both hooks and keep the original slots-free discipline.
"""

from __future__ import annotations

import logging
import queue
import threading
import time
from collections import deque

import numpy as np

from oobleck_tpu.obs import spans
from oobleck_tpu.serve.kv_blocks import PagesExhausted
from oobleck_tpu.utils import background, metrics
from oobleck_tpu.utils.metrics import SERVE_LATENCY_BUCKETS

logger = logging.getLogger("oobleck.serve")


class QueueFull(Exception):
    """Admission queue at capacity; the client should back off (429)."""


class GenRequest:
    """One generation request's lifecycle state."""

    _ids = iter(range(1 << 62))

    def __init__(self, tokens: list[int], *, max_tokens: int,
                 temperature: float = 0.0, deadline_s: float | None = None,
                 eos_token: int | None = None, trace_id: str | None = None,
                 speculation: str | None = None):
        self.id = next(self._ids)
        self.tokens = list(tokens)
        self.max_tokens = int(max_tokens)
        self.temperature = float(temperature)
        # Per-request speculation mode (off|lookup|draft); None = the
        # serving plane's default. Resolved against what the plane has
        # enabled — a request can narrow but never force speculation on.
        self.speculation = speculation
        self.admit_ordinal = 0  # admission order (chaos @<req> targeting)
        self.submitted = time.monotonic()
        self.deadline = (self.submitted + deadline_s) if deadline_s else None
        self.eos_token = eos_token
        self.out_tokens: list[int] = []
        self.finish_reason: str | None = None
        self.step = -1          # weights step that served the request
        self.ttft_s: float | None = None
        self.total_s: float | None = None
        self.done = threading.Event()
        # Tracing (obs/spans): the request is one trace; queue wait,
        # prefill, and decode become child spans at finish, so TTFT is
        # decomposed by cause. Wall stamps ride next to the monotonic
        # latency fields — spans need an epoch timeline.
        self.trace_id = trace_id or spans.new_trace_id()
        self.t_submit_wall = time.time()
        self.t_admit_wall: float | None = None
        self.t_prefill_wall: float | None = None

    def expired(self, now: float) -> bool:
        return self.deadline is not None and now > self.deadline

    def wait(self, timeout: float | None = None) -> bool:
        return self.done.wait(timeout)


class ContinuousBatcher:
    """Bounded-queue continuous batching over a DecodeEngine's slots."""

    def __init__(self, engine, *, max_queue: int = 64,
                 default_max_tokens: int = 64, idle_sleep: float = 0.002,
                 seed: int = 0, spec=None):
        self.engine = engine
        self.default_max_tokens = default_max_tokens
        # Speculative-decode controller (serve/speculative.SpecController);
        # None, or an engine without a verify path, keeps the classic
        # one-token decode step.
        self.spec = spec if getattr(engine, "supports_verify", False) else None
        self._admit_seq = 0
        self._queue: queue.Queue[GenRequest] = queue.Queue(maxsize=max_queue)
        # Requests pulled off the queue but not yet admittable (paged
        # engines: waiting for pages). FIFO — no head-of-line skip — and
        # capped at the lane count so the bounded queue keeps its
        # backpressure meaning.
        self._waiting: deque[GenRequest] = deque()
        # Paged-engine hooks; dense engines (and test fakes) lack them and
        # keep the original slots-free admission.
        self._can_admit = getattr(engine, "can_admit", None)
        self._lane_release = getattr(engine, "release", None)
        self._rng = np.random.default_rng(seed)
        self._slots: list[GenRequest | None] = [None] * engine.slots
        self._token = np.zeros(engine.slots, np.int32)
        self._pos = np.zeros(engine.slots, np.int32)
        self._idle_sleep = idle_sleep
        self._pending_swap: tuple[int, object] | None = None
        self._swap_lock = threading.Lock()
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name="oobleck-serve-batcher", daemon=True)
        self._tok_window = (time.monotonic(), 0)
        # Queue drain rate (completed requests/sec, EWMA over ~1 s
        # windows): what an honest Retry-After is derived from — how fast
        # this replica actually works its queue off, not a guess.
        self._completions = 0
        self._drain_window = (time.monotonic(), 0)
        self._drain_rate = 0.0

        reg = metrics.registry()
        self.m_ttft = reg.histogram(
            "oobleck_serve_ttft_seconds",
            "Time from request admission queue to first generated token",
            buckets=SERVE_LATENCY_BUCKETS)
        self.m_step = reg.histogram(
            "oobleck_serve_token_latency_seconds",
            "Per-TOKEN decode latency: step wall time normalized by tokens "
            "emitted per active slot (speculative steps emit up to k+1)",
            buckets=SERVE_LATENCY_BUCKETS)
        self.m_reload_pause = reg.histogram(
            "oobleck_serve_reload_pause_seconds",
            "Decode-loop pause taken to swap weights at a hot-reload",
            buckets=SERVE_LATENCY_BUCKETS)
        self.m_queue = reg.gauge(
            "oobleck_serve_queue_depth", "Requests waiting for a slot")
        self.m_active = reg.gauge(
            "oobleck_serve_slots_active", "Decode slots currently generating")
        self.m_tps = reg.gauge(
            "oobleck_serve_tokens_per_sec", "Generated tokens/sec (rolling)")
        self.m_tokens = reg.counter(
            "oobleck_serve_tokens_total", "Generated tokens")
        self.m_requests = reg.counter(
            "oobleck_serve_requests_total", "Requests by outcome")
        self.m_reloads = reg.counter(
            "oobleck_serve_reloads_total", "Completed weight hot-reloads")

    # -- client side ----------------------------------------------------- #

    def submit(self, req: GenRequest) -> GenRequest:
        """Enqueue or reject-now (bounded queue = backpressure). A request
        that is ALREADY past its deadline never enters the queue — it
        finishes as deadline_queued without consuming any capacity."""
        if req.expired(time.monotonic()):
            self._finish(req, "deadline_queued")
            return req
        try:
            self._queue.put_nowait(req)
        except queue.Full:
            self.m_requests.inc(outcome="rejected")
            raise QueueFull(
                f"admission queue full ({self._queue.maxsize})") from None
        self.m_queue.set(self.queue_depth)
        return req

    def post_swap(self, step: int, device_params) -> None:
        """Stage a weight swap; the scheduler applies it between decode
        steps. A newer pending swap supersedes an unapplied older one."""
        with self._swap_lock:
            if self._pending_swap is None or step > self._pending_swap[0]:
                self._pending_swap = (step, device_params)

    # -- lifecycle ------------------------------------------------------- #

    def start(self) -> "ContinuousBatcher":
        self._thread.start()
        return self

    def stop(self, timeout: float = 30.0) -> None:
        self._stop.set()
        if self._thread.is_alive():
            self._thread.join(timeout)
        for i, req in enumerate(self._slots):
            if req is not None:
                self._finish(req, "shutdown")
                self._free_lane(i)
        while self._waiting:
            self._finish(self._waiting.popleft(), "shutdown")
        while True:
            try:
                self._finish(self._queue.get_nowait(), "shutdown")
            except queue.Empty:
                break

    @property
    def slots_active(self) -> int:
        return sum(1 for s in self._slots if s is not None)

    @property
    def queue_depth(self) -> int:
        return self._queue.qsize() + len(self._waiting)

    @property
    def drain_rate(self) -> float:
        """Completed requests/sec (EWMA). 0.0 until the first window."""
        return self._drain_rate

    def retry_after_s(self, default: float = 5.0,
                      cap: float = 60.0) -> int:
        """Honest Retry-After for a 429: the whole-second wait the current
        queue takes to drain at the measured completion rate, clamped to
        [1, cap]. Before any completion window lands, `default` — a flat
        guess beats advertising an infinite wait."""
        rate = self._drain_rate
        wait = default if rate <= 0.0 else self.queue_depth / rate
        return int(max(1.0, min(wait, cap)))

    # -- scheduler ------------------------------------------------------- #

    def _finish(self, req: GenRequest, reason: str) -> None:
        req.finish_reason = reason
        req.step = self.engine.params_step
        req.total_s = time.monotonic() - req.submitted
        self._completions += 1
        self.m_requests.inc(outcome=reason)
        self._record_spans(req, reason)
        req.done.set()

    def _record_spans(self, req: GenRequest, reason: str) -> None:
        """One stitched timeline per request: serve.request parent span
        with queue_wait / prefill / decode children — the Perfetto view
        of the TTFT histogram, decomposed by cause."""
        end = time.time()
        rec = spans.span_recorder()
        root = rec.record(
            "serve.request", req.t_submit_wall, end,
            trace_id=req.trace_id, request_id=req.id, outcome=reason,
            tokens_in=len(req.tokens), tokens_out=len(req.out_tokens),
            ttft_s=req.ttft_s, params_step=req.step)
        parent = root["span_id"]
        if req.t_admit_wall is not None:
            rec.record("serve.queue_wait", req.t_submit_wall,
                       req.t_admit_wall, trace_id=req.trace_id,
                       parent_id=parent, request_id=req.id)
            if req.t_prefill_wall is not None:
                rec.record("serve.prefill", req.t_admit_wall,
                           req.t_prefill_wall, trace_id=req.trace_id,
                           parent_id=parent, request_id=req.id)
                rec.record("serve.decode", req.t_prefill_wall, end,
                           trace_id=req.trace_id, parent_id=parent,
                           request_id=req.id,
                           tokens_out=len(req.out_tokens))

    def _sample(self, logits_row: np.ndarray, temperature: float) -> int:
        if temperature <= 0.0:
            return int(np.argmax(logits_row))
        z = logits_row.astype(np.float64) / temperature
        z -= z.max()
        p = np.exp(z)
        p /= p.sum()
        return int(self._rng.choice(len(p), p=p))

    def _emit(self, req: GenRequest, token: int, now: float) -> bool:
        """Record one generated token; True when the request is finished."""
        req.out_tokens.append(token)
        self.m_tokens.inc()
        if req.ttft_s is None:
            req.ttft_s = now - req.submitted
            self.m_ttft.observe(req.ttft_s)
        if req.eos_token is not None and token == req.eos_token:
            self._finish(req, "eos")
            return True
        if len(req.out_tokens) >= req.max_tokens:
            self._finish(req, "length")
            return True
        if req.expired(now):
            self._finish(req, "deadline")
            return True
        return False

    def _maybe_swap(self) -> None:
        with self._swap_lock:
            pending, self._pending_swap = self._pending_swap, None
        if pending is None:
            return
        step, params = pending
        t0 = time.perf_counter()
        # The swap runs on the scheduler thread while the reload watcher
        # may be staging the NEXT checkpoint — fence the device call
        # (utils/background.py) so their XLA dispatch cannot interleave.
        with background.device_work("serve_swap"):
            self.engine.set_params(params, step)
        pause = time.perf_counter() - t0
        self.m_reloads.inc()
        self.m_reload_pause.observe(pause)
        metrics.flight_recorder().record(
            "serve_reload", step=step, pause_s=pause,
            slots_active=self.slots_active)
        logger.info("hot-reloaded weights to step %d (pause %.6fs, "
                    "%d requests in flight)", step, pause, self.slots_active)

    def _free_lane(self, i: int) -> None:
        """Clear a lane and (paged engines) return its pages immediately."""
        self._slots[i] = None
        if self._lane_release is not None:
            self._lane_release(i)
        if self.spec is not None:
            self.spec.reset_lane(i)  # acceptance history is per-request

    def _pull_waiting(self) -> None:
        # A small peek-buffer (capped at the lane count) so FIFO order
        # survives page-capacity waits without draining the bounded
        # queue's backpressure into an unbounded line.
        while len(self._waiting) < len(self._slots):
            try:
                self._waiting.append(self._queue.get_nowait())
            except queue.Empty:
                break

    def _next_admittable(self) -> GenRequest | None:
        """Head of the waiting line once dead/invalid requests are swept.
        Returns None when empty OR when the head is waiting on pages —
        FIFO admission never skips over a starved request."""
        while True:
            self._pull_waiting()
            if not self._waiting:
                return None
            req = self._waiting[0]
            if req.expired(time.monotonic()):
                # Queue-expired: swept before any prefill, under its own
                # outcome so dashboards separate dead-on-arrival work from
                # mid-generation deadline cuts.
                self._waiting.popleft()
                self._finish(req, "deadline_queued")
                continue
            n = len(req.tokens)
            if n == 0 or self.engine.bucket_for(n) is None \
                    or n + req.max_tokens > self.engine.max_seq:
                self._waiting.popleft()
                self._finish(req, "too_long")
                continue
            if self._can_admit is not None \
                    and not self._can_admit(req.tokens, req.max_tokens):
                return None
            self._waiting.popleft()
            return req

    def _admit(self) -> None:
        for i in range(len(self._slots)):
            if self._slots[i] is not None:
                continue
            req = self._next_admittable()
            if req is None:
                break
            req.t_admit_wall = time.time()
            self._admit_seq += 1
            req.admit_ordinal = self._admit_seq
            try:
                with background.device_work("serve_prefill"):
                    if self._can_admit is not None:
                        logits = self.engine.prefill(
                            req.tokens, i, max_tokens=req.max_tokens)
                    else:
                        logits = self.engine.prefill(req.tokens, i)
            except PagesExhausted:
                # can_admit gates admission on the same thread, so this is
                # a defensive backstop: put the request back at the front
                # and retry next iteration.
                self._waiting.appendleft(req)
                break
            req.t_prefill_wall = time.time()
            now = time.monotonic()
            token = self._sample(logits, req.temperature)
            if not self._emit(req, token, now):
                self._slots[i] = req
                self._token[i] = token
                self._pos[i] = len(req.tokens)
            elif self._lane_release is not None:
                self._lane_release(i)

    def _decode_step(self) -> None:
        t0 = time.perf_counter()
        with background.device_work("serve_decode"):
            logits = self.engine.decode(self._token, self._pos)
        self.m_step.observe(time.perf_counter() - t0)
        now = time.monotonic()
        for i, req in enumerate(self._slots):
            if req is None:
                continue
            token = self._sample(logits[i], req.temperature)
            self._pos[i] += 1
            self._token[i] = token
            if self._emit(req, token, now):
                self._free_lane(i)

    # -- speculative decode (draft -> verify -> accept/rollback) ---------- #

    def _collect_drafts(self) -> dict[int, list[int]]:
        """Ask the controller for each lane's draft this step. Lanes at
        k=0 (collapsed, sampled, or nearly done) stay out of the dict and
        ride the verify batch as plain one-token rows."""
        drafts: dict[int, list[int]] = {}
        for i, req in enumerate(self._slots):
            if req is None:
                continue
            mode = self.spec.mode_for(req.speculation)
            remaining = req.max_tokens - len(req.out_tokens)
            k = self.spec.k_for(i, mode=mode, temperature=req.temperature,
                                remaining=remaining)
            if k <= 0:
                continue
            d = self.spec.draft(i, req.tokens + req.out_tokens, k, mode,
                                req.admit_ordinal)
            if d:
                drafts[i] = d
        return drafts

    def _spec_step(self) -> None:
        """One speculative decode step over all lanes.

        Each drafting lane feeds [last_token, draft_1..draft_k] at
        positions pos..pos+k through ONE verify forward; verify row j of
        a lane is exactly the logits sequential decode would produce
        there, so emitting each row's sample until it disagrees with the
        next draft token keeps greedy output byte-identical to the
        non-speculative path. Rejected draft positions get their KV
        write cursor rewound (engine.rollback) so the prefix cache can
        never serve a poisoned page. With no drafts this step, falls
        through to the classic one-token path — k=0 everywhere IS
        today's decode."""
        t_draft0 = time.perf_counter()
        t_draft_wall0 = time.time()
        drafts = self._collect_drafts()
        if not drafts:
            self._decode_step()
            return
        draft_s = time.perf_counter() - t_draft0
        self.spec.m_draft_s.observe(draft_s)
        spans.span_recorder().record(
            "serve.spec.draft", t_draft_wall0, time.time(),
            lanes=len(drafts),
            tokens=sum(len(d) for d in drafts.values()))
        t0 = time.perf_counter()

        # Fixed verify width (k_max + 1) regardless of per-lane draft
        # lengths: ONE compiled program for the life of the server, not a
        # retrace per draft-length combination. Columns past a lane's
        # n_live scatter KV to the garbage page and compute junk logits
        # nobody reads.
        t_wide = 1 + self.spec.config.k
        tokens = np.zeros((len(self._slots), t_wide), np.int32)
        tokens[:, 0] = self._token
        live = np.zeros(len(self._slots), np.int32)
        for i, req in enumerate(self._slots):
            if req is None:
                continue
            d = drafts.get(i, ())
            tokens[i, 1:1 + len(d)] = d
            live[i] = 1 + len(d)

        tv0 = time.perf_counter()
        t_wall0 = time.time()
        with background.device_work("serve_verify"):
            logits = self.engine.verify(tokens, self._pos, live)
        verify_s = time.perf_counter() - tv0
        self.spec.m_verify_s.observe(verify_s)

        now = time.monotonic()
        active = emitted_total = 0
        for i, req in enumerate(self._slots):
            if req is None:
                continue
            active += 1
            d = drafts.get(i, [])
            k = len(d)
            p0 = int(self._pos[i])
            matched = 0
            finished = False
            for j in range(k + 1):
                token = self._sample(logits[i, j], req.temperature)
                self._pos[i] = p0 + j + 1
                self._token[i] = token
                emitted_total += 1
                if self._emit(req, token, now):
                    # eos/max_tokens/deadline cut mid-acceptance: stop
                    # HERE — tokens past the cut are never emitted even
                    # if the draft would have matched them.
                    finished = True
                    break
                if j < k and token == d[j]:
                    matched += 1
                    continue
                break
            if k > 0:
                self.spec.m_tokens_step.observe(matched + 1)
                self.spec.observe(i, drafted=k, matched=matched)
                if matched < k:
                    # Columns matched+1..k hold rejected drafts' KV at
                    # positions p0+matched+1..p0+k: rewind before anyone
                    # (prefix cache, next allocation) can see the pages.
                    self.spec.m_rollbacks.inc()
                    tr0 = time.time()
                    with background.device_work("serve_rollback"):
                        self.engine.rollback(i, p0 + matched + 1, p0 + k)
                    spans.span_recorder().record(
                        "serve.spec.rollback", tr0, time.time(),
                        trace_id=req.trace_id, request_id=req.id,
                        rejected=k - matched)
            if finished:
                self._free_lane(i)
        spans.span_recorder().record(
            "serve.spec.verify", t_wall0, time.time(), lanes=active,
            t_wide=t_wide, tokens_emitted=emitted_total,
            draft_s=draft_s, verify_s=verify_s)
        # m_step keeps its per-TOKEN meaning: step wall time divided by
        # tokens emitted per active slot (reduces to the classic
        # observation when every lane emits exactly one).
        elapsed = draft_s + (time.perf_counter() - t0)
        if emitted_total:
            self.m_step.observe(elapsed * active / emitted_total)

    def _update_gauges(self) -> None:
        self.m_queue.set(self.queue_depth)
        self.m_active.set(self.slots_active)
        t_last, n_last = self._tok_window
        now = time.monotonic()
        if now - t_last >= 1.0:
            n = self.m_tokens.value()
            self.m_tps.set((n - n_last) / (now - t_last))
            self._tok_window = (now, n)
        t_last, c_last = self._drain_window
        if now - t_last >= 1.0:
            rate = (self._completions - c_last) / (now - t_last)
            # EWMA so one quiet second doesn't zero the advertised drain.
            self._drain_rate = rate if self._drain_rate == 0.0 \
                else 0.5 * self._drain_rate + 0.5 * rate
            self._drain_window = (now, self._completions)

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self._maybe_swap()
                self._admit()
                if self.slots_active:
                    if self.spec is not None:
                        self._spec_step()
                    else:
                        self._decode_step()
                else:
                    time.sleep(self._idle_sleep)
                self._update_gauges()
            except Exception:  # noqa: BLE001
                # A scheduler death would hang every waiting client; fail
                # the in-flight requests and keep serving.
                logger.exception("batcher iteration failed")
                for i, req in enumerate(self._slots):
                    if req is not None:
                        self._finish(req, "error")
                        self._free_lane(i)
