"""Speculative-decode microbenchmark: tokens/sec vs the k=0 baseline.

Speculation's win case is LATENCY-bound decode: per-step cost dominated
by the fixed program-dispatch/weight-read overhead rather than by
per-position FLOPs, so folding k+1 positions into one verify forward
collapses step count into wall-clock speedup. The CPU proxy here
reproduces that regime with a single decode lane on a tiny model (each
step is mostly dispatch) and an ACCEPTANCE-FRIENDLY workload: llama-tiny
under greedy decode settles into a short repeating cycle, which is
exactly the kind of self-repetition the prompt-lookup drafter exploits —
the same bet that pays off on real models for quoted spans, structured
output, and code.

Two arms over identical requests, batcher driven synchronously (no
scheduler thread — deterministic step counts, no sampling artifacts):

  k=0   the classic one-token decode step (speculation off)
  spec  lookup drafting at k, greedy acceptance, rollback on rejection

Reported: tokens/sec both arms, `speedup_vs_k0` (the >= 1.5x headline),
`acceptance_rate`, `tokens_per_step`, and `draft_overhead` (fraction of
spec wall time spent proposing — the cost side of the trade).

Standalone:  python -m oobleck_tpu.serve.spec_bench
Embedded:    bench.py folds the result under its "spec" key.
"""

from __future__ import annotations

import json
import time

import jax

from oobleck_tpu.utils import metrics


def _hist_sum(hist) -> float:
    return sum(s["sum"] for s in hist.series())


def _run_arm(model, params, *, mode: str, k: int, n_requests: int,
             prompt_len: int, gen_tokens: int, max_seq: int,
             max_steps: int = 10_000) -> dict:
    """One arm: fresh engine + synchronously driven batcher until every
    request finishes. Single lane — the latency-bound regime speculation
    targets; requests queue and run back to back."""
    from oobleck_tpu.serve.batcher import ContinuousBatcher, GenRequest
    from oobleck_tpu.serve.engine import PagedDecodeEngine
    from oobleck_tpu.serve.speculative import SpecConfig, build_controller

    metrics.registry().clear()
    engine = PagedDecodeEngine(
        model, lanes=1, max_seq=max_seq, page_size=16,
        num_pages=2 + 2 * (max_seq // 16))
    engine.set_params(engine.stage_params(params), 0)
    engine.warmup()
    spec = None
    if mode != "off":
        spec = build_controller(SpecConfig(mode=mode, k=k, min_accept=0.05))
        engine.warmup_verify(k + 1)
    b = ContinuousBatcher(engine, max_queue=n_requests, spec=spec)
    reqs = [GenRequest([5 + (j + i) % 7 for j in range(prompt_len)],
                       max_tokens=gen_tokens) for i in range(n_requests)]
    for r in reqs:
        b.submit(r)

    t0 = time.perf_counter()
    steps = 0
    while not all(r.done.is_set() for r in reqs) and steps < max_steps:
        b._admit()
        if b.slots_active:
            if b.spec is not None:
                b._spec_step()
            else:
                b._decode_step()
            steps += 1
    elapsed = time.perf_counter() - t0
    tokens = sum(len(r.out_tokens) for r in reqs)

    out = {
        "tokens": tokens,
        "steps": steps,
        "tokens_per_sec": round(tokens / elapsed, 1) if elapsed else None,
        "tokens_per_step": round(tokens / steps, 3) if steps else None,
    }
    if spec is not None:
        drafted = spec.m_drafted.value()
        draft_s = _hist_sum(spec.m_draft_s)
        out["acceptance_rate"] = round(
            spec.m_accepted.value() / drafted, 3) if drafted else 0.0
        out["rollbacks"] = int(spec.m_rollbacks.value())
        # Fraction of the arm's wall time spent proposing drafts: the
        # overhead the acceptance wins have to beat.
        out["draft_overhead"] = round(draft_s / elapsed, 4) if elapsed else None
    b.stop()
    return out


def measure_spec(model_name: str = "llama-tiny", *, k: int = 8,
                 n_requests: int = 3, prompt_len: int = 16,
                 gen_tokens: int = 96, max_seq: int = 128,
                 best_of: int = 2) -> dict:
    """Both arms on identical requests; spec arm keeps its best-of-N
    tokens/sec (first-run jit/allocator noise on shared CI boxes would
    otherwise dominate a ~100 ms measurement)."""
    import jax.numpy as jnp

    from oobleck_tpu.models import build_model

    model = build_model(model_name, {"dtype": jnp.float32})
    params = model.init_params(jax.random.PRNGKey(0))
    kw = dict(k=k, n_requests=n_requests, prompt_len=prompt_len,
              gen_tokens=gen_tokens, max_seq=max_seq)

    base = spec = None
    for _ in range(best_of):
        b = _run_arm(model, params, mode="off", **kw)
        if base is None or (b["tokens_per_sec"] or 0) > (base["tokens_per_sec"] or 0):
            base = b
        s = _run_arm(model, params, mode="lookup", **kw)
        if spec is None or (s["tokens_per_sec"] or 0) > (spec["tokens_per_sec"] or 0):
            spec = s
    assert base["tokens"] == spec["tokens"], "arms generated unequal work"

    speedup = None
    if base["tokens_per_sec"] and spec["tokens_per_sec"]:
        speedup = round(spec["tokens_per_sec"] / base["tokens_per_sec"], 3)
    return {
        "model": model_name,
        "k": k,
        "requests": n_requests,
        "gen_tokens_per_request": gen_tokens,
        "baseline_tokens_per_sec": base["tokens_per_sec"],
        "spec_tokens_per_sec": spec["tokens_per_sec"],
        "speedup_vs_k0": speedup,
        "acceptance_rate": spec.get("acceptance_rate"),
        "tokens_per_step": spec.get("tokens_per_step"),
        "draft_overhead": spec.get("draft_overhead"),
        "rollbacks": spec.get("rollbacks"),
        "baseline_steps": base["steps"],
        "spec_steps": spec["steps"],
    }


def main() -> None:
    print(json.dumps(measure_spec(), indent=2))


if __name__ == "__main__":
    main()
