"""Elastic serving plane (L6): continuous-batching inference over the
durable-state plane's checkpoints.

The training side of this repo survives faults by reconfiguring instead
of restarting; this package extends the same posture to inference: a
server bound to a live training job's checkpoint root
(`OOBLECK_CKPT_DIR`) hot-reloads the newest committed step while
serving, without dropping in-flight requests.

    engine.py    DecodeEngine — KV cache + jitted prefill/decode
                 (persistent-compile-cache routed, cache donated)
    batcher.py   ContinuousBatcher — bounded admission queue, slot
                 scheduling between decode steps, backpressure
    reload.py    CheckpointWatcher — poll committed steps, stage off the
                 decode path, swap at a decode-step barrier
    server.py    stdlib HTTP: POST /v1/generate, GET /healthz, /metrics
    bench.py     tokens/sec, TTFT and reload-pause percentiles
    router/      multi-replica front door: prefix-affine routing,
                 failover, pool-driven scale-out (own package docstring)

`ServingPlane` wires the four together over one checkpoint root; pass
`router_url=` (or set `OOBLECK_ROUTER_URL`) and the replica
self-registers with a router on start and deregisters on stop.
"""

from __future__ import annotations

import logging
import os
import threading
import time

from oobleck_tpu.config import ServeArguments
from oobleck_tpu.serve.batcher import ContinuousBatcher, GenRequest, QueueFull
from oobleck_tpu.serve.engine import DecodeEngine, PagedDecodeEngine
from oobleck_tpu.serve.kv_blocks import BlockAllocator, PagesExhausted
from oobleck_tpu.serve.reload import (
    CheckpointWatcher,
    load_latest_params,
    params_from_payload,
    publish_params,
)
from oobleck_tpu.serve.server import ServeHTTPServer

__all__ = [
    "BlockAllocator", "CheckpointWatcher", "ContinuousBatcher",
    "DecodeEngine", "GenRequest", "PagedDecodeEngine", "PagesExhausted",
    "QueueFull", "ServeArguments", "ServeHTTPServer", "ServingPlane",
    "load_latest_params", "params_from_payload", "publish_params",
]

logger = logging.getLogger("oobleck.serve")


class ServingPlane:
    """One process's serving stack over one checkpoint root.

    start() blocks until a committed checkpoint exists (a server may come
    up before its training job's first save), loads it, warms the decode
    programs, and starts batcher + reload watcher + HTTP server."""

    def __init__(self, root, *, model=None, model_name: str | None = None,
                 model_args: dict | None = None,
                 args: ServeArguments | None = None,
                 wait_secs: float = 60.0, ip: str | None = None,
                 router_url: str | None = None):
        self.root = root
        self.model = model
        self.model_name = model_name
        self.model_args = model_args
        self.args = args or ServeArguments()
        self.args.apply_serve_env_overrides()
        self.wait_secs = wait_secs
        self.ip = ip
        # Multi-replica mode: a router front door to self-register with
        # (serve/router/). Explicit arg wins; env covers deployments that
        # launch replicas as plain `python -m oobleck_tpu.serve.server`.
        self.router_url = router_url \
            if router_url is not None \
            else (os.environ.get("OOBLECK_ROUTER_URL") or None)
        self.engine: DecodeEngine | None = None
        self.batcher: ContinuousBatcher | None = None
        self.watcher: CheckpointWatcher | None = None
        self.server: ServeHTTPServer | None = None

    def _wait_for_checkpoint(self):
        from oobleck_tpu.ckpt import restore

        deadline = time.monotonic() + self.wait_secs
        while True:
            res = restore.load_latest(self.root, quarantine_bad=False)
            if res is not None:
                return res
            if time.monotonic() >= deadline:
                raise TimeoutError(
                    f"no committed checkpoint under {self.root} after "
                    f"{self.wait_secs}s")
            time.sleep(0.2)

    def _resolve_model(self, payload: dict):
        if self.model is not None:
            return self.model
        meta = payload.get("meta", {})
        name = self.model_name or meta.get("model_name")
        if not name:
            raise ValueError(
                "no model: pass model/model_name or checkpoint meta must "
                "carry model_name")
        margs = dict(meta.get("model_args") or {})
        margs.update(self.model_args or {})
        from oobleck_tpu.models import build_model

        return build_model(name, margs)

    def _build_engine(self, model, max_seq: int):
        """kv_cache="paged" (default): block/paged pool sized to the SAME
        HBM budget the dense slot cache would take (slots * max_seq
        tokens), with the decode width (`lanes`) freed from that budget —
        short requests no longer pay a max_seq reservation. "dense"
        restores the slot cache."""
        a = self.args
        if a.kv_cache == "dense":
            return DecodeEngine(model, slots=a.slots, max_seq=max_seq)
        if a.kv_cache != "paged":
            raise ValueError(f"unknown kv_cache {a.kv_cache!r}")
        page = a.page_size
        num_pages = a.kv_pages or max(2, a.slots * max_seq // page)
        lanes = a.lanes or max(a.slots, min(num_pages - 1, 8 * a.slots))
        return PagedDecodeEngine(model, lanes=lanes, max_seq=max_seq,
                                 page_size=page, num_pages=num_pages)

    def _build_spec(self):
        """Speculative-decode controller from the serve args; None when
        speculation is off or the engine has no multi-token verify path
        (dense engines). Warms the fixed-width verify program so the
        first drafting request doesn't pay a compile."""
        a = self.args
        if a.speculation == "off" \
                or not getattr(self.engine, "supports_verify", False):
            return None
        from oobleck_tpu.serve.speculative import SpecConfig, build_controller

        spec = build_controller(SpecConfig(
            mode=a.speculation, k=a.spec_k, min_accept=a.spec_min_accept,
            ngram=a.spec_ngram, probe_every=a.spec_probe_every,
            draft_root=a.spec_draft_root))
        if spec is not None:
            self.engine.warmup_verify(spec.config.k + 1)
        return spec

    def start(self) -> "ServingPlane":
        step, payload = self._wait_for_checkpoint()
        model = self._resolve_model(payload)
        max_seq = min(self.args.max_seq,
                      model.config.max_position_embeddings)
        if max_seq != self.args.max_seq:
            logger.info("clamping max_seq %d -> model max positions %d",
                        self.args.max_seq, max_seq)
        self.engine = self._build_engine(model, max_seq)
        self.engine.set_params(
            self.engine.stage_params(params_from_payload(model, payload)),
            step)
        self.engine.warmup()
        spec = self._build_spec()
        self.batcher = ContinuousBatcher(
            self.engine, max_queue=self.args.max_queue,
            default_max_tokens=self.args.max_tokens_default,
            spec=spec).start()
        self.watcher = CheckpointWatcher(
            self.root, model, self.engine, self.batcher,
            poll_secs=self.args.reload_secs, current_step=step,
            ip=self.ip).start()
        self.server = ServeHTTPServer(self.batcher,
                                      port=self.args.port).start()
        logger.info("serving plane up: step %d, %d slots, max_seq %d, "
                    "port %d", step, self.args.slots, max_seq,
                    self.server.port)
        if self.router_url:
            # Register off-thread: a replica may come up before its
            # router, and serving must not block on the handshake.
            threading.Thread(target=self._register_with_router,
                             name="oobleck-serve-register",
                             daemon=True).start()
        return self

    def _register_with_router(self, attempts: int = 30,
                              backoff_s: float = 1.0) -> None:
        from oobleck_tpu.serve.router import register_with_router
        from oobleck_tpu.serve.server import REPLICA_WIRE_V

        payload = {
            "v": REPLICA_WIRE_V,
            "host": self.ip or "127.0.0.1",
            "port": self.server.port,
            "lanes": int(getattr(self.engine, "slots", 0) or 1),
            "weights_step": self.engine.params_step,
            "page_size": int(getattr(self.engine, "page_size", 0) or 0),
        }
        for _ in range(attempts):
            ack = register_with_router(self.router_url, payload)
            if ack is not None:
                logger.info("registered with router %s as %s:%d",
                            self.router_url, payload["host"],
                            payload["port"])
                return
            time.sleep(backoff_s)
        logger.warning("could not register with router %s after %d "
                       "attempts", self.router_url, attempts)

    def stop(self) -> None:
        if self.router_url and self.server is not None:
            from oobleck_tpu.serve.router import deregister_from_router

            # Best-effort clean exit; a missed deregister just means the
            # router's prober declares us down in a couple of sweeps.
            deregister_from_router(self.router_url,
                                   self.ip or "127.0.0.1",
                                   self.server.port, timeout_s=2.0)
        if self.server is not None:
            self.server.close()
        if self.watcher is not None:
            self.watcher.stop()
        if self.batcher is not None:
            self.batcher.stop()
