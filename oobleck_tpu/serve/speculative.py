"""Speculative multi-token decode: drafters and the acceptance controller.

The serve hot path is decode-bound: one ~1ms device step per token per
batch, dominated by weight reads, not FLOPs. Speculative decoding buys
back that bandwidth by guessing k tokens cheaply on the host (or with a
small draft model), then verifying all k in ONE batched forward through
the real model (`forward_verify_paged` — same weights read once for k+1
positions). Greedy acceptance keeps the output BYTE-IDENTICAL to plain
greedy decode: the verify logits at row j are exactly what step-by-step
decode would have produced at that position, so emitting the argmax of
each row until it disagrees with the next draft token reproduces the
non-speculative stream token for token — speculation changes latency,
never content.

Three pieces live here:

  * `Drafter` — the proposal seam. `LookupDrafter` (the default) is
    model-free prompt-lookup: the last n-gram of the context is matched
    against earlier occurrences and the tokens that followed are
    proposed. Zero extra weights, wins on repetitive continuations
    (code, quoted spans, structured output) and costs ~nothing when it
    misses. `ModelDrafter` runs a second, smaller checkpoint greedily
    for k steps — real drafting quality at real (small) compute cost.
  * `SpecController` — per-lane acceptance EWMAs that ADAPT k: lanes
    whose drafts keep matching run at k_max, lanes that keep missing
    collapse to k=0 (exactly today's one-token path, no verify overhead)
    with a periodic k=1 probe so a lane can recover when its tail turns
    repetitive. Also the injection point for the `spec_misdraft` chaos
    directive (deliberately wrong draft tokens, to exercise rollback).
  * Spec metrics — acceptance rate, tokens/step, draft/verify time
    split — all under `oobleck_serve_spec_*`.

The batcher owns the loop: draft -> verify -> accept/rollback; see
`ContinuousBatcher._spec_step`.
"""

from __future__ import annotations

import logging
import time

import numpy as np

from oobleck_tpu.utils import metrics
from oobleck_tpu.utils.chaos import chaos

logger = logging.getLogger("oobleck.serve")

SPEC_MODES = ("off", "lookup", "draft")


class Drafter:
    """Proposal seam: guess up to k continuation tokens for a context.

    `propose` may return FEWER than k tokens (or none) — the controller
    verifies whatever came back. It must never raise on short contexts.
    """

    name = "base"

    def propose(self, ctx, k: int) -> list[int]:  # pragma: no cover
        raise NotImplementedError


class LookupDrafter(Drafter):
    """Model-free prompt-lookup (n-gram) drafting.

    Finds the most recent EARLIER occurrence of the context's trailing
    n-gram (longest n first, `max_ngram` down to `min_ngram`) and
    proposes the tokens that followed it. The bet: generation that
    re-enters previously seen material — quoting the prompt, repeating
    structure, cycling — continues the same way it did last time.
    """

    name = "lookup"

    def __init__(self, max_ngram: int = 3, min_ngram: int = 1):
        if min_ngram < 1 or max_ngram < min_ngram:
            raise ValueError("need 1 <= min_ngram <= max_ngram")
        self.max_ngram = int(max_ngram)
        self.min_ngram = int(min_ngram)

    def propose(self, ctx, k: int) -> list[int]:
        n_ctx = len(ctx)
        if k <= 0 or n_ctx < self.min_ngram + 1:
            return []
        for n in range(min(self.max_ngram, n_ctx - 1),
                       self.min_ngram - 1, -1):
            suffix = tuple(ctx[n_ctx - n:])
            # j is the exclusive end of a candidate match window; j < n_ctx
            # keeps it strictly earlier than the suffix itself.
            for j in range(n_ctx - 1, n - 1, -1):
                if tuple(ctx[j - n:j]) == suffix:
                    cont = [int(t) for t in ctx[j:j + k]]
                    if cont:
                        return cont
        return []


class ModelDrafter(Drafter):
    """Draft with a second (smaller) model run greedily for k steps.

    Full-context forwards on the draft model — it keeps no KV state, so
    it composes with lane swaps and rollback trivially. Worth it only
    when the draft model is much smaller than the target; the seam
    exists so a real deployment can plug one in from a second
    checkpoint root (`OOBLECK_SERVE_SPEC_DRAFT_ROOT`).
    """

    name = "draft"

    def __init__(self, model, params, *, max_ctx: int = 0):
        import jax.numpy as jnp
        self._jnp = jnp
        self.model = model
        self.params = params
        # 0 = no clamp; otherwise feed only the trailing max_ctx tokens
        # (positions shift, but a DRAFT only has to be plausible —
        # verification is what guarantees correctness).
        self.max_ctx = int(max_ctx)

    @classmethod
    def from_checkpoint(cls, root: str, *, model=None):
        """Load the newest complete checkpoint under `root` as the draft
        model. `model` overrides discovery when the caller already built
        one; otherwise the checkpoint's meta names the architecture the
        same way the serving plane resolves its target model. Returns
        None (drafting falls back to lookup) when nothing loads."""
        from oobleck_tpu.ckpt.restore import load_latest
        from oobleck_tpu.serve.reload import params_from_payload

        loaded = load_latest(root)
        if loaded is None:
            logger.warning("spec: no checkpoint under %r; draft model "
                           "unavailable", root)
            return None
        _step, payload = loaded
        if model is None:
            from oobleck_tpu.models import build_model
            meta = payload.get("meta", {}) or {}
            name = meta.get("model")
            if not name:
                logger.warning("spec: checkpoint under %r has no model "
                               "meta; draft model unavailable", root)
                return None
            model = build_model(name, meta.get("model_args", {}))
        params = params_from_payload(model, payload)
        return cls(model, params)

    def propose(self, ctx, k: int) -> list[int]:
        if k <= 0 or not len(ctx):
            return []
        toks = [int(t) for t in ctx]
        out: list[int] = []
        for _ in range(k):
            feed = toks[-self.max_ctx:] if self.max_ctx else toks
            logits = self.model.forward(
                self.params, self._jnp.asarray(feed, self._jnp.int32)[None])
            nxt = int(np.argmax(np.asarray(logits[0, -1])))
            out.append(nxt)
            toks.append(nxt)
        return out


class SpecConfig:
    """Knobs for the speculative path (serve-plane defaults; per-request
    `speculation` picks the mode within what the plane enables)."""

    def __init__(self, *, mode: str = "off", k: int = 4,
                 min_accept: float = 0.25, ngram: int = 3,
                 probe_every: int = 32, ewma_alpha: float = 0.3,
                 draft_root: str = ""):
        if mode not in SPEC_MODES:
            raise ValueError(f"speculation mode {mode!r} not in {SPEC_MODES}")
        if k < 1:
            raise ValueError("spec k must be >= 1")
        if not 0.0 <= min_accept <= 1.0:
            raise ValueError("spec min_accept must be in [0, 1]")
        self.mode = mode
        self.k = int(k)
        self.min_accept = float(min_accept)
        self.ngram = int(ngram)
        self.probe_every = int(probe_every)
        self.ewma_alpha = float(ewma_alpha)
        self.draft_root = draft_root


class _LaneState:
    __slots__ = ("ewma", "steps_at_zero")

    def __init__(self):
        self.ewma = 1.0        # optimistic: first steps draft at full k
        self.steps_at_zero = 0


# Tokens emitted per spec step land in [1, k+1]; integer-edge buckets so
# the histogram reads directly as a tokens/step distribution.
_TOKENS_PER_STEP_BUCKETS = (1.0, 2.0, 3.0, 4.0, 6.0, 8.0, 12.0, 16.0)


class SpecController:
    """Per-lane draft policy: who drafts, how many tokens, and how the
    acceptance feedback adapts k.

    Greedy acceptance is only exact at temperature 0, so sampled
    (temperature > 0) requests always run k=0 — they still ride in the
    same verify batch (a T=1 row IS the decode row), so mixed batches
    cost nothing extra.
    """

    def __init__(self, config: SpecConfig, drafters: dict[str, Drafter],
                 *, seed: int = 0):
        self.config = config
        self.drafters = drafters
        self._lanes: dict[int, _LaneState] = {}
        self._misdraft_rng = np.random.default_rng(seed)

        reg = metrics.registry()
        self.m_accept = reg.gauge(
            "oobleck_serve_spec_acceptance_rate",
            "Draft-token acceptance rate (EWMA across drafting lanes)")
        self.m_tokens_step = reg.histogram(
            "oobleck_serve_spec_tokens_per_step",
            "Tokens emitted per lane per speculative step (1 = no draft "
            "accepted; k+1 = full acceptance plus bonus token)",
            buckets=_TOKENS_PER_STEP_BUCKETS)
        self.m_draft_s = reg.histogram(
            "oobleck_serve_spec_draft_seconds",
            "Host/draft-model time proposing tokens per spec step",
            buckets=metrics.SERVE_LATENCY_BUCKETS)
        self.m_verify_s = reg.histogram(
            "oobleck_serve_spec_verify_seconds",
            "Device time in the batched multi-token verify forward",
            buckets=metrics.SERVE_LATENCY_BUCKETS)
        self.m_drafted = reg.counter(
            "oobleck_serve_spec_drafted_tokens_total",
            "Draft tokens submitted to verification")
        self.m_accepted = reg.counter(
            "oobleck_serve_spec_accepted_tokens_total",
            "Draft tokens accepted by verification")
        self.m_rollbacks = reg.counter(
            "oobleck_serve_spec_rollbacks_total",
            "KV rollbacks after a rejected draft suffix")

    # -- lane lifecycle --------------------------------------------------- #

    def reset_lane(self, lane: int) -> None:
        """Called at admit/free: acceptance history is per-REQUEST."""
        self._lanes.pop(lane, None)

    def _state(self, lane: int) -> _LaneState:
        st = self._lanes.get(lane)
        if st is None:
            st = self._lanes[lane] = _LaneState()
        return st

    # -- policy ----------------------------------------------------------- #

    def mode_for(self, req_mode: str | None) -> str:
        """Resolve a request's speculation mode against the plane's: a
        request can only narrow (off) or pick among enabled drafters."""
        if self.config.mode == "off":
            return "off"
        if req_mode is None:
            return self.config.mode
        if req_mode == "draft" and "draft" not in self.drafters:
            return "lookup"
        return req_mode

    def k_for(self, lane: int, *, mode: str, temperature: float,
              remaining: int) -> int:
        """Draft length for this lane this step. 0 = plain decode row."""
        if mode == "off" or temperature > 0.0 or remaining <= 1:
            return 0
        st = self._state(lane)
        if st.ewma < self.config.min_accept:
            # Collapsed lane: k=0 except a periodic k=1 probe so a tail
            # that turns repetitive can climb back out.
            st.steps_at_zero += 1
            if self.config.probe_every > 0 \
                    and st.steps_at_zero % self.config.probe_every == 0:
                return 1
            return 0
        k = int(round(self.config.k * st.ewma))
        return max(1, min(k, self.config.k, remaining - 1))

    def draft(self, lane: int, ctx, k: int, mode: str,
              request_ordinal: int = 0) -> list[int]:
        """Propose up to k tokens; applies the spec_misdraft chaos
        directive (deliberately wrong tokens) before returning."""
        drafter = self.drafters.get(mode)
        if drafter is None or k <= 0:
            return []
        draft = drafter.propose(ctx, k)[:k]
        if draft:
            rate = chaos().spec_misdraft_rate(request_ordinal)
            if rate:
                vocab_guess = max(max(draft), max(int(t) for t in ctx)) + 2
                for i, t in enumerate(draft):
                    if self._misdraft_rng.random() < rate:
                        draft[i] = (t + 1) % vocab_guess
            self.m_drafted.inc(len(draft))
        return draft

    def observe(self, lane: int, *, drafted: int, matched: int) -> None:
        """Feed one lane-step's acceptance back into its EWMA."""
        if drafted <= 0:
            return
        st = self._state(lane)
        rate = matched / drafted
        a = self.config.ewma_alpha
        st.ewma = (1.0 - a) * st.ewma + a * rate
        if st.ewma >= self.config.min_accept:
            st.steps_at_zero = 0
        self.m_accepted.inc(matched)
        if self._lanes:
            self.m_accept.set(
                sum(s.ewma for s in self._lanes.values()) / len(self._lanes))


def build_controller(config: SpecConfig, *, seed: int = 0,
                     draft_model=None) -> SpecController | None:
    """Wire drafters for `config`; None when speculation is off.

    "draft" mode needs a second checkpoint root (or an explicit
    `draft_model`); when neither loads, the plane falls back to lookup
    drafting rather than silently serving without speculation.
    """
    if config.mode == "off":
        return None
    drafters: dict[str, Drafter] = {
        "lookup": LookupDrafter(max_ngram=config.ngram)}
    if config.mode == "draft" or config.draft_root:
        md = draft_model
        if md is None and config.draft_root:
            md = ModelDrafter.from_checkpoint(config.draft_root)
        if md is not None:
            drafters["draft"] = md
        elif config.mode == "draft":
            logger.warning("spec: draft model unavailable; falling back "
                           "to lookup drafting")
            config.mode = "lookup"
    return SpecController(config, drafters, seed=seed)
