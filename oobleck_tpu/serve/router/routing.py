"""Routing policy: prefix affinity first, deadlines override, load decides.

A request's prompt head is mapped to a replica with the SAME rolling
page-chain hash the paged KV allocator keys its prefix cache with
(serve/kv_blocks.py `chain_hashes`): the hash of the first
``affinity_pages`` full pages is a stable fingerprint of the prompt
head, and rendezvous hashing (highest-random-weight) over it picks the
replica most likely to already hold those prefix pages. Rendezvous —
not modulo — so replica churn remaps only the keys that MUST move:
when a replica joins or dies, every other key keeps its owner, which is
exactly the property a prefix cache wants.

Affinity is a preference, not a promise. When the affine replica's
projected wait (queue depth x TTFT EWMA) would blow the request's
deadline while a less-loaded replica would not, the policy spills to
power-of-two-choices over load — a cold prefill beats a missed
deadline. Requests without a usable head (shorter than one page, or no
paged replicas) go straight to po2.

The policy returns an ORDERED candidate list, not a single pick: the
proxy layer walks it on 429 spill and on failover, so "where next?" is
decided once, here, and every hop downstream is mechanical.
Weights-cooled replicas (registry skew gate) always sort last — stale
weights serve only when nothing fresh can.
"""

from __future__ import annotations

import random

from oobleck_tpu.serve.kv_blocks import chain_hashes
from oobleck_tpu.utils import metrics

# Fallback page granularity for the affinity fingerprint when no replica
# advertises one (dense-engine fleets still get stable prompt-head
# affinity; they just don't get prefix-cache hits out of it).
DEFAULT_AFFINITY_PAGE = 16
# Affine replica must project under deadline * margin to keep the
# request; the slack absorbs estimate noise before spilling.
DEADLINE_MARGIN = 0.8


class RoutingPolicy:
    """Orders routable replicas for one request."""

    def __init__(self, registry, *, affinity: bool = True,
                 affinity_pages: int = 2, seed: int | None = None):
        self.registry = registry
        self.affinity = affinity
        self.affinity_pages = max(int(affinity_pages), 1)
        self._rng = random.Random(seed)
        self.m_decisions = metrics.registry().counter(
            "oobleck_router_decisions_total",
            "Routing decisions by reason (affine/balanced/deadline_spill/"
            "cooled_only)")

    # -- affinity fingerprint --------------------------------------------- #

    def head_key(self, tokens: list[int]) -> int | None:
        """Prompt-head fingerprint: the rolling chain hash of the first
        `affinity_pages` FULL pages — the same chain the replicas' prefix
        caches are keyed with, so affinity lands requests where their
        pages already are. None when the prompt is shorter than one page
        (nothing cacheable to be affine to)."""
        page = max((r.page_size for r in self.registry.replicas()
                    if r.page_size > 0), default=DEFAULT_AFFINITY_PAGE)
        chain = chain_hashes(tokens, page)[:self.affinity_pages]
        return chain[-1] if chain else None

    @staticmethod
    def rendezvous_score(key: int, replica_key: str) -> int:
        """Highest-random-weight score of (prompt head, replica)."""
        return hash((key, replica_key))

    # -- candidate ordering ------------------------------------------------ #

    def plan(self, tokens: list[int],
             deadline_s: float | None = None) -> tuple[list, str]:
        """(ordered candidate replicas, decision reason).

        First element is the primary pick; the rest are fallbacks in
        preference order (load-ascending, cooled replicas last). Empty
        list: nothing registered and alive.
        """
        fresh, cooled = self.registry.routable()
        cooled_tail = sorted(cooled, key=lambda r: (r.est_wait_s(), r.key))
        if not fresh:
            reason = "cooled_only" if cooled_tail else "no_replicas"
            if cooled_tail:
                self.m_decisions.inc(reason=reason)
            return cooled_tail, reason
        by_load = sorted(fresh, key=lambda r: (r.est_wait_s(), r.key))
        key = self.head_key(tokens) if self.affinity else None
        if key is None:
            order, reason = self._po2_order(by_load), "balanced"
        else:
            affine = max(fresh, key=lambda r:
                         self.rendezvous_score(key, r.key))
            if (deadline_s is not None
                    and affine.est_wait_s() > deadline_s * DEADLINE_MARGIN
                    and len(fresh) > 1
                    and by_load[0] is not affine):
                # The warm replica can't make the deadline and a colder
                # one can — recompute beats late.
                order, reason = self._po2_order(by_load), "deadline_spill"
            else:
                order = [affine] + [r for r in by_load if r is not affine]
                reason = "affine"
        self.m_decisions.inc(reason=reason)
        return order + cooled_tail, reason

    def _po2_order(self, by_load: list) -> list:
        """Power-of-two-choices: sample two distinct replicas, lead with
        the less loaded; everyone else follows load-ascending. Two random
        probes avoid the thundering herd a strict argmin invites when
        many routers (or threads) share stale load estimates."""
        if len(by_load) < 2:
            return list(by_load)
        a, b = self._rng.sample(by_load, 2)
        pick = a if a.est_wait_s() <= b.est_wait_s() else b
        return [pick] + [r for r in by_load if r is not pick]
