"""Pool leases become replicas; reclaims become drains. Zero drops.

The borrow half of the elasticity loop: FleetPressureMonitor
(pressure.py) prices sustained fleet-wide pressure onto a POOL_BORROW
request, the arbiter grants a lease of borrowed chips, and this module
turns the grant into a NEW serving replica — started via an injected
factory (tests hand in stub replicas; production hands in a
ServingPlane launcher targeting the leased chips), registered with the
router, confirmed routable before the lease is considered absorbed.

The reclaim half is where the zero-drop guarantee lives: LEASE_RECLAIM
means training wants its chips BACK, but a replica holding in-flight
requests cannot just die — that would convert a scheduling decision
into user-visible failures. So ``drain()`` goes through the router:
mark the replica draining (the policy stops routing NEW work to it
instantly), poll its probed state until queue and lanes are empty
(every accepted request finishes), then deregister and stop. Only a
drain that outlives ``timeout_s`` force-stops — and says so in the
flight record, because a forced stop IS a drop risk and must be
forensically visible.

Both transitions are flight-recorded (``router_scale_out`` /
``router_drain``) so a pool-elasticity cycle reads back out of the
flight recorder as a narrative: borrow granted -> replica up ->
reclaim -> drained clean.
"""

from __future__ import annotations

import logging
import threading
import time

from oobleck_tpu.utils import metrics

logger = logging.getLogger("oobleck.router")


class ReplicaScaler:
    """Lease -> replica lifecycle against a ReplicaRegistry.

    ``factory(lease)`` must return a handle exposing ``.port`` (int,
    listening when the call returns) and ``.stop()``; anything more is
    the factory's business. The scaler registers the replica itself when
    the factory's replica does not self-register.
    """

    def __init__(self, registry, factory, *, host: str = "127.0.0.1",
                 poll_s: float = 0.05):
        self.registry = registry
        self._factory = factory
        self.host = host
        self.poll_s = poll_s
        self._lock = threading.Lock()
        self._handles: dict[str, object] = {}   # lease_id -> handle
        self._ports: dict[str, int] = {}

    def scale_out(self, lease: dict, *, timeout_s: float = 60.0):
        """Turn a granted lease into a routable replica.

        Blocks until the router's registry has the new replica probed
        and routable (a lease the router cannot route to has absorbed
        nothing). Returns the factory handle; raises TimeoutError when
        the replica never becomes routable (the handle is stopped — a
        half-joined replica must not leak).
        """
        lease_id = str(lease.get("lease_id") or lease.get("id") or "lease")
        handle = self._factory(lease)
        port = int(handle.port)
        key = f"{self.host}:{port}"
        try:
            deadline = time.monotonic() + timeout_s
            while time.monotonic() < deadline:
                rep = self.registry.get(key)
                if rep is None:
                    # Factory replicas that don't self-register get
                    # registered here; probes fill in live state.
                    self.registry.register({
                        "host": self.host, "port": port,
                        "v": 1,
                        "lanes": int(getattr(handle, "lanes", 0) or 1),
                        "weights_step": int(
                            getattr(handle, "weights_step", -1)),
                        "page_size": int(
                            getattr(handle, "page_size", 0) or 0)})
                elif not rep.down and rep.last_probe_t is not None:
                    break
                else:
                    self.registry.probe_once()
                time.sleep(self.poll_s)
            else:
                raise TimeoutError(
                    f"leased replica {key} never became routable")
        except Exception:
            try:
                handle.stop()
            except Exception:  # noqa: BLE001 — best-effort cleanup, original error wins
                pass
            raise
        with self._lock:
            self._handles[lease_id] = handle
            self._ports[lease_id] = port
        metrics.flight_recorder().record(
            "router_scale_out", lease_id=lease_id, replica=key)
        logger.info("router: lease %s absorbed as replica %s",
                    lease_id, key)
        return handle

    def drain(self, lease_id: str, *, timeout_s: float = 30.0) -> dict:
        """Reclaim path: drain the leased replica THROUGH the router and
        stop it. Returns {"replica", "drained_clean", "drain_s"};
        drained_clean False means the timeout forced the stop (drop
        risk — flight-recorded as such)."""
        with self._lock:
            handle = self._handles.pop(lease_id, None)
            port = self._ports.pop(lease_id, None)
        if handle is None:
            raise KeyError(f"no replica held for lease {lease_id}")
        key = f"{self.host}:{port}"
        t0 = time.monotonic()
        self.registry.mark_draining(key)
        clean = False
        deadline = t0 + timeout_s
        while time.monotonic() < deadline:
            self.registry.probe_once()
            rep = self.registry.get(key)
            if rep is None or rep.down:
                # Died while draining; nothing left to wait for.
                break
            if rep.queue_depth <= 0 and rep.slots_active <= 0:
                clean = True
                break
            time.sleep(self.poll_s)
        self.registry.deregister(self.host, port)
        handle.stop()
        drain_s = time.monotonic() - t0
        metrics.flight_recorder().record(
            "router_drain", lease_id=lease_id, replica=key,
            drained_clean=clean, drain_s=round(drain_s, 6))
        logger.info("router: lease %s drained (%s, %.2fs)", lease_id,
                    "clean" if clean else "FORCED", drain_s)
        return {"replica": key, "drained_clean": clean,
                "drain_s": drain_s}

    def held_leases(self) -> list[str]:
        with self._lock:
            return list(self._handles)
