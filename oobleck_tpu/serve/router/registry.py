"""Replica registry: who is routable, how loaded, and how stale.

Replicas self-register over a versioned JSON handshake (``v`` =
``ROUTER_WIRE_V``) advertising ``host``/``port``/``lanes``/
``weights_step``/``page_size``. Legacy replicas — older builds whose
handshake and ``/healthz`` carry none of the router keys — register and
route fine on conservative defaults (wire compat is a test, not an
accident).

Liveness is two signals, because replicas fail two ways:

  * **probes** — a daemon thread GETs every replica's ``/healthz`` each
    ``OOBLECK_ROUTER_PROBE_S`` seconds, refreshing queue depth, lane
    occupancy, and ``weights_step``, and folding the round-trip into an
    RTT EWMA. ``DOWN_AFTER`` consecutive probe failures (refused, reset,
    or hung past the probe timeout — the alive-but-unresponsive case TCP
    disconnects never surface) mark the replica DOWN.
  * **proxy errors** — a connection that dies mid-request marks the
    replica down immediately (the router was just told, no need to wait
    for the prober).

Marking a replica down is an INCIDENT, not a log line: the transition is
flight-recorded and committed through the obs incident machinery under
the trace id of the request (or probe) that saw it die, so a replica
death is forensically reconstructible exactly like a training host loss.

Weights-skew gate: a replica lagging more than ``OOBLECK_ROUTER_SKEW_MAX``
hot-reloads behind the fleet's newest ``weights_step`` is COOLED — kept
registered and probed, but routed to only when nothing fresher can take
the request. Serving stale weights silently is how A/B mysteries are
born; cooling is visible in ``/replicas`` and the state gauge.
"""

from __future__ import annotations

import http.client
import json
import logging
import threading
import time

from oobleck_tpu.obs import incident as incident_mod
from oobleck_tpu.utils import metrics

logger = logging.getLogger("oobleck.router")

# Handshake/wire version the router speaks. Registrations without "v"
# (legacy replicas) are accepted with conservative defaults.
ROUTER_WIRE_V = 1

ENV_PROBE_S = "OOBLECK_ROUTER_PROBE_S"
ENV_SKEW_MAX = "OOBLECK_ROUTER_SKEW_MAX"

DEFAULT_PROBE_S = 1.0
DEFAULT_SKEW_MAX = 2        # hot-reloads behind fleet max before cooling
DOWN_AFTER = 2              # consecutive probe failures -> DOWN
# Service-time floor for load estimates before any TTFT has been
# measured: an idle fleet must not estimate zero wait for a deep queue.
DEFAULT_SERVICE_S = 0.05


def _env_float(name: str, default: float) -> float:
    import os

    raw = os.environ.get(name, "")
    try:
        return float(raw) if raw else default
    except ValueError:
        return default


class Replica:
    """One serving replica's registered identity + probed live state."""

    def __init__(self, host: str, port: int, *, lanes: int = 1,
                 weights_step: int = -1, page_size: int = 0,
                 wire_v: int = 0):
        self.host = host
        self.port = int(port)
        self.lanes = max(int(lanes), 1)
        self.weights_step = int(weights_step)   # -1 = unknown (legacy)
        self.page_size = int(page_size)
        self.wire_v = int(wire_v)
        # Probed state.
        self.queue_depth = 0.0
        self.slots_active = 0
        self.retry_after_s = 1
        self.rtt_ewma_s: float | None = None
        self.ttft_ewma_s: float | None = None   # router-measured
        self.probe_failures = 0
        self.last_probe_t: float | None = None
        # Lifecycle.
        self.down = False
        self.down_reason: str | None = None
        self.draining = False

    @property
    def key(self) -> str:
        return f"{self.host}:{self.port}"

    def est_wait_s(self) -> float:
        """Projected time-to-first-token for a NEW request on this
        replica: queued requests plus fractional lane occupancy, each
        costed at the router-measured TTFT EWMA (floor: a nominal service
        time, so a deep queue is never estimated free)."""
        service = self.ttft_ewma_s if self.ttft_ewma_s else DEFAULT_SERVICE_S
        occupancy = self.slots_active / self.lanes
        return (self.queue_depth + occupancy) * service

    def observe_ttft(self, ttft_s: float) -> None:
        self.ttft_ewma_s = ttft_s if self.ttft_ewma_s is None \
            else 0.7 * self.ttft_ewma_s + 0.3 * ttft_s

    def as_dict(self, *, cooled: bool = False) -> dict:
        return {
            "replica": self.key, "wire_v": self.wire_v,
            "lanes": self.lanes, "weights_step": self.weights_step,
            "page_size": self.page_size,
            "queue_depth": self.queue_depth,
            "slots_active": self.slots_active,
            "est_wait_s": round(self.est_wait_s(), 6),
            "rtt_ewma_s": round(self.rtt_ewma_s, 6)
            if self.rtt_ewma_s is not None else None,
            "ttft_ewma_s": round(self.ttft_ewma_s, 6)
            if self.ttft_ewma_s is not None else None,
            "state": ("down" if self.down else
                      "draining" if self.draining else
                      "cooled" if cooled else "up"),
            "down_reason": self.down_reason,
        }


class ReplicaRegistry:
    """Thread-safe replica book + background ``/healthz`` prober."""

    def __init__(self, *, probe_s: float | None = None,
                 skew_max: int | None = None,
                 probe_timeout_s: float | None = None):
        self.probe_s = probe_s if probe_s is not None \
            else _env_float(ENV_PROBE_S, DEFAULT_PROBE_S)
        self.skew_max = int(skew_max if skew_max is not None
                            else _env_float(ENV_SKEW_MAX, DEFAULT_SKEW_MAX))
        # A hung replica is only as detectable as the probe's patience.
        self.probe_timeout_s = probe_timeout_s if probe_timeout_s \
            else max(self.probe_s, 0.25)
        self._lock = threading.Lock()
        self._replicas: dict[str, Replica] = {}
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        reg = metrics.registry()
        self.m_replicas = reg.gauge(
            "oobleck_router_replicas", "Registered replicas by state")
        self.m_fleet_queue = reg.gauge(
            "oobleck_router_fleet_queue_depth",
            "Sum of probed replica admission-queue depths")
        self.m_probe_failures = reg.counter(
            "oobleck_router_probe_failures_total",
            "Replica health probes that failed or timed out")

    # -- handshake -------------------------------------------------------- #

    def register(self, payload: dict, *, default_host: str = "127.0.0.1") \
            -> dict:
        """Versioned registration handshake. Required: ``port``. Legacy
        payloads (no ``v``/``lanes``/``weights_step``/``page_size``)
        register with conservative defaults. Re-registration supersedes
        (a restarted replica on the same port is the same replica,
        fresher)."""
        if not isinstance(payload, dict) or "port" not in payload:
            raise ValueError("registration needs a 'port'")
        port = int(payload["port"])
        if port <= 0:
            raise ValueError(f"bad replica port {port}")
        wire_v = int(payload.get("v") or 0)
        rep = Replica(
            str(payload.get("host") or default_host), port,
            lanes=int(payload.get("lanes") or 1),
            weights_step=int(payload.get("weights_step", -1)),
            page_size=int(payload.get("page_size") or 0),
            wire_v=wire_v)
        with self._lock:
            self._replicas[rep.key] = rep
        metrics.flight_recorder().record(
            "router_register", replica=rep.key, wire_v=wire_v,
            lanes=rep.lanes, weights_step=rep.weights_step,
            legacy=wire_v < ROUTER_WIRE_V)
        logger.info("router: replica %s registered (v%d, %d lanes, "
                    "step %d)", rep.key, wire_v, rep.lanes,
                    rep.weights_step)
        self._set_state_gauges()
        return {"ok": True, "v": ROUTER_WIRE_V, "replica": rep.key,
                "probe_s": self.probe_s}

    def deregister(self, host: str, port: int) -> bool:
        with self._lock:
            rep = self._replicas.pop(f"{host}:{int(port)}", None)
        if rep is not None:
            logger.info("router: replica %s deregistered", rep.key)
        self._set_state_gauges()
        return rep is not None

    # -- lookups ---------------------------------------------------------- #

    def get(self, key: str) -> Replica | None:
        with self._lock:
            return self._replicas.get(key)

    def replicas(self) -> list[Replica]:
        with self._lock:
            return list(self._replicas.values())

    def fleet_weights_step(self) -> int:
        """Newest weights_step any live replica serves (-1: unknown)."""
        return max((r.weights_step for r in self.replicas()
                    if not r.down), default=-1)

    def is_cooled(self, rep: Replica) -> bool:
        """Weights-skew gate: lagging more than skew_max hot-reloads
        behind the fleet's newest step. Unknown steps (legacy replicas)
        are never cooled — the gate needs evidence, not absence."""
        if rep.weights_step < 0:
            return False
        fleet = self.fleet_weights_step()
        return fleet >= 0 and fleet - rep.weights_step > self.skew_max

    def routable(self) -> tuple[list[Replica], list[Replica]]:
        """(fresh, cooled): fresh replicas are up, not draining, within
        the skew gate; cooled ones are routable only as a last resort."""
        fresh, cooled = [], []
        for r in self.replicas():
            if r.down or r.draining:
                continue
            (cooled if self.is_cooled(r) else fresh).append(r)
        return fresh, cooled

    # -- state transitions ------------------------------------------------- #

    def mark_down(self, key: str, *, reason: str,
                  trace_id: str | None = None) -> Replica | None:
        """Mark a replica down (idempotent). The DOWN transition is a
        first-class incident: flight-recorded and committed through the
        obs incident machinery under the observing request's trace id.
        Returns the replica iff this call performed the transition."""
        with self._lock:
            rep = self._replicas.get(key)
            if rep is None or rep.down:
                return None
            rep.down = True
            rep.down_reason = reason
        logger.warning("router: replica %s marked down (%s)", key, reason)
        metrics.flight_recorder().record(
            "router_replica_down", replica=key, reason=reason,
            trace_id=trace_id)
        builder = incident_mod.IncidentBuilder(
            key, trace_id=trace_id, cause="serve_replica_down",
            reason=reason)
        builder.mark("detect")
        builder.commit()
        self._set_state_gauges()
        return rep

    def mark_draining(self, key: str) -> Replica | None:
        with self._lock:
            rep = self._replicas.get(key)
            if rep is not None:
                rep.draining = True
        self._set_state_gauges()
        return rep

    def _set_state_gauges(self) -> None:
        counts = {"up": 0, "cooled": 0, "down": 0, "draining": 0}
        for r in self.replicas():
            if r.down:
                counts["down"] += 1
            elif r.draining:
                counts["draining"] += 1
            elif self.is_cooled(r):
                counts["cooled"] += 1
            else:
                counts["up"] += 1
        for state, n in counts.items():
            self.m_replicas.set(n, state=state)

    # -- probing ----------------------------------------------------------- #

    def probe_once(self) -> None:
        """One sweep over every replica's /healthz. Down replicas stay
        probed: one that answers again self-heals (DOWN is a judgment,
        not a tombstone — a deregister is the tombstone)."""
        fleet_queue = 0.0
        for rep in self.replicas():
            t0 = time.monotonic()
            try:
                conn = http.client.HTTPConnection(
                    rep.host, rep.port, timeout=self.probe_timeout_s)
                conn.request("GET", "/healthz")
                resp = conn.getresponse()
                health = json.loads(resp.read())
                conn.close()
                if resp.status != 200 or not health.get("ok"):
                    raise OSError(f"healthz status {resp.status}")
            except (OSError, ValueError) as e:
                rep.probe_failures += 1
                self.m_probe_failures.inc()
                if rep.probe_failures >= DOWN_AFTER and not rep.down:
                    self.mark_down(
                        rep.key,
                        reason=f"probe: {type(e).__name__}: {e}")
                continue
            rtt = time.monotonic() - t0
            rep.rtt_ewma_s = rtt if rep.rtt_ewma_s is None \
                else 0.8 * rep.rtt_ewma_s + 0.2 * rtt
            rep.probe_failures = 0
            rep.last_probe_t = time.monotonic()
            if rep.down:
                logger.info("router: replica %s back up", rep.key)
                rep.down = False
                rep.down_reason = None
            # Versioned healthz: fall back to the legacy keys when the
            # richer ones are absent (wire compat both directions).
            rep.queue_depth = float(health.get("queue_depth") or 0.0)
            rep.slots_active = int(health.get("slots_active") or 0)
            step = health.get("weights_step", health.get("step", -1))
            rep.weights_step = int(step if step is not None else -1)
            if health.get("lanes"):
                rep.lanes = max(int(health["lanes"]), 1)
            if health.get("page_size"):
                rep.page_size = int(health["page_size"])
            if health.get("retry_after_s"):
                rep.retry_after_s = int(health["retry_after_s"])
            if not rep.draining:
                fleet_queue += rep.queue_depth
        self.m_fleet_queue.set(fleet_queue)
        self._set_state_gauges()

    def start(self) -> "ReplicaRegistry":
        self._thread = threading.Thread(
            target=self._probe_loop, name="oobleck-router-probe",
            daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(self.probe_timeout_s + self.probe_s + 5.0)

    def _probe_loop(self) -> None:
        while not self._stop.wait(self.probe_s):
            try:
                self.probe_once()
            except Exception:  # noqa: BLE001 — the prober must outlive any bad sweep
                logger.exception("router probe sweep failed")


def register_with_router(router_url: str, payload: dict,
                         *, timeout_s: float = 5.0) -> dict | None:
    """POST a registration handshake to ``router_url`` (``host:port`` or
    ``http://host:port``); the ack dict, or None on failure (callers
    retry — a replica may come up before its router)."""
    host, port = _parse_url(router_url)
    try:
        conn = http.client.HTTPConnection(host, port, timeout=timeout_s)
        conn.request("POST", "/v1/register", json.dumps(payload),
                     {"Content-Type": "application/json"})
        resp = conn.getresponse()
        ack = json.loads(resp.read())
        conn.close()
        return ack if resp.status == 200 else None
    except (OSError, ValueError):
        return None


def deregister_from_router(router_url: str, host: str, port: int,
                           *, timeout_s: float = 5.0) -> bool:
    host_r, port_r = _parse_url(router_url)
    try:
        conn = http.client.HTTPConnection(host_r, port_r,
                                          timeout=timeout_s)
        conn.request("POST", "/v1/deregister",
                     json.dumps({"host": host, "port": int(port)}),
                     {"Content-Type": "application/json"})
        resp = conn.getresponse()
        resp.read()
        conn.close()
        return resp.status == 200
    except OSError:
        return False


def _parse_url(url: str) -> tuple[str, int]:
    u = url.strip()
    if u.startswith("http://"):
        u = u[len("http://"):]
    u = u.rstrip("/")
    host, _, port = u.partition(":")
    return host or "127.0.0.1", int(port or 80)
