"""Router HTTP front door: one address in front of N serving replicas.

Same stdlib ThreadingHTTPServer pattern as the replicas it fronts (and
the master's /metrics endpoint): daemon thread, port 0 binds ephemeral,
read ``.port`` after start.

Endpoints:
  POST /v1/generate    Same body the replicas take. The router tokenizes
                       the prompt head for affinity, asks the policy for
                       an ordered candidate list, and proxies down it:
                       429 from a full replica SPILLS to the next
                       candidate; a dead connection FAILS OVER (below);
                       success returns the replica's payload annotated
                       with "routed_to" and "route_reason". Every
                       candidate full -> 429 with the soonest honest
                       Retry-After any replica advertised. No replicas
                       -> 503.
  POST /v1/register    Replica handshake (registry.ROUTER_WIRE_V).
  POST /v1/deregister  {"host", "port"} — clean replica exit.
  GET  /healthz        Router + fleet summary (replica state counts,
                       fleet weights span, fleet queue depth).
  GET  /replicas       Full per-replica registry view.
  GET  /metrics        Prometheus text for the router process.

Failover is an incident, not a retry loop: a connection that dies
mid-request marks the replica DOWN in the registry (which commits the
obs incident under this request's trace id), flight-records the
failover, and — only if the request is idempotent — retries ONCE (knob:
``OOBLECK_ROUTER_RETRY``) on the next candidate. Non-idempotent
requests get a fast 503 with the trace id instead of a silent
double-execution; clients decide. A request is idempotent when greedy
(temperature 0) or when the body says ``"idempotent": true/false``
explicitly (the body wins — greedy-but-stateful callers exist).

Every request carries one trace id end to end: the router injects it
into the proxied body (replicas echo it and tag their server-side spans
with it), records its own ``router.request`` span under it, and stamps
it on any failover incident — so "what happened to request X" is one
trace query even when X crossed three replicas.
"""

from __future__ import annotations

import http.client
import json
import logging
import os
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from oobleck_tpu.obs import spans
from oobleck_tpu.utils import metrics
from oobleck_tpu.utils.metrics import SERVE_LATENCY_BUCKETS

logger = logging.getLogger("oobleck.router")

ENV_PORT = "OOBLECK_ROUTER_PORT"
ENV_RETRY = "OOBLECK_ROUTER_RETRY"

DEFAULT_RETRY = 1          # failover retries per request (idempotent only)
SHED_RETRY_AFTER_S = 5     # Retry-After floor when no replica advertised one


def _env_int(name: str, default: int) -> int:
    raw = os.environ.get(name, "")
    try:
        return int(raw) if raw else default
    except ValueError:
        return default


class RouterHTTPServer:
    """Routing proxy over a ReplicaRegistry + RoutingPolicy."""

    def __init__(self, registry, policy, *, port: int | None = None,
                 host: str = "0.0.0.0", proxy_timeout_s: float = 120.0,
                 retry_max: int | None = None):
        self.registry = registry
        self.policy = policy
        self.proxy_timeout_s = proxy_timeout_s
        self.retry_max = retry_max if retry_max is not None \
            else _env_int(ENV_RETRY, DEFAULT_RETRY)
        reg = metrics.registry()
        self.m_requests = reg.counter(
            "oobleck_router_requests_total",
            "Routed requests by outcome (finish_reason, shed, "
            "failover_503, retries_exhausted, no_replicas, error)")
        self.m_failovers = reg.counter(
            "oobleck_router_failovers_total",
            "Mid-request replica failures the router absorbed")
        self.m_spills = reg.counter(
            "oobleck_router_spills_total",
            "Hops to a fallback replica because the pick returned 429")
        self.m_ttft = reg.histogram(
            "oobleck_router_ttft_seconds",
            "Replica-reported TTFT as seen through the router",
            buckets=SERVE_LATENCY_BUCKETS)
        self.m_latency = reg.histogram(
            "oobleck_router_request_seconds",
            "Router-side end-to-end request latency",
            buckets=SERVE_LATENCY_BUCKETS)
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # keep test logs quiet
                logger.debug("router http: " + fmt, *args)

            def _reply(self, code: int, payload,
                       ctype: str = "application/json",
                       headers: dict | None = None) -> None:
                body = json.dumps(payload).encode() \
                    if ctype == "application/json" else payload
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                for k, v in (headers or {}).items():
                    self.send_header(k, str(v))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                try:
                    path = self.path.split("?")[0]
                    if path == "/healthz":
                        self._reply(200, outer._health())
                    elif path == "/replicas":
                        self._reply(200, {
                            "replicas": [
                                r.as_dict(
                                    cooled=outer.registry.is_cooled(r))
                                for r in outer.registry.replicas()]})
                    elif path == "/metrics":
                        text = metrics.render_prometheus(
                            [metrics.registry().snapshot()]).encode()
                        self._reply(
                            200, text,
                            "text/plain; version=0.0.4; charset=utf-8")
                    else:
                        self.send_error(404)
                except Exception:  # noqa: BLE001 — endpoint must never kill the router
                    logger.exception("router GET failed")
                    self.send_error(500)

            def do_POST(self):
                try:
                    path = self.path.split("?")[0]
                    length = int(self.headers.get("Content-Length") or 0)
                    try:
                        body = json.loads(self.rfile.read(length) or b"{}")
                        if not isinstance(body, dict):
                            raise ValueError("body must be a JSON object")
                    except ValueError as e:
                        self._reply(400, {"error": str(e)})
                        return
                    if path == "/v1/generate":
                        code, payload, headers = outer._route(body)
                        self._reply(code, payload, headers=headers)
                    elif path == "/v1/register":
                        try:
                            self._reply(
                                200,
                                outer.registry.register(
                                    body,
                                    default_host=self.client_address[0]))
                        except (ValueError, TypeError) as e:
                            self._reply(400, {"error": str(e)})
                    elif path == "/v1/deregister":
                        ok = outer.registry.deregister(
                            str(body.get("host") or self.client_address[0]),
                            int(body.get("port") or 0))
                        self._reply(200 if ok else 404, {"ok": ok})
                    else:
                        self.send_error(404)
                except Exception:  # noqa: BLE001 — endpoint must never kill the router
                    logger.exception("router POST failed")
                    self.send_error(500)

        self._server = ThreadingHTTPServer(
            (host, port if port is not None else _env_int(ENV_PORT, 0)),
            Handler)
        self._server.daemon_threads = True
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="oobleck-router-http",
            daemon=True)

    # -- fleet summary ----------------------------------------------------- #

    def _health(self) -> dict:
        reps = self.registry.replicas()
        states: dict[str, int] = {}
        for r in reps:
            state = r.as_dict(cooled=self.registry.is_cooled(r))["state"]
            states[state] = states.get(state, 0) + 1
        return {
            "ok": any(not r.down and not r.draining for r in reps),
            "replicas": len(reps),
            "states": states,
            "fleet_weights_step": self.registry.fleet_weights_step(),
            "fleet_queue_depth": sum(
                r.queue_depth for r in reps if not r.down),
        }

    # -- the routed request ------------------------------------------------ #

    def _route(self, body: dict) -> tuple[int, dict, dict | None]:
        t0 = time.time()
        trace_id = body.get("trace_id")
        if not isinstance(trace_id, str) or not trace_id:
            trace_id = spans.new_trace_id()
        body = dict(body)
        body["trace_id"] = trace_id
        tokens = self._head_tokens(body)
        deadline_ms = body.get("deadline_ms")
        try:
            deadline_s = float(deadline_ms) / 1e3 if deadline_ms else None
        except (TypeError, ValueError):
            deadline_s = None
        idempotent = bool(body.get(
            "idempotent", float(body.get("temperature") or 0.0) <= 0.0))
        order, reason = self.policy.plan(tokens, deadline_s)
        if not order:
            self.m_requests.inc(outcome="no_replicas")
            return 503, {"error": "no routable replicas",
                         "trace_id": trace_id}, None
        failovers = 0
        retry_afters: list[int] = []
        for hop, rep in enumerate(order):
            status, payload, err = self._proxy(rep, body)
            if err is not None:
                failovers += 1
                self.m_failovers.inc()
                self.registry.mark_down(rep.key, reason=f"proxy: {err}",
                                        trace_id=trace_id)
                metrics.flight_recorder().record(
                    "router_failover", replica=rep.key, error=err,
                    idempotent=idempotent, retry=failovers,
                    trace_id=trace_id)
                spans.span_recorder().record(
                    "router.failover", t0, time.time(),
                    trace_id=trace_id, replica=rep.key, error=err,
                    idempotent=idempotent)
                if not idempotent:
                    # The replica may have executed side effects before
                    # dying; replaying a non-idempotent request is the
                    # router silently double-spending. Fail fast, tell
                    # the client which trace to investigate.
                    self.m_requests.inc(outcome="failover_503")
                    return 503, {
                        "error": f"replica {rep.key} failed mid-request; "
                                 "request not idempotent, not retried",
                        "trace_id": trace_id}, None
                if failovers > self.retry_max:
                    self.m_requests.inc(outcome="retries_exhausted")
                    return 503, {
                        "error": f"{failovers} replicas failed "
                                 "mid-request; retries exhausted",
                        "trace_id": trace_id}, None
                continue
            if status == 429:
                # Replica full: spill down the plan, remember its honest
                # Retry-After in case everyone is full.
                self.m_spills.inc()
                retry_afters.append(
                    int((payload or {}).get("retry_after_s") or 0))
                continue
            route_reason = reason if hop == 0 else (
                "failover" if failovers else "spill")
            outcome = str(payload.get("finish_reason") or f"status_{status}") \
                if status == 200 else f"status_{status}"
            self.m_requests.inc(outcome=outcome)
            if status == 200:
                ttft_s = float(payload.get("ttft_ms") or 0.0) / 1e3
                self.m_ttft.observe(ttft_s)
                rep.observe_ttft(ttft_s)
                payload["routed_to"] = rep.key
                payload["route_reason"] = route_reason
            self.m_latency.observe(time.time() - t0)
            spans.span_recorder().record(
                "router.request", t0, time.time(), trace_id=trace_id,
                replica=rep.key, reason=route_reason, status=status,
                hops=hop + 1, failovers=failovers)
            return status, payload, None
        # Every candidate admitted nothing: shed with the SOONEST honest
        # Retry-After any replica advertised (first slot to free anywhere
        # in the fleet is when retrying can succeed).
        retry_after = min((ra for ra in retry_afters if ra > 0),
                          default=SHED_RETRY_AFTER_S)
        self.m_requests.inc(outcome="shed")
        self.m_latency.observe(time.time() - t0)
        return 429, {"error": "all replicas at capacity",
                     "retry_after_s": retry_after,
                     "trace_id": trace_id}, \
            {"Retry-After": retry_after}

    def _head_tokens(self, body: dict) -> list[int]:
        """Prompt head as ints for the affinity fingerprint. Mirrors the
        replica's tokenization (explicit ids, else byte-level prompt) but
        never raises — malformed bodies route balanced and let the
        replica produce the authoritative 400."""
        tokens = body.get("tokens")
        if isinstance(tokens, list) and all(
                isinstance(t, int) and not isinstance(t, bool)
                for t in tokens):
            return tokens
        prompt = body.get("prompt")
        if isinstance(prompt, str):
            return list(prompt.encode("utf-8"))
        return []

    def _proxy(self, rep, body: dict) \
            -> tuple[int, dict, None] | tuple[None, None, str]:
        """One proxied attempt: (status, payload, None) on any HTTP
        response (429s and 4xx/5xx included — those are the replica
        SPEAKING, not dead), (None, None, error) when the connection
        refused, reset, or timed out."""
        try:
            conn = http.client.HTTPConnection(
                rep.host, rep.port, timeout=self.proxy_timeout_s)
            try:
                conn.request("POST", "/v1/generate", json.dumps(body),
                             {"Content-Type": "application/json"})
                resp = conn.getresponse()
                raw = resp.read()
            finally:
                conn.close()
            payload = json.loads(raw) if raw else {}
            if not isinstance(payload, dict):
                payload = {}
            return resp.status, payload, None
        except (OSError, ValueError, http.client.HTTPException) as e:
            return None, None, f"{type(e).__name__}: {e}"

    # -- lifecycle --------------------------------------------------------- #

    def start(self) -> "RouterHTTPServer":
        self._thread.start()
        logger.info("router http listening on :%d", self.port)
        return self

    def close(self) -> None:
        self._server.shutdown()
        self._server.server_close()
