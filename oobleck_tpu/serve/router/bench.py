"""Router microbenchmark: scale-out, affinity, failover, pool cycle.

Four measurements, all CPU-friendly on a tiny model, one JSON line out
(consumed by bench.py's "router" key and `make router-bench`):

  1. 1 -> 3 replica scaling: the same open-loop request mix against a
     single replica and against three behind the router — sustained
     rps and replica-measured TTFT p50/p99 for both (the router's win
     is the p99 under load, where the single replica queues).
  2. Prefix affinity vs random: repeated shared-prefix prompts routed
     affine (rendezvous on the page-chain hash) vs balanced-random;
     the fleet-wide prefix-cache hit rate each routing mode earns is
     the direct measure of why affinity exists.
  3. Failover: a `kill_replica` chaos directive murders the affine
     replica mid-request; idempotent traffic continues; reported are
     failed idempotent requests (bar: ZERO) and recovery seconds
     (kill -> first post-failover request routed cleanly).
  4. Pool elasticity: a burst overloads the fleet, the router's
     FleetPressureMonitor prices it onto a POOL_BORROW against a real
     (scripted-agent) training master, the granted lease becomes a 4th
     replica via ReplicaScaler, absorbs live traffic, and the release
     rides LEASE_RECLAIM into a router drain — dropped bar: ZERO.

Standalone:  python -m oobleck_tpu.serve.router.bench
"""

from __future__ import annotations

import asyncio
import http.client
import json
import os
import shutil
import tempfile
import threading
import time

import numpy as np

MODEL = "gpt2-tiny"
MODEL_ARGS = {"num_layers": 2}
PAGE = 16
GEN_TOKENS = 4
SCALE_REQUESTS = 30      # per scaling phase
# Bursty arrivals of tiny generations: TTFT is queue wait for a decode
# lane (2 per replica, 6 behind the router), not raw FLOPs — the regime
# where replica count matters even on a shared-CPU bench host.
SCALE_RATE_HZ = 150.0
AFFINITY_HEADS = 8
AFFINITY_ROUNDS = 3
POOL_AGENTS = ("10.9.0.1", "10.9.0.2", "10.9.0.3")
LEASE_TTL_S = 60.0
PHASE_TIMEOUT_S = 30.0


def _post(port: int, body: dict, timeout: float = 120.0):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    conn.request("POST", "/v1/generate", json.dumps(body),
                 {"Content-Type": "application/json"})
    resp = conn.getresponse()
    out = json.loads(resp.read())
    conn.close()
    return resp.status, out


def _pcts(values: list[float]) -> dict:
    if not values:
        return {"p50": None, "p99": None}
    return {"p50": round(float(np.percentile(values, 50)), 3),
            "p99": round(float(np.percentile(values, 99)), 3)}


def _heads(rng, n: int) -> list[list[int]]:
    """Distinct 2-page prompt heads (the affinity fingerprint unit)."""
    return [[int(t) for t in rng.integers(1, 200, 2 * PAGE)]
            for _ in range(n)]


def _open_loop(port: int, prompts: list[list[int]], *, rate_hz: float,
               gen_tokens: int = GEN_TOKENS, seed: int = 0) -> dict:
    """Open-loop Poisson arrivals through the router; returns sustained
    rps, replica-reported TTFT values, and the failure count."""
    rng = np.random.default_rng(seed)
    ttfts, failed = [], []
    lock = threading.Lock()

    def one(tokens):
        try:
            status, out = _post(port, {"tokens": tokens,
                                       "max_tokens": gen_tokens,
                                       "temperature": 0.0})
            if status != 200:
                raise RuntimeError(f"status {status}: {out}")
            with lock:
                ttfts.append(float(out["ttft_ms"]))
        except Exception as exc:  # noqa: BLE001 — failure IS the measurement
            with lock:
                failed.append(f"{type(exc).__name__}: {exc}")

    t0 = time.perf_counter()
    threads = []
    for tokens in prompts:
        t = threading.Thread(target=one, args=(tokens,))
        t.start()
        threads.append(t)
        time.sleep(float(rng.exponential(1.0 / rate_hz)))
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    return {"rps": round(len(ttfts) / max(wall, 1e-9), 2),
            "ttft_ms": _pcts(ttfts), "completed": len(ttfts),
            "failed": len(failed), "errors": failed[:3]}


def _mk_plane(root, model, *, router_url=None):
    from oobleck_tpu.config import ServeArguments
    from oobleck_tpu.serve import ServingPlane

    return ServingPlane(
        root, model=model,
        args=ServeArguments(port=0, slots=2, max_seq=64, reload_secs=5.0,
                            page_size=PAGE, kv_pages=64, lanes=2),
        router_url=router_url).start()


def _wait_routable(router, n: int, timeout_s: float = 60.0) -> None:
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        fresh, _ = router.registry.routable()
        if len(fresh) >= n:
            return
        time.sleep(0.05)
    raise TimeoutError(f"fleet never reached {n} routable replicas")


def _warm(planes) -> None:
    """One direct request per replica so JIT compilation happens outside
    the measurement window (fresh engines otherwise pay it on their
    first routed request)."""
    for p in planes:
        _post(p.server.port, {"tokens": [1, 2, 3], "max_tokens": 2})


def _prefix_hit_rate(router, prompts: list[list[int]]) -> float:
    """Fleet-wide prefix-cache hit rate for a closed-loop pass (the
    engines share the process-global hit counter, so the delta IS the
    fleet total)."""
    from oobleck_tpu.utils import metrics

    hits0 = metrics.registry().counter(
        "oobleck_serve_prefix_hits_total", "").value()
    n = 0
    for tokens in prompts:
        status, out = _post(router.port, {"tokens": tokens,
                                          "max_tokens": 4})
        if status == 200:
            n += 1
    hits = metrics.registry().counter(
        "oobleck_serve_prefix_hits_total", "").value() - hits0
    return round(hits / max(n, 1), 4)


def _measure_failover(router) -> dict:
    from oobleck_tpu.utils import chaos as chaos_mod

    rng = np.random.default_rng(7)
    head = _heads(rng, 1)[0]
    # Warm the head so it has an affine owner, then murder that owner
    # on its next generate request.
    status, out = _post(router.port, {"tokens": head, "max_tokens": 4})
    assert status == 200, out
    victim = out["routed_to"]
    chaos_mod.reset(f"kill_replica={int(victim.split(':')[1])}@1")
    t0 = time.perf_counter()
    failed = 0
    failover_seen = False
    recovery_s = None
    for i in range(8):
        status, out = _post(router.port, {
            "tokens": head + [i + 1], "max_tokens": 4,
            "temperature": 0.0})
        if status != 200:
            failed += 1
            continue
        if out["route_reason"] == "failover":
            failover_seen = True
        elif failover_seen and recovery_s is None:
            recovery_s = round(time.perf_counter() - t0, 4)
    chaos_mod.reset("")
    return {"victim": victim, "failover_absorbed": failover_seen,
            "failed_idempotent": failed,
            "recovery_s": recovery_s}


async def _wait_verb(agents, verb: str) -> None:
    for a in agents:
        deadline = time.monotonic() + PHASE_TIMEOUT_S
        while time.monotonic() < deadline:
            if any(m.get("kind") == verb for m in a.inbox):
                break
            await asyncio.sleep(0.01)
        else:
            raise TimeoutError(f"{a.ip}: no {verb} broadcast")


async def _pool_cycle(router, root, model) -> dict:
    """Borrow -> scale-out -> absorb -> reclaim -> drain, against a real
    journaling master with scripted training agents (elastic/
    master_bench harness — real TCP, no workers)."""
    from oobleck_tpu.config import OobleckArguments
    from oobleck_tpu.elastic import journal as journal_mod
    from oobleck_tpu.elastic.master_bench import (
        ScriptedAgent,
        _hard_kill,
        _start_master,
    )
    from oobleck_tpu.elastic.message import (
        LEASE_KEY,
        TENANT_KEY,
        RequestType,
        ResponseType,
        recv_msg,
        send_request,
    )
    from oobleck_tpu.pool import arbiter as pool_arbiter
    from oobleck_tpu.serve.router import ReplicaScaler

    tmp = tempfile.mkdtemp(prefix="oobleck-router-bench-journal-")
    os.environ[journal_mod.ENV_STATE_DIR] = tmp
    os.environ[pool_arbiter.ENV_POOL] = "1"

    args = OobleckArguments()
    args.dist.node_ips = list(POOL_AGENTS)
    m, mtask = await _start_master(0)
    mport = m.port
    r, w = await asyncio.open_connection("127.0.0.1", mport)
    await send_request(w, RequestType.LAUNCH_JOB, {"args": args.to_dict()})
    assert (await recv_msg(r))["kind"] == ResponseType.SUCCESS.value
    w.close()
    fleet = [ScriptedAgent(ip) for ip in POOL_AGENTS]
    for a in fleet:
        await a.register(mport)

    monitor = router.pressure
    monitor.queue_high = 1.0
    monitor.hysteresis = 1

    planes = []

    def factory(lease):
        plane = _mk_plane(root, model)
        planes.append(plane)
        plane.port = plane.server.port
        plane.lanes = 2
        plane.weights_step = plane.engine.params_step
        plane.page_size = PAGE
        return plane

    scaler = ReplicaScaler(router.registry, factory, poll_s=0.05)
    rng = np.random.default_rng(11)
    try:
        # Overload the fleet so queues build behind every replica: the
        # FLEET aggregate, not one replica's, is what must pressure.
        burst_prompts = [[int(t) for t in rng.integers(1, 90, 8)]
                         for _ in range(24)]
        burst = asyncio.create_task(asyncio.to_thread(
            _open_loop, router.port, burst_prompts, rate_hz=60.0,
            gen_tokens=48, seed=3))
        pressure = None
        deadline = time.monotonic() + PHASE_TIMEOUT_S
        while time.monotonic() < deadline:
            monitor.sample()
            if monitor.pressured \
                    and monitor.slo_debt_s(LEASE_TTL_S) >= 5.0:
                pressure = monitor.as_payload(horizon_s=LEASE_TTL_S)
                break
            await asyncio.sleep(0.02)
        assert pressure is not None, "fleet never pressured under burst"

        t0 = time.monotonic()
        r, w = await asyncio.open_connection("127.0.0.1", mport)
        await send_request(w, RequestType.POOL_BORROW, {
            TENANT_KEY: "router-serve", "chips": 1, "pressure": pressure,
            "slo": {"ttft_p99_s": monitor.ttft_slo_s},
            "lease_ttl_s": LEASE_TTL_S, "cause": "router_fleet_pressure"})
        msg = await recv_msg(r)
        w.close()
        borrow_latency = time.monotonic() - t0
        assert msg["kind"] == ResponseType.SUCCESS.value, msg
        lease = msg[LEASE_KEY]
        victim_ip = lease["hosts"][0]
        # Grant broadcast first, THEN the victim drains out of the
        # training fleet — a lease is a clean exit, not a failure, but
        # only once the master has marked it leaving.
        await _wait_verb(fleet, ResponseType.LEASE_GRANT.value)
        next(a for a in fleet if a.ip == victim_ip).close()

        # Lease -> new replica, registered and probed routable.
        t0 = time.monotonic()
        handle = await asyncio.to_thread(
            scaler.scale_out, dict(lease), timeout_s=60.0)
        scale_out_s = time.monotonic() - t0
        new_key = f"127.0.0.1:{handle.port}"

        # The new replica absorbs live traffic (short prompts balance
        # by load; the fresh empty replica wins the po2 pick).
        absorbed = 0
        absorb_failed = 0
        for i in range(8):
            status, out = _post(router.port, {
                "tokens": [int(t) for t in rng.integers(1, 90, 6)],
                "max_tokens": 4, "temperature": 0.0})
            if status != 200:
                absorb_failed += 1
            elif out["routed_to"] == new_key:
                absorbed += 1
        burst_out = await burst

        # Off-peak: release; the reclaim broadcast reaches the training
        # fleet while the router drains the leased replica to zero.
        monitor.sample()
        t0 = time.monotonic()
        r, w = await asyncio.open_connection("127.0.0.1", mport)
        await send_request(w, RequestType.POOL_BORROW, {
            TENANT_KEY: "router-serve", "release": lease["lease_id"],
            "pressure": monitor.as_payload(horizon_s=LEASE_TTL_S)})
        msg = await recv_msg(r)
        w.close()
        assert msg["kind"] == ResponseType.SUCCESS.value, msg
        survivors = [a for a in fleet if a.ip != victim_ip]
        await _wait_verb(survivors, ResponseType.LEASE_RECLAIM.value)
        drain = await asyncio.to_thread(
            scaler.drain, lease["lease_id"], timeout_s=30.0)
        reclaim_s = time.monotonic() - t0

        return {
            "pressure_at_borrow": {
                "score": pressure["score"],
                "queue_depth": pressure["queue_depth"],
                "slo_debt_s": pressure["slo_debt_s"]},
            "borrow_latency_s": round(borrow_latency, 6),
            "victim": victim_ip,
            "scale_out_s": round(scale_out_s, 6),
            "new_replica": new_key,
            "absorbed_requests": absorbed,
            "burst": {"completed": burst_out["completed"],
                      "failed": burst_out["failed"]},
            "dropped": absorb_failed + burst_out["failed"],
            "drained_clean": drain["drained_clean"],
            "drain_s": round(drain["drain_s"], 6),
            "release_to_drained_s": round(reclaim_s, 6),
        }
    finally:
        for p in planes:
            try:
                p.stop()
            except Exception:  # noqa: BLE001 — teardown is best-effort
                pass
        _hard_kill(m)
        mtask.cancel()
        await m.stop()
        for a in fleet:
            a.close()
        shutil.rmtree(tmp, ignore_errors=True)


def measure_router() -> dict:
    import jax

    from oobleck_tpu.models import build_model
    from oobleck_tpu.serve.reload import publish_params
    from oobleck_tpu.serve.router import RouterPlane
    from oobleck_tpu.utils import chaos as chaos_mod

    chaos_mod.reset("")
    tmp = tempfile.mkdtemp(prefix="oobleck_router_bench_")
    router = None
    planes = []
    rng = np.random.default_rng(0)
    try:
        model = build_model(MODEL, MODEL_ARGS)
        params = model.init_params(jax.random.PRNGKey(0))
        publish_params(tmp, model, params, step=1, model_name=MODEL)
        router = RouterPlane(host="127.0.0.1", probe_s=0.1,
                             seed=0).start()
        url = f"127.0.0.1:{router.port}"

        # -- 1 replica vs 3, same workload shape -------------------- #
        planes.append(_mk_plane(tmp, model, router_url=url))
        _wait_routable(router, 1)
        _warm(planes)
        single_prompts = [h + [i] for i, h in
                          enumerate(_heads(rng, SCALE_REQUESTS))]
        single = _open_loop(router.port, single_prompts,
                            rate_hz=SCALE_RATE_HZ, seed=1)
        planes.extend(_mk_plane(tmp, model, router_url=url)
                      for _ in range(2))
        _wait_routable(router, 3)
        _warm(planes[1:])
        multi_prompts = [h + [i] for i, h in
                         enumerate(_heads(rng, SCALE_REQUESTS))]
        multi = _open_loop(router.port, multi_prompts,
                           rate_hz=SCALE_RATE_HZ, seed=2)
        multi["replicas"] = 3
        speedup = round(multi["rps"] / max(single["rps"], 1e-9), 3)

        # -- prefix affinity vs random routing ---------------------- #
        # Fresh head sets per mode so each starts with a cold cache.
        affine_heads = _heads(rng, AFFINITY_HEADS)
        affine_prompts = [h + [r] for r in range(AFFINITY_ROUNDS)
                          for h in affine_heads]
        affine_rate = _prefix_hit_rate(router, affine_prompts)
        router.policy.affinity = False
        random_heads = _heads(rng, AFFINITY_HEADS)
        random_prompts = [h + [r] for r in range(AFFINITY_ROUNDS)
                          for h in random_heads]
        random_rate = _prefix_hit_rate(router, random_prompts)
        router.policy.affinity = True

        # -- failover under chaos ----------------------------------- #
        failover = _measure_failover(router)

        # -- pool borrow -> scale-out -> reclaim -> drain ----------- #
        pool = asyncio.run(_pool_cycle(router, tmp, model))

        return {
            "model": MODEL,
            "single_replica": single,
            "multi_replica": multi,
            "rps_speedup": speedup,
            "prefix": {
                "affine_hit_rate": affine_rate,
                "random_hit_rate": random_rate,
                "affinity_gain": round(affine_rate - random_rate, 4)},
            "failover": failover,
            "pool": pool,
            "note": ("tiny model on CPU; 3 in-process replicas behind "
                     "one router over real sockets; pool cycle against "
                     "a scripted-agent training master"),
        }
    finally:
        chaos_mod.reset("")
        if router is not None:
            router.stop()
        for p in planes:
            try:
                p.stop()
            except Exception:  # noqa: BLE001 — teardown is best-effort
                pass
        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    print(json.dumps(measure_router()))
