"""Fleet-wide pressure: the router's view feeds the pool arbiter.

The pool arbiter (pool/pressure.py) already knows how to turn serve
metrics into a borrow verdict — hysteresis, SLO-debt pricing, the
``as_payload`` dict that rides POOL_BORROW. What changes behind a
router is WHICH metrics: one replica's queue depth is noise, the
FLEET's aggregate is signal (one hot replica with two idle siblings is
a routing problem, not a capacity problem — the fleet queue stays low
and no borrow fires; every replica deep is a capacity problem and the
aggregate says so).

So this subclass swaps only the three raw reads for the router-side
aggregates the registry and proxy path publish, and inherits the entire
verdict/debt/payload model unchanged:

  * queue depth   <- ``oobleck_router_fleet_queue_depth`` (the probe
                     loop's sum of replica admission queues)
  * TTFT p99      <- ``oobleck_router_ttft_seconds`` (replica-reported
                     TTFT as observed through the proxy path)
  * deadline debt <- ``oobleck_router_requests_total`` with
                     outcome=deadline_queued (replicas' own verdicts,
                     counted where the fleet total lives)

Because ``sample()``/``slo_debt_s()``/``as_payload()`` are inherited,
the router's pressure rides the existing POOL_BORROW wire format with
zero master-side changes: sustained fleet-wide peak -> borrow -> the
ReplicaScaler (scale.py) turns the granted lease into a new replica.
"""

from __future__ import annotations

from oobleck_tpu.pool.pressure import PressureMonitor
from oobleck_tpu.utils import metrics


class FleetPressureMonitor(PressureMonitor):
    """PressureMonitor over the router's fleet-wide aggregates."""

    def _queue_depth(self) -> float:
        series = self._reg().gauge(
            "oobleck_router_fleet_queue_depth", "").series()
        return max((s["value"] for s in series), default=0.0)

    def _ttft_p99(self) -> float | None:
        hist = self._reg().histogram("oobleck_router_ttft_seconds", "")
        merged = metrics.merge_histogram_series(hist.series())
        if merged is None:
            return None
        return metrics.histogram_percentile(merged, 0.99)

    def _deadline_queued_total(self) -> float:
        counter = self._reg().counter("oobleck_router_requests_total", "")
        return sum(s["value"] for s in counter.series()
                   if s["labels"].get("outcome") == "deadline_queued")
