"""Multi-replica serving router — the L6 front door.

One address in front of N ServingPlane replicas. The serving plane
(layer below) made ONE replica resilient: hot-reloading weights from a
live training job, admission control, paged KV. This package makes the
REPLICA SET a single dependable endpoint, and closes the loop with the
chip pool so the set can grow under load and shrink on reclaim:

  registry.py   Who is routable: versioned self-registration handshake,
                /healthz probe loop with RTT EWMAs, DOWN on consecutive
                failures, weights-skew COOLING (a replica lagging the
                fleet's hot-reloads serves only as a last resort).
  routing.py    Where a request goes: prefix-affine rendezvous hashing
                over the SAME rolling page-chain hash the paged KV cache
                is keyed with, deadline-aware spill to
                power-of-two-choices, cooled replicas last.
  server.py     The proxy itself: ordered-candidate walk (429 spills,
                dead connections fail over — retried once when
                idempotent, fast 503 when not), one trace id per request
                across every hop, honest fleet-wide Retry-After when
                everyone is full.
  pressure.py   Fleet-wide PressureMonitor: the pool arbiter's borrow
                verdict fed by router aggregates instead of one
                replica's metrics.
  scale.py      Leases -> replicas: POOL_BORROW grants become registered
                replicas absorbing traffic; LEASE_RECLAIM drains them
                through the router with zero dropped requests.

``RouterPlane`` wires the pieces; tests and the bench compose the parts
directly when they need seams.

Env knobs: ``OOBLECK_ROUTER_PORT`` (listen port, 0 = ephemeral),
``OOBLECK_ROUTER_PROBE_S`` (health-probe period),
``OOBLECK_ROUTER_SKEW_MAX`` (hot-reloads behind fleet max before a
replica is cooled), ``OOBLECK_ROUTER_RETRY`` (failover retries for
idempotent requests). Replicas point ``OOBLECK_ROUTER_URL`` (or
``ServingPlane(router_url=...)``) at the router to self-register.
"""

from __future__ import annotations

from oobleck_tpu.serve.router.pressure import FleetPressureMonitor
from oobleck_tpu.serve.router.registry import (
    ROUTER_WIRE_V,
    Replica,
    ReplicaRegistry,
    deregister_from_router,
    register_with_router,
)
from oobleck_tpu.serve.router.routing import RoutingPolicy
from oobleck_tpu.serve.router.scale import ReplicaScaler
from oobleck_tpu.serve.router.server import RouterHTTPServer

__all__ = [
    "ROUTER_WIRE_V",
    "FleetPressureMonitor",
    "Replica",
    "ReplicaRegistry",
    "ReplicaScaler",
    "RouterHTTPServer",
    "RouterPlane",
    "RoutingPolicy",
    "deregister_from_router",
    "register_with_router",
]


class RouterPlane:
    """Registry + policy + HTTP proxy + fleet pressure, wired and
    lifecycle-managed. ``start()`` binds the port and begins probing;
    ``stop()`` tears both down. Replica scale-out is opt-in: hand
    ``attach_scaler`` a factory when the deployment can grow."""

    def __init__(self, *, port: int | None = None, host: str = "0.0.0.0",
                 probe_s: float | None = None, skew_max: int | None = None,
                 affinity: bool = True, retry_max: int | None = None,
                 proxy_timeout_s: float = 120.0, seed: int | None = None):
        self.registry = ReplicaRegistry(probe_s=probe_s, skew_max=skew_max)
        self.policy = RoutingPolicy(self.registry, affinity=affinity,
                                    seed=seed)
        self.server = RouterHTTPServer(
            self.registry, self.policy, port=port, host=host,
            proxy_timeout_s=proxy_timeout_s, retry_max=retry_max)
        self.pressure = FleetPressureMonitor()
        self.scaler: ReplicaScaler | None = None

    @property
    def port(self) -> int:
        return self.server.port

    def attach_scaler(self, factory, *, host: str = "127.0.0.1") \
            -> ReplicaScaler:
        self.scaler = ReplicaScaler(self.registry, factory, host=host)
        return self.scaler

    def start(self) -> "RouterPlane":
        self.registry.start()
        self.server.start()
        return self

    def stop(self) -> None:
        self.registry.stop()
        self.server.close()
