"""Crash-consistent restore for the durable-state plane.

`restore_latest` walks step dirs newest-first and returns the first one
that proves itself whole: global manifest present (the commit marker),
per-process manifest crcs match, data-file crcs match, every sharded
array fully covered by the pieces on disk. Anything less is QUARANTINED
(renamed into `<root>/quarantine/`) rather than crashing the restore or
— worse — being silently half-loaded: a torn checkpoint must cost at
most `interval` steps of progress, never the run.

Quarantining only happens from one process (the caller passes
`quarantine_bad=False` on non-zero ranks) so a shared-filesystem
multi-process restore doesn't race renames; every process still skips
the same dirs because validation is deterministic over the same bytes.
"""

from __future__ import annotations

import logging
import os
import time
from pathlib import Path
from typing import Any

import numpy as np

from oobleck_tpu.ckpt import manifest as mf
from oobleck_tpu.utils import metrics

logger = logging.getLogger("oobleck.ckpt")


class CheckpointCorrupt(Exception):
    """A step dir failed validation (checksum / coverage / parse)."""


def step_dirs(root: str | Path) -> list[tuple[int, Path]]:
    """All step dirs under root, newest step first."""
    root = Path(root)
    if not root.is_dir():
        return []
    out = []
    for child in root.iterdir():
        step = mf.parse_step_dir(child.name)
        if step is not None and child.is_dir():
            out.append((step, child))
    out.sort(reverse=True)
    return out


def complete_step_dirs(root: str | Path) -> list[tuple[int, Path]]:
    """Step dirs with a committed global manifest, newest first. No deep
    validation — cheap enough for `latest_checkpoint` queries."""
    return [(s, d) for s, d in step_dirs(root)
            if (d / mf.GLOBAL_MANIFEST).exists()]


def quarantine(root: str | Path, step_dir: Path, reason: str) -> Path | None:
    """Move a distrusted step dir aside (never deleted: it is evidence).
    Returns the new location, or None when the move fails (e.g. a
    concurrent quarantine won the rename)."""
    qdir = Path(root) / mf.QUARANTINE_DIR
    qdir.mkdir(parents=True, exist_ok=True)
    dest = qdir / f"{step_dir.name}.{reason}.{os.getpid()}-{time.time_ns()}"
    try:
        os.rename(step_dir, dest)
    except OSError as e:
        logger.warning("could not quarantine %s: %s", step_dir, e)
        return None
    metrics.registry().counter(
        "oobleck_ckpt_quarantined_total",
        "Corrupt/incomplete checkpoint step dirs quarantined",
    ).inc(reason=reason)
    metrics.flight_recorder().record(
        "ckpt_quarantine", dir=step_dir.name, reason=reason)
    logger.warning("quarantined checkpoint dir %s -> %s (%s)",
                   step_dir.name, dest, reason)
    return dest


# -- validation + assembly ---------------------------------------------- #

def _validated_manifests(d: Path) -> tuple[dict, list[dict]]:
    gm_path = d / mf.GLOBAL_MANIFEST
    try:
        gm = mf.read_json(gm_path)
    except (OSError, ValueError) as e:
        raise CheckpointCorrupt(f"unreadable global manifest: {e}") from e
    if gm.get("format") != mf.FORMAT_VERSION:
        raise CheckpointCorrupt(
            f"unknown manifest format {gm.get('format')!r}")
    procs = []
    for rec in gm.get("processes", []):
        path = d / rec["file"]
        if not path.exists():
            raise CheckpointCorrupt(f"missing manifest {rec['file']}")
        if mf.file_crc32(path) != rec["crc32"] \
                or path.stat().st_size != rec["bytes"]:
            raise CheckpointCorrupt(f"manifest checksum mismatch: "
                                    f"{rec['file']}")
        try:
            pm = mf.read_json(path)
        except (OSError, ValueError) as e:
            raise CheckpointCorrupt(
                f"unreadable manifest {rec['file']}: {e}") from e
        if pm.get("step") != gm.get("step"):
            raise CheckpointCorrupt(f"step mismatch in {rec['file']}")
        procs.append(pm)
    if not procs:
        raise CheckpointCorrupt("global manifest lists no processes")
    return gm, procs


def _assemble(d: Path, procs: list[dict]) -> dict[str, np.ndarray]:
    """Merge every process's pieces into full host arrays, verifying data
    checksums and global-index coverage."""
    values: dict[str, np.ndarray] = {}
    masks: dict[str, np.ndarray] = {}
    for pm in procs:
        data_path = d / pm["data_file"]
        if not data_path.exists():
            raise CheckpointCorrupt(f"missing data file {pm['data_file']}")
        if mf.file_crc32(data_path) != pm["data_crc32"]:
            raise CheckpointCorrupt(
                f"data checksum mismatch: {pm['data_file']}")
        with np.load(data_path) as data:
            for e in pm["entries"]:
                key = e["key"]
                dt = mf.dtype_from_name(e["dtype"])
                try:
                    arr = data[e["npz"]].view(dt).reshape(e["shape"])
                except (KeyError, ValueError) as err:
                    raise CheckpointCorrupt(
                        f"bad piece {e['npz']} in {pm['data_file']}: {err}"
                    ) from err
                gshape = tuple(e["global_shape"])
                if e["index"] is None:
                    try:
                        # np.ascontiguousarray promoted 0-d scalars to 1-d
                        # at write time; the global shape is authoritative.
                        values.setdefault(key, arr.reshape(gshape))
                    except ValueError as err:
                        raise CheckpointCorrupt(
                            f"{key}: full piece shape {arr.shape} != "
                            f"global {gshape}") from err
                    continue
                out = values.get(key)
                if out is None or key not in masks:
                    out = values[key] = np.empty(gshape, dt)
                    masks[key] = np.zeros(gshape, bool)
                idx = mf.decode_index(e["index"])
                out[idx] = arr
                masks[key][idx] = True
    for key, mask in masks.items():
        if not mask.all():
            raise CheckpointCorrupt(
                f"{key}: shard pieces cover only "
                f"{int(mask.sum())}/{mask.size} elements")
    return values


def _nest(flat: dict[str, Any]):
    """Rebuild a tree from '/'-joined path keys; '#i' components become
    list elements (tuples restore as lists). An empty path ('') is a bare
    leaf."""
    if list(flat.keys()) == [""]:
        return flat[""]
    root: dict = {}
    for key, v in flat.items():
        comps = key.split("/")
        node = root
        for c in comps[:-1]:
            node = node.setdefault(c, {})
        node[comps[-1]] = v

    def conv(node):
        if not isinstance(node, dict):
            return node
        if node and all(k.startswith("#") for k in node):
            return [conv(node[f"#{i}"]) for i in range(len(node))]
        return {k: conv(v) for k, v in node.items()}

    return conv(root)


def _rebuild(values: dict[str, np.ndarray], kind: str, meta: dict) -> dict:
    if kind == mf.KIND_FUSED_STACKED:
        pflat = {k[len("fs/p"):].lstrip("/"): v for k, v in values.items()
                 if k == "fs/p" or k.startswith("fs/p/")}
        oflat = {int(k.rsplit("/", 1)[1]): values[k] for k in values
                 if k.startswith("fs/o/")}
        return {"kind": kind,
                "params": _nest(pflat),
                "opt": [oflat[i] for i in range(len(oflat))],
                "meta": meta}
    params: dict[int, dict[str, Any]] = {}
    opt: dict[int, dict[int, np.ndarray]] = {}
    for key, v in values.items():
        tag, _, rest = key.partition("/")
        li_s, _, path = rest.partition("/")
        li = int(li_s)
        if tag == "p":
            params.setdefault(li, {})[path] = v
        elif tag == "o":
            leaves = opt.setdefault(li, {})
            if path != "~":  # "~" marks a leafless state: layer, no leaves
                leaves[int(path)] = v
        else:
            raise CheckpointCorrupt(f"unknown key namespace {key!r}")
    return {
        "params": {li: _nest(flat) for li, flat in params.items()},
        "opt": {li: [leaves[i] for i in range(len(leaves))]
                for li, leaves in opt.items()},
        "meta": meta,
    }


def load_step_dir(d: str | Path) -> dict:
    """Validate + load ONE committed step dir. Raises CheckpointCorrupt.

    Returns the engine checkpoint payload: {"params": {layer: tree},
    "opt": {layer: [flat leaves]}, "meta": {...}} — or, for
    kind=fused_stacked, {"kind", "params": stacked tree, "opt": [leaves],
    "meta"} for the engine to layerize."""
    d = Path(d)
    if not (d / mf.GLOBAL_MANIFEST).exists():
        raise CheckpointCorrupt("no committed global manifest")
    gm, procs = _validated_manifests(d)
    values = _assemble(d, procs)
    return _rebuild(values, gm.get("kind", mf.KIND_LAYERS), gm.get("meta", {}))


def load_latest(root: str | Path, *, quarantine_bad: bool = False
                ) -> tuple[int, dict] | None:
    """Newest complete step -> (step, payload), or None.

    The single source of truth for "which checkpoint do we load": walks
    step dirs newest-first, skips torn/corrupt dirs (quarantining them only
    when `quarantine_bad`), and returns the first that validates. The
    default is read-only because most callers are not the owner of the
    root: the serve loader (serve/reload.py) polls a root a live trainer
    is still writing to and must never rename dirs out from under it —
    only the trainer's own startup restore may quarantine."""
    root = Path(root)
    for step, d in step_dirs(root):
        if not (d / mf.GLOBAL_MANIFEST).exists():
            logger.warning(
                "checkpoint %s has no committed manifest (crash "
                "mid-write?); skipping", d.name)
            if quarantine_bad:
                quarantine(root, d, "uncommitted")
            continue
        try:
            payload = load_step_dir(d)
        except CheckpointCorrupt as e:
            logger.error("checkpoint %s failed validation: %s", d.name, e)
            if quarantine_bad:
                quarantine(root, d, "corrupt")
            continue
        logger.info("restored checkpoint %s (step %d)", d.name, step)
        return step, payload
    return None


def restore_latest(root: str | Path, *, quarantine_bad: bool = True
                   ) -> dict | None:
    """Newest restorable checkpoint payload under root, or None.

    Thin wrapper over `load_latest` keeping the trainer-startup contract:
    uncommitted and corrupt step dirs are quarantined by default (call only
    when no writer is active on this root)."""
    res = load_latest(root, quarantine_bad=quarantine_bad)
    return None if res is None else res[1]
