"""Checkpoint-stall microbenchmark: async writer vs synchronous baseline.

The durable-state plane's design claim is that the train loop's stall per
checkpoint is drain-wait + reference capture, not device_get + disk. This
measures both modes on the same state and reports p50/p99 stall plus the
async/sync ratio — the number the <25% acceptance bar is judged on.

Standalone:  python -m oobleck_tpu.ckpt.bench
Embedded:    bench.py folds the result under its "ckpt" key.
"""

from __future__ import annotations

import json
import shutil
import tempfile
import time

import numpy as np

from oobleck_tpu.ckpt import DurableStatePlane


def _state(mb: int) -> tuple[dict, dict]:
    """~mb MB of layer-keyed state, split params/opt like a real engine
    (random bytes: npz is uncompressed, but keep the disk honest anyway)."""
    n = (mb << 20) // 2 // 4  # float32 elements per leaf, 2 leaves
    rng = np.random.default_rng(0)
    leaf = rng.standard_normal(n, dtype=np.float32)
    return ({0: {"w": leaf}}, {0: (leaf.copy(),)})


def measure_stalls(root: str | None = None, *, saves: int = 6,
                   mb: int = 32) -> dict:
    """Stall percentiles for both writer modes on ~2*mb MB of state.

    Async saves are spaced by the median sync stall, mimicking a train
    loop whose inter-checkpoint compute exceeds the write time (the
    regime the at-most-one-in-flight design targets); back-to-back saves
    would measure drain-wait instead."""
    tmp = root or tempfile.mkdtemp(prefix="oobleck_ckpt_bench_")
    params, opt = _state(mb)
    try:
        sync = DurableStatePlane(f"{tmp}/sync", asynchronous=False,
                                 keep_last=2)
        sync_stalls = [sync.save(step=s, params=params, opt_state=opt)
                       for s in range(1, saves + 1)]
        sync.close()
        gap = float(np.median(sync_stalls))

        plane = DurableStatePlane(f"{tmp}/async", asynchronous=True,
                                  keep_last=2)
        async_stalls = []
        for s in range(1, saves + 1):
            async_stalls.append(plane.save(step=s, params=params,
                                           opt_state=opt))
            time.sleep(gap)
        drained = plane.flush(timeout=120.0)
        plane.close()
    finally:
        if root is None:
            shutil.rmtree(tmp, ignore_errors=True)

    def pct(xs: list[float]) -> dict:
        return {"p50": round(float(np.percentile(xs, 50)), 6),
                "p99": round(float(np.percentile(xs, 99)), 6)}

    out = {
        "state_bytes": int(sum(a.nbytes for a in (params[0]["w"], opt[0][0]))),
        "saves_per_mode": saves,
        "sync_stall_s": pct(sync_stalls),
        "async_stall_s": pct(async_stalls),
        "async_vs_sync": round(
            float(np.median(async_stalls)) / max(gap, 1e-9), 4),
    }
    if not drained:
        out["note"] = "async writer did not drain within 120s"
    return out


if __name__ == "__main__":
    print(json.dumps(measure_stalls(), indent=2))
