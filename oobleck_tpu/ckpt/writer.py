"""Background checkpoint writer: async, sharded, atomically committed.

The train loop calls `submit(snapshot)` at a step barrier. With
`asynchronous=True` (default) submit blocks while a PREVIOUS write is
still draining (at most one in flight), stages the snapshot to host
COPIES (mandatory — the train step donates its state buffers, see
snapshot.py), then hands it to a daemon thread; the loop's stall per
checkpoint is drain-wait + host staging, not pack + disk + commit.
`asynchronous=False` is the synchronous baseline the stall histogram is
judged against.

Write protocol per process (see manifest.py for the layout):

    1. stage shards to host, pack into `.tmp-shards-<p>.npz`, fsync
    2. crc32 the file, os.replace to `shards-<p>.npz`
    3.   -- chaos barrier "ckpt_mid_write" (SIGKILL injection point) --
    4. atomically write `manifest-<p>.json` (data file crc inside)
    5. process 0 only: poll the shared directory until every process's
       manifest exists and parses, then atomically commit MANIFEST.json
       (per-process manifest crcs inside) and GC old steps (keep-last-k)

No collective appears anywhere: cross-process coordination is the shared
filesystem, so the writer thread can never interleave with (or deadlock
against) the train loop's collectives. If a peer dies mid-write, the
rank-0 commit poll times out, the step stays uncommitted, and restore
later quarantines it — durability degrades to the previous complete
step, never to a torn one.

SIGTERM (the TPU maintenance/preemption notice) is handled by
`install_preemption_hook`: drain the in-flight snapshot within
OOBLECK_CKPT_FLUSH_GRACE seconds (default 10), then hand the signal
back, so a preempted worker keeps its newest checkpoint instead of
tearing it.
"""

from __future__ import annotations

import logging
import os
import shutil
import signal
import threading
import time
from pathlib import Path

import numpy as np

from oobleck_tpu.ckpt import manifest as mf
from oobleck_tpu.ckpt import snapshot as snp
from oobleck_tpu.utils import background, metrics
from oobleck_tpu.utils.chaos import chaos

logger = logging.getLogger("oobleck.ckpt")

# Chaos barrier hit between shard-data rename and manifest write: a
# kill_at=ckpt_mid_write directive leaves exactly the torn-checkpoint
# state restore must survive.
CHAOS_BARRIER_MID_WRITE = "ckpt_mid_write"

FLUSH_GRACE_ENV = "OOBLECK_CKPT_FLUSH_GRACE"


def _flush_grace() -> float:
    try:
        return float(os.environ.get(FLUSH_GRACE_ENV, "10"))
    except ValueError:
        return 10.0


class SnapshotWriter:
    """Per-process writer for one checkpoint root directory."""

    def __init__(self, root: str | Path, *, process_index: int = 0,
                 world_size: int = 1, keep_last: int = 3,
                 asynchronous: bool = True, commit_timeout: float = 120.0,
                 ip: str | None = None):
        self.root = Path(root).resolve()
        self.root.mkdir(parents=True, exist_ok=True)
        self.process_index = process_index
        self.world_size = world_size
        self.keep_last = keep_last          # <= 0 disables GC
        self.asynchronous = asynchronous
        self.commit_timeout = commit_timeout
        self.ip = ip
        self.last_durable_step = -1
        self.last_error: BaseException | None = None

        self._cond = threading.Condition()
        self._job: snp.Snapshot | None = None
        self._thread: threading.Thread | None = None
        self._closed = False
        self._hook_installed = False

        reg = metrics.registry()
        self._m_stall = reg.histogram(
            "oobleck_ckpt_stall_seconds",
            "Train-loop stall per checkpoint (mode=async: drain+enqueue; "
            "mode=sync: full capture+write+commit)",
            buckets=metrics.CKPT_STALL_BUCKETS)
        self._m_write = reg.histogram(
            "oobleck_ckpt_write_seconds",
            "Wall time of one full checkpoint write (stage+data+manifest"
            "+commit), off-thread in async mode")
        self._m_bytes = reg.counter(
            "oobleck_ckpt_bytes_total", "Checkpoint shard bytes written")
        self._m_saves = reg.counter(
            "oobleck_ckpt_saves_total", "Checkpoint snapshots written")
        self._m_last_durable = reg.gauge(
            "oobleck_ckpt_last_durable_step",
            "Newest step with a committed (restorable) checkpoint")
        self._m_gc = reg.counter(
            "oobleck_ckpt_gc_deleted_total",
            "Old checkpoint step dirs pruned by keep-last-k GC")
        self._m_commit_timeouts = reg.counter(
            "oobleck_ckpt_commit_timeouts_total",
            "Global-manifest commits abandoned waiting for peer manifests")

    # -- submission ------------------------------------------------------ #

    def submit(self, snap: snp.Snapshot) -> float:
        """Queue one snapshot; returns the train-loop stall in seconds.

        Async: blocks while the previous write is in flight (the
        double-buffer drain), stages the snapshot to host copies, then
        enqueues and returns. Sync: performs the full write inline."""
        t0 = time.perf_counter()
        # Staging reads device buffers back to host; fence it against the
        # recovery precompiler's background compiles (utils/background.py).
        with background.device_work("ckpt_stage"):
            snp.stage_to_host(snap)
        if not self.asynchronous:
            try:
                self._write(snap)
            except Exception as e:  # noqa: BLE001 — durability must not kill training
                self.last_error = e
                logger.exception("checkpoint write failed (step %d)",
                                 snap.step)
            stall = time.perf_counter() - t0
            self._m_stall.observe(stall, mode="sync")
            return stall
        with self._cond:
            while self._job is not None and not self._closed:
                self._cond.wait(0.05)
            if self._closed:
                raise RuntimeError("SnapshotWriter is closed")
            self._job = snap
            if self._thread is None or not self._thread.is_alive():
                self._thread = threading.Thread(
                    target=self._worker, name="oobleck-ckpt-writer",
                    daemon=True)
                self._thread.start()
            self._cond.notify_all()
        stall = time.perf_counter() - t0
        self._m_stall.observe(stall, mode="async")
        return stall

    def _worker(self) -> None:
        while True:
            with self._cond:
                while self._job is None and not self._closed:
                    self._cond.wait(0.5)
                if self._job is None:
                    return
                snap = self._job
            try:
                self._write(snap)
            except Exception as e:  # noqa: BLE001
                self.last_error = e
                logger.exception("checkpoint write failed (step %d)",
                                 snap.step)
            finally:
                with self._cond:
                    self._job = None
                    self._cond.notify_all()

    def flush(self, timeout: float | None = None) -> bool:
        """Wait until no write is in flight; True when drained."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cond:
            while self._job is not None:
                remaining = None if deadline is None \
                    else deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    return False
                self._cond.wait(0.05 if remaining is None
                                else min(0.05, remaining))
        return True

    def close(self) -> None:
        self.flush()
        with self._cond:
            self._closed = True
            self._cond.notify_all()
        if self._thread is not None and self._thread.is_alive():
            self._thread.join(timeout=5.0)

    # -- preemption ------------------------------------------------------ #

    def install_preemption_hook(self) -> None:
        """Chain a SIGTERM handler that drains the in-flight snapshot
        before the process obeys the signal. No-op off the main thread
        (signal.signal would raise) and when already installed."""
        if self._hook_installed:
            return
        try:
            prev = signal.getsignal(signal.SIGTERM)

            def _handler(signum, frame):
                grace = _flush_grace()
                logger.warning(
                    "SIGTERM: flushing in-flight checkpoint "
                    "(grace %.1fs, last durable step %d)",
                    grace, self.last_durable_step)
                self.flush(timeout=grace)
                metrics.flight_recorder().record(
                    "ckpt_preemption_flush", step=self.last_durable_step,
                    ip=self.ip)
                if callable(prev):
                    prev(signum, frame)
                else:
                    signal.signal(signal.SIGTERM, signal.SIG_DFL)
                    os.kill(os.getpid(), signal.SIGTERM)

            signal.signal(signal.SIGTERM, _handler)
            self._hook_installed = True
        except ValueError:
            logger.debug("not on the main thread; preemption hook skipped")

    # -- the write ------------------------------------------------------- #

    def _write(self, snap: snp.Snapshot) -> None:
        t0 = time.monotonic()
        p = self.process_index
        d = self.root / mf.step_dir_name(snap.step)
        d.mkdir(parents=True, exist_ok=True)
        # Re-saving a step from a previous incarnation: clear our own stale
        # artifacts so the commit poll can't trust old bytes.
        data_path = d / mf.data_file_name(p)
        man_path = d / mf.proc_manifest_name(p)
        if p == 0:
            (d / mf.GLOBAL_MANIFEST).unlink(missing_ok=True)
        data_path.unlink(missing_ok=True)
        man_path.unlink(missing_ok=True)

        # Stage to host + pack. Every piece rides as a flat uint8 view
        # (ml_dtypes have no portable npz descr); manifest entries carry
        # dtype/shape/global placement.
        arrays: dict[str, np.ndarray] = {}
        entries: list[dict] = []
        total = 0
        for key, value in snap.entries:
            gshape = snp.global_shape_of(value)
            gdtype = snp.global_dtype_of(value)
            for index, arr in snp.materialize_value(value):
                arr = np.ascontiguousarray(arr)
                name = f"e{len(arrays)}"
                arrays[name] = arr.reshape(-1).view(np.uint8)
                entries.append({
                    "key": key,
                    "npz": name,
                    "dtype": gdtype,
                    "shape": list(arr.shape),
                    "global_shape": list(gshape),
                    "index": mf.encode_index(index),
                })
                total += arr.nbytes
        tmp = d / f".tmp-{mf.data_file_name(p)}"
        with open(tmp, "wb") as f:
            np.savez(f, **arrays)
            f.flush()
            os.fsync(f.fileno())
        crc = mf.file_crc32(tmp)
        nbytes = tmp.stat().st_size
        os.replace(tmp, data_path)
        mf.fsync_dir(d)

        chaos().barrier(CHAOS_BARRIER_MID_WRITE, ip=self.ip)

        mf.atomic_write_json(man_path, {
            "format": mf.FORMAT_VERSION,
            "process": p,
            "world_size": self.world_size,
            "step": snap.step,
            "kind": snap.kind,
            "data_file": data_path.name,
            "data_crc32": crc,
            "data_bytes": nbytes,
            "entries": entries,
        })
        self._m_bytes.inc(total)
        self._m_saves.inc()
        if p == 0:
            self._commit(d, snap)
        dur = time.monotonic() - t0
        self._m_write.observe(dur)
        logger.info("ckpt write step %d: %.3fs, %d B, %d pieces (proc %d)",
                    snap.step, dur, total, len(entries), p)

    def _commit(self, d: Path, snap: snp.Snapshot) -> None:
        """Rank 0: wait for every per-process manifest, then atomically
        commit the global manifest and prune old steps."""
        deadline = time.monotonic() + self.commit_timeout
        names = [mf.proc_manifest_name(q) for q in range(self.world_size)]
        while True:
            missing = [n for n in names if not (d / n).exists()]
            if not missing:
                break
            if time.monotonic() > deadline:
                self._m_commit_timeouts.inc()
                logger.error(
                    "ckpt step %d: gave up waiting %.0fs for peer "
                    "manifests %s; step stays uncommitted", snap.step,
                    self.commit_timeout, missing)
                return
            time.sleep(0.02)
        procs = []
        for n in names:
            path = d / n
            pm = mf.read_json(path)
            if pm.get("step") != snap.step or pm.get("kind") != snap.kind:
                logger.error("ckpt step %d: stale peer manifest %s; "
                             "not committing", snap.step, n)
                return
            procs.append({"file": n, "crc32": mf.file_crc32(path),
                          "bytes": path.stat().st_size})
        mf.atomic_write_json(d / mf.GLOBAL_MANIFEST, {
            "format": mf.FORMAT_VERSION,
            "step": snap.step,
            "kind": snap.kind,
            "world_size": self.world_size,
            "meta": snap.meta,
            "processes": procs,
        })
        self.last_durable_step = snap.step
        self._m_last_durable.set(snap.step)
        logger.info("saved checkpoint %s", d)
        metrics.flight_recorder().record(
            "ckpt_commit", step=snap.step, world_size=self.world_size)
        self._gc()

    def _gc(self) -> None:
        if self.keep_last <= 0:
            return
        complete = []
        for child in self.root.iterdir():
            step = mf.parse_step_dir(child.name)
            if step is None or not child.is_dir():
                continue
            if (child / mf.GLOBAL_MANIFEST).exists():
                complete.append((step, child))
        complete.sort(reverse=True)
        for step, child in complete[self.keep_last:]:
            # Remove the commit marker FIRST so a crash mid-delete leaves
            # an uncommitted (ignorable) dir, not a torn "complete" one.
            (child / mf.GLOBAL_MANIFEST).unlink(missing_ok=True)
            shutil.rmtree(child, ignore_errors=True)
            self._m_gc.inc()
            logger.info("ckpt GC: pruned %s (keep_last=%d)", child.name,
                        self.keep_last)
