"""On-disk checkpoint layout + atomic manifest commit.

A checkpoint for step N is a directory of per-process artifacts plus ONE
global commit marker:

    <root>/
      step_<N>/
        shards-00007.npz       process 7's shard data (uint8 lanes)
        manifest-00007.json    process 7's shard manifest
        MANIFEST.json          global manifest == the commit marker
      quarantine/
        step_<N>.<reason>.<nonce>/   dirs restore refused to trust

Every process writes only its own `shards-*` / `manifest-*` pair (tmp file
+ fsync + os.replace, so a file either has its full content or does not
exist), and process 0 commits `MANIFEST.json` LAST, also via atomic
rename, after observing every per-process manifest on the shared
filesystem. Restore treats a step dir without `MANIFEST.json` as
nonexistent — a crash at ANY point mid-write is therefore invisible to
resume, which is the property the old orbax wrapper lacked.

Checksums: the global manifest records crc32+size of each per-process
manifest, and each per-process manifest records crc32+size of its data
file, so a single root checksum chain covers every byte restore will
read.

Shard data rides `.npz` as flattened uint8 views (np.save has no portable
descr for ml_dtypes such as bfloat16 — the same trick the live-mirror wire
format uses); the manifest entry carries dtype + shape to view/reshape it
back losslessly.
"""

from __future__ import annotations

import json
import logging
import os
import zlib
from pathlib import Path

import numpy as np

logger = logging.getLogger("oobleck.ckpt")

FORMAT_VERSION = 1
GLOBAL_MANIFEST = "MANIFEST.json"
QUARANTINE_DIR = "quarantine"

# Payload kinds: "layers" is the engine's layer-keyed checkpoint form;
# "fused_stacked" is the fused path's raw stacked TrainState (written when
# cross-host sharding makes host-local layer assembly impossible — the
# engine converts back to layer-keyed form at restore time, where it has
# the model + optimizer).
KIND_LAYERS = "layers"
KIND_FUSED_STACKED = "fused_stacked"


def step_dir_name(step: int) -> str:
    return f"step_{step}"


def parse_step_dir(name: str) -> int | None:
    if not name.startswith("step_"):
        return None
    try:
        return int(name.split("_", 1)[1])
    except ValueError:
        return None


def data_file_name(process: int) -> str:
    return f"shards-{process:05d}.npz"


def proc_manifest_name(process: int) -> str:
    return f"manifest-{process:05d}.json"


# -- dtype names (ml_dtypes-aware) -------------------------------------- #

def dtype_name(dt) -> str:
    return np.dtype(dt).name


def dtype_from_name(name: str) -> np.dtype:
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes

        return np.dtype(getattr(ml_dtypes, name))


# -- index (shard placement) encoding ----------------------------------- #

def encode_index(index) -> list | None:
    """Tuple of slices (a jax Shard.index) -> JSON-safe triplet list.
    None means "the full array"."""
    if index is None:
        return None
    return [[s.start, s.stop, s.step] for s in index]


def decode_index(enc: list | None):
    if enc is None:
        return tuple()
    return tuple(slice(a, b, c) for a, b, c in enc)


# -- checksums + atomic writes ------------------------------------------ #

def file_crc32(path: str | Path) -> int:
    crc = 0
    with open(path, "rb") as f:
        while True:
            chunk = f.read(1 << 20)
            if not chunk:
                return crc
            crc = zlib.crc32(chunk, crc)


def fsync_dir(path: str | Path) -> None:
    """Make a completed rename durable (best-effort: some filesystems
    refuse O_RDONLY dir fsync)."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def fsync_file(path: str | Path) -> None:
    try:
        fd = os.open(path, os.O_RDONLY)
        try:
            os.fsync(fd)
        finally:
            os.close(fd)
    except OSError:
        pass


def atomic_write_json(path: Path, obj: dict) -> None:
    """tmp + fsync + rename: the file either exists with full content or
    not at all. Tmp names are dot-prefixed so directory scans skip them."""
    tmp = path.parent / f".tmp-{path.name}"
    with open(tmp, "w") as f:
        json.dump(obj, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    fsync_dir(path.parent)


def read_json(path: Path) -> dict:
    with open(path) as f:
        return json.load(f)
