"""Durable-state plane: async sharded checkpointing with atomic
manifests, preemption-aware flush, and crash-consistent restore.

The reference has NO checkpointing (weights are randomly re-materialized
at startup) and the in-memory mirror plane cannot survive whole-slice
preemption — routine on TPU. This package is the durable half of the
two-tier story (Chameleon, arXiv:2508.21613: cheap in-memory redundancy
for peer failures, durable checkpoints for slice loss), built from four
parts:

  snapshot.py  reference-capture snapshots at a step barrier (stall ==
               drain-wait + traversal, never device_get + disk)
  writer.py    background writer: at most one snapshot in flight,
               per-process shard files, rank-0 atomic manifest commit,
               keep-last-k GC, SIGTERM flush hook, chaos barrier
  manifest.py  on-disk layout, checksums, atomic rename commit
  restore.py   validate -> quarantine -> assemble -> rebuild trees

`DurableStatePlane` is the facade the engine (and the
execution/checkpoint.py compat shim) talks to.
"""

from __future__ import annotations

from pathlib import Path
from typing import Any

from oobleck_tpu.ckpt import manifest, restore, snapshot, writer  # noqa: F401
from oobleck_tpu.ckpt.restore import (  # noqa: F401
    CheckpointCorrupt,
    complete_step_dirs,
    load_latest,
    load_step_dir,
    restore_latest,
)
from oobleck_tpu.ckpt.writer import SnapshotWriter  # noqa: F401


class DurableStatePlane:
    """One process's handle on a checkpoint root directory.

    save()/save_stacked() return the train-loop stall in seconds (the
    metric the async-writer design is judged on); restore_latest()
    returns the engine checkpoint payload or None. The plane is safe to
    keep for the engine's lifetime — flush() before reading the root
    from another process."""

    def __init__(self, root: str | Path, *, process_index: int = 0,
                 world_size: int = 1, keep_last: int = 3,
                 asynchronous: bool = True, commit_timeout: float = 120.0,
                 ip: str | None = None):
        self.root = Path(root).resolve()
        self.writer = SnapshotWriter(
            self.root, process_index=process_index, world_size=world_size,
            keep_last=keep_last, asynchronous=asynchronous,
            commit_timeout=commit_timeout, ip=ip)

    @property
    def process_index(self) -> int:
        return self.writer.process_index

    @property
    def world_size(self) -> int:
        return self.writer.world_size

    @property
    def last_durable_step(self) -> int:
        return self.writer.last_durable_step

    def _meta(self, step: int, num_iterations_done: int, epoch: int,
              extra: dict | None) -> dict:
        return {"step": step, "num_iterations_done": num_iterations_done,
                "epoch": epoch, **(extra or {})}

    def save(self, *, step: int, params: dict[int, Any],
             opt_state: dict[int, Any], num_iterations_done: int = 0,
             epoch: int = 0, extra: dict | None = None) -> float:
        """Checkpoint layer-keyed state; returns stall seconds."""
        snap = snapshot.capture_layers(
            params, opt_state, step=step,
            meta=self._meta(step, num_iterations_done, epoch, extra))
        return self.writer.submit(snap)

    def save_stacked(self, *, step: int, params: Any, opt_leaves: list,
                     num_iterations_done: int = 0, epoch: int = 0,
                     extra: dict | None = None) -> float:
        """Checkpoint the fused path's raw stacked state (cross-host
        sharded arrays ride as per-process shard pieces)."""
        snap = snapshot.capture_stacked(
            params, opt_leaves, step=step,
            meta=self._meta(step, num_iterations_done, epoch, extra))
        return self.writer.submit(snap)

    def load_latest(self, *, quarantine_bad: bool | None = None
                    ) -> tuple[int, dict] | None:
        """Newest restorable (step, payload); quarantining defaults to
        process 0 only (one renamer per shared filesystem). Shared
        step-selection for the engine restore and the serve loader."""
        self.writer.flush()
        if quarantine_bad is None:
            quarantine_bad = self.writer.process_index == 0
        return restore.load_latest(self.root, quarantine_bad=quarantine_bad)

    def restore_latest(self, *, quarantine_bad: bool | None = None
                       ) -> dict | None:
        """Newest restorable payload (load_latest without the step)."""
        res = self.load_latest(quarantine_bad=quarantine_bad)
        return None if res is None else res[1]

    def flush(self, timeout: float | None = None) -> bool:
        return self.writer.flush(timeout)

    def close(self) -> None:
        self.writer.close()

    def install_preemption_hook(self) -> None:
        self.writer.install_preemption_hook()
