"""Step-barrier state snapshots for the durable-state plane.

A Snapshot is taken ON the training thread, and `stage_to_host` (called
by the writer at submit time, still on the training thread) copies every
captured array to host memory before the train loop proceeds. Reference
capture alone is NOT safe: the train step donates its input state
(donate_argnums), so by the next step the captured buffers may be reused
by XLA — and on CPU `np.asarray(jax_array)` is a zero-copy VIEW of the
XLA buffer, so even a "host" reference can alias donated memory (a
use-after-free SIGSEGV, observed in the multiprocess elastic test). The
checkpoint stall is therefore drain-wait + device→host staging; the npz
pack, fsync, and manifest commit — the expensive part — still run on the
writer thread. The memory bill is one staged host copy of the state
until the write drains, bounded by the at-most-one-in-flight rule.

Key schema (flat string keys; the restore side rebuilds trees from them):

    p/<layer>/<path...>   a params leaf of layer <layer>
    o/<layer>/<i>         the i-th flat optimizer-state leaf of the layer
    fs/p/<path...>        fused_stacked: a raw stacked params leaf
    fs/o/<i>              fused_stacked: the i-th flat opt-state leaf

Path components are dict keys verbatim and `#<i>` for sequence elements
(tuples restore as lists — model params are nested dicts, so the engine
never sees the difference).

Sharded capture: an array that is not fully replicated materializes as
its distinct replica-0 addressable shards, each tagged with its global
index — every process contributes only what its devices hold, which is
what makes cross-host-sharded (FSDP-across-hosts) state checkpointable
at all. A process holding only redundant replicas of an array contributes
no piece for it; the global manifest merge makes the union whole.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any

import jax
import numpy as np

from oobleck_tpu.ckpt import manifest as mf


def _path_component(entry) -> str:
    from jax.tree_util import (
        DictKey,
        FlattenedIndexKey,
        GetAttrKey,
        SequenceKey,
    )

    if isinstance(entry, DictKey):
        key = str(entry.key)
        if "/" in key or key.startswith("#") or key.startswith("."):
            raise ValueError(f"unserializable tree key {key!r}")
        return key
    if isinstance(entry, SequenceKey):
        return f"#{entry.idx}"
    if isinstance(entry, GetAttrKey):
        return entry.name
    if isinstance(entry, FlattenedIndexKey):
        return f"#{entry.key}"
    raise ValueError(f"unserializable tree path entry {entry!r}")


def flatten_with_keys(tree, prefix: str) -> list[tuple[str, Any]]:
    """[(key, leaf)] with keys `<prefix>/<comp>/<comp>...`; a bare leaf
    (no tree structure) keys as `<prefix>` alone."""
    leaves, _ = jax.tree_util.tree_flatten_with_path(tree)
    out = []
    for path, leaf in leaves:
        comps = [_path_component(e) for e in path]
        out.append(("/".join([prefix, *comps]) if comps else prefix, leaf))
    return out


@dataclass
class Snapshot:
    """One step's durable state: flat (key, value) pairs plus run-position
    metadata. Values start as jax arrays / numpy arrays / scalars;
    `stage_to_host` rewrites them to HostValue copies before the writer
    thread ever sees them."""

    step: int
    kind: str
    meta: dict
    entries: list[tuple[str, Any]] = field(default_factory=list)


class HostValue:
    """A captured value staged to independent host memory: the global
    shape/dtype plus this process's [(index, array-copy)] pieces."""

    __slots__ = ("shape", "dtype", "pieces")

    def __init__(self, shape: tuple, dtype: str,
                 pieces: list[tuple[Any, np.ndarray]]):
        self.shape = shape
        self.dtype = dtype
        self.pieces = pieces


def stage_to_host(snap: Snapshot) -> None:
    """Replace every entry's value with a HostValue COPY, in place.

    Must run on the training thread before the next train step can
    donate the captured buffers (writer.submit calls it for both sync
    and async modes)."""
    snap.entries = [
        (key, value if isinstance(value, HostValue) else HostValue(
            global_shape_of(value), global_dtype_of(value),
            materialize_value(value)))
        for key, value in snap.entries
    ]


def capture_layers(params: dict[int, Any], opt_state: dict[int, Any],
                   *, step: int, meta: dict) -> Snapshot:
    """Layer-keyed engine state -> Snapshot. `opt_state` values may be
    optax trees or already-flat leaf lists; both store as flat leaves
    (checkpoint convention: the engine re-derives the optax structure
    from optimizer.init at restore)."""
    entries: list[tuple[str, Any]] = []
    for li in sorted(params):
        entries.extend(flatten_with_keys(params[li], f"p/{li}"))
    for li in sorted(opt_state):
        leaves = jax.tree.leaves(opt_state[li])
        if not leaves:
            # Leafless states (e.g. a bare EmptyState) must still restore
            # as "layer present, zero leaves", not "layer unknown".
            entries.append((f"o/{li}/~", np.zeros(0, np.float32)))
        for i, leaf in enumerate(leaves):
            entries.append((f"o/{li}/{i}", leaf))
    return Snapshot(step=step, kind=mf.KIND_LAYERS, meta=dict(meta),
                    entries=entries)


def capture_stacked(params: Any, opt_leaves: list, *, step: int,
                    meta: dict) -> Snapshot:
    """Fused path, cross-host-sharded state: capture the raw stacked
    TrainState leaves shard-wise (kind=fused_stacked)."""
    entries = flatten_with_keys(params, "fs/p")
    for i, leaf in enumerate(opt_leaves):
        entries.append((f"fs/o/{i}", leaf))
    return Snapshot(step=step, kind=mf.KIND_FUSED_STACKED, meta=dict(meta),
                    entries=entries)


def materialize_value(value) -> list[tuple[Any, np.ndarray]]:
    """Stage one captured value to host: [(index, array)] pieces.

    index None = the piece IS the full array. For a sharded jax array the
    pieces are this process's distinct replica-0 shards with their global
    indices; the list may be EMPTY on a process holding only redundant
    replicas (some other process owns replica 0 of every region).

    jax-array pieces are COPIED (np.array, not np.asarray): a view of an
    XLA CPU buffer would alias memory the next donating train step reuses.
    """
    if isinstance(value, HostValue):
        return value.pieces
    if isinstance(value, jax.Array) and not value.is_fully_replicated:
        pieces: list[tuple[Any, np.ndarray]] = []
        seen: set = set()
        full = value.is_fully_addressable
        for sh in value.addressable_shards:
            # Across processes, replica_id==0 selects exactly one copy of
            # each global region; within one process (fully addressable)
            # index dedup alone suffices.
            if not full and sh.replica_id != 0:
                continue
            key = tuple((s.start, s.stop, s.step) for s in sh.index)
            if key in seen:
                continue
            seen.add(key)
            pieces.append((sh.index, np.array(sh.data)))
        if len(pieces) == 1 and pieces[0][1].shape == value.shape:
            return [(None, pieces[0][1])]
        return pieces
    if isinstance(value, jax.Array):
        return [(None, np.array(value))]
    return [(None, np.asarray(value))]


def global_shape_of(value) -> tuple:
    if isinstance(value, HostValue):
        return value.shape
    return tuple(np.shape(value)) if not isinstance(value, jax.Array) \
        else tuple(value.shape)


def global_dtype_of(value) -> str:
    if isinstance(value, HostValue):
        return value.dtype
    if isinstance(value, (jax.Array, np.ndarray)):
        return mf.dtype_name(value.dtype)
    return mf.dtype_name(np.asarray(value).dtype)
