"""ctypes bindings for the C++ planner (csrc/planner.cpp).

The reference binds its C++ planner with pybind11 (csrc/planning/bind.cpp);
pybind11 is not in this image, so the native side exposes a C API and this
module marshals flat arrays in and JSON out. The .so is built on demand with
the csrc Makefile and cached next to the source.
"""

from __future__ import annotations

import ctypes
import json
import subprocess
from pathlib import Path

import numpy as np

from oobleck_tpu.planning.templates import LayerProfile, PipelineTemplate

_CSRC = Path(__file__).resolve().parent.parent / "csrc"
_SO = _CSRC / "libplanner.so"
_lib = None


def _load() -> ctypes.CDLL:
    global _lib
    if _lib is not None:
        return _lib
    if not _SO.exists() or _SO.stat().st_mtime < (_CSRC / "planner.cpp").stat().st_mtime:
        subprocess.run(
            ["make", "-C", str(_CSRC)], check=True, capture_output=True, text=True
        )
    lib = ctypes.CDLL(str(_SO))
    lib.planner_create_templates.restype = ctypes.c_char_p
    lib.planner_create_templates.argtypes = [
        ctypes.c_int,                      # num_layers
        ctypes.POINTER(ctypes.c_double),   # fwd
        ctypes.POINTER(ctypes.c_double),   # bwd
        ctypes.c_int,                      # num_ar
        ctypes.POINTER(ctypes.c_int),      # ar_chips
        ctypes.POINTER(ctypes.c_double),   # ar_in_host
        ctypes.POINTER(ctypes.c_int64),    # mem_params
        ctypes.POINTER(ctypes.c_int64),    # mem_activation
        ctypes.c_int, ctypes.c_int,        # min/max hosts
        ctypes.c_int,                      # chips_per_host
        ctypes.c_int,                      # num_threads
    ]
    lib.planner_free.restype = None
    _lib = lib
    return lib


def create_pipeline_templates(
    profiles: list[LayerProfile],
    num_hosts: tuple[int, int],
    chips_per_host: int,
    num_threads: int = 0,
) -> list[PipelineTemplate]:
    lib = _load()
    L = len(profiles)
    fwd = np.array([p.forward for p in profiles], dtype=np.float64)
    bwd = np.array([p.backward for p in profiles], dtype=np.float64)
    ar_chips_set = sorted({c for p in profiles for c in p.allreduce_in_host})
    ar_chips = np.array(ar_chips_set, dtype=np.int32)
    ar = np.array(
        [[p.allreduce_in_host.get(c, 0.0) for c in ar_chips_set] for p in profiles],
        dtype=np.float64,
    ).reshape(L, -1)
    mem_p = np.array([p.mem_params for p in profiles], dtype=np.int64)
    mem_a = np.array([p.mem_activation for p in profiles], dtype=np.int64)

    raw = lib.planner_create_templates(
        L,
        fwd.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        bwd.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        len(ar_chips_set),
        ar_chips.ctypes.data_as(ctypes.POINTER(ctypes.c_int)),
        ar.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        mem_p.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        mem_a.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        num_hosts[0], num_hosts[1], chips_per_host, num_threads,
    )
    data = json.loads(raw.decode())
    lib.planner_free()
    return [PipelineTemplate.from_json(d, L) for d in data]
