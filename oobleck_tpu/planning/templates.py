"""Pipeline templates and the divide-and-conquer generator.

Semantics match the reference planner (SOSP '23 §4.1.2;
/root/reference/oobleck/csrc/planning/execution_result.h:60-204,
pipeline_template.cpp:82-339), re-termed for TPU: a *host* owns
`chips_per_host` chips (reference: node/GPU). For every feasible host count n
the generator finds the stage partition minimizing the t1+t2+t3 pipeline cost
model:

  stage latency  = Σ_layers (fwd+bwd)/chips + allreduce_in_host[chips] (if >1)
  t1 = Σ stage latencies
  t2 = (2·S + k* + 1) · latency(k*)        k* = bottleneck stage index
  t3 = Σ latencies of stages after k*
  mem(stage) = Σ 6·param_bytes + activation_bytes

Feasibility rules (pipeline_template.cpp:193-214): stages ≤ layers; multiple
hosts never share one stage; a single host needs chips ≥ stages; a one-stage
single-host assignment requires a power-of-2 chip count; in-host chip splits
are even bisections only.

Two interchangeable engines: this pure-Python implementation (reference
behavior, used in tests and as fallback) and the C++ one in
oobleck_tpu/csrc/planner.cpp (threaded, GIL-free, same memo key) loaded via
ctypes — `TemplateGenerator(engine="native")`; the default "auto" prefers
native with Python fallback.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from functools import lru_cache


@dataclass(frozen=True)
class LayerProfile:
    """Per-layer planning costs (reference LayerExecutionResult,
    execution_result.h:17-38). Times in milliseconds, memory in bytes."""

    layer_index: int
    forward: float
    backward: float
    allreduce_in_host: dict[int, float]      # chips -> time
    allreduce_across_hosts: dict[int, float]  # hosts -> time
    mem_params: int
    mem_activation: int

    def to_json(self) -> dict:
        return {
            "forward": self.forward,
            "backward": self.backward,
            "mem_required": [self.mem_params, self.mem_activation],
        }


@dataclass(frozen=True)
class StageSpec:
    """A contiguous layer range on one host slice (reference
    StageExecutionResult, execution_result.h:60-112)."""

    layer_indices: tuple[int, ...]
    num_chips: int
    forward: float
    backward: float
    mem_required: int

    @property
    def latency(self) -> float:
        return self.forward + self.backward

    @classmethod
    def build(cls, profiles: list[LayerProfile], start: int, end: int,
              num_chips: int,
              comm_hidden_fraction: float = 0.0) -> "StageSpec":
        """`comm_hidden_fraction` > 0 charges each layer its EFFECTIVE
        allreduce cost — max(0, ar - hf * layer_compute) — instead of the
        fully-serialized ar, matching a deployment that runs the overlapped
        step (parallel/overlap.py). 0.0 reproduces the reference cost model
        exactly."""
        from oobleck_tpu.parallel.overlap import effective_comm

        fwd = bwd = 0.0
        mem = 0
        for i in range(start, end):
            p = profiles[i]
            f = p.forward / num_chips
            b = p.backward / num_chips
            fwd += f
            bwd += b
            if num_chips > 1:
                ar = p.allreduce_in_host.get(num_chips, 0.0)
                fwd += effective_comm(ar, f, comm_hidden_fraction)
                bwd += effective_comm(ar, b, comm_hidden_fraction)
            mem += 6 * p.mem_params + p.mem_activation
        return cls(tuple(range(start, end)), num_chips, fwd, bwd, mem)


@dataclass(frozen=True)
class PipelineTemplate:
    """One optimal pipeline shape for a given host count (reference
    PipelineTemplate, pipeline_template.h:20-91)."""

    stages: tuple[StageSpec, ...]
    iteration_time: float
    num_layers: int
    num_hosts: int
    chips_per_host: int

    @property
    def num_stages(self) -> int:
        return len(self.stages)

    @property
    def num_chips(self) -> int:
        return sum(s.num_chips for s in self.stages)

    def layers_per_stage(self) -> list[tuple[int, int]]:
        return [(s.layer_indices[0], s.layer_indices[-1] + 1) for s in self.stages]

    def get_rank_grid(self, ranks: list[int]) -> dict[int, list[int]]:
        """layer index -> chips_per_host ranks, repeating when a stage holds
        fewer chips (reference pipeline_template.h:57-84)."""
        assert len(ranks) == self.num_chips, (len(ranks), self.num_chips)
        grid: dict[int, list[int]] = {}
        cursor = 0
        for stage in self.stages:
            stage_ranks = ranks[cursor:cursor + stage.num_chips]
            cursor += stage.num_chips
            repeat = self.chips_per_host // stage.num_chips
            layer_ranks: list[int] = []
            for r in stage_ranks:
                layer_ranks.extend([r] * repeat)
            for layer in stage.layer_indices:
                grid[layer] = layer_ranks
        return grid

    def mem_required_per_chip(self) -> int:
        return max(s.mem_required // s.num_chips for s in self.stages)

    def to_json(self) -> dict:
        return {
            "num_hosts": self.num_hosts,
            "chips_per_host": self.chips_per_host,
            "iteration_time": self.iteration_time,
            "stages": [
                {
                    "layers": [s.layer_indices[0], s.layer_indices[-1] + 1],
                    "num_chips": s.num_chips,
                    "forward": s.forward,
                    "backward": s.backward,
                    "mem_required": s.mem_required,
                }
                for s in self.stages
            ],
        }

    @classmethod
    def from_json(cls, d: dict, num_layers: int) -> "PipelineTemplate":
        stages = tuple(
            StageSpec(
                tuple(range(s["layers"][0], s["layers"][1])),
                s["num_chips"], s["forward"], s["backward"], s["mem_required"],
            )
            for d_s in [d["stages"]] for s in d_s
        )
        return cls(stages, d["iteration_time"], num_layers,
                   d["num_hosts"], d["chips_per_host"])


@dataclass
class _DCResult:
    """Divide-and-conquer cost node (reference DCExecutionResult,
    execution_result.h:114-204)."""

    t1: float
    t2: float
    t3: float
    kstar: int
    stages: tuple[StageSpec, ...]

    @property
    def t(self) -> float:
        return self.t1 + self.t2 + self.t3

    @property
    def kstar_latency(self) -> float:
        return self.stages[self.kstar].latency

    @classmethod
    def base(cls, stage: StageSpec, virtual_stages: int = 1) -> "_DCResult":
        lat = stage.latency
        return cls(t1=lat, t2=(2 / virtual_stages) * lat, t3=lat, kstar=0,
                   stages=(stage,))

    @classmethod
    def combine(cls, left: "_DCResult", right: "_DCResult",
                virtual_stages: int = 1) -> "_DCResult":
        if left.kstar_latency > right.kstar_latency:
            kstar = left.kstar
        else:
            kstar = right.kstar + len(left.stages)
        t1 = left.t1 + right.t1
        num_stages = len(left.stages) + len(right.stages)
        # The 2·S ramp term is the schedule's warmup+drain bubble; the
        # interleaved schedule runs it on 1/v-sized model chunks, so it
        # shrinks by the virtual-stage degree (bubble (S-1)/(v·M+S-1)).
        mb_factor = 2 * num_stages / virtual_stages + kstar + 1
        if kstar == left.kstar:
            t2 = mb_factor * left.kstar_latency
            t3 = sum(s.latency for s in left.stages[left.kstar:]) + \
                sum(s.latency for s in right.stages)
        else:
            t2 = mb_factor * right.kstar_latency
            t3 = sum(s.latency for s in right.stages[right.kstar:])
        return cls(t1=t1, t2=t2, t3=t3, kstar=kstar,
                   stages=left.stages + right.stages)


class TemplateGenerator:
    """Divide-and-conquer template search.

    `engine="python"` runs the in-process implementation below;
    `engine="native"` dispatches to the C++ planner (csrc/planner.cpp) and
    `engine="auto"` prefers native with Python fallback.
    """

    def __init__(self, engine: str = "auto"):
        self.engine = engine

    def create_pipeline_templates(
        self,
        profiles: list[LayerProfile],
        num_hosts: tuple[int, int],
        chips_per_host: int,
        virtual_stages: int = 1,
        comm_hidden_fraction: float = 0.0,
    ) -> list[PipelineTemplate]:
        """One min-cost template per feasible host count in
        [num_hosts[0], num_hosts[1]] (reference pipeline_template.cpp:82-161).

        virtual_stages > 1 evaluates the cost model under the interleaved
        schedule (warmup/drain ramp divided by v); comm_hidden_fraction > 0
        evaluates it under the overlapped step (allreduce discounted by the
        measured hidden fraction). Both are python-engine only — the C++
        planner predates the interleaved schedule and the overlap path.
        """
        if (self.engine in ("auto", "native") and virtual_stages == 1
                and comm_hidden_fraction == 0.0):
            try:
                from oobleck_tpu.planning import _native

                return _native.create_pipeline_templates(
                    profiles, num_hosts, chips_per_host
                )
            except Exception:  # noqa: BLE001 — auto mode falls back to python
                if self.engine == "native":
                    raise
        return _python_create_templates(profiles, num_hosts, chips_per_host,
                                        virtual_stages,
                                        comm_hidden_fraction)


def _python_create_templates(
    profiles: list[LayerProfile],
    num_hosts: tuple[int, int],
    chips_per_host: int,
    virtual_stages: int = 1,
    comm_hidden_fraction: float = 0.0,
) -> list[PipelineTemplate]:
    lo, hi = num_hosts
    num_layers = len(profiles)
    templates = []
    # One memo across every host count: keys include num_hosts, and multi-host
    # splits recurse into smaller host counts, so sharing is both safe and a
    # large win (the reference shares one dc_cache_ the same way). The
    # virtual-stage degree and comm-hidden fraction are fixed per call, so
    # they stay out of the key (the memo never outlives the call).
    memo: dict = {}
    for n in range(lo, hi + 1):
        best: _DCResult | None = None
        for num_stages in range(n, num_layers + 1):
            r = _dc(profiles, 0, num_layers, num_stages, n, chips_per_host,
                    memo, virtual_stages, comm_hidden_fraction)
            if r is not None and (best is None or r.t < best.t):
                best = r
        if best is None:
            continue
        templates.append(
            PipelineTemplate(best.stages, best.t, num_layers, n, chips_per_host)
        )
    return templates


def _dc(profiles, start, end, num_stages, num_hosts, chips_per_host, memo,
        virtual_stages: int = 1, comm_hidden_fraction: float = 0.0):
    """Reference divide_and_conquer (pipeline_template.cpp:166-339)."""
    key = (num_stages, start, end, num_hosts, chips_per_host)
    if key in memo:
        return memo[key]

    # Feasibility (pipeline_template.cpp:193-214)
    infeasible = False
    if num_stages > end - start:
        infeasible = True
    if num_hosts == 1:
        if chips_per_host < num_stages:
            infeasible = True
        if num_stages == 1 and (chips_per_host & (chips_per_host - 1)) != 0:
            infeasible = True
    elif num_hosts > num_stages:
        infeasible = True
    if infeasible:
        memo[key] = None
        return None

    # Base case
    if num_stages == 1:
        stage = StageSpec.build(profiles, start, end, chips_per_host,
                                comm_hidden_fraction)
        result = _DCResult.base(stage, virtual_stages)
        memo[key] = result
        return result

    best: _DCResult | None = None
    for k in range(start + 1, end):
        if num_hosts == 1:
            # Even in-host chip bisection only (cpp:243-247)
            half = chips_per_host // 2
            if half * 2 != chips_per_host or half == 0:
                continue
            for s_left in range(1, num_stages):
                left = _dc(profiles, start, k, s_left, 1, half, memo,
                           virtual_stages, comm_hidden_fraction)
                right = _dc(profiles, k, end, num_stages - s_left, 1,
                            chips_per_host - half, memo, virtual_stages,
                            comm_hidden_fraction)
                if left is None or right is None:
                    continue
                cand = _DCResult.combine(left, right, virtual_stages)
                if best is None or cand.t < best.t:
                    best = cand
        else:
            for h_left in range(1, num_hosts):
                for s_left in range(1, num_stages):
                    left = _dc(profiles, start, k, s_left, h_left,
                               chips_per_host, memo, virtual_stages,
                               comm_hidden_fraction)
                    right = _dc(profiles, k, end, num_stages - s_left,
                                num_hosts - h_left, chips_per_host, memo,
                                virtual_stages, comm_hidden_fraction)
                    if left is None or right is None:
                        continue
                    cand = _DCResult.combine(left, right, virtual_stages)
                    if best is None or cand.t < best.t:
                        best = cand

    memo[key] = best
    return best
