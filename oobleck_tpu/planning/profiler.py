"""Per-layer profiler: the planner's input.

Capability match for the reference profiler
(/root/reference/oobleck/planning/profiler.py:241-323), TPU-native:

  * forward latency: each layer jitted and timed on the local device with a
    host readback barrier (the axon relay makes block_until_ready unreliable);
  * backward latency: the layer's VJP jitted and timed the same way —
    *measured*, not the reference's 3x-forward estimate (profiler.py:104);
  * memory: exact parameter bytes + activation output bytes from abstract
    evaluation (no allocation);
  * collective latencies (allreduce within a host / across hosts): measured
    with a real psum when multiple devices are visible, otherwise an
    ICI/DCN bandwidth-latency model — a single tunneled chip cannot measure
    multi-chip collectives.

Results are cached as JSON with the reference's file layout
(profiler.py:255-257, 290-319): {cache}/{model}-{tag}/mb{N}.json,
allreduce_in_node.json, allreduce_across_nodes.json, model_args.json,
so the planner is fully decoupled from profiling.
"""

from __future__ import annotations

import json
import math
import os
import time
from pathlib import Path

import jax
import jax.numpy as jnp

from oobleck_tpu.models.base import param_bytes
from oobleck_tpu.planning.templates import LayerProfile

WARMUP = 2
ITERS = 3  # matches reference profiler.py:18-19
# In-graph repetitions per timed call: a single dispatch over the axon relay
# costs ~80ms round-trip, far above a layer's real latency, so each timed
# call scans the layer REPS times on-device and the overhead (measured with a
# trivial program) is subtracted before dividing.
REPS = 16

# Bandwidth-latency model constants for unmeasurable collectives.
# ICI (intra-host, chip-to-chip): ~1e11 B/s effective allreduce bandwidth,
# ~10us base latency per hop; DCN (cross-host): ~2.5e10 B/s, ~50us base.
ICI_BW = 1.0e11
ICI_LAT_MS = 0.01
DCN_BW = 2.5e10
DCN_LAT_MS = 0.05


def default_cache_dir() -> Path:
    return Path(
        os.environ.get("OOBLECK_TPU_CACHE", "/tmp/oobleck_tpu")
    ) / "profiles"


def get_profile_path(model_name: str, model_tag: str) -> Path:
    return default_cache_dir() / f"{model_name}-{model_tag}"


def _sync(x) -> float:
    """Force completion; returns a value to defeat DCE."""
    return float(jnp.sum(jax.tree.leaves(x)[0].ravel()[0]))


def _time_call(fn, *args) -> float:
    """Median wall-time of fn(*args) in ms with warmup + readback sync."""
    for _ in range(WARMUP):
        _sync(fn(*args))
    times = []
    for _ in range(ITERS):
        t0 = time.perf_counter()
        _sync(fn(*args))
        times.append((time.perf_counter() - t0) * 1e3)
    times.sort()
    return times[len(times) // 2]


_overhead_cache: list[float] = []


def _dispatch_overhead_ms() -> float:
    """Round-trip cost of a trivial dispatch+readback (axon relay ~80ms)."""
    if not _overhead_cache:
        f = jax.jit(lambda x: x + 1.0)
        _overhead_cache.append(_time_call(f, jnp.float32(0.0)))
    return _overhead_cache[0]


def _time_repeated(fn_once, x0, reps: int = REPS) -> float:
    """Time `fn_once(x)` by scanning it `reps` times inside one jit call.

    Each iteration's input is data-perturbed by 0 derived from the previous
    output, forcing a sequential chain XLA cannot hoist or CSE (a float*0 is
    not folded). Returns per-iteration ms with dispatch overhead removed.
    """
    def perturb(x, leaf):
        zero = leaf * 0.0
        return jax.tree.map(
            lambda v: v + zero.astype(v.dtype), x
        )

    def run(x):
        def body(carry, _):
            x, acc = carry
            out = fn_once(x)
            leaf = jax.tree.leaves(out)[0].ravel()[0].astype(jnp.float32)
            return (perturb(x, leaf), acc + leaf), None

        (_, acc), _ = jax.lax.scan(body, (x, jnp.float32(0.0)), None, length=reps)
        return acc

    total = _time_call(jax.jit(run), x0)
    return max((total - _dispatch_overhead_ms()) / reps, 1e-4)


def allreduce_time_model(nbytes: int, n: int, *, cross_host: bool) -> float:
    """Ring-allreduce time estimate in ms for n participants."""
    if n <= 1:
        return 0.0
    bw, lat = (DCN_BW, DCN_LAT_MS) if cross_host else (ICI_BW, ICI_LAT_MS)
    volume = 2 * (n - 1) / n * nbytes
    return lat * math.ceil(math.log2(n)) + volume / bw * 1e3


def _measure_allreduce(nbytes: int, devices: list) -> float:
    """Measured psum across `devices` in ms (when hardware is available)."""
    n = len(devices)
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    mesh = Mesh(devices, ("x",))
    # Round up to a multiple of n: P("x") requires dim 0 divisible by the
    # mesh size (layer param counts are arbitrary, e.g. t5-tiny's 778).
    elems = -(-max(nbytes // 4, n) // n) * n
    arr = jnp.ones((elems,), jnp.float32)
    arr = jax.device_put(arr, NamedSharding(mesh, P("x")))

    def psum_fn(a):
        return jax.shard_map(
            lambda v: jax.lax.psum(v, "x"), mesh=mesh,
            in_specs=P("x"), out_specs=P(None), axis_names={"x"},
        )(a)

    fn = jax.jit(psum_fn)
    return _time_call(fn, arr)


def profile_execution_layers(model, microbatch_size: int, seq_len: int | None = None
                             ) -> list[dict]:
    """Time each pipeline layer's forward and backward on the local device.

    Returns the reference's mb{N}.json rows: {forward, backward,
    mem_required: [param_bytes, activation_bytes]} per layer
    (cf. reference profile_execution_layers, profiler.py:41-123).
    """
    c = model.config
    if seq_len is None:
        seq_len = min(getattr(c, "max_position_embeddings", 1024), 1024)
    rng = jax.random.PRNGKey(0)
    batch = model.sample_batch(microbatch_size, seq_len)
    results = []
    last_layer = model.num_pipeline_layers - 1
    # Layers whose name shares a numbered prefix (block_i, enc_i, dec_i) are
    # structurally identical by construction: measure the first of each
    # prefix and reuse (the reference times every fx-split layer because its
    # shards can differ).
    proto_rows: dict[str, dict] = {}
    carry_t = None  # previous layer's output shape tree (eval_shape)

    def _ones_like_tree(shapes):
        return jax.tree.map(lambda s: jnp.ones(s.shape, s.dtype), shapes)

    for idx in range(model.num_pipeline_layers):
        name = model.layer_name(idx)
        prefix = name.rsplit("_", 1)[0] if "_" in name else None

        params = model.init_layer(rng, idx)

        # Uniform layer signature: x is the layer's input (the batch for the
        # embed layer, activations otherwise) so the repeated-scan timer can
        # chain it. `batch` rides along for mid-pipeline consumers (T5's
        # bridge reads decoder_input_ids).
        if idx == 0:
            def fwd(x, p=params):
                return model.apply_layer(0, p, None, x)
            x0 = batch
        else:
            def fwd(x, p=params, i=idx):
                return model.apply_layer(i, p, x, batch)
            x0 = _ones_like_tree(carry_t)

        out_t = jax.eval_shape(fwd, x0)
        reused = proto_rows.get(prefix) if prefix else None
        if reused is not None:
            results.append(dict(reused))
            carry_t = out_t
            continue
        pbytes = param_bytes(params)
        fwd_ms = _time_repeated(fwd, x0)
        ct0 = _ones_like_tree(out_t)

        if idx == 0:
            # Embed backward: VJP wrt params only (int inputs give no
            # activation cotangent to chain on) — measured, not the
            # reference's 3x-forward estimate (profiler.py:41-123) nor the
            # earlier 2x guess here.
            def bwd(ct, p=params):
                _, vjp = jax.vjp(
                    lambda p_: model.apply_layer(0, p_, None, batch), p
                )
                return vjp(ct)
        else:
            # VJP wrt (activations, params) — both cotangent paths, like the
            # real backward. jax.vjp re-runs the forward inside, so this cost
            # includes recompute, matching execution under jax.checkpoint.
            def bwd(ct, x=x0, p=params, i=idx):
                _, vjp = jax.vjp(
                    lambda x_, p_: model.apply_layer(i, p_, x_, batch), x, p
                )
                return vjp(ct)

        bwd_ms = _time_repeated(bwd, ct0)

        act_bytes = sum(
            math.prod(s.shape) * s.dtype.itemsize
            for s in jax.tree.leaves(out_t)
        )
        row = {
            "forward": fwd_ms,
            "backward": bwd_ms,
            "mem_required": [int(pbytes), int(act_bytes)],
        }
        if prefix:
            proto_rows[prefix] = row
        results.append(row)
        carry_t = out_t
    return results


def profile_allreduce_in_node(model, chips_per_host: int) -> list[dict]:
    """Per-layer allreduce time for 1,2,4.. chips within a host (ICI).

    Measured when the chips are actually visible, modeled otherwise
    (cf. reference profile_allreduce_in_node, profiler.py:187-234).
    LOCAL devices only — in a live jax.distributed world, jax.devices()
    includes other hosts' chips, and an "in-node" mesh spanning processes
    is both semantically wrong and a deadlock (profiling is per-process,
    not lockstep; the peer never joins the collective).
    """
    devices = jax.local_devices()
    rng = jax.random.PRNGKey(0)
    rows = []
    for idx in range(model.num_pipeline_layers):
        pbytes = param_bytes(model.init_layer(rng, idx))
        row = {}
        n = 1
        while n <= chips_per_host:
            if n == 1:
                row["1"] = 0.0
            elif len(devices) >= n:
                row[str(n)] = _measure_allreduce(pbytes, devices[:n])
            else:
                row[str(n)] = allreduce_time_model(pbytes, n, cross_host=False)
            n *= 2
        rows.append(row)
    return rows


def profile_allreduce_across_nodes(model, max_hosts: int) -> list[dict]:
    """Per-layer allreduce time across 1..max_hosts hosts (DCN model;
    cf. reference profiler.py:141-185). Offline fallback — in a live
    multi-host world the engine replaces these rows with MEASURED psums
    over real process meshes (measure_allreduce_across_processes)."""
    rng = jax.random.PRNGKey(0)
    rows = []
    for idx in range(model.num_pipeline_layers):
        pbytes = param_bytes(model.init_layer(rng, idx))
        row = {"1": 0.0}
        for n in range(2, max_hosts + 1):
            row[str(n)] = allreduce_time_model(pbytes, n, cross_host=True)
        rows.append(row)
    return rows


def measure_allreduce_across_processes(comm, sizes_bytes: list[int],
                                       iters: int = ITERS
                                       ) -> dict[tuple[int, int], float]:
    """MEASURED cross-host allreduce profile over a live jax.distributed
    world: for each distinct byte size and each process-subset prefix
    {0..n-1} (n = 2..P), time a real psum over the process mesh the DP
    engine itself uses. The reference measures torch.distributed allreduce
    across 1..N node groups and feeds the planner
    (/root/reference/oobleck/planning/profiler.py:141-234); these are the
    TPU/DCN equivalents, riding the same ProcessComm process-mesh
    collectives as training.

    COLLECTIVE: every process of `comm` must call with identical
    `sizes_bytes` (processes >= n skip group n in lockstep — the same
    total-order discipline the DP engine uses). Returns {(nbytes, n): ms}
    complete only on processes < 2 (process 0 broadcasts its table via
    _broadcast-style psum at the call site)."""
    import numpy as np

    P = comm.process_count
    me = comm.process_index
    table: dict[tuple[int, int], float] = {}
    for nbytes in sorted(set(sizes_bytes)):
        length = max(int(nbytes) // 4, 1)
        for n in range(2, P + 1):
            participants = tuple(range(n))
            if me >= n:
                continue
            vec = np.zeros(length, np.float32)
            # Warmup compiles the mesh program; then time synced rounds.
            np.asarray(comm.group_sum_device(vec, length, participants))
            t0 = time.perf_counter()
            for _ in range(iters):
                np.asarray(
                    comm.group_sum_device(vec, length, participants)
                )
            table[(int(nbytes), n)] = (
                (time.perf_counter() - t0) / iters * 1e3
            )
    return table


def effective_tag(model_tag: str, execution=None) -> str:
    """Profile cache tag incorporating the execution knobs that change layer
    timing and memory (precision / remat / attention_impl): a bf16 profile
    must never be mistaken for an f32 one when planning memory bounds."""
    if execution is None:
        return model_tag
    parts = [model_tag]
    if getattr(execution, "precision", "bfloat16") != "bfloat16":
        parts.append(execution.precision)
    if not getattr(execution, "remat", True):
        parts.append("noremat")
    impl = getattr(execution, "attention_impl", "auto")
    if impl != "auto":
        parts.append(impl)
    return "+".join(parts)


def profile(model_name: str, model_args: dict, *, model_tag: str = "default",
            microbatch_size: int = 1, seq_len: int | None = None,
            chips_per_host: int = 4, max_hosts: int = 32,
            force: bool = False, execution=None) -> Path:
    """Run all profiles and write the JSON cache; returns the cache dir.

    `execution` (ExecutionArguments, duck-typed) must match what the engine
    trains with: it changes the measured model (dtype/remat/attention) AND
    the cache tag (pass the same object to effective_tag for loading).

    File layout matches the reference (profiler.py:290-319) so the planner's
    loader is schema-compatible.
    """
    from oobleck_tpu.models import build_model

    path = get_profile_path(model_name, effective_tag(model_tag, execution))
    files = [f"mb{microbatch_size}.json", "allreduce_in_node.json",
             "allreduce_across_nodes.json", "model_args.json"]
    if all((path / f).exists() for f in files) and not force:
        # Cache hit requires ALL files: a killed run may have written some.
        validate_model_args(path, model_args)
        return path
    path.mkdir(parents=True, exist_ok=True)
    model = build_model(model_name, model_args, execution=execution)

    contents = {
        f"mb{microbatch_size}.json":
            json.dumps(profile_execution_layers(model, microbatch_size, seq_len)),
        "allreduce_in_node.json":
            json.dumps(profile_allreduce_in_node(model, chips_per_host)),
        "allreduce_across_nodes.json":
            json.dumps(profile_allreduce_across_nodes(model, max_hosts)),
        "model_args.json": json.dumps(model_args),
    }
    # Atomic publish: write temps, then rename — a crash mid-profile never
    # leaves a partial cache that later runs mistake for a hit.
    for fname, text in contents.items():
        tmp = path / (fname + ".tmp")
        tmp.write_text(text)
    for fname in contents:
        (path / (fname + ".tmp")).rename(path / fname)
    return path


def validate_model_args(path: Path, model_args: dict) -> None:
    """Cached profile must match the requested model shape
    (cf. reference validate_model_args, profiler.py:326-340)."""
    f = path / "model_args.json"
    if not f.exists():
        return
    cached = json.loads(f.read_text())
    if cached != model_args:
        raise ValueError(
            f"cached profile at {path} was made with model_args={cached}, "
            f"requested {model_args}; use force=True to re-profile"
        )


def load_profile(model_name: str, model_tag: str, microbatch_size: int
                 ) -> list[LayerProfile]:
    """Load the JSON cache into LayerProfiles (reference get_profile_results,
    pipeline_template.cpp:29-80)."""
    path = get_profile_path(model_name, model_tag)
    mb = json.loads((path / f"mb{microbatch_size}.json").read_text())
    ar_in = json.loads((path / "allreduce_in_node.json").read_text())
    ar_across = json.loads((path / "allreduce_across_nodes.json").read_text())
    profiles = []
    for i, row in enumerate(mb):
        profiles.append(LayerProfile(
            layer_index=i,
            forward=row["forward"],
            backward=row["backward"],
            # Non-numeric keys are annotations (e.g. "measured": true on
            # live-world rows), not host counts.
            allreduce_in_host={int(k): v for k, v in ar_in[i].items()
                               if str(k).isdigit()},
            allreduce_across_hosts={int(k): v for k, v in ar_across[i].items()
                                    if str(k).isdigit()},
            mem_params=row["mem_required"][0],
            mem_activation=row["mem_required"][1],
        ))
    return profiles
