"""Planning layer: profiler → pipeline-template generator → instantiator.

Capability match for the reference's L3 (/root/reference/oobleck/planning/ +
oobleck/csrc/planning/): a per-layer profiler measures TPU costs, a
divide-and-conquer generator (C++ with a pure-Python twin) computes one
optimal pipeline template per feasible host count, and the instantiator picks
the best multiset of templates for the current cluster plus the per-pipeline
microbatch distribution.
"""

from oobleck_tpu.planning.templates import (
    LayerProfile,
    PipelineTemplate,
    StageSpec,
    TemplateGenerator,
)
from oobleck_tpu.planning.profiler import (
    get_profile_path,
    load_profile,
    profile,
    validate_model_args,
)
from oobleck_tpu.planning.instantiator import (
    HeterogeneousPlan,
    PipelineInstantiator,
)

__all__ = [
    "LayerProfile",
    "PipelineTemplate",
    "StageSpec",
    "TemplateGenerator",
    "profile",
    "load_profile",
    "get_profile_path",
    "validate_model_args",
    "PipelineInstantiator",
    "HeterogeneousPlan",
]
