"""Pipeline instantiation: which templates to run, and the microbatch split.

Capability match for the reference instantiator (paper §4.2;
/root/reference/oobleck/planning/instantiator.py:155-329):

  * `_enumerate_instantiation_options` — knapsack-style DP over all multisets
    of templates whose host counts sum to the cluster size (ref :224-252);
  * `_distribute_batch` — the reference solves a Pyomo MINLP (glpk+ipopt
    subprocesses, ref :254-329) minimizing the variance of per-pipeline
    iteration time (T_i/s_i)·nb_i subject to Σ nb_i = B. Here the same
    objective is solved per *instance* (the reference solves per template,
    which makes e.g. B=8 over three identical pipelines infeasible since all
    instances of a template share one nb; per-instance counts are strictly
    more flexible and the heterogeneous sampler already takes a per-pipeline
    list) with a relaxation-guided window search + greedy fallback — no
    solver dependency (SURVEY §7.3.6);
  * `HeterogeneousPlan` — plan selection by estimated iteration time =
    max_i(T_i · nb_i) + first-layer cross-host allreduce overhead
    (ref HeterogeneousPipelinesExecutionPlan.iteration_time, :54-68).
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass

from oobleck_tpu.planning.templates import PipelineTemplate


@dataclass(frozen=True)
class PipelineAssignment:
    """One concrete pipeline instance: a template + its global chip ranks."""

    pipeline_index: int
    template: PipelineTemplate
    ranks: tuple[int, ...]
    num_microbatches: int


@dataclass
class HeterogeneousPlan:
    """A chosen list of pipeline instances + per-instance microbatch counts."""

    instances: list[PipelineTemplate]       # one entry per pipeline
    num_microbatches: list[int]             # parallel to `instances`
    allreduce_across_hosts: list[dict[int, float]]

    def __post_init__(self):
        assert len(self.instances) == len(self.num_microbatches)
        # Canonical order: by host count, so rank blocks are deterministic.
        order = sorted(range(len(self.instances)),
                       key=lambda i: (self.instances[i].num_hosts, i))
        self.instances = [self.instances[i] for i in order]
        self.num_microbatches = [self.num_microbatches[i] for i in order]

    @property
    def num_instances(self) -> dict[PipelineTemplate, int]:
        out: dict[PipelineTemplate, int] = {}
        for t in self.instances:
            out[t] = out.get(t, 0) + 1
        return out

    @property
    def total_num_pipelines(self) -> int:
        return len(self.instances)

    @property
    def total_num_microbatches(self) -> int:
        return sum(self.num_microbatches)

    @property
    def iteration_time(self) -> float:
        longest = max(
            t.iteration_time * nb
            for t, nb in zip(self.instances, self.num_microbatches)
        )
        # Only the first layer's cross-host grad allreduce is charged; the
        # rest overlaps with backward compute (reference instantiator.py:61-66).
        sync = self.allreduce_across_hosts[0].get(self.total_num_pipelines, 0.0)
        return longest + sync

    def assignments(self, ranks: list[list[int]] | None = None
                    ) -> list[PipelineAssignment]:
        """Materialize pipeline instances with contiguous rank blocks (or the
        explicit per-pipeline `ranks` used after reconfiguration;
        reference instantiate(), instantiator.py:103-152)."""
        out: list[PipelineAssignment] = []
        cursor = 0
        for index, (template, nb) in enumerate(
            zip(self.instances, self.num_microbatches)
        ):
            n = template.num_chips
            if ranks is not None:
                block = tuple(ranks[index])
                assert len(block) == n, (len(block), n)
            else:
                block = tuple(range(cursor, cursor + n))
            out.append(PipelineAssignment(index, template, block, nb))
            cursor += n
        return out

    def pipeline_index_of_rank(self, rank: int) -> int:
        for a in self.assignments():
            if rank in a.ranks:
                return a.pipeline_index
        raise RuntimeError(f"rank {rank} is not in any pipeline")

    def __repr__(self) -> str:
        parts = [
            f"{t.num_hosts}-host/{t.num_stages}-stage(nb={nb})"
            for t, nb in zip(self.instances, self.num_microbatches)
        ]
        return f"HeterogeneousPlan[{', '.join(parts)}; B={self.total_num_microbatches}]"


class PipelineInstantiator:
    def get_best_execution_plan(
        self,
        templates: list[PipelineTemplate],
        allreduce_across_hosts: list[dict[int, float]],
        num_hosts: int,
        global_num_microbatch: int,
    ) -> HeterogeneousPlan:
        """Enumerate feasible instance sets, distribute the batch over each,
        pick the min-iteration-time plan (reference :156-200)."""
        options = self._enumerate_instantiation_options(templates, num_hosts)
        plans: list[HeterogeneousPlan] = []
        for num_instances in options:
            instances = [t for t, n in num_instances.items() for _ in range(n)]
            nbs = self._distribute_batch(global_num_microbatch, instances)
            if nbs is None:
                continue
            plans.append(
                HeterogeneousPlan(instances, nbs, allreduce_across_hosts)
            )
        if not plans:
            raise RuntimeError(
                f"No feasible execution plan for {num_hosts} hosts / "
                f"{global_num_microbatch} microbatches"
            )
        return min(plans, key=lambda p: p.iteration_time)

    def get_new_execution_plan(
        self,
        new_num_instances: dict[PipelineTemplate, int],
        allreduce_across_hosts: list[dict[int, float]],
        global_num_microbatch: int,
    ) -> HeterogeneousPlan:
        """Redistribute the batch for a fixed instance set (reconfiguration
        path, reference :202-222)."""
        instances = [t for t, n in new_num_instances.items() for _ in range(n)]
        nbs = self._distribute_batch(global_num_microbatch, instances)
        if nbs is None:
            raise RuntimeError(
                f"batch of {global_num_microbatch} microbatches cannot cover "
                f"{len(instances)} pipelines"
            )
        return HeterogeneousPlan(instances, nbs, allreduce_across_hosts)

    # ------------------------------------------------------------------ #

    def _enumerate_instantiation_options(
        self, templates: list[PipelineTemplate], num_hosts: int
    ) -> list[dict[PipelineTemplate, int]]:
        """All multisets {template: count} with Σ count·hosts == num_hosts
        (reference DP, instantiator.py:224-252)."""
        dp: list[list[list[dict]]] = [
            [[] for _ in range(num_hosts + 1)] for _ in range(len(templates) + 1)
        ]
        for i in range(1, len(templates) + 1):
            dp[i][0] = [dict()]
            t = templates[i - 1]
            for j in range(1, num_hosts + 1):
                dp[i][j] = [dict(c) for c in dp[i - 1][j]]
                if t.num_hosts <= j:
                    for combo in dp[i][j - t.num_hosts]:
                        new_combo = dict(combo)
                        new_combo[t] = new_combo.get(t, 0) + 1
                        dp[i][j].append(new_combo)
        return dp[-1][-1]

    def _distribute_batch(
        self,
        global_num_microbatch: int,
        instances: list[PipelineTemplate],
        window: int = 3,
    ) -> list[int] | None:
        """min variance of (T_i/s_i)·nb_i  s.t.  Σ nb_i = B, nb_i ≥ 1.

        Continuous relaxation: (T_i/s_i)·nb_i = c ⟹ nb_i = c·s_i/T_i with c
        from the budget; search an integer window around the relaxed point
        for all but the last instance (constraint fixes the last), widening
        the window until feasible, with a greedy fill as the backstop.
        """
        k = len(instances)
        B = global_num_microbatch
        w = [t.iteration_time / t.num_stages for t in instances]

        if k > B:
            return None  # cannot give every pipeline ≥1 microbatch
        if k == 1:
            return [B]

        c = B / sum(1.0 / wi for wi in w)
        relaxed = [max(1.0, c / wi) for wi in w]

        def search(win: int) -> tuple[float, list[int]] | None:
            best = None
            ranges = []
            for i in range(k - 1):
                lo = max(1, int(relaxed[i]) - win)
                hi = min(B - (k - 1), int(relaxed[i]) + win)
                if hi < lo:
                    return None
                ranges.append(range(lo, hi + 1))
            size = 1
            for r in ranges:
                size *= len(r)
            if size > 2_000_000:
                return None
            for combo in itertools.product(*ranges):
                rem = B - sum(combo)
                if rem < 1:
                    continue
                nbs = list(combo) + [rem]
                times = [w[i] * nbs[i] for i in range(k)]
                mean = sum(times) / k
                var = sum((t - mean) ** 2 for t in times)
                if best is None or var < best[0]:
                    best = (var, nbs)
            return best

        best = None
        for win in (window, 4 * window, 16 * window, B):
            best = search(win)
            if best is not None:
                break
        if best is None:
            best = self._greedy_fill(B, w)
        if best is None:
            return None
        return best[1]

    @staticmethod
    def _greedy_fill(B: int, w: list[float]) -> tuple[float, list[int]] | None:
        """Every pipeline gets 1; each further unit goes to the pipeline whose
        resulting time stays smallest (LPT-style)."""
        k = len(w)
        if k > B:
            return None
        nbs = [1] * k
        for _ in range(B - k):
            i = min(range(k), key=lambda j: w[j] * (nbs[j] + 1))
            nbs[i] += 1
        times = [w[i] * nbs[i] for i in range(k)]
        mean = sum(times) / k
        return (sum((t - mean) ** 2 for t in times), nbs)
