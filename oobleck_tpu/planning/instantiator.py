"""Pipeline instantiation: which templates to run, and the microbatch split.

Capability match for the reference instantiator (paper §4.2;
/root/reference/oobleck/planning/instantiator.py:155-329):

  * `_enumerate_instantiation_options` — knapsack-style DP over all multisets
    of templates whose host counts sum to the cluster size (ref :224-252);
  * `_distribute_batch` — the reference solves a Pyomo MINLP (glpk+ipopt
    subprocesses, ref :254-329) minimizing the variance of per-pipeline
    iteration time T_i/s_i · nb_i subject to Σ nb_i·x_i = B. Here the same
    objective is solved exactly with a continuous-relaxation-guided window
    search (nb_i are small integers) — no solver dependency, deterministic,
    and ~µs instead of subprocess round-trips (SURVEY §7.3.6);
  * `HeterogeneousPlan` — plan selection by estimated iteration time =
    max_i(T_i · nb_i) + first-layer cross-host allreduce overhead
    (ref HeterogeneousPipelinesExecutionPlan.iteration_time, :54-68).
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass, field

from oobleck_tpu.planning.templates import LayerProfile, PipelineTemplate


@dataclass(frozen=True)
class PipelineAssignment:
    """One concrete pipeline instance: a template + its global chip ranks."""

    pipeline_index: int
    template: PipelineTemplate
    ranks: tuple[int, ...]
    num_microbatches: int


@dataclass
class HeterogeneousPlan:
    """A chosen multiset of templates + per-template microbatch counts."""

    num_instances: dict[PipelineTemplate, int]
    num_microbatches: dict[PipelineTemplate, int]
    allreduce_across_hosts: list[dict[int, float]]

    @property
    def templates(self) -> list[PipelineTemplate]:
        return sorted(self.num_instances, key=lambda t: t.num_hosts)

    @property
    def total_num_pipelines(self) -> int:
        return sum(self.num_instances.values())

    @property
    def total_num_microbatches(self) -> int:
        return sum(
            self.num_instances[t] * self.num_microbatches[t]
            for t in self.num_instances
        )

    @property
    def iteration_time(self) -> float:
        longest = max(
            t.iteration_time * self.num_microbatches[t] for t in self.num_instances
        )
        # Only the first layer's cross-host grad allreduce is charged; the
        # rest overlaps with backward compute (reference instantiator.py:61-66).
        sync = self.allreduce_across_hosts[0].get(self.total_num_pipelines, 0.0)
        return longest + sync

    def assignments(self, ranks: list[list[int]] | None = None
                    ) -> list[PipelineAssignment]:
        """Materialize pipeline instances with contiguous rank blocks (or the
        explicit per-pipeline `ranks` used after reconfiguration;
        reference instantiate(), instantiator.py:103-152)."""
        out: list[PipelineAssignment] = []
        cursor = 0
        index = 0
        for template in self.templates:
            for _ in range(self.num_instances[template]):
                n = template.num_chips
                if ranks is not None:
                    block = tuple(ranks[index])
                    assert len(block) == n, (len(block), n)
                else:
                    block = tuple(range(cursor, cursor + n))
                out.append(PipelineAssignment(
                    pipeline_index=index,
                    template=template,
                    ranks=block,
                    num_microbatches=self.num_microbatches[template],
                ))
                cursor += n
                index += 1
        return out

    def pipeline_index_of_rank(self, rank: int) -> int:
        for a in self.assignments():
            if rank in a.ranks:
                return a.pipeline_index
        raise RuntimeError(f"rank {rank} is not in any pipeline")

    def __repr__(self) -> str:
        parts = [
            f"{self.num_instances[t]} x {t.num_hosts}-host/{t.num_stages}-stage "
            f"(nb={self.num_microbatches[t]})"
            for t in self.templates
        ]
        return f"HeterogeneousPlan[{', '.join(parts)}; B={self.total_num_microbatches}]"


class PipelineInstantiator:
    def get_best_execution_plan(
        self,
        templates: list[PipelineTemplate],
        allreduce_across_hosts: list[dict[int, float]],
        num_hosts: int,
        global_num_microbatch: int,
    ) -> HeterogeneousPlan:
        """Enumerate feasible instance sets, distribute the batch over each,
        pick the min-iteration-time plan (reference :156-200)."""
        options = self._enumerate_instantiation_options(templates, num_hosts)
        plans: list[HeterogeneousPlan] = []
        for num_instances in options:
            nb = self._distribute_batch(global_num_microbatch, num_instances)
            if nb is None:
                continue
            plans.append(HeterogeneousPlan(num_instances, nb, allreduce_across_hosts))
        if not plans:
            raise RuntimeError(
                f"No feasible execution plan for {num_hosts} hosts / "
                f"{global_num_microbatch} microbatches"
            )
        return min(plans, key=lambda p: p.iteration_time)

    def get_new_execution_plan(
        self,
        new_num_instances: dict[PipelineTemplate, int],
        allreduce_across_hosts: list[dict[int, float]],
        global_num_microbatch: int,
    ) -> HeterogeneousPlan:
        """Redistribute the batch for a fixed instance set (reconfiguration
        path, reference :202-222)."""
        nb = self._distribute_batch(global_num_microbatch, new_num_instances)
        if nb is None:
            raise RuntimeError("batch cannot be distributed over the new instances")
        return HeterogeneousPlan(new_num_instances, nb, allreduce_across_hosts)

    # ------------------------------------------------------------------ #

    def _enumerate_instantiation_options(
        self, templates: list[PipelineTemplate], num_hosts: int
    ) -> list[dict[PipelineTemplate, int]]:
        """All multisets {template: count} with Σ count·hosts == num_hosts
        (reference DP, instantiator.py:224-252)."""
        dp: list[list[list[dict]]] = [
            [[] for _ in range(num_hosts + 1)] for _ in range(len(templates) + 1)
        ]
        for i in range(1, len(templates) + 1):
            dp[i][0] = [dict()]
            t = templates[i - 1]
            for j in range(1, num_hosts + 1):
                dp[i][j] = [dict(c) for c in dp[i - 1][j]]
                if t.num_hosts <= j:
                    for combo in dp[i][j - t.num_hosts]:
                        new_combo = dict(combo)
                        new_combo[t] = new_combo.get(t, 0) + 1
                        dp[i][j].append(new_combo)
        return dp[-1][-1]

    def _distribute_batch(
        self,
        global_num_microbatch: int,
        num_instances: dict[PipelineTemplate, int],
        window: int = 3,
    ) -> dict[PipelineTemplate, int] | None:
        """min variance of (T_i/s_i)·nb_i  s.t.  Σ nb_i·x_i = B, nb_i ≥ 1.

        Continuous relaxation: (T_i/s_i)·nb_i = c ⟹ nb_i = c·s_i/T_i with c
        from the budget constraint. Search an integer window of ±`window`
        around the relaxed nb_i for all but the last template; the last
        template's nb is determined by the constraint. Exact for the small
        integer ranges involved (reference uses a Pyomo MINLP here).
        """
        templates = list(num_instances.keys())
        k = len(templates)
        B = global_num_microbatch
        x = [num_instances[t] for t in templates]
        w = [t.iteration_time / t.num_stages for t in templates]

        if sum(x) > B:
            return None  # cannot give every pipeline ≥1 microbatch
        if k == 1:
            if B % x[0] != 0:
                return None
            return {templates[0]: B // x[0]}

        c = B / sum(x[i] / w[i] for i in range(k))
        relaxed = [max(1.0, c / w[i]) for i in range(k)]

        best: tuple[float, list[int]] | None = None
        ranges = [
            range(max(1, int(relaxed[i]) - window), int(relaxed[i]) + window + 1)
            for i in range(k - 1)
        ]
        for combo in itertools.product(*ranges):
            used = sum(nb * xi for nb, xi in zip(combo, x[:-1]))
            rem = B - used
            if rem <= 0 or rem % x[-1] != 0:
                continue
            nb_last = rem // x[-1]
            nbs = list(combo) + [nb_last]
            times = [w[i] * nbs[i] for i in range(k)]
            mean = sum(times) / k
            var = sum((t - mean) ** 2 for t in times)
            if best is None or var < best[0]:
                best = (var, nbs)
        if best is None:
            return None
        return {t: nb for t, nb in zip(templates, best[1])}
