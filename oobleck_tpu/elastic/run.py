"""Job-submission CLI: `python -m oobleck_tpu.elastic.run --config-path job.yaml`.

Capability match for /root/reference/oobleck/run.py:18-72: parse yaml + CLI
overrides into OobleckArguments, connect to the master, request the launch.
"""

from __future__ import annotations

import argparse
import asyncio
import logging

from oobleck_tpu.config import OobleckArguments
from oobleck_tpu.elastic.message import (
    RequestType,
    ResponseType,
    recv_msg,
    send_request,
)

logger = logging.getLogger("oobleck.run")


class OobleckClient:
    """Reference OobleckClient (run.py:18-41)."""

    def __init__(self, args: OobleckArguments):
        self.args = args
        self._reader = None
        self._writer = None

    async def connect_to_master(self) -> None:
        self._reader, self._writer = await asyncio.open_connection(
            self.args.dist.master_ip, self.args.dist.master_port
        )

    async def request_job_launch(self) -> None:
        await send_request(self._writer, RequestType.LAUNCH_JOB,
                           {"args": self.args.to_dict()})
        msg = await recv_msg(self._reader)
        if msg.get("kind") != ResponseType.SUCCESS.value:
            raise RuntimeError(f"job launch failed: {msg}")
        logger.info("job launched")


def parse_args(argv=None) -> OobleckArguments:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--config-path", required=True, help="yaml job config")
    p.add_argument("--node-ips", nargs="*", default=None,
                   help="override dist.node_ips")
    p.add_argument("--master-ip", default=None)
    p.add_argument("--master-port", type=int, default=None)
    a = p.parse_args(argv)
    args = OobleckArguments.from_yaml(a.config_path)
    if a.node_ips:
        args.dist.node_ips = a.node_ips
    if a.master_ip:
        args.dist.master_ip = a.master_ip
    if a.master_port:
        args.dist.master_port = a.master_port
    return args


async def amain(args: OobleckArguments) -> None:
    client = OobleckClient(args)
    await client.connect_to_master()
    await client.request_job_launch()


def main(argv=None) -> None:
    logging.basicConfig(level=logging.INFO)
    asyncio.run(amain(parse_args(argv)))


if __name__ == "__main__":
    main()
