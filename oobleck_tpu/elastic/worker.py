"""Worker entry point: one process per TPU host.

Capability match for /root/reference/oobleck/elastic/worker.py:13-34. The
worker owns every local chip (no per-device pinning) and drives the engine:
build -> initialize distributed -> instantiate pipelines -> train.
"""

from __future__ import annotations

import logging

from oobleck_tpu.config import OobleckArguments

logger = logging.getLogger("oobleck.worker")


def worker_main(pipe, agent_ip: str, args_dict: dict) -> None:
    args = OobleckArguments.from_dict(args_dict)
    job = args.job
    # Sanity mirrored from the reference (worker.py:27-28); JobArguments also
    # enforces this at construction.
    assert job.global_microbatch_size % job.microbatch_size == 0

    from oobleck_tpu.execution.engine import OobleckEngine

    engine = OobleckEngine(args, agent_ip=agent_ip, agent_pipe=pipe)
    engine.initialize_distributed()
    engine.instantiate_pipelines(job.global_num_microbatch)
    engine.train()
