"""Worker entry point: one process per TPU host.

Capability match for /root/reference/oobleck/elastic/worker.py:13-34. The
worker owns every local chip (no per-device pinning) and drives the engine:
initialize the JAX runtime -> build -> instantiate pipelines -> train.

Multi-host (OOBLECK_MULTIHOST=1): the JAX distributed runtime MUST come up
before anything touches a backend (profiling, model init), so the coordinator
chain runs here, first thing — host 0's worker picks a free port and
announces `ip:port` up its agent pipe (agent -> master -> every agent ->
every worker pipe), the TPU equivalent of the reference's rank-0 TCPStore
port chain + NCCL world init (engine.py:563-593).
"""

from __future__ import annotations

import logging
import os
import socket
import time

from oobleck_tpu.config import OobleckArguments

logger = logging.getLogger("oobleck.worker")


def coordinator_announcement(address: str, world: int) -> dict:
    """The coordinator message. `world` is the generation tag: the survivor
    set only ever shrinks, so its size uniquely identifies a reconfiguration
    round — stale announcements from an earlier (larger) world must not be
    adopted by respawned workers. Shared by the worker-side chain here and
    the embedded-engine chain (engine._initialize_multihost)."""
    return {"kind": "coordinator", "address": address, "world": world}


def coordinator_address_if_current(msg, world: int) -> str | None:
    """Address from a coordinator message iff it matches this generation
    (untagged messages are trusted — the legacy single-generation form)."""
    if not isinstance(msg, dict) or msg.get("kind") != "coordinator":
        return None
    if msg.get("world", world) != world:
        return None
    return msg["address"]


def _init_jax_distributed(pipe, agent_ip: str, args: OobleckArguments,
                          timeout_s: float = 120.0) -> None:
    """Run the coordinator chain and bring up jax.distributed.

    Called before the engine exists, so this owns the pipe exclusively:
    non-coordinator messages seen while waiting are dropped (none are
    expected before initialization completes)."""
    import jax

    node_ips = list(args.dist.node_ips)
    world = len(node_ips)
    process_id = node_ips.index(agent_ip)
    if process_id == 0:
        with socket.socket() as s:
            s.bind((agent_ip, 0))
            port = s.getsockname()[1]
        address = f"{agent_ip}:{port}"
        pipe.send(coordinator_announcement(address, world))
    else:
        deadline = time.monotonic() + timeout_s
        address = None
        while time.monotonic() < deadline:
            if pipe.poll(1.0):
                msg = pipe.recv()
                addr = coordinator_address_if_current(msg, world)
                if addr is not None:
                    address = addr
                    break
        if address is None:
            raise TimeoutError("no coordinator address from the agent")
    jax.distributed.initialize(
        coordinator_address=address,
        num_processes=len(node_ips),
        process_id=process_id,
    )
    logger.info("jax.distributed initialized: %s (process %d/%d)",
                address, process_id, len(node_ips))


def worker_main(pipe, agent_ip: str, args_dict: dict) -> None:
    # Fresh spawned process: without a handler, INFO logs (per-step loss,
    # checkpoint/restore lines — the operator's training signal) vanish.
    logging.basicConfig(
        level=logging.INFO,
        format=f"[worker {agent_ip}] %(name)s: %(message)s")
    # Stack dump on demand (`kill -USR1 <worker>`): a wedged collective or
    # a stuck compile is otherwise undebuggable in a spawned worker —
    # operators (and this repo's own hang triage) get every thread's
    # Python stack on stderr without killing training.
    import faulthandler
    import signal as _signal

    faulthandler.register(_signal.SIGUSR1, all_threads=True)
    from oobleck_tpu.utils import metrics
    from oobleck_tpu.utils.chaos import chaos

    metrics.set_role("worker")
    chaos().barrier("worker_start", ip=agent_ip)
    args = OobleckArguments.from_dict(args_dict)
    job = args.job
    # Sanity mirrored from the reference (worker.py:27-28); JobArguments also
    # enforces this at construction.
    assert job.global_microbatch_size % job.microbatch_size == 0

    if os.environ.get("OOBLECK_MULTIHOST") == "1":
        # One shared jax.distributed world for BOTH paths: the fused SPMD
        # program spans it directly; the MPMD engine runs host-local stage
        # jits inside it, with cross-host pipeline edges and the layer-
        # granularity DP allreduce riding XLA collectives over process
        # meshes (parallel/cross_host.py) — the TPU-native equivalent of
        # the reference's node-spanning NCCL pipelines + DP groups
        # (pipeline.py:582-617, engine.py:363-412).
        _init_jax_distributed(pipe, agent_ip, args)

    from oobleck_tpu.execution.engine import OobleckEngine

    engine = OobleckEngine(args, agent_ip=agent_ip, agent_pipe=pipe)
    engine.initialize_distributed()
    engine.instantiate_pipelines(job.global_num_microbatch)
    # Warm recovery: AOT-compile the stage executables of the likely
    # post-failure plans into the persistent compilation cache on a
    # background thread (execution/precompile.py) — at failure time the
    # re-planned world deserializes instead of cold-compiling.
    # OOBLECK_PRECOMPILE_WAIT=1 blocks until warm before step 1 (tests
    # that inject a failure at a fixed step need the warmth guaranteed).
    engine.start_recovery_precompile(
        wait=os.environ.get("OOBLECK_PRECOMPILE_WAIT") == "1"
    )
    engine.train()
    # Held-out evaluation at the end of the run (the reference builds eval
    # machinery it never drives, dataset.py:39-54 / dataloader.py:101).
    # Collective in multi-host mode — every worker reaches here after its
    # train loop completes the same step count.
    final = engine.evaluate()
    logger.info("final eval loss %.4f%s", final,
                "" if engine.last_eval_metrics is None
                or "accuracy" not in engine.last_eval_metrics
                else f" accuracy {engine.last_eval_metrics['accuracy']:.4f}")
