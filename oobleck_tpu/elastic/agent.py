"""Per-host agent: supervises this host's worker process.

Capability match for the reference agent
(/root/reference/oobleck/elastic/agent.py:27-302), with TPU process topology:
ONE worker process per host (a TPU host drives all its local chips through a
single JAX process) instead of one per GPU with CUDA_VISIBLE_DEVICES pinning
(reference agent.py:148-174).

Responsibilities:
  * register with the master over TCP, receive the job args;
  * ensure profile data exists for the model (runs the profiler on miss,
    reference _run_profiler, agent.py:84-110);
  * spawn the worker with a multiprocessing Pipe for control messages;
  * relay the JAX coordinator address worker -> master and master -> worker
    (the reference's rank-0 port chain, agent.py:181-194);
  * on RECONFIGURATION: remove the lost ip, push it down the worker pipe; if
    *we* are the lost host, self-terminate — the built-in fault-injection
    kill switch (reference agent.py:217-232);
  * heartbeat PING on an interval (the reference defines but never schedules
    it, agent.py:280-288 — actually scheduled here).
"""

from __future__ import annotations

import asyncio
import logging
import multiprocessing as mp
import os
import time
from dataclasses import dataclass

from oobleck_tpu.config import OobleckArguments
from oobleck_tpu.elastic.message import (
    RequestType,
    ResponseType,
    recv_msg,
    send_request,
)

logger = logging.getLogger("oobleck.agent")

PING_INTERVAL = 10.0
# Multi-host: how long an unexplained worker death may wait for the
# RECONFIGURATION that explains it (a peer died mid-collective) before the
# agent gives up and terminates.
WORKER_DEATH_GRACE = 30.0


@dataclass
class Worker:
    pipe: object  # mp.connection.Connection
    process: object  # mp.Process


class OobleckAgent:
    def __init__(self, master_ip: str, master_port: int, agent_ip: str):
        self.master_ip = master_ip
        self.master_port = master_port
        self.agent_ip = agent_ip
        self.args: OobleckArguments | None = None
        self.worker: Worker | None = None
        self.node_ips: list[str] = []
        self._reader: asyncio.StreamReader | None = None
        self._writer: asyncio.StreamWriter | None = None
        self._send_lock = asyncio.Lock()

    # ------------------------------------------------------------------ #

    async def run(self) -> None:
        await self.connect_to_master()
        await self.register()
        self.ensure_profile()
        self.launch_worker()
        await asyncio.gather(self.response_loop(), self.ping_loop(),
                             self.worker_port_loop(), self.worker_watch_loop())

    async def worker_watch_loop(self) -> None:
        """Worker death must surface as a host failure: drop the master
        connection so disconnect-based detection fires (the reference treats
        worker-level failure as out of scope, agent.py:171-173 — here the
        agent exits with its worker so the cluster reconfigures).

        Exceptions: exit code 0 is training completing normally (exit
        cleanly, don't declare the host dead); and in multi-host mode a
        worker dying of a PEER's failure (collective partner gone) gets a
        grace window for the explaining RECONFIGURATION to arrive — the
        respawn replaces self.worker, clearing the pending death."""
        pending: tuple[object, float] | None = None
        while True:
            await asyncio.sleep(1.0)
            w = self.worker
            if w is None or w.process.is_alive():
                pending = None
                continue
            if w.process.exitcode == 0:
                logger.info("worker finished training; agent exiting")
                try:
                    async with self._send_lock:
                        await send_request(self._writer, RequestType.JOB_DONE)
                except (ConnectionError, OSError):
                    pass
                raise SystemExit(0)
            if self._multihost():
                if pending is None or pending[0] is not w:
                    pending = (w, time.monotonic())
                    logger.warning(
                        "worker died (exit=%s); waiting %.0fs for a "
                        "reconfiguration that explains it",
                        w.process.exitcode, WORKER_DEATH_GRACE)
                    continue
                if time.monotonic() - pending[1] < WORKER_DEATH_GRACE:
                    continue
            logger.error("worker process died (exit=%s); terminating agent",
                         w.process.exitcode)
            self.terminate()

    @staticmethod
    def _multihost() -> bool:
        return os.environ.get("OOBLECK_MULTIHOST") == "1"

    async def connect_to_master(self) -> None:
        self._reader, self._writer = await asyncio.open_connection(
            self.master_ip, self.master_port
        )

    async def register(self) -> None:
        """Reference _register_agent (agent.py:70-82)."""
        async with self._send_lock:
            await send_request(self._writer, RequestType.REGISTER_AGENT,
                               {"ip": self.agent_ip})
        msg = await recv_msg(self._reader)
        if msg.get("kind") != ResponseType.SUCCESS.value:
            raise RuntimeError(f"registration failed: {msg}")
        self.args = OobleckArguments.from_dict(msg["args"])
        self.node_ips = list(self.args.dist.node_ips)
        logger.info("registered; job model=%s", self.args.model.model_name)

    # ------------------------------------------------------------------ #

    def ensure_profile(self) -> None:
        """Profile-on-miss (reference _launch_workers, agent.py:112-134)."""
        assert self.args is not None
        from oobleck_tpu.planning.profiler import (
            effective_tag,
            get_profile_path,
            profile,
        )

        m = self.args.model
        ex = self.args.execution
        path = get_profile_path(m.model_name, effective_tag(m.model_tag, ex))
        if not (path / f"mb{self.args.job.microbatch_size}.json").exists():
            logger.info("profile missing for %s; profiling now", m.model_name)
            profile(m.model_name, m.model_args, model_tag=m.model_tag,
                    execution=ex,
                    microbatch_size=self.args.job.microbatch_size)

    def launch_worker(self) -> None:
        """One worker per host with a control pipe (reference agent.py:148-174)."""
        from oobleck_tpu.elastic import worker as worker_mod

        ctx = mp.get_context("spawn")
        parent_pipe, child_pipe = ctx.Pipe()
        proc = ctx.Process(
            target=worker_mod.worker_main,
            args=(child_pipe, self.agent_ip, self.args.to_dict()),
            daemon=True,
        )
        proc.start()
        self.worker = Worker(pipe=parent_pipe, process=proc)
        logger.info("agent %s launched worker pid=%d", self.agent_ip, proc.pid)

    def _stop_worker(self, timeout: float = 15.0) -> None:
        """Terminate the worker, escalating to SIGKILL — a worker wedged in
        a collective with a dead peer can ignore SIGTERM."""
        w = self.worker
        self.worker = None  # watch loop must not treat this as a death
        if w is None or not w.process.is_alive():
            return
        w.process.terminate()
        w.process.join(timeout)
        if w.process.is_alive():
            logger.warning("worker ignored SIGTERM; killing")
            w.process.kill()
            w.process.join(5.0)

    def respawn_worker(self) -> None:
        """Multi-host recovery: restart the worker against the surviving
        hosts. The fresh worker re-runs the coordinator chain (a new
        jax.distributed world of the survivors) and restores position and
        weights from the surviving live-state mirrors (checkpoint-free)
        or, failing that, the latest checkpoint."""
        t0 = time.monotonic()
        self._stop_worker()
        self.args.dist.node_ips = list(self.node_ips)
        self.launch_worker()
        logger.info("worker respawned for %d survivors in %.1fs",
                    len(self.node_ips), time.monotonic() - t0)

    # ------------------------------------------------------------------ #

    async def response_loop(self) -> None:
        """Dispatch master messages (reference on_receive_response,
        agent.py:234-278)."""
        while True:
            try:
                msg = await recv_msg(self._reader, timeout=None)
            except (asyncio.IncompleteReadError, ConnectionError):
                logger.error("master connection lost; exiting")
                self.terminate()
                return
            kind = msg.get("kind")
            if kind == ResponseType.PONG.value:
                continue
            if kind == ResponseType.RECONFIGURATION.value:
                await self.on_reconfiguration(msg["lost_ip"])
            elif kind == ResponseType.FORWARD_COORDINATOR.value:
                if self.worker is not None:
                    payload = {"kind": "coordinator", "address": msg["address"]}
                    if msg.get("world") is not None:
                        payload["world"] = msg["world"]
                    self.worker.pipe.send(payload)
            elif kind == ResponseType.SUCCESS.value and "dist_info" in msg:
                if self.worker is not None:
                    self.worker.pipe.send(
                        {"kind": "dist_info", "dist_info": msg["dist_info"]}
                    )

    async def on_reconfiguration(self, lost_ip: str) -> None:
        """Reference on_receive_reconfiguration (agent.py:217-232)."""
        logger.warning("host %s lost", lost_ip)
        if lost_ip == self.agent_ip:
            # We are declared dead: the built-in failure-injection kill switch.
            logger.warning("this host is the victim; terminating")
            self.terminate()
            return
        if lost_ip in self.node_ips:
            self.node_ips.remove(lost_ip)
        if self._multihost():
            w = self.worker
            if w is not None and w.process.exitcode == 0:
                # Our own training already completed; a peer's departure
                # (however the master classified it) changes nothing.
                logger.info("training already complete; ignoring host loss")
                return
            # A peer process is gone: the shared jax.distributed world is
            # broken and cannot shrink in place — restart the worker over
            # the survivors. Weights + data position come from the live
            # state mirror when configured (checkpoint-free recovery), else
            # the latest checkpoint. to_thread: _stop_worker joins for up
            # to 20s and must not stall the response/ping/relay loops
            # mid-recovery.
            await asyncio.to_thread(self.respawn_worker)
        elif self.worker is not None:
            # Single-host: the engine reconfigures in place — the
            # reference's NCCL-rebuild model (engine.py:91-180).
            self.worker.pipe.send({"kind": "reconfigure", "lost_ip": lost_ip})

    async def ping_loop(self) -> None:
        while True:
            await asyncio.sleep(PING_INTERVAL)
            try:
                async with self._send_lock:
                    await send_request(self._writer, RequestType.PING)
            except ConnectionError:
                return

    async def worker_port_loop(self) -> None:
        """Poll the worker pipe for upward messages: the coordinator
        announcement (reference forward_worker_port, agent.py:181-188)."""
        while True:
            try:
                if self.worker is not None and self.worker.pipe.poll():
                    msg = self.worker.pipe.recv()
                    if msg.get("kind") == "coordinator":
                        # Keep the `world` generation tag intact: dropping
                        # it here would make every downstream worker take
                        # the untagged-trust branch and accept stale
                        # pre-failure coordinator addresses.
                        payload = {"address": msg["address"]}
                        if msg.get("world") is not None:
                            payload["world"] = msg["world"]
                        async with self._send_lock:
                            await send_request(
                                self._writer, RequestType.FORWARD_COORDINATOR,
                                payload,
                            )
            except (EOFError, OSError):
                # Worker died with the pipe open mid-poll; the watch loop
                # owns death handling.
                await asyncio.sleep(1.0)
            await asyncio.sleep(0.05)

    def terminate(self) -> None:
        if self.worker is not None and self.worker.process.is_alive():
            self.worker.process.terminate()
        raise SystemExit(1)


def main() -> None:
    import argparse

    p = argparse.ArgumentParser()
    p.add_argument("--master-ip", required=True)
    p.add_argument("--master-port", type=int, required=True)
    p.add_argument("--agent-ip", required=True)
    a = p.parse_args()
    logging.basicConfig(level=logging.INFO)
    agent = OobleckAgent(a.master_ip, a.master_port, a.agent_ip)
    asyncio.run(agent.run())


if __name__ == "__main__":
    main()
