"""Per-host agent: supervises this host's worker process.

Capability match for the reference agent
(/root/reference/oobleck/elastic/agent.py:27-302), with TPU process topology:
ONE worker process per host (a TPU host drives all its local chips through a
single JAX process) instead of one per GPU with CUDA_VISIBLE_DEVICES pinning
(reference agent.py:148-174).

Responsibilities:
  * register with the master over TCP, receive the job args;
  * ensure profile data exists for the model (runs the profiler on miss,
    reference _run_profiler, agent.py:84-110);
  * spawn the worker with a multiprocessing Pipe for control messages;
  * relay the JAX coordinator address worker -> master and master -> worker
    (the reference's rank-0 port chain, agent.py:181-194);
  * on RECONFIGURATION: remove the lost ip, push it down the worker pipe; if
    *we* are the lost host, self-terminate — the built-in fault-injection
    kill switch (reference agent.py:217-232);
  * heartbeat PING on an interval (the reference defines but never schedules
    it, agent.py:280-288 — actually scheduled here).
"""

from __future__ import annotations

import asyncio
import collections
import logging
import multiprocessing as mp
import os
import time
from dataclasses import dataclass

from oobleck_tpu.config import OobleckArguments
from oobleck_tpu.elastic.message import (
    EPOCH_KEY,
    JOINED_KEY,
    PROTOCOL_VERSION,
    TELEMETRY_KEY,
    RequestType,
    ResponseType,
    recv_msg,
    send_request,
)
from oobleck_tpu.obs import spans
from oobleck_tpu.policy.engine import DECISION_KEY
from oobleck_tpu.utils import metrics, recovery
from oobleck_tpu.utils.chaos import chaos

logger = logging.getLogger("oobleck.agent")

PING_INTERVAL = 10.0
# Multi-host: how long an unexplained worker death may wait for the
# RECONFIGURATION that explains it (a peer died mid-collective) before the
# agent gives up and terminates.
WORKER_DEATH_GRACE = 30.0
# Bounded connect/register retries with exponential backoff: a master that
# is still binding its port (agents race the launcher) or briefly
# partitioned gets retried; a genuinely absent master fails loudly in
# bounded time instead of hanging the host forever. The bound applies to
# BRING-UP only — once a job is established, losing the master flips the
# agent into masterless mode (capped-backoff redial forever, training
# uninterrupted) instead of terminating: a master outage must stall
# *detection*, never *training*.
CONNECT_ATTEMPTS = 6
REGISTER_ATTEMPTS = 4
BACKOFF_INITIAL = 0.5
BACKOFF_CAP = 10.0
# Worker-observed events buffered while masterless, replayed on REATTACH.
MASTERLESS_BUFFER = 64


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        logger.warning("ignoring malformed %s", name)
        return default


@dataclass
class Worker:
    pipe: object  # mp.connection.Connection
    process: object  # mp.Process


class OobleckAgent:
    def __init__(self, master_ip: str, master_port: int, agent_ip: str):
        self.master_ip = master_ip
        self.master_port = master_port
        self.agent_ip = agent_ip
        self.args: OobleckArguments | None = None
        self.worker: Worker | None = None
        self.node_ips: list[str] = []
        self._reader: asyncio.StreamReader | None = None
        self._writer: asyncio.StreamWriter | None = None
        self._send_lock = asyncio.Lock()
        # Serializes worker creation between bring-up and a concurrent
        # RECONFIGURATION-driven respawn (both may run once the control
        # loops start ahead of the worker launch).
        self._worker_lock = asyncio.Lock()
        self.ping_interval = _env_float("OOBLECK_PING_INTERVAL",
                                        PING_INTERVAL)
        # Stamp of the last RECONFIGURATION we acted on, for the
        # RECOVERY_DEADLINE respawn accounting.
        self._notified_at: float | None = None
        # Latest coordinator announcement, replayed to a freshly launched
        # worker: the response loop runs during bring-up (it must — the
        # heartbeat deadline is ticking), so a broadcast can land before
        # the worker exists. The `world` tag makes replaying a stale one
        # safe (the worker rejects mismatched generations).
        self._last_coordinator: dict | None = None
        # Heartbeat RTT: stamp of the last PING sent; the PONG in the
        # response loop closes the measurement.
        self._ping_sent_at: float | None = None
        # True while a chaos flap cycle holds the master connection down:
        # the response/ping loops must ride it out instead of terminating
        # on the (intentional) connection loss.
        self._flapping = False
        # Masterless degraded mode: monotonic stamp of when the master
        # link died mid-job (None while attached). Training continues;
        # the response loop owns the redial-forever/REATTACH cycle.
        self._masterless_since: float | None = None
        # Highest master epoch this agent has applied a verb from: the
        # split-brain fence floor. 0 = no epoch seen (legacy trust).
        self._last_epoch = 0
        # Latest telemetry digest observed in a worker metrics snapshot
        # (obs/telemetry.py); epoch-stamped onto every heartbeat so the
        # master's fleet-health plane gets per-host samples for free.
        self._telemetry_digest: dict | None = None
        # Worker-observed failures / committed incidents that could not be
        # pushed while masterless; bounded, replayed on REATTACH.
        self._buffer: collections.deque = collections.deque(
            maxlen=MASTERLESS_BUFFER)
        # chaos partition_master: monotonic deadline before which redial
        # attempts are suppressed (the link is "partitioned", not down).
        self._partition_until = 0.0
        reg = metrics.registry()
        self._m_rtt = reg.gauge(
            "oobleck_agent_heartbeat_rtt_seconds",
            "Round-trip time of the last PING/PONG to the master")
        self._m_worker_alive = reg.gauge(
            "oobleck_agent_worker_alive",
            "1 while this host's worker process is alive")
        self._m_respawns = reg.counter(
            "oobleck_agent_worker_respawns_total",
            "Worker respawns triggered by reconfiguration")
        self._m_masterless = reg.gauge(
            "oobleck_agent_masterless_seconds",
            "Seconds this agent has been without a master (0 = attached)")

    # ------------------------------------------------------------------ #

    async def run(self) -> None:
        metrics.set_role("agent")
        await self.connect_to_master()
        await self.register()
        # Heartbeats must start the moment we are registered: the master's
        # read deadline (3x ping cadence) is already ticking, and the
        # profile-on-miss bring-up below is compile-bound — minutes, not
        # seconds. Pinging only after profiling would get a healthy agent
        # evicted as hung before its worker ever launched, so the bring-up
        # runs off-thread while the event loop keeps the control plane live.
        tasks = [self._bringup(), self.response_loop(),
                 self.ping_loop(), self.worker_port_loop(),
                 self.worker_watch_loop()]
        # Churn fault injections owned by the agent (utils/chaos.py).
        flap = chaos().flap_period(self.agent_ip)
        if flap is not None:
            tasks.append(self._flap_loop(flap))
        notice = chaos().preempt_notice(self.agent_ip)
        if notice is not None:
            tasks.append(self._preemption_chaos(*notice))
        partition = chaos().partition_master_secs(self.agent_ip)
        if partition is not None:
            tasks.append(self._partition_chaos(partition))
        await asyncio.gather(*tasks)

    async def _bringup(self) -> None:
        await asyncio.to_thread(self.ensure_profile)
        async with self._worker_lock:
            if self.worker is None:  # a mid-bringup respawn already launched
                await asyncio.to_thread(self.launch_worker)

    async def worker_watch_loop(self) -> None:
        """Worker death must surface as a host failure: drop the master
        connection so disconnect-based detection fires (the reference treats
        worker-level failure as out of scope, agent.py:171-173 — here the
        agent exits with its worker so the cluster reconfigures).

        Exceptions: exit code 0 is training completing normally (exit
        cleanly, don't declare the host dead); and in multi-host mode a
        worker dying of a PEER's failure (collective partner gone) gets a
        grace window for the explaining RECONFIGURATION to arrive — the
        respawn replaces self.worker, clearing the pending death."""
        pending: tuple[object, float] | None = None
        while True:
            await asyncio.sleep(1.0)
            w = self.worker
            alive = w is not None and w.process.is_alive()
            self._m_worker_alive.set(1.0 if alive else 0.0)
            if w is None or alive:
                pending = None
                continue
            if w.process.exitcode == 0:
                logger.info("worker finished training; agent exiting")
                try:
                    async with self._send_lock:
                        await send_request(self._writer, RequestType.JOB_DONE)
                except (ConnectionError, OSError):
                    pass
                raise SystemExit(0)
            if self._multihost():
                grace = _env_float("OOBLECK_WORKER_DEATH_GRACE",
                                   WORKER_DEATH_GRACE)
                if pending is None or pending[0] is not w:
                    pending = (w, time.monotonic())
                    if self._masterless_since is not None:
                        # Nobody is watching: queue the observation for
                        # replay on REATTACH so the restarted master still
                        # learns about the death.
                        self._buffer.append(
                            {"kind": "failure", "ip": self.agent_ip,
                             "cause": "worker_exit"})
                    logger.warning(
                        "worker died (exit=%s); waiting %.0fs for a "
                        "reconfiguration that explains it",
                        w.process.exitcode, grace)
                    continue
                if time.monotonic() - pending[1] < grace:
                    continue
            logger.error("worker process died (exit=%s); terminating agent",
                         w.process.exitcode)
            self.terminate()

    @staticmethod
    def _multihost() -> bool:
        return os.environ.get("OOBLECK_MULTIHOST") == "1"

    # -- churn fault injections (utils/chaos.py directives) -------------- #

    async def _flap_loop(self, period: float) -> None:
        """flap_host: drop the master connection every `period` seconds and
        re-register — the repeated down/up the policy plane's quarantine
        exists for. The gap before re-dialing lets the master observe the
        disconnect as a failure (that is the point of the fault). Once the
        master quarantines this host, register() exhausts its bounded
        retries and the agent dies for real — a quarantined flapper must
        not hammer the control plane forever."""
        while True:
            await asyncio.sleep(period)
            logger.warning("chaos: flap — dropping master connection")
            metrics.flight_recorder().record(
                "chaos_injection", action="flap_drop", ip=self.agent_ip)
            self._flapping = True
            try:
                self._writer.close()
                await self._writer.wait_closed()
            except (ConnectionError, OSError):
                pass
            await asyncio.sleep(min(1.0, period / 4))
            await self.connect_to_master()
            await self.register()  # raises once quarantined -> agent exits
            self._flapping = False
            logger.warning("chaos: flap — re-registered")

    async def _partition_chaos(self, secs: float) -> None:
        """partition_master: sever this host's master link for `secs`
        seconds — the master stays up, the agent simply cannot reach it.
        The agent must ride it out in masterless mode (training
        uninterrupted) and REATTACH once the partition heals; the master
        meanwhile sees a heartbeat-deadline eviction and broadcasts the
        loss, so healing also exercises the stale-membership reconcile."""
        await asyncio.sleep(1.0)  # let registration settle first
        logger.warning("chaos: partitioned from master for %.1fs", secs)
        metrics.flight_recorder().record(
            "chaos_injection", action="partition_master", ip=self.agent_ip,
            seconds=secs)
        self._partition_until = time.monotonic() + secs
        try:
            self._writer.close()
            await self._writer.wait_closed()
        except (ConnectionError, OSError):
            pass

    async def _preemption_chaos(self, warn_s: float, delay_s: float) -> None:
        """preempt_notice: after `delay_s`, send the master a SIGTERM-style
        advance warning, then die for real `warn_s` later — whatever state
        the drain managed to flush by then is all that survives."""
        await asyncio.sleep(delay_s)
        logger.warning("chaos: preemption notice (host dies in %.1fs)",
                       warn_s)
        try:
            async with self._send_lock:
                await send_request(self._writer,
                                   RequestType.PREEMPTION_NOTICE,
                                   {"ip": self.agent_ip,
                                    "deadline_s": warn_s})
        except (ConnectionError, OSError):
            pass
        await asyncio.sleep(warn_s)
        logger.warning("chaos: preemption deadline reached; host dies now")
        metrics.flight_recorder().record(
            "chaos_injection", action="preempt_kill", ip=self.agent_ip)
        metrics.flight_recorder().dump("preemption_deadline")
        w = self.worker
        if w is not None and w.process.is_alive():
            w.process.kill()
        logging.shutdown()
        os._exit(1)

    async def connect_to_master(self, attempts: int = CONNECT_ATTEMPTS) -> None:
        """Exponential-backoff reconnect: agents race the master's listener
        at cluster bring-up (the launcher fires them before the accept loop
        necessarily exists on a remote host), and a refused connect must be
        a retry, not a dead host."""
        delay = BACKOFF_INITIAL
        for attempt in range(1, attempts + 1):
            try:
                self._reader, self._writer = await asyncio.open_connection(
                    self.master_ip, self.master_port
                )
                return
            except OSError as e:
                if attempt == attempts:
                    raise
                logger.warning(
                    "master %s:%d not reachable (%s); retry %d/%d in %.1fs",
                    self.master_ip, self.master_port, e, attempt,
                    attempts - 1, delay,
                )
                await asyncio.sleep(delay)
                delay = min(delay * 2, BACKOFF_CAP)

    async def register(self, attempts: int = REGISTER_ATTEMPTS) -> None:
        """Reference _register_agent (agent.py:70-82), with bounded retry:
        an agent that reaches the master before LAUNCH_JOB configured it
        gets FAILURE + a closed socket — reconnect and try again instead of
        dying at bring-up. Registration advertises the heartbeat cadence
        (protocol v2) so the master can derive this agent's read deadline."""
        delay = BACKOFF_INITIAL
        last: Exception | None = None
        for attempt in range(1, attempts + 1):
            try:
                async with self._send_lock:
                    await send_request(
                        self._writer, RequestType.REGISTER_AGENT,
                        {"ip": self.agent_ip,
                         "protocol": PROTOCOL_VERSION,
                         "ping_interval": self.ping_interval},
                    )
                msg = await recv_msg(self._reader)
                if msg.get("kind") == ResponseType.SUCCESS.value:
                    # A master that crashes mid-handshake can emit the
                    # SUCCESS frame without (or with a torn) job-args
                    # payload; that is a retryable half-handshake against
                    # the restarted master, not a fatal protocol error.
                    try:
                        args = OobleckArguments.from_dict(msg["args"])
                    except (KeyError, TypeError, ValueError) as e:
                        last = RuntimeError(
                            f"half-handshake: SUCCESS without usable "
                            f"job args ({e})")
                    else:
                        self.args = args
                        self.node_ips = list(self.args.dist.node_ips)
                        logger.info("registered; job model=%s",
                                    self.args.model.model_name)
                        return
                else:
                    last = RuntimeError(f"registration failed: {msg}")
            except (ConnectionError, OSError, asyncio.IncompleteReadError,
                    asyncio.TimeoutError, TimeoutError) as e:
                last = e
            if attempt == attempts:
                break
            logger.warning("registration attempt %d/%d failed (%s); "
                           "retrying in %.1fs", attempt, attempts, last, delay)
            await asyncio.sleep(delay)
            delay = min(delay * 2, BACKOFF_CAP)
            # The master closes the connection on FAILURE; re-dial. Close
            # our side first — a leaked half-dead socket lingers in a
            # master _agent_loop until its read deadline, where it would be
            # mistaken for THIS agent hanging and evicted.
            if self._writer is not None:
                self._writer.close()
                try:
                    await self._writer.wait_closed()
                except (ConnectionError, OSError):
                    pass
            await self.connect_to_master()
        raise RuntimeError(
            f"registration failed after {attempts} attempts: {last}"
        )

    # ------------------------------------------------------------------ #

    def ensure_profile(self) -> None:
        """Profile-on-miss (reference _launch_workers, agent.py:112-134)."""
        assert self.args is not None
        from oobleck_tpu.planning.profiler import (
            effective_tag,
            get_profile_path,
            profile,
        )

        m = self.args.model
        ex = self.args.execution
        path = get_profile_path(m.model_name, effective_tag(m.model_tag, ex))
        if not (path / f"mb{self.args.job.microbatch_size}.json").exists():
            logger.info("profile missing for %s; profiling now", m.model_name)
            profile(m.model_name, m.model_args, model_tag=m.model_tag,
                    execution=ex,
                    microbatch_size=self.args.job.microbatch_size)

    def launch_worker(self) -> None:
        """One worker per host with a control pipe (reference agent.py:148-174)."""
        from oobleck_tpu.elastic import worker as worker_mod

        ctx = mp.get_context("spawn")
        parent_pipe, child_pipe = ctx.Pipe()
        proc = ctx.Process(
            target=worker_mod.worker_main,
            args=(child_pipe, self.agent_ip, self.args.to_dict()),
            daemon=True,
        )
        proc.start()
        self.worker = Worker(pipe=parent_pipe, process=proc)
        logger.info("agent %s launched worker pid=%d", self.agent_ip, proc.pid)
        if self._last_coordinator is not None:
            # Deliver an announcement that arrived before the worker did;
            # worker-side generation tagging drops it if it is stale.
            parent_pipe.send(self._last_coordinator)

    def _stop_worker(self, timeout: float | None = None) -> None:
        """Terminate the worker, escalating to SIGKILL — a worker wedged in
        a collective with a dead peer can ignore SIGTERM.

        SIGTERM triggers the worker's checkpoint preemption hook (ckpt/
        writer.py drains any in-flight snapshot before obeying), so the
        default join timeout covers the flush grace: killing inside the
        grace window would tear the very checkpoint the hook protects."""
        if timeout is None:
            from oobleck_tpu.ckpt.writer import FLUSH_GRACE_ENV

            try:
                grace = float(os.environ.get(FLUSH_GRACE_ENV, "10"))
            except ValueError:
                grace = 10.0
            timeout = max(15.0, grace + 5.0)
        w = self.worker
        self.worker = None  # watch loop must not treat this as a death
        if w is None or not w.process.is_alive():
            return
        w.process.terminate()
        w.process.join(timeout)
        if w.process.is_alive():
            logger.warning("worker ignored SIGTERM; killing")
            w.process.kill()
            w.process.join(5.0)

    def respawn_worker(self) -> None:
        """Multi-host recovery: restart the worker against the surviving
        hosts. The fresh worker re-runs the coordinator chain (a new
        jax.distributed world of the survivors) and restores position and
        weights from the surviving live-state mirrors (checkpoint-free)
        or, failing that, the latest checkpoint."""
        t0 = time.monotonic()
        self._stop_worker()
        self.args.dist.node_ips = list(self.node_ips)
        self.launch_worker()
        elapsed = time.monotonic() - t0
        logger.info("worker respawned for %d survivors in %.1fs",
                    len(self.node_ips), elapsed)
        self._m_respawns.inc()
        metrics.flight_recorder().record("worker_respawn", ip=self.agent_ip,
                                         survivors=len(self.node_ips),
                                         elapsed_s=round(elapsed, 3))
        since_notice = (
            time.monotonic() - self._notified_at
            if self._notified_at is not None else None
        )
        recovery.mark(recovery.RESPAWN, ip=self.agent_ip,
                      survivors=len(self.node_ips),
                      elapsed=round(elapsed, 3),
                      since_notified=(round(since_notice, 3)
                                      if since_notice is not None else None))

    # ------------------------------------------------------------------ #

    async def response_loop(self) -> None:
        """Dispatch master messages (reference on_receive_response,
        agent.py:234-278)."""
        while True:
            if self._flapping:
                # A chaos flap cycle owns the connection (and its register
                # handshake reads); stay off the stream until it is back.
                await asyncio.sleep(0.1)
                continue
            try:
                msg = await recv_msg(self._reader, timeout=None)
            except (asyncio.IncompleteReadError, ConnectionError, OSError):
                if self._flapping:
                    continue
                # Masterless degraded mode: a lost master mid-job stalls
                # detection, never training. Redial forever; the worker
                # keeps stepping the whole time.
                await self._ride_out_masterless()
                continue
            kind = msg.get("kind")
            if not self._epoch_admits(msg):
                continue
            if kind == ResponseType.PONG.value:
                if self._ping_sent_at is not None:
                    rtt = time.monotonic() - self._ping_sent_at
                    self._ping_sent_at = None
                    self._m_rtt.set(rtt)
                continue
            if kind == ResponseType.RECONFIGURATION.value:
                await self.on_reconfiguration(msg["lost_ip"],
                                              trace=spans.extract(msg),
                                              decision=msg.get(DECISION_KEY))
            elif kind == ResponseType.DEGRADE.value:
                await self.on_reconfiguration(msg["lost_ip"], degrade=True,
                                              trace=spans.extract(msg),
                                              decision=msg.get(DECISION_KEY))
            elif kind == ResponseType.RESTORE.value:
                await self.on_reconfiguration(msg["lost_ip"], restore=True,
                                              trace=spans.extract(msg),
                                              decision=msg.get(DECISION_KEY))
            elif kind == ResponseType.GROW.value:
                await self.on_grow(list(msg.get(JOINED_KEY) or ()),
                                   trace=spans.extract(msg),
                                   decision=msg.get(DECISION_KEY))
            elif kind == ResponseType.LEASE_GRANT.value:
                # Pool plane: one of our hosts is leased to another
                # tenant. Same path as a proactive drain — the decision
                # rides flagged proactive+inplace, so the victim drains
                # (checkpoint flush, clean exit) and survivors reroute
                # in place, zero respawns.
                await self.on_reconfiguration(msg["lost_ip"], degrade=True,
                                              trace=spans.extract(msg),
                                              decision=msg.get(DECISION_KEY))
            elif kind == ResponseType.LEASE_RECLAIM.value:
                # Pool plane: leased chips flowing back — membership
                # extends through the same grow path a JOIN batch rides.
                await self.on_grow(list(msg.get(JOINED_KEY) or ()),
                                   trace=spans.extract(msg),
                                   decision=msg.get(DECISION_KEY))
            elif kind == ResponseType.FORWARD_COORDINATOR.value:
                payload = {"kind": "coordinator", "address": msg["address"]}
                if msg.get("world") is not None:
                    payload["world"] = msg["world"]
                self._last_coordinator = payload
                if self.worker is not None:
                    self.worker.pipe.send(payload)
            elif kind == ResponseType.SUCCESS.value and "dist_info" in msg:
                if self.worker is not None:
                    self.worker.pipe.send(
                        {"kind": "dist_info", "dist_info": msg["dist_info"]}
                    )
            elif kind == ResponseType.FAILURE.value:
                # Explicit absorb: a FAILURE reply to an in-band request
                # (e.g. a forward the master refused) is diagnostic, not
                # fatal — log it so the verb never vanishes silently.
                logger.warning("master replied FAILURE: %s",
                               msg.get("error", msg))

    def _epoch_admits(self, msg: dict) -> bool:
        """Split-brain fence: reject any verb stamped with a master epoch
        LOWER than the highest this agent has applied — a resurrected old
        master (or a delayed frame from one) must never drive the fleet.
        Unstamped messages are admitted (legacy masters predate the fence;
        untagged trust is the pre-fence behavior)."""
        epoch = msg.get(EPOCH_KEY)
        if epoch is None:
            return True
        epoch = int(epoch)
        if epoch < self._last_epoch:
            logger.error(
                "rejecting %s from stale master epoch %d (< applied %d)",
                msg.get("kind"), epoch, self._last_epoch)
            metrics.flight_recorder().record(
                "stale_epoch_rejected", ip=self.agent_ip,
                kind=msg.get("kind"), epoch=epoch,
                applied_epoch=self._last_epoch)
            return False
        self._last_epoch = epoch
        return True

    async def _ride_out_masterless(self) -> None:
        """Masterless degraded mode: the master link died mid-job. Training
        continues untouched; this coroutine owns the capped-backoff
        redial-forever cycle and returns only once a REATTACH (or legacy
        re-register fallback) lands. The bring-up CONNECT_ATTEMPTS bound
        deliberately does NOT apply here — an established job must survive
        an arbitrarily long master outage."""
        self._masterless_since = time.monotonic()
        logger.error("master connection lost mid-job; entering masterless "
                     "mode (training continues; redialing forever)")
        metrics.flight_recorder().record("masterless_enter",
                                         ip=self.agent_ip)
        delay = BACKOFF_INITIAL
        while True:
            self._m_masterless.set(
                time.monotonic() - self._masterless_since)
            wait = self._partition_until - time.monotonic()
            if wait > 0:  # chaos partition: link is severed, not down
                await asyncio.sleep(min(1.0, wait))
                continue
            try:
                self._reader, self._writer = await asyncio.open_connection(
                    self.master_ip, self.master_port)
            except OSError:
                await asyncio.sleep(delay)
                delay = min(delay * 2, BACKOFF_CAP)
                continue
            if await self._reattach():
                break
            try:
                self._writer.close()
                await self._writer.wait_closed()
            except (ConnectionError, OSError):
                pass
            await asyncio.sleep(delay)
            delay = min(delay * 2, BACKOFF_CAP)
        outage = time.monotonic() - self._masterless_since
        self._masterless_since = None
        self._m_masterless.set(0.0)
        logger.warning("reattached to master after %.1fs masterless",
                       outage)
        metrics.flight_recorder().record(
            "masterless_exit", ip=self.agent_ip,
            outage_s=round(outage, 3))

    async def _reattach(self) -> bool:
        """One REATTACH handshake against a freshly dialed master. Carries
        the worker's liveness (the master must NOT relaunch it), the
        highest applied epoch (fence baseline exchange), and the bounded
        buffer of events observed while masterless."""
        w = self.worker
        worker_alive = bool(w is not None and w.process.is_alive())
        try:
            async with self._send_lock:
                await send_request(
                    self._writer, RequestType.REATTACH,
                    {"ip": self.agent_ip,
                     "protocol": PROTOCOL_VERSION,
                     "ping_interval": self.ping_interval,
                     "last_epoch": self._last_epoch,
                     "worker_alive": worker_alive,
                     "buffered": list(self._buffer)})
            msg = await recv_msg(self._reader)
        except (ConnectionError, OSError, asyncio.IncompleteReadError,
                asyncio.TimeoutError, TimeoutError):
            return False
        if msg.get("kind") == ResponseType.SUCCESS.value:
            epoch = msg.get(EPOCH_KEY)
            if epoch is not None:
                self._last_epoch = max(self._last_epoch, int(epoch))
            self._buffer.clear()  # delivered — the master replayed them
            return True
        if "stale master" in str(msg.get("error", "")):
            # The fence cut the other way: WE have seen a newer epoch than
            # this master. Keep dialing — the current master will answer.
            logger.error("dialed a stale master (our epoch %d); retrying",
                         self._last_epoch)
            return False
        # Legacy master (predates REATTACH) answers FAILURE: fall back to
        # plain REGISTER_AGENT, which it treats as a fresh bring-up —
        # slower (worker relaunch on the next reconfiguration), never wrong.
        logger.warning("master refused REATTACH (%s); falling back to "
                       "REGISTER_AGENT", msg.get("error", msg))
        try:
            await self.connect_to_master()
            await self.register()
        except (RuntimeError, OSError):
            return False
        self._buffer.clear()
        return True

    async def on_reconfiguration(self, lost_ip: str,
                                 degrade: bool = False,
                                 restore: bool = False,
                                 trace: dict | None = None,
                                 decision: dict | None = None) -> None:
        """Reference on_receive_reconfiguration (agent.py:217-232).

        `degrade` / `restore` carry the master's verb through to the
        worker: reroute the loss into pipeline bubbles (oobleck_tpu/
        degrade) or resume from the last durable checkpoint, instead of
        the default template re-instantiation. `decision` is the policy
        plane's full verdict (oobleck_tpu/policy), forwarded down the
        worker pipe so the engine honors the same mechanism the master
        chose; a proactive decision (preemption notice) makes the VICTIM
        drain — checkpoint flush before the host dies — rather than
        self-terminate on the spot. A DEGRADE decision flagged `inplace`
        on multihost is forwarded to the live worker (survivors reroute
        at a consensus step boundary, zero respawns) instead of paying
        the ~21 s respawn.

        `trace` is the incident's propagated trace context (obs/spans);
        the agent stamps its notified_at wall time into it and forwards it
        down the worker pipe so the engine's incident report spans master,
        agent, and worker."""
        verb = ("restore" if restore
                else "degrade" if degrade else "reconfiguration")
        logger.warning("host %s lost (verb=%s)", lost_ip, verb)
        self._notified_at = time.monotonic()
        notified_wall = time.time()
        if trace is not None:
            trace = {**trace, "notified_at": notified_wall}
            spans.span_recorder().record(
                "incident.notified", notified_wall, notified_wall,
                trace_id=trace.get("trace_id"), lost_ip=lost_ip,
                ip=self.agent_ip)
        metrics.flight_recorder().record("reconfiguration_notified",
                                         lost_ip=lost_ip, ip=self.agent_ip,
                                         verb=verb)
        recovery.mark(recovery.NOTIFIED, lost_ip=lost_ip, ip=self.agent_ip)
        if lost_ip == self.agent_ip:
            w = self.worker
            if (decision and decision.get("proactive") and w is not None
                    and w.process.is_alive()):
                # Advance notice: the host is still alive — drain. The
                # worker flushes its checkpoint and exits 0; the watch
                # loop then reports JOB_DONE and the agent exits cleanly.
                logger.warning("this host is being preempted; draining "
                               "worker before death")
                payload = {"kind": "drain", "lost_ip": lost_ip}
                if trace is not None:
                    payload[spans.TRACE_KEY] = trace
                w.pipe.send(payload)
                return
            # We are declared dead: the built-in failure-injection kill switch.
            logger.warning("this host is the victim; terminating")
            self.terminate()
            return
        if lost_ip in self.node_ips:
            self.node_ips.remove(lost_ip)
        if self._multihost():
            w = self.worker
            if w is not None and w.process.exitcode == 0:
                # Our own training already completed; a peer's departure
                # (however the master classified it) changes nothing.
                logger.info("training already complete; ignoring host loss")
                return
            if (degrade and decision and decision.get("inplace")
                    and w is not None and w.process.is_alive()):
                # ROADMAP item-1 remainder: survivors apply the reroute in
                # place. The victim is still draining (proactive notice),
                # so the jax.distributed world is not yet broken — all
                # processes agree on a reroute generation and apply it at
                # the same step boundary (engine-side consensus). If the
                # engine can't, it sends `degrade_fallback` back up and we
                # respawn after all.
                payload = {"kind": "degrade", "lost_ip": lost_ip,
                           "inplace": True}
                if trace is not None:
                    payload[spans.TRACE_KEY] = trace
                payload[DECISION_KEY] = decision
                w.pipe.send(payload)
                return
            # A peer process is gone: the shared jax.distributed world is
            # broken and cannot shrink in place — restart the worker over
            # the survivors. Weights + data position come from the live
            # state mirror when configured (checkpoint-free recovery), else
            # the latest checkpoint. to_thread: _stop_worker joins for up
            # to 20s and must not stall the response/ping/relay loops
            # mid-recovery.
            async with self._worker_lock:
                await asyncio.to_thread(self.respawn_worker)
        elif self.worker is not None:
            # Single-host: the engine reconfigures in place — the
            # reference's NCCL-rebuild model (engine.py:91-180). The verb
            # survives the pipe so the engine's listener sees what the
            # master asked for.
            kind = ("restore" if restore
                    else "degrade" if degrade else "reconfigure")
            payload = {"kind": kind, "lost_ip": lost_ip}
            if trace is not None:
                payload[spans.TRACE_KEY] = trace
            if decision is not None:
                payload[DECISION_KEY] = decision
            self.worker.pipe.send(payload)

    async def on_grow(self, joined_ips: list[str],
                      trace: dict | None = None,
                      decision: dict | None = None) -> None:
        """GROW broadcast: hosts `joined_ips` arrived mid-training and the
        master's policy plane scored the absorption. Nothing terminates and
        no survivor respawns — the verb only extends membership and rides
        the worker pipe down to the engine, which applies the chosen grow
        arm at its next step boundary. The joining host receives the same
        broadcast: its membership now includes itself, and its worker (when
        one eventually launches into the grown world) sees the same
        verdict."""
        logger.warning("hosts %s joined (grow verdict=%s)", joined_ips,
                       (decision or {}).get("mechanism"))
        self._notified_at = time.monotonic()
        notified_wall = time.time()
        if trace is not None:
            trace = {**trace, "notified_at": notified_wall}
            spans.span_recorder().record(
                "incident.notified", notified_wall, notified_wall,
                trace_id=trace.get("trace_id"),
                joined_ips=",".join(joined_ips), ip=self.agent_ip)
        metrics.flight_recorder().record("grow_notified",
                                         joined_ips=joined_ips,
                                         ip=self.agent_ip)
        for ip in joined_ips:
            if ip not in self.node_ips:
                self.node_ips.append(ip)
        if self.worker is not None:
            payload: dict = {"kind": "grow", JOINED_KEY: joined_ips}
            if trace is not None:
                payload[spans.TRACE_KEY] = trace
            if decision is not None:
                payload[DECISION_KEY] = decision
            self.worker.pipe.send(payload)

    async def ping_loop(self) -> None:
        while True:
            await asyncio.sleep(self.ping_interval)
            if self._flapping:
                continue  # connection intentionally down (chaos flap)
            if self._masterless_since is not None:
                continue  # the response loop owns the redial cycle
            if chaos().heartbeat_stalled(self.agent_ip):
                # Fault injection: go silent WITHOUT closing the socket —
                # the hung-peer case only the master's heartbeat deadline
                # (never TCP disconnect) can detect.
                logger.warning("chaos: heartbeat stalled (socket held open)")
                continue
            try:
                async with self._send_lock:
                    self._ping_sent_at = time.monotonic()
                    payload: dict = {"ip": self.agent_ip}
                    if self._telemetry_digest is not None:
                        # Piggybacked fleet-health digest: legacy masters
                        # ignore the key; the epoch stamp lets a restarted
                        # master drop samples from a dead incarnation.
                        payload[TELEMETRY_KEY] = dict(
                            self._telemetry_digest,
                            epoch=self._last_epoch)
                    await send_request(self._writer, RequestType.PING,
                                       payload)
                # Piggyback this agent's registry snapshot on the heartbeat
                # cadence — one extra fire-and-forget frame per interval.
                await self._push_metrics("agent",
                                         metrics.registry().snapshot())
            except (ConnectionError, OSError):
                # The response loop observes the same dead socket and
                # enters masterless mode; keep ticking for the reattach.
                continue

    async def _push_metrics(self, role: str, snapshot: dict) -> None:
        """Ship one registry snapshot to the master (METRICS, no reply)."""
        if self._masterless_since is not None:
            # No master to push to. The only snapshot content the master
            # cannot reconstruct after the outage is the engine's committed
            # incident report — keep it in the bounded replay buffer.
            report = (snapshot or {}).get("incident")
            if isinstance(report, dict):
                self._buffer.append({"kind": "incident",
                                     "report": dict(report)})
            return
        try:
            async with self._send_lock:
                await send_request(self._writer, RequestType.METRICS,
                                   {"ip": self.agent_ip, "role": role,
                                    "snapshot": snapshot})
        except (ConnectionError, OSError):
            pass  # the response/ping loops own connection-loss handling

    async def worker_port_loop(self) -> None:
        """Poll the worker pipe for upward messages: the coordinator
        announcement (reference forward_worker_port, agent.py:181-188)."""
        while True:
            try:
                if self.worker is not None and self.worker.pipe.poll():
                    msg = self.worker.pipe.recv()
                    if msg.get("kind") == "metrics":
                        # Relay the worker's registry snapshot upward so the
                        # master's /metrics covers training-quality gauges.
                        snap = msg.get("snapshot") or {}
                        if isinstance(snap.get("telemetry"), dict):
                            # Keep only the newest digest; the ping loop
                            # stamps it onto each heartbeat.
                            self._telemetry_digest = snap["telemetry"]
                        await self._push_metrics("worker", snap)
                    elif msg.get("kind") == "degrade_fallback":
                        # The engine judged the in-place multihost reroute
                        # infeasible after all — pay for the respawn.
                        logger.warning(
                            "worker cannot apply in-place reroute (%s); "
                            "respawning", msg.get("reason"))
                        metrics.flight_recorder().record(
                            "degrade_fallback", ip=self.agent_ip,
                            reason=msg.get("reason"))
                        async with self._worker_lock:
                            await asyncio.to_thread(self.respawn_worker)
                    elif msg.get("kind") == "coordinator":
                        # Keep the `world` generation tag intact: dropping
                        # it here would make every downstream worker take
                        # the untagged-trust branch and accept stale
                        # pre-failure coordinator addresses.
                        payload = {"address": msg["address"]}
                        if msg.get("world") is not None:
                            payload["world"] = msg["world"]
                        async with self._send_lock:
                            await send_request(
                                self._writer, RequestType.FORWARD_COORDINATOR,
                                payload,
                            )
            except (EOFError, OSError):
                # Worker died with the pipe open mid-poll; the watch loop
                # owns death handling.
                await asyncio.sleep(1.0)
            await asyncio.sleep(0.05)

    def terminate(self) -> None:
        if self.worker is not None and self.worker.process.is_alive():
            self.worker.process.terminate()
        raise SystemExit(1)


def main() -> None:
    import argparse

    p = argparse.ArgumentParser()
    p.add_argument("--master-ip", required=True)
    p.add_argument("--master-port", type=int, required=True)
    p.add_argument("--agent-ip", required=True)
    a = p.parse_args()
    logging.basicConfig(level=logging.INFO)
    agent = OobleckAgent(a.master_ip, a.master_port, a.agent_ip)
    asyncio.run(agent.run())


if __name__ == "__main__":
    main()
