"""Wire protocol for the elastic control plane.

Capability match for the reference protocol
(/root/reference/oobleck/elastic/message_util.py:10-93) with one deliberate
change: messages are length-prefixed JSON, not pickle — the control plane
crosses trust boundaries (SSH-launched agents, job clients), and pickle
deserialization is code execution. Layout per message:

    [4-byte big-endian length][UTF-8 JSON body]

Body always carries "kind" (request/response tag). Timeouts mirror the
reference's 5 s default (message_util.py:7).

Protocol v2 adds heartbeat-deadline fields to REGISTER_AGENT
("protocol", "ping_interval"): the master derives a per-agent read
deadline from the agent's own advertised ping cadence, so a
hung-but-connected peer (socket open, no traffic) is evicted instead of
stalling failure detection forever behind a `timeout=None` read. v1
agents (no fields) get the default cadence — the bump is
backward-compatible in both directions.
"""

from __future__ import annotations

import asyncio
import json
from dataclasses import asdict, dataclass, field
from enum import Enum

from oobleck_tpu.utils.chaos import chaos

PROTOCOL_VERSION = 2
TIMEOUT = 5.0
MAX_MSG_BYTES = 64 * 1024 * 1024

# Heartbeat-derived liveness: an agent that misses this many consecutive
# ping intervals is declared hung. 3x tolerates one lost ping plus
# scheduler jitter without ever leaving detection unbounded.
DEFAULT_PING_INTERVAL = 10.0
HEARTBEAT_MISS_FACTOR = 3.0


def read_deadline(ping_interval: float) -> float:
    """Master-side read deadline for an agent pinging at `ping_interval`.

    Floored at TIMEOUT so a pathologically small advertised interval
    can't make the master evict agents on scheduler noise."""
    return max(TIMEOUT, float(ping_interval) * HEARTBEAT_MISS_FACTOR)


class RequestType(str, Enum):
    LAUNCH_JOB = "launch_job"
    GET_DIST_INFO = "get_dist_info"
    REGISTER_AGENT = "register_agent"
    PING = "ping"
    FORWARD_COORDINATOR = "forward_coordinator"  # reference: FORWARD_RANK0_PORT
    # Clean departure: the agent's worker completed training. The master
    # drops the agent WITHOUT broadcasting RECONFIGURATION — completion must
    # not look like a failure to the surviving agents.
    JOB_DONE = "job_done"
    # Fire-and-forget metrics push: an agent ships registry snapshots
    # ({"ip", "role", "snapshot"}) for itself and its workers so the master
    # can serve a merged cluster-wide /metrics view. No response — a slow
    # metrics path must never back-pressure the heartbeat channel.
    METRICS = "metrics"
    # Spot-preemption advance notice ({"ip", "deadline_s"}): the agent's
    # host received a SIGTERM-style warning and will die in ~deadline_s.
    # The master reacts proactively — drain + checkpoint flush + reroute
    # decided BEFORE the host disappears — instead of waiting for the
    # heartbeat deadline to notice the corpse.
    PREEMPTION_NOTICE = "preemption_notice"
    # Mid-training capacity arrival ({"ip", optional "spot_lifetime_s"}):
    # a freshly provisioned host announces itself AFTER the job launched —
    # distinct from initial bring-up (REGISTER_AGENT before launch) and
    # from a quarantine-lifted host re-registering. The master batches
    # near-simultaneous JOINs into one grow incident and answers with a
    # GROW broadcast; masters that predate the verb answer FAILURE, and
    # the joining agent falls back to plain REGISTER_AGENT (parked until
    # the next restart picks it up).
    JOIN = "join"
    # Post-outage re-attachment ({"ip", "protocol", "ping_interval",
    # "last_epoch", optional "worker_alive", "buffered" events}): an agent
    # that survived a master outage in masterless mode re-dials the
    # RESTARTED master and re-attaches — distinct from REGISTER_AGENT
    # (first contact: the master launches workers and the agent brings one
    # up) in that the agent's worker is ALIVE and must not be disturbed;
    # the master reconciles the reattachment against its replayed journal.
    # Masters that predate the verb answer FAILURE; the agent falls back
    # to plain REGISTER_AGENT (which the old master treats as a fresh
    # bring-up — slower, never wrong).
    REATTACH = "reattach"
    # Chip-pool borrow/release ({"tenant", "chips", optional "reason",
    # "pressure", "lease_ttl_s"} or {"tenant", "release": lease_id}): a
    # serve replica group under traffic pressure asks the pool arbiter
    # for leased training chips, or returns a lease early once the peak
    # passes. First message on a fresh connection (like LAUNCH_JOB), one
    # SUCCESS/FAILURE answer carrying the lease (LEASE_KEY) or the
    # arbiter's denial reason. Masters that predate the verb (or run with
    # the pool plane disabled) answer FAILURE — the requester backs off
    # and sheds load through its own admission queue, which is exactly
    # the pre-pool behavior.
    POOL_BORROW = "pool_borrow"


class ResponseType(str, Enum):
    SUCCESS = "success"
    FAILURE = "failure"
    PONG = "pong"
    RECONFIGURATION = "reconfiguration"
    # Degraded-mode hint: the lost host's work should first be REROUTED
    # into surviving DP peers' pipeline bubbles (oobleck_tpu/degrade) —
    # same payload as RECONFIGURATION, distinct verb so agents, the flight
    # recorder, and the wire traces can tell a fast-path recovery from a
    # full re-instantiation. Receivers that predate the verb fall back to
    # treating it as RECONFIGURATION (the engine funnels both into the
    # same recovery entry point, which tries reroute first anyway).
    DEGRADE = "degrade"
    # Checkpoint-restore verb: the policy plane judged in-memory recovery
    # a losing bet (churn storm, correlated loss) and the cluster should
    # resume from the last durable checkpoint. Same payload shape as
    # RECONFIGURATION; receivers that predate the verb treat it as
    # RECONFIGURATION (the respawned worker restores from durable state
    # on bringup anyway, so the fallback is correct, just slower).
    RESTORE = "restore"
    # Grow verb: one or more hosts JOINed mid-training and the policy
    # plane scored the grow arms (absorb_spare / grow_dp / grow_reshape).
    # Payload carries "lost_ip": "" (no host was lost — the shared
    # broadcast machinery requires the key) plus JOINED_KEY, the policy
    # decision, and trace context. Receivers that predate the verb IGNORE
    # it (it funnels to the engine's control queue, not to recovery): an
    # old survivor simply keeps training at the old size, which is safe —
    # capacity absorption degrades to a no-op, never to an outage.
    GROW = "grow"
    # Lease-grant verb: the pool arbiter leased a training host's chips to
    # another tenant (a serve replica group at a traffic peak). Payload is
    # the preemption-pattern DEGRADE shape — "lost_ip" names the leased
    # host, the policy decision rides DECISION_KEY with proactive=True so
    # the victim drains through a checkpoint flush (zero respawns) while
    # survivors reroute in place — plus LEASE_KEY describing the lease.
    # Receivers that predate the verb funnel it to the same recovery entry
    # point as DEGRADE (the engine tries reroute first anyway), which is
    # correct: to a pre-pool agent a leased-away host is just a proactive
    # departure.
    LEASE_GRANT = "lease_grant"
    # Lease-reclaim verb: a lease ended (returned early, expired, or
    # reclaimed off-peak) and the chips come back to training through the
    # grow path. Payload is the GROW shape — "lost_ip": "", JOINED_KEY
    # lists the returning hosts — plus LEASE_KEY naming the closed lease.
    # Receivers that predate the verb IGNORE it, same safe degradation as
    # GROW: the fleet keeps training at the smaller size.
    LEASE_RECLAIM = "lease_reclaim"
    FORWARD_COORDINATOR = "forward_coordinator"


# Broadcast-payload key naming the joined host ips on the GROW verb (a
# named constant so oobleck-lint OBL004 can pin the master's broadcast
# payloads to the core key set).
JOINED_KEY = "joined_ips"

# PING-payload key carrying the agent's compact telemetry digest
# (obs/telemetry.py): the digest piggybacks on the heartbeat the agent
# already sends, so fleet-health telemetry costs zero extra messages.
# Legacy masters ignore the key; new masters tolerate its absence (a v1
# agent simply contributes no fleet-health row) — the TRACE_KEY/
# DECISION_KEY legacy-tolerance pattern. The digest is epoch-stamped
# ("epoch" inside the digest dict) so a master restarted under the
# split-brain fence can drop samples describing a dead incarnation.
TELEMETRY_KEY = "telemetry"

# Broadcast-payload key carrying the master's monotonic epoch (split-brain
# fence): every broadcast from an epoch-aware master is stamped with it,
# and agents REJECT verbs whose epoch is lower than the highest they have
# applied — a resurrected old master can never drive the fleet. Legacy
# receivers ignore the key (untagged trust, the pre-fence behavior); a
# named constant per the TRACE_KEY/DECISION_KEY legacy-tolerance pattern.
EPOCH_KEY = "master_epoch"

# Payload key carrying a chip lease record (pool/leases.py as_record():
# lease_id, tenant, hosts, granted_at, expires_at, state) on the
# POOL_BORROW answer and the LEASE_GRANT / LEASE_RECLAIM broadcasts.
# Legacy receivers ignore the key — the broadcasts are self-sufficient
# DEGRADE/GROW shapes without it; a named constant per the TRACE_KEY/
# DECISION_KEY legacy-tolerance pattern.
LEASE_KEY = "lease"

# Payload key naming the tenant a message acts for: stamped on
# POOL_BORROW requests and on the journal's per-tenant EV_JOB entries so
# replay can keep N jobs apart instead of folding them last-writer-wins.
# Absent means the single-job default tenant — every pre-pool message.
TENANT_KEY = "tenant"


@dataclass
class DistributionInfo:
    """Cluster membership snapshot (reference message_util.py:10-13)."""

    agent_ips: list[str] = field(default_factory=list)
    world_size: int = 0

    def to_dict(self) -> dict:
        return asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "DistributionInfo":
        return cls(agent_ips=list(d["agent_ips"]), world_size=int(d["world_size"]))


async def send_msg(writer: asyncio.StreamWriter, body: dict) -> None:
    c = chaos()
    if c.active:
        kind = str(body.get("kind", ""))
        delay = c.send_delay(kind)
        if delay > 0:
            await asyncio.sleep(delay)
        if c.drop_send(kind):
            # Length-prefixed framing: dropping a whole message leaves the
            # stream well-formed (unlike truncating one mid-frame).
            return
    data = json.dumps(body).encode()
    if len(data) > MAX_MSG_BYTES:
        raise ValueError(f"message too large: {len(data)}")
    writer.write(len(data).to_bytes(4, "big") + data)
    await writer.drain()


async def recv_msg(reader: asyncio.StreamReader, timeout: float | None = TIMEOUT
                   ) -> dict:
    async def _read():
        header = await reader.readexactly(4)
        length = int.from_bytes(header, "big")
        if length > MAX_MSG_BYTES:
            raise ValueError(f"message too large: {length}")
        return json.loads(await reader.readexactly(length))

    if timeout is None:
        return await _read()
    return await asyncio.wait_for(_read(), timeout)


async def send_request(writer, req: RequestType, payload: dict | None = None) -> None:
    await send_msg(writer, {"kind": req.value, **(payload or {})})


async def send_response(writer, resp: ResponseType, payload: dict | None = None) -> None:
    await send_msg(writer, {"kind": resp.value, **(payload or {})})
