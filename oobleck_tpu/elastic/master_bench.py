"""Control-plane outage microbench: master death, journal replay,
REATTACH, and journal-vs-reality reconcile over real sockets.

Two measured phases against a journaling master (fresh tmpdir state):

  * restart_to_reconciled: kill the master mid-job (every agent
    survives), restart it against the journal, and time restart ->
    replay -> all REATTACHes -> reconcile-window close. The reattach
    window is part of the number on purpose — it is the price the
    config pays for tolerating stragglers.
  * failure_during_outage: kill the master AND one agent, restart, and
    time restart -> the recovery verb landing at the surviving agents —
    the stale-membership case where only the journal knows the fleet
    ever had that host. Scripted agents do not train, so verb receipt
    (the moment a real worker would begin recovery) is the endpoint.

The fleet is scripted agent CLIENTS (register/reattach/read-broadcasts
over real TCP), not full OobleckAgents: no workers, no JAX — the
numbers isolate the control plane. The in-process master "kill"
emulates SIGKILL faithfully: journaling stops instantly, every agent
transport is aborted (RST, no FIN), and nothing runs a dying gasp.

Prints ONE JSON line (consumed by bench.py's "master" key and
`make master-bench`).
"""

from __future__ import annotations

import asyncio
import json
import os
import statistics
import tempfile
import time

from oobleck_tpu.config import OobleckArguments
from oobleck_tpu.elastic import journal as journal_mod
from oobleck_tpu.elastic import master as master_mod
from oobleck_tpu.elastic.message import (
    EPOCH_KEY,
    PROTOCOL_VERSION,
    RequestType,
    ResponseType,
    recv_msg,
    send_request,
)

AGENTS = ("10.9.0.1", "10.9.0.2", "10.9.0.3")
REATTACH_WINDOW_S = 0.5
PHASE_TIMEOUT_S = 30.0


class ScriptedAgent:
    """A fleet member reduced to its control-plane behavior: register,
    reattach after an outage, and collect broadcasts."""

    def __init__(self, ip: str):
        self.ip = ip
        self.reader = None
        self.writer = None
        self.inbox: list[dict] = []
        self.last_epoch = 0
        self._drain: asyncio.Task | None = None

    async def register(self, port: int) -> None:
        self.reader, self.writer = await asyncio.open_connection(
            "127.0.0.1", port)
        await send_request(self.writer, RequestType.REGISTER_AGENT,
                           {"ip": self.ip, "protocol": PROTOCOL_VERSION,
                            "ping_interval": 10.0})
        msg = await recv_msg(self.reader)
        assert msg["kind"] == ResponseType.SUCCESS.value, msg
        self._start_drain()

    async def reattach(self, port: int) -> float:
        """Redial + REATTACH; returns handshake seconds."""
        if self._drain is not None:
            self._drain.cancel()
        t0 = time.monotonic()
        self.reader, self.writer = await asyncio.open_connection(
            "127.0.0.1", port)
        await send_request(self.writer, RequestType.REATTACH,
                           {"ip": self.ip, "protocol": PROTOCOL_VERSION,
                            "ping_interval": 10.0,
                            "last_epoch": self.last_epoch,
                            "worker_alive": True, "buffered": []})
        msg = await recv_msg(self.reader)
        assert msg["kind"] == ResponseType.SUCCESS.value, msg
        if msg.get(EPOCH_KEY) is not None:
            self.last_epoch = int(msg[EPOCH_KEY])
        self._start_drain()
        return time.monotonic() - t0

    def _start_drain(self) -> None:
        async def _loop(reader):
            try:
                while True:
                    self.inbox.append(await recv_msg(reader, timeout=None))
            except (asyncio.IncompleteReadError, ConnectionError, OSError):
                pass

        self._drain = asyncio.ensure_future(_loop(self.reader))

    async def wait_verb(self, verbs: set[str], timeout: float) -> dict:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            for msg in self.inbox:
                if msg.get("kind") in verbs:
                    return msg
            await asyncio.sleep(0.01)
        raise TimeoutError(f"{self.ip}: no {verbs} broadcast in {timeout}s")

    def close(self) -> None:
        if self._drain is not None:
            self._drain.cancel()
        if self.writer is not None:
            self.writer.close()


def _hard_kill(m) -> None:
    """Emulate SIGKILL on an in-process master: journaling stops NOW (a
    dead master appends nothing), registrations vanish without close
    handlers, and every agent transport is aborted (RST — the fleet sees
    a dead connection, never a goodbye)."""
    infos = list(m.agents.values())
    m.agents.clear()       # _is_failure: loops exit without detection
    m.journal = None       # no EV_DEPART dying gasp
    for info in infos:
        try:
            info.writer.transport.abort()
        except Exception:  # noqa: BLE001 — teardown best-effort
            pass


async def _start_master(port: int):
    m = master_mod.OobleckMasterDaemon(port=port, launcher=None)
    await m.start()
    return m, asyncio.create_task(m.serve_forever())


async def _bench() -> dict:
    tmp = tempfile.mkdtemp(prefix="oobleck-master-bench-")
    os.environ[journal_mod.ENV_STATE_DIR] = tmp
    os.environ[master_mod.ENV_REATTACH_WINDOW] = str(REATTACH_WINDOW_S)

    args = OobleckArguments()
    args.dist.node_ips = list(AGENTS)

    m1, t1 = await _start_master(0)
    port = m1.port
    r, w = await asyncio.open_connection("127.0.0.1", port)
    await send_request(w, RequestType.LAUNCH_JOB, {"args": args.to_dict()})
    assert (await recv_msg(r))["kind"] == ResponseType.SUCCESS.value
    w.close()
    fleet = [ScriptedAgent(ip) for ip in AGENTS]
    for a in fleet:
        await a.register(port)

    # ---- phase 1: outage, full fleet survives ------------------------- #
    _hard_kill(m1)
    t1.cancel()
    await m1.stop()
    t_restart = time.monotonic()
    m2, t2 = await _start_master(port)
    replay_s = m2.journal.last_replay_s or 0.0
    replayed = m2.journal.replayed_entries
    reattach_lat = [await a.reattach(port) for a in fleet]
    await asyncio.wait_for(m2._reconcile_task, timeout=PHASE_TIMEOUT_S)
    restart_to_reconciled = time.monotonic() - t_restart
    epoch_after_restart = m2.master_epoch
    zero_lost = not any(
        msg.get("lost_ip") for a in fleet for msg in a.inbox)

    # ---- phase 2: one host dies DURING the outage --------------------- #
    _hard_kill(m2)
    t2.cancel()
    await m2.stop()
    fleet[2].close()  # the host the journal remembers but reality lost
    t_restart2 = time.monotonic()
    m3, t3 = await _start_master(port)
    for a in fleet[:2]:
        await a.reattach(port)
    verbs = {ResponseType.RECONFIGURATION.value, ResponseType.DEGRADE.value,
             ResponseType.RESTORE.value}
    msg = await fleet[0].wait_verb(verbs, PHASE_TIMEOUT_S)
    restart_to_recovery = time.monotonic() - t_restart2

    summary = {
        "agents": len(AGENTS),
        "reattach_window_s": REATTACH_WINDOW_S,
        "journal_replay_s": round(replay_s, 6),
        "journal_replayed_entries": replayed,
        "reattach_handshake_p50_s": round(
            statistics.median(reattach_lat), 6),
        "reattach_handshake_max_s": round(max(reattach_lat), 6),
        "restart_to_reconciled_s": round(restart_to_reconciled, 6),
        "clean_reattach_zero_recoveries": zero_lost,
        "epoch_after_restart": epoch_after_restart,
        "failure_during_outage": {
            "lost_ip": msg.get("lost_ip"),
            "recovery_verb": msg.get("kind"),
            "restart_to_recovery_broadcast_s": round(
                restart_to_recovery, 6),
        },
        "note": ("scripted agent clients over real TCP, no workers — "
                 "control-plane latency only; the reattach window is "
                 "included in restart_to_reconciled_s by design"),
    }
    _hard_kill(m3)
    t3.cancel()
    await m3.stop()
    for a in fleet:
        a.close()
    return summary


def main() -> None:
    print(json.dumps(asyncio.run(_bench())))


if __name__ == "__main__":
    main()
