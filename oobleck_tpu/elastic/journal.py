"""Durable control-plane journal: the master survives its own failure.

Everything the master knows that cannot be re-derived from the fleet —
registrations and departures, quarantine transitions, per-host MTBF
observations, policy latency EWMAs, open incident and grow batches, and
the monotonic ``master_epoch`` itself — is write-ahead journaled here so
a restarted master resumes *deciding* instead of resuming *amnesiac*.

Layout under ``OOBLECK_MASTER_STATE_DIR``:

    <dir>/
      SNAPSHOT.json       compacted state + the epoch (atomic-rename commit)
      journal.jsonl       entries since the snapshot (append, fsync'd)
      .tmp-SNAPSHOT.json  in-flight snapshot (invisible to recovery)

Durability discipline mirrors the checkpoint plane (ckpt/manifest.py):
the snapshot commits via tmp + fsync + ``os.replace`` + dir fsync, so it
either exists with full content or not at all; journal appends are one
JSON object per line, fsync'd per entry — a torn final line (crash mid-
append) is detected and dropped at replay, never propagated. Replay =
snapshot + tail, and compaction (every ``OOBLECK_JOURNAL_SNAPSHOT_EVERY``
entries) folds the tail into a fresh snapshot then truncates the journal.

The epoch is bumped and PERSISTED inside ``open()`` before the caller
sees it: a master that crashes between boot and its first broadcast still
burned the epoch, so no two master incarnations can ever stamp the same
one (the split-brain fence's ground truth).

Timestamps in journal entries are wall-clock (``time.time``) — monotonic
clocks do not survive a process restart, and the health tracker's replay
path converts ages back into its own clock domain.
"""

from __future__ import annotations

import json
import logging
import os
import time
from pathlib import Path

from oobleck_tpu.ckpt.manifest import atomic_write_json, fsync_dir, read_json

logger = logging.getLogger("oobleck.journal")

ENV_STATE_DIR = "OOBLECK_MASTER_STATE_DIR"
ENV_SNAPSHOT_EVERY = "OOBLECK_JOURNAL_SNAPSHOT_EVERY"
DEFAULT_SNAPSHOT_EVERY = 64

SNAPSHOT_FILE = "SNAPSHOT.json"
JOURNAL_FILE = "journal.jsonl"
FORMAT_VERSION = 1

# Entry kinds, named here so master/replay/tests share one vocabulary.
EV_REGISTER = "register"
EV_DEPART = "depart"
EV_QUARANTINE = "quarantine"
EV_FAILURE = "failure"            # per-host MTBF observation
EV_EWMA = "ewma"                  # policy latency EWMA snapshot
EV_INCIDENT_OPEN = "incident_open"
EV_INCIDENT_CLOSE = "incident_close"
EV_JOB = "job"                    # job launched (args ride the entry)
EV_JOB_DONE = "job_done"
EV_LEASE = "lease"                # chip-lease transition (pool plane)

# Tenant id stamped on entries from messages that predate TENANT_KEY.
# EV_JOB/EV_JOB_DONE entries are keyed by tenant so replaying N jobs no
# longer folds them last-writer-wins into one.
DEFAULT_TENANT = "default"


def state_dir() -> str | None:
    """The configured journal directory, or None (journaling off)."""
    return os.environ.get(ENV_STATE_DIR) or None


def snapshot_every() -> int:
    raw = os.environ.get(ENV_SNAPSHOT_EVERY, "")
    try:
        n = int(raw) if raw else DEFAULT_SNAPSHOT_EVERY
    except ValueError:
        n = DEFAULT_SNAPSHOT_EVERY
    return max(n, 1)


class MasterJournal:
    """Write-ahead journal + snapshot compaction for one master daemon.

    Not thread-safe by itself: the master's single event loop serializes
    every append (same contract as the registry / policy engine)."""

    def __init__(self, directory: str | Path):
        self.dir = Path(directory)
        self.dir.mkdir(parents=True, exist_ok=True)
        self.epoch = 0
        # In-memory mirror of the durable state, replayed on open() and
        # folded into SNAPSHOT.json at compaction.
        self.state: dict = _empty_state()
        self.entries_since_snapshot = 0
        self.last_replay_s: float | None = None
        self.replayed_entries = 0
        self._fh = None  # append handle, opened lazily

    # -- boot -------------------------------------------------------------- #

    def open(self) -> None:
        """Replay snapshot + journal tail, then bump and persist the epoch.

        After open() returns, ``self.epoch`` is a value no previous master
        incarnation ever stamped on a broadcast — even one that crashed
        before broadcasting anything."""
        t0 = time.monotonic()
        snap_path = self.dir / SNAPSHOT_FILE
        if snap_path.exists():
            try:
                snap = read_json(snap_path)
                self.state = _merge_state(snap.get("state") or {})
                self.epoch = int(snap.get("epoch") or 0)
            except (json.JSONDecodeError, OSError, ValueError) as e:
                # A torn snapshot cannot happen (atomic rename) — this is
                # operator damage; refuse to guess and start fresh loudly.
                logger.error("unreadable %s (%s); starting fresh", snap_path, e)
                self.state = _empty_state()
                self.epoch = 0
        self.replayed_entries = self._replay_tail()
        self.epoch += 1
        # Persist the bumped epoch BEFORE the caller can broadcast with it:
        # the snapshot write is the epoch burn.
        self._write_snapshot()
        self._truncate_journal()
        self.last_replay_s = time.monotonic() - t0
        logger.info(
            "journal replayed: epoch=%d entries=%d agents=%s (%.3fs)",
            self.epoch, self.replayed_entries,
            sorted(self.state["agents"]), self.last_replay_s)

    def _replay_tail(self) -> int:
        path = self.dir / JOURNAL_FILE
        if not path.exists():
            return 0
        n = 0
        try:
            raw = path.read_bytes()
        except OSError:
            return 0
        for line in raw.splitlines():
            if not line.strip():
                continue
            try:
                entry = json.loads(line)
            except json.JSONDecodeError:
                # Torn final line: the crash hit mid-append. Everything
                # before it is intact (one fsync per entry); drop the tail.
                logger.warning("dropping torn journal tail (%d bytes)",
                               len(line))
                break
            self._apply(entry)
            n += 1
        return n

    # -- append ------------------------------------------------------------ #

    def append(self, kind: str, **fields) -> None:
        """Write-ahead: the entry is durable before the caller proceeds."""
        entry = {"kind": kind, "ts": time.time(), **fields}
        self._apply(entry)
        if self._fh is None:
            self._fh = open(self.dir / JOURNAL_FILE, "ab")
        self._fh.write(json.dumps(entry).encode() + b"\n")
        self._fh.flush()
        os.fsync(self._fh.fileno())
        self.entries_since_snapshot += 1
        if self.entries_since_snapshot >= snapshot_every():
            self.compact()

    def _apply(self, entry: dict) -> None:
        """Fold one entry into the in-memory state mirror."""
        kind = entry.get("kind")
        s = self.state
        ip = entry.get("ip")
        if kind == EV_REGISTER:
            if ip:
                s["agents"][ip] = {
                    "registered_at": entry.get("ts"),
                    "tenant": entry.get("tenant") or DEFAULT_TENANT,
                }
        elif kind == EV_DEPART:
            s["agents"].pop(ip, None)
        elif kind == EV_FAILURE:
            log = s["failures"].setdefault(ip, [])
            log.append(entry.get("ts"))
            del log[:-32]
            if entry.get("cause"):
                s["causes"][ip] = entry["cause"]
        elif kind == EV_QUARANTINE:
            if entry.get("entered"):
                s["quarantined"][ip] = entry.get("ts")
            else:
                s["quarantined"].pop(ip, None)
        elif kind == EV_EWMA:
            s["ewma"] = dict(entry.get("ewma") or {})
        elif kind == EV_INCIDENT_OPEN:
            tid = entry.get("trace_id")
            if tid:
                s["open_incidents"][tid] = {
                    k: entry.get(k) for k in
                    ("lost_ip", "joined_ips", "cause", "ts")}
        elif kind == EV_INCIDENT_CLOSE:
            s["open_incidents"].pop(entry.get("trace_id"), None)
        elif kind == EV_JOB:
            # Keyed by tenant so N concurrent jobs replay as N jobs, not
            # one last-writer-wins survivor. s["job"] stays a live mirror
            # of the default tenant's entry for pre-pool readers.
            tenant = entry.get("tenant") or DEFAULT_TENANT
            s["jobs"][tenant] = entry.get("args")
            if tenant == DEFAULT_TENANT:
                s["job"] = entry.get("args")
        elif kind == EV_JOB_DONE:
            tenant = entry.get("tenant") or DEFAULT_TENANT
            s["jobs"].pop(tenant, None)
            if tenant == DEFAULT_TENANT:
                s["job"] = None
        elif kind == EV_LEASE:
            lease_id = entry.get("lease_id")
            if lease_id:
                if entry.get("state") == "active":
                    s["leases"][lease_id] = {
                        k: entry.get(k) for k in
                        ("tenant", "lender", "hosts", "expires_at", "ts")}
                else:  # returned / reclaimed / expired end the lease
                    s["leases"].pop(lease_id, None)

    # -- compaction -------------------------------------------------------- #

    def compact(self) -> None:
        """Fold the tail into a fresh snapshot, then truncate the journal.
        Crash-ordering: the snapshot rename commits FIRST; a crash between
        it and the truncate leaves already-folded entries in the journal,
        which replay idempotently (set/dict semantics), never corrupt."""
        self._write_snapshot()
        self._truncate_journal()

    def _write_snapshot(self) -> None:
        atomic_write_json(self.dir / SNAPSHOT_FILE, {
            "version": FORMAT_VERSION,
            "epoch": self.epoch,
            "written_at": time.time(),
            "state": self.state,
        })

    def _truncate_journal(self) -> None:
        if self._fh is not None:
            self._fh.close()
            self._fh = None
        path = self.dir / JOURNAL_FILE
        with open(path, "wb") as f:
            f.flush()
            os.fsync(f.fileno())
        fsync_dir(self.dir)
        self.entries_since_snapshot = 0

    def close(self) -> None:
        if self._fh is not None:
            self._fh.close()
            self._fh = None

    # -- /status ----------------------------------------------------------- #

    def status(self) -> dict:
        """Bounded control_plane digest for the master's /status."""
        return {
            "epoch": self.epoch,
            "journal_lag": self.entries_since_snapshot,
            "last_replay_s": (round(self.last_replay_s, 6)
                              if self.last_replay_s is not None else None),
            "replayed_entries": self.replayed_entries,
            "open_incidents": len(self.state["open_incidents"]),
        }


def _empty_state() -> dict:
    return {
        "agents": {},          # ip -> {"registered_at": ts}
        "failures": {},        # ip -> [wall ts, ...]
        "causes": {},          # ip -> last cause
        "quarantined": {},     # ip -> entered ts
        "ewma": {},            # mechanism -> seconds
        "open_incidents": {},  # trace_id -> digest
        "job": None,           # default tenant's job args (legacy mirror)
        "jobs": {},            # tenant -> job args dict while running
        "leases": {},          # lease_id -> {tenant, hosts, expires_at}
    }


def _merge_state(loaded: dict) -> dict:
    """A snapshot from an older format is merged over the empty shape so
    missing keys never KeyError the replay path."""
    s = _empty_state()
    for k in s:
        if k in loaded and loaded[k] is not None:
            s[k] = loaded[k]
    # Pre-multi-job snapshots carry only the single "job" slot: lift it
    # into the tenant-keyed map so new readers see one default-tenant job.
    if s["job"] is not None and not s["jobs"]:
        s["jobs"] = {DEFAULT_TENANT: s["job"]}
    return s
