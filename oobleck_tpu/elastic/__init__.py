"""Elastic control plane (L4): master daemon ⇄ per-host agents ⇄ workers.

Capability match for /root/reference/oobleck/elastic/: the master launches
one agent per TPU host, detects host failure via TCP disconnect, and
broadcasts reconfiguration to survivors; agents supervise one worker process
per host (a TPU host owns all its local chips — no per-GPU pinning) and relay
the JAX coordinator address the way the reference relays the rank-0 TCPStore
port (master.py:137-154). Pure-Python networking; training data never crosses
this plane.
"""
