"""Master daemon: single-job cluster manager.

Capability match for the reference master
(/root/reference/oobleck/elastic/master.py:22-274):

  * accepts one job (LAUNCH_JOB) and launches one agent per host — over SSH
    when an ssh client is available, else as local subprocesses (the test
    harness injects a mock launcher, like the reference's mocked asyncssh,
    tests/elastic/test_master.py:46-49);
  * registers agents and serves DistributionInfo;
  * detects host failure by TCP disconnect (master.py:214-231) AND by
    heartbeat deadline — every agent read carries a deadline derived from
    the agent's advertised ping cadence (protocol v2, message.py), so a
    hung-but-connected peer (socket open, no traffic) is evicted in
    bounded time instead of stalling detection forever; either way the
    master broadcasts (RECONFIGURATION, lost_ip) to survivors
    (close_agent, master.py:192-203) and stamps the RECOVERY_DEADLINE
    detect/broadcast marks (utils/recovery.py);
  * relays the JAX coordinator address from the first agent to all agents
    (the reference's rank0-port chain, master.py:137-154);
  * answers PING (the reference defines ping but never schedules it,
    agent.py:54-61 — here the agent actually pings, see agent.py).

Max cluster size mirrors the reference's 32 (master.py:19).
"""

from __future__ import annotations

import asyncio
import logging
import os
import shutil
import subprocess
import sys
import threading
import time
from dataclasses import dataclass, field

from oobleck_tpu.config import OobleckArguments
from oobleck_tpu.elastic import journal as journal_mod
from oobleck_tpu.elastic.message import (
    DEFAULT_PING_INTERVAL,
    EPOCH_KEY,
    JOINED_KEY,
    LEASE_KEY,
    TELEMETRY_KEY,
    TENANT_KEY,
    DistributionInfo,
    RequestType,
    ResponseType,
    read_deadline,
    recv_msg,
    send_response,
)
from oobleck_tpu.obs import fleet as obs_fleet
from oobleck_tpu.obs import spans
from oobleck_tpu.obs import telemetry as obs_telemetry
from oobleck_tpu.policy import PolicyEngine
from oobleck_tpu.policy.engine import DECISION_KEY, MECH_DRAIN, \
    MECH_OBSERVE, MECH_QUARANTINE, MECH_REINSTANTIATE, MECH_REROUTE, \
    MECH_RESTORE
from oobleck_tpu.pool import arbiter as pool_arbiter
from oobleck_tpu.pool.leases import ST_EXPIRED, ST_RETURNED
from oobleck_tpu.pool.tenants import KIND_SERVE, KIND_TRAIN, TenantSpec
from oobleck_tpu.utils import metrics, recovery
from oobleck_tpu.utils.chaos import chaos

MAX_NUM_HOSTS = 32

# Near-simultaneous JOINs (a whole spot batch provisioning at once) are
# folded into ONE grow incident: the first arrival opens this window, and
# everything landing inside it rides the same policy decision + broadcast
# (mirrors the correlated-LOSS batching of _maybe_reconfigure).
ENV_JOIN_WINDOW = "OOBLECK_JOIN_WINDOW"
DEFAULT_JOIN_WINDOW_S = 0.25

# Committed incident reports pushed up from workers, kept for /status.
MAX_INCIDENTS = 16

# Post-restart reconciliation window: a restarted master waits this long
# for masterless agents to REATTACH before journal-vs-reality reconcile —
# every expected host still missing at the close becomes ONE batched loss
# incident through the normal policy chain (the grow-window mirror for
# the restart direction).
ENV_REATTACH_WINDOW = "OOBLECK_REATTACH_WINDOW"
DEFAULT_REATTACH_WINDOW_S = 10.0

logger = logging.getLogger("oobleck.master")


@dataclass
class AgentInfo:
    ip: str
    reader: asyncio.StreamReader
    writer: asyncio.StreamWriter
    clean_exit: bool = False  # JOB_DONE received: departure is not a failure
    protocol: int = 1
    ping_interval: float = DEFAULT_PING_INTERVAL
    read_deadline: float = read_deadline(DEFAULT_PING_INTERVAL)
    # monotonic stamp of the last message on this channel; /status reports
    # heartbeat ages from it.
    last_seen: float = field(default_factory=time.monotonic)


class LocalLauncher:
    """Spawn agents as local subprocesses (single-host / test deployments)."""

    def __init__(self):
        self.procs: list[subprocess.Popen] = []

    async def launch(self, ip: str, master_ip: str, master_port: int,
                     args: OobleckArguments) -> None:
        proc = subprocess.Popen(
            [sys.executable, "-m", "oobleck_tpu.elastic.agent",
             "--master-ip", master_ip, "--master-port", str(master_port),
             "--agent-ip", ip],
        )
        self.procs.append(proc)
        logger.info("launched agent for %s (pid %d)", ip, proc.pid)


class SSHLauncher:
    """Launch agents over ssh (reference run_node_agents, master.py:60-91,
    which uses asyncssh + conda; here: the system ssh client). Each agent's
    combined stdout/stderr streams to a per-host log file under
    {log_dir}/{timestamp}-{model}/{ip}.out (reference master.py:79-91) —
    DEVNULLing them would make remote worker crashes invisible."""

    def __init__(self, username: str | None, node_port: int = 22,
                 log_dir: str | None = None):
        import tempfile

        self.username = username
        self.node_port = node_port
        self.log_dir = log_dir or os.path.join(
            tempfile.gettempdir(), "oobleck_tpu", "logs"
        )
        self._job_dir: str | None = None
        self._launch_counts: dict[str, int] = {}
        if shutil.which("ssh") is None:
            raise RuntimeError("no ssh client available; use LocalLauncher")

    def start_job(self, args: OobleckArguments) -> None:
        """New per-job log directory; the master calls this at LAUNCH_JOB so
        a long-lived daemon never mixes two jobs' logs into one dir."""
        ts = time.strftime("%Y%m%d-%H%M%S")
        self._job_dir = os.path.join(
            self.log_dir, f"{ts}-{args.model.model_name}"
        )
        self._launch_counts = {}
        os.makedirs(self._job_dir, exist_ok=True)

    def _log_path(self, ip: str, args: OobleckArguments) -> str:
        if self._job_dir is None:
            self.start_job(args)
        # Per-launch suffix: repeated launches for one host (the config
        # allows num_agents_per_node in principle) must not interleave into
        # one file.
        k = self._launch_counts.get(ip, 0)
        self._launch_counts[ip] = k + 1
        name = f"{ip}.out" if k == 0 else f"{ip}-{k}.out"
        return os.path.join(self._job_dir, name)

    async def launch(self, ip: str, master_ip: str, master_port: int,
                     args: OobleckArguments) -> None:
        target = f"{self.username}@{ip}" if self.username else ip
        cmd = (
            f"{sys.executable} -m oobleck_tpu.elastic.agent "
            f"--master-ip {master_ip} --master-port {master_port} "
            f"--agent-ip {ip}"
        )
        path = self._log_path(ip, args)
        # open() can block on slow/remote filesystems (the log dir may be
        # NFS); never stall the heartbeat loop for it.
        logf = await asyncio.to_thread(open, path, "ab")
        try:
            proc = await asyncio.create_subprocess_exec(
                "ssh", "-p", str(self.node_port), target, cmd,
                stdout=logf, stderr=asyncio.subprocess.STDOUT,
            )
        finally:
            logf.close()  # the child holds its own descriptor
        logger.info("launched agent on %s (ssh pid %s, log %s)",
                    ip, proc.pid, path)


class OobleckMasterDaemon:
    def __init__(self, port: int = 0, launcher=None):
        self._requested_port = port
        self.port: int | None = None
        self.launcher = launcher
        self.job: OobleckArguments | None = None
        self.agents: dict[str, AgentInfo] = {}
        self.coordinator: str | None = None  # "ip:port" of the JAX coordinator
        self.coordinator_world: int | None = None  # its generation tag
        self._server: asyncio.Server | None = None
        self._pending_ips: list[str] = []
        # Cluster metrics aggregation: latest registry snapshot per
        # (host, role), pushed over METRICS. The threading.Lock (not an
        # asyncio one) is deliberate — the HTTP endpoint reads this map
        # from its own daemon threads.
        self._snap_lock = threading.Lock()
        self._remote_snapshots: dict[tuple[str, str], dict] = {}
        # Recovery lifecycle for /status: detect → broadcast → resolved
        # (first post-broadcast worker snapshot = the pipeline is stepping
        # again).
        self._recoveries: list[dict] = []
        # Mid-training JOINs waiting for the batching window to close; the
        # first arrival schedules the flush task, every arrival inside the
        # window rides the same grow incident.
        self._pending_joins: list[tuple[str, float | None]] = []
        self._join_flush_task: asyncio.Task | None = None
        # Incident forensics reports (obs/incident.py) committed by workers
        # and pushed up piggybacked on METRICS snapshots; bounded ring.
        self._incidents: list[dict] = []
        # Adaptive fault-tolerance policy: scores reroute / reinstantiate /
        # restore per incident from live signals (oobleck_tpu/policy).
        self.policy = PolicyEngine(
            multihost=os.environ.get("OOBLECK_MULTIHOST") == "1")
        # Fleet-health plane (obs/fleet.py): per-host telemetry rows fed
        # by heartbeat digests; a persistently slow-but-alive host raises
        # a SLOWDOWN incident through the same classify -> policy chain
        # failures use.
        self.fleet = obs_fleet.FleetTracker()
        # Shared chip-pool plane (oobleck_tpu/pool): serve<->train chip
        # borrowing through leases, arbitrated by the same cost scorer
        # the recovery planes use. Inert unless OOBLECK_POOL=1 — a
        # single-job cluster keeps its exact pre-pool behavior.
        self._train_tenant = (
            os.environ.get(pool_arbiter.ENV_POOL_TENANT, "").strip()
            or journal_mod.DEFAULT_TENANT)
        self.pool: pool_arbiter.PoolArbiter | None = None
        if pool_arbiter.pool_enabled():
            self.pool = pool_arbiter.PoolArbiter()
            self.pool.tenants.register(
                TenantSpec(name=self._train_tenant, kind=KIND_TRAIN))
        self._lease_sweep_task: asyncio.Task | None = None
        # Durable control-plane journal (OOBLECK_MASTER_STATE_DIR): the
        # master's own survival plane. None = journaling off (the pre-PR
        # in-memory-only behavior); epoch 0 means "no fence" to agents.
        self.journal: journal_mod.MasterJournal | None = None
        self.master_epoch = 0
        # Post-restart reconciliation: agents the replayed journal expects,
        # the set that actually REATTACHed, and the window-close task.
        self._expected_reattach: set[str] = set()
        self._reattached: set[str] = set()
        self._reattached_total = 0
        self._reconcile_task: asyncio.Task | None = None
        self._outage_trace_id: str | None = None
        self.metrics_port: int | None = None
        self._http: metrics.MetricsHTTPServer | None = None
        reg = metrics.registry()
        self._m_agents = reg.gauge(
            "oobleck_master_agents", "Currently registered agents")
        self._m_registrations = reg.counter(
            "oobleck_master_registrations_total", "Agent registrations")
        self._m_reconfigs = reg.counter(
            "oobleck_master_reconfigurations_total",
            "RECONFIGURATION broadcasts sent to survivors")
        self._m_pushes = reg.counter(
            "oobleck_master_metrics_pushes_total",
            "METRICS snapshots received", )
        self._m_grows = reg.counter(
            "oobleck_master_grow_broadcasts_total",
            "GROW broadcasts sent for mid-training JOIN batches")
        self._m_epoch = reg.gauge(
            "oobleck_master_epoch",
            "Monotonic master incarnation epoch (split-brain fence)")
        self._m_reattaches = reg.counter(
            "oobleck_master_reattaches_total",
            "Agents re-attached after a master restart")
        self._m_journal_lag = reg.gauge(
            "oobleck_master_journal_lag_entries",
            "Journal entries appended since the last snapshot compaction")
        self._m_slowdowns = reg.counter(
            "oobleck_master_slowdown_incidents_total",
            "SLOWDOWN incidents raised for gray-failing (alive but "
            "persistently slow) hosts")
        self._m_lease_broadcasts = reg.counter(
            "oobleck_master_lease_broadcasts_total",
            "LEASE_GRANT / LEASE_RECLAIM broadcasts (pool plane)")

    # ------------------------------------------------------------------ #

    async def start(self) -> None:
        metrics.set_role("master")
        self._open_journal()
        self._server = await asyncio.start_server(
            self._on_connected, host="0.0.0.0", port=self._requested_port
        )
        self.port = self._server.sockets[0].getsockname()[1]
        logger.info("master listening on :%d", self.port)
        self._start_metrics_endpoint()
        if self.pool is not None:
            self._lease_sweep_task = asyncio.ensure_future(
                self._lease_sweep_loop())
        if self._expected_reattach:
            # A restarted master with a replayed fleet: give masterless
            # agents one reattach window before journal-vs-reality
            # reconciliation declares the no-shows lost.
            self._reconcile_task = asyncio.ensure_future(
                self._reconcile_after_window())
        kill = chaos().kill_master_after()
        if kill is not None:
            asyncio.ensure_future(self._kill_master_chaos(kill[0]))

    @staticmethod
    async def _kill_master_chaos(after_s: float) -> None:
        """kill_master: SIGKILL this process after `after_s` — no cleanup,
        no dying gasp, exactly the outage the journal's per-entry fsync
        must survive. The flight recorder is dumped first: SIGKILL leaves
        no other trace of the injection in the postmortem artifacts."""
        import signal

        await asyncio.sleep(after_s)
        logger.warning("chaos: master SIGKILLing itself now")
        metrics.flight_recorder().dump("chaos_kill_master")
        logging.shutdown()
        os.kill(os.getpid(), signal.SIGKILL)

    def _open_journal(self) -> None:
        """Boot against the durable journal when configured: replay the
        snapshot + tail, burn a fresh epoch, rehydrate the policy plane's
        adaptive state, and — when the journal shows a job mid-flight —
        arm the reattach/reconcile machinery for the fleet it expects."""
        state_dir = journal_mod.state_dir()
        if not state_dir:
            return
        self.journal = journal_mod.MasterJournal(state_dir)
        self.journal.open()
        self.master_epoch = self.journal.epoch
        self._m_epoch.set(self.master_epoch)
        state = self.journal.state
        restart = bool(state["agents"]) or state["job"] is not None
        self.policy.restore_persisted(state)
        if state["job"] is not None:
            try:
                self.job = OobleckArguments.from_dict(state["job"])
            except Exception as e:  # noqa: BLE001 — a bad journaled job must
                logger.error("journaled job unparseable (%s); dropped", e)
                self.job = None  # not brick the restart
        if self.job is not None:
            self._expected_reattach = set(state["agents"])
        if self.pool is not None and state.get("leases"):
            # Who holds whose chips survives the master: the lease book
            # rehydrates from the replayed EV_LEASE entries and the sweep
            # resumes exactly where the dead incarnation left off.
            self.pool.leases.restore(state["leases"])
            logger.warning("pool: %d active lease(s) restored from journal",
                           len(self.pool.leases.active()))
        if restart:
            # The outage is itself an incident: one trace stitches the
            # restart → replay → reattached → reconciled phase marks (the
            # detect mark belongs to whoever killed us — SIGKILL leaves
            # no dying gasp — so the trace opens at restart).
            self._outage_trace_id = spans.new_trace_id()
            t = time.time()
            spans.span_recorder().record(
                "outage.restart", t, t, trace_id=self._outage_trace_id,
                epoch=self.master_epoch)
            spans.span_recorder().record(
                "outage.replay", t - (self.journal.last_replay_s or 0.0), t,
                trace_id=self._outage_trace_id,
                entries=self.journal.replayed_entries)
            metrics.flight_recorder().record(
                "master_restart", epoch=self.master_epoch,
                trace_id=self._outage_trace_id,
                expected_agents=sorted(self._expected_reattach),
                replayed_entries=self.journal.replayed_entries,
                replay_s=round(self.journal.last_replay_s or 0.0, 6))
            logger.warning(
                "master restarted at epoch %d: %d journal entries replayed, "
                "expecting %d agents to reattach", self.master_epoch,
                self.journal.replayed_entries, len(self._expected_reattach))

    def _journal(self, kind: str, **fields) -> None:
        if self.journal is not None:
            self.journal.append(kind, **fields)
            self._m_journal_lag.set(self.journal.entries_since_snapshot)

    def _start_metrics_endpoint(self) -> None:
        raw = os.environ.get(metrics.ENV_METRICS_PORT, "0")
        try:
            port = int(raw)
        except ValueError:
            logger.warning("malformed %s=%r ignored; using an ephemeral "
                           "port", metrics.ENV_METRICS_PORT, raw)
            port = 0
        if port < 0:  # explicit opt-out
            return
        try:
            self._http = metrics.MetricsHTTPServer(
                self._render_metrics, self._status, port=port).start()
        except OSError as e:
            logger.warning("metrics endpoint unavailable: %s", e)
            return
        self.metrics_port = self._http.port
        logger.info("metrics endpoint on :%d (/metrics, /status)",
                    self.metrics_port)

    async def serve_forever(self) -> None:
        assert self._server is not None
        # NOT `async with self._server`: its __aexit__ awaits wait_closed(),
        # which on Python 3.12 blocks until every connection handler returns —
        # agent loops are intentionally long-lived, so cancellation would hang.
        await self._server.serve_forever()

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
        if self._http is not None:
            self._http.close()
            self._http = None
        if self._reconcile_task is not None:
            self._reconcile_task.cancel()
            self._reconcile_task = None
        if self._lease_sweep_task is not None:
            self._lease_sweep_task.cancel()
            self._lease_sweep_task = None
        if self.journal is not None:
            self.journal.close()

    # ------------------------------------------------------------------ #
    # metrics plane (called from the HTTP server's daemon threads)

    def _render_metrics(self) -> str:
        self._m_agents.set(len(self.agents))
        snaps = [metrics.registry().snapshot()]
        labels = [{"host": "master", "role": "master"}]
        with self._snap_lock:
            remotes = dict(self._remote_snapshots)
        for (host, role), snap in sorted(remotes.items()):
            snaps.append(snap)
            labels.append({"host": host, "role": role})
        return metrics.render_prometheus(snaps, labels)

    def _status(self) -> dict:
        now = time.monotonic()
        agents = [
            {
                "ip": a.ip,
                "protocol": a.protocol,
                "ping_interval_s": a.ping_interval,
                "read_deadline_s": a.read_deadline,
                "heartbeat_age_s": round(now - a.last_seen, 3),
                "clean_exit": a.clean_exit,
            }
            for a in self.agents.values()
        ]
        with self._snap_lock:
            recoveries = [dict(r) for r in self._recoveries]
            # Full reports are heavy; /status carries the forensic digest
            # (phases + totals), the JSON file on the worker has the rest.
            incidents = [
                {k: i.get(k) for k in ("trace_id", "lost_ip", "cause",
                                       "phases", "total_s", "committed_at")}
                for i in self._incidents
            ]
            worker_snaps = {
                host: snap for (host, role), snap
                in self._remote_snapshots.items() if role == "worker"
            }
        # Current pipeline template, as reported by the workers themselves:
        # the info-gauge value is the adoption step, so the highest value
        # across all series (old plans linger in the registry) is current.
        template = None
        best = -1.0
        for snap in worker_snaps.values():
            for m in snap.get("metrics", []):
                if m["name"] == "oobleck_engine_pipeline_template_info":
                    for s in m["series"]:
                        if s.get("value", 0) >= best:
                            best = s.get("value", 0)
                            template = s.get("labels", {})
        # Newest restorable checkpoint step across the cluster (rank 0 owns
        # the commit, so max over workers is the committed truth); -1 until
        # the first durable commit, None when checkpointing is off.
        last_durable = None
        for snap in worker_snaps.values():
            for m in snap.get("metrics", []):
                if m["name"] == "oobleck_ckpt_last_durable_step":
                    for s in m["series"]:
                        v = int(s.get("value", -1))
                        if last_durable is None or v > last_durable:
                            last_durable = v
        # Fleet health: the tracker's per-host z/ratio rows plus the
        # goodput ledger view from the most-advanced worker snapshot and
        # the cluster's best MFU estimate.
        goodput = None
        best_step = -1
        for snap in worker_snaps.values():
            g = snap.get("goodput")
            if isinstance(g, dict) and snap.get("step", 0) >= best_step:
                best_step = snap.get("step", 0)
                goodput = g
        fleet_health = dict(self.fleet.snapshot())
        fleet_health["goodput"] = goodput
        fleet_health["mfu"] = self._worker_gauge_max("oobleck_engine_mfu")
        return {
            "job": self.job.model.model_name if self.job else None,
            "agents": agents,
            "coordinator": self.coordinator,
            "pipeline_template": template,
            "last_durable_step": last_durable,
            "recoveries": recoveries,
            "in_flight_recoveries": [
                r for r in recoveries if r.get("resolved_at") is None
            ],
            "incidents": incidents,
            "fleet_health": fleet_health,
            # Bounded like the incident digest: quarantine set, per-host
            # MTBF estimates, and the last MAX_DECISIONS policy decisions.
            "policy": self.policy.status(),
            "control_plane": self._control_plane_status(),
            # Always present so dashboards need no key probe; the full
            # tenant/lease/decision block only when the plane is on.
            "pool": (self.pool.status() if self.pool is not None
                     else {"enabled": False}),
        }

    def _control_plane_status(self) -> dict:
        """Bounded control-plane block: the master's own survival state —
        epoch, journal lag, replay cost, and how much of the fleet came
        back after the last restart."""
        block: dict = {
            "master_epoch": self.master_epoch,
            "journaling": self.journal is not None,
            "reattached_agents": self._reattached_total,
            "awaiting_reattach": sorted(self._expected_reattach),
        }
        if self.journal is not None:
            j = self.journal.status()
            block["journal_lag"] = j["journal_lag"]
            block["last_replay_s"] = j["last_replay_s"]
            block["replayed_entries"] = j["replayed_entries"]
            block["open_incidents"] = j["open_incidents"]
        return block

    # -- live signals for the policy scorer (worker-pushed metrics) ------ #

    def _worker_series(self, name: str):
        """All series of one metric family across worker snapshots."""
        with self._snap_lock:
            snaps = [snap for (_, role), snap
                     in self._remote_snapshots.items() if role == "worker"]
        for snap in snaps:
            for m in snap.get("metrics", []):
                if m["name"] == name:
                    yield from m["series"]

    def _worker_gauge_max(self, name: str) -> float | None:
        vals = [s.get("value", 0) for s in self._worker_series(name)]
        return max(vals) if vals else None

    def _step_seconds(self) -> float | None:
        """Mean step wall time across the cluster, or None pre-training."""
        total = count = 0.0
        for s in self._worker_series("oobleck_engine_step_seconds"):
            total += s.get("sum", 0.0)
            count += s.get("count", 0)
        return total / count if count else None

    def _staleness_steps(self) -> float | None:
        """current step - last durable checkpoint step, or None when no
        restorable checkpoint exists (restore infeasible)."""
        durable = self._worker_gauge_max("oobleck_ckpt_last_durable_step")
        if durable is None or durable < 0:
            return None
        step = self._worker_gauge_max("oobleck_engine_steps_total")
        return max(float(step) - durable, 0.0) if step is not None else 0.0

    def _projected_retention(self) -> float | None:
        """The degrade plane's replay-projected survivor throughput, as
        published by the workers (planner projection when one exists)."""
        return self._worker_gauge_max("oobleck_degrade_projected_retention")

    def decide_recovery(self, lost_ips: list[str], *,
                        proactive: bool = False):
        """Consult the policy engine with master-side live signals."""
        degrade = os.environ.get("OOBLECK_DEGRADE", "1").lower() not in (
            "0", "false", "no")
        survivors = [ip for ip in self.agents if ip not in lost_ips]
        total = len(survivors) + len(lost_ips)
        return self.policy.decide(
            lost_ips,
            degrade_enabled=degrade,
            reroute_retention=self._projected_retention(),
            survivor_frac=len(survivors) / total if total else 1.0,
            staleness_steps=self._staleness_steps(),
            step_seconds=self._step_seconds(),
            proactive=proactive,
        )

    def decide_grow(self, joined_ips: list[str], *,
                    lifetime_hints: dict[str, float] | None = None):
        """Consult the policy engine's grow direction with master-side
        live signals. `current_hosts` excludes the joiners themselves —
        they are already in self.agents by flush time, but the retention
        math needs the pre-grow fleet size."""
        current = max(len(self.agents) - len(joined_ips), 1)
        return self.policy.decide_grow(
            joined_ips,
            current_hosts=current,
            staleness_steps=self._staleness_steps(),
            step_seconds=self._step_seconds(),
            lifetime_hints=lifetime_hints,
            cause="join",
        )

    def _record_metrics_push(self, msg: dict) -> None:
        ip = msg.get("ip", "?")
        role = msg.get("role", "agent")
        snap = msg.get("snapshot") or {}
        self._m_pushes.inc(role=role)
        incident = msg.get("incident") or snap.get("incident")
        with self._snap_lock:
            self._remote_snapshots[(ip, role)] = snap
            if isinstance(incident, dict):
                # A worker committed incident-<n>.json and piggybacked the
                # report on its metrics push; keep it for /status forensics
                # (dedup by trace_id — periodic pushes may resend it).
                tid = incident.get("trace_id")
                if not any(i.get("trace_id") == tid for i in self._incidents):
                    self._incidents.append(incident)
                    del self._incidents[:-MAX_INCIDENTS]
        resolved: list[str] = []
        with self._snap_lock:
            if role == "worker":
                # A worker shipping fresh metrics after a broadcast means
                # the pipeline is stepping again: close open recoveries.
                for r in self._recoveries:
                    if (r.get("resolved_at") is None
                            and r.get("broadcast_at") is not None):
                        r["resolved_at"] = time.time()
                        if r.get("trace_id"):
                            resolved.append(r["trace_id"])
        for tid in resolved:
            self._journal(journal_mod.EV_INCIDENT_CLOSE, trace_id=tid)
        if resolved:
            # Snapshot the policy EWMAs alongside the close: the adaptive
            # state a restarted master scores its first decisions with.
            self._journal(journal_mod.EV_EWMA,
                          ewma=self.policy.ewma_snapshot())

    # ------------------------------------------------------------------ #

    async def _on_connected(self, reader: asyncio.StreamReader,
                            writer: asyncio.StreamWriter) -> None:
        try:
            # Bounded first read: a connection that registers nothing within
            # a default heartbeat deadline is dead weight (or a socket-
            # holding DoS), not a future agent.
            msg = await recv_msg(reader,
                                 timeout=read_deadline(DEFAULT_PING_INTERVAL))
        except (asyncio.TimeoutError, TimeoutError,
                asyncio.IncompleteReadError, ConnectionError):
            writer.close()
            return
        kind = msg.get("kind")
        if kind == RequestType.LAUNCH_JOB.value:
            await self._handle_launch_job(msg, reader, writer)
        elif kind == RequestType.REGISTER_AGENT.value:
            await self._handle_register_agent(msg, reader, writer)
        elif kind == RequestType.JOIN.value:
            await self._handle_join(msg, reader, writer)
        elif kind == RequestType.REATTACH.value:
            await self._handle_reattach(msg, reader, writer)
        elif kind == RequestType.POOL_BORROW.value:
            await self._handle_pool_borrow(msg, writer)
        else:
            await send_response(writer, ResponseType.FAILURE,
                                {"error": f"unexpected first message {kind}"})
            writer.close()

    async def _handle_launch_job(self, msg, reader, writer) -> None:
        """Reference request_job_handler (master.py:93-135)."""
        if self.job is not None:
            await send_response(writer, ResponseType.FAILURE,
                                {"error": "job already running"})
            return
        try:
            args = OobleckArguments.from_dict(msg["args"])
        except Exception as e:  # noqa: BLE001 — any parse failure becomes FAILURE
            await send_response(writer, ResponseType.FAILURE, {"error": str(e)})
            return
        if len(args.dist.node_ips) > MAX_NUM_HOSTS:
            await send_response(writer, ResponseType.FAILURE,
                                {"error": f"too many hosts (max {MAX_NUM_HOSTS})"})
            return
        if args.dist.num_agents_per_node != 1:
            # The registry is keyed by host IP; multiple agents per host would
            # alias each other (and a TPU host needs exactly one JAX process).
            await send_response(writer, ResponseType.FAILURE,
                                {"error": "num_agents_per_node must be 1"})
            return
        self.job = args
        self._pending_ips = list(args.dist.node_ips)
        # Tenant-keyed: N jobs replay as N jobs (journal.py EV_JOB).
        self._journal(journal_mod.EV_JOB, args=args.to_dict(),
                      tenant=self._train_tenant)
        await send_response(writer, ResponseType.SUCCESS)
        if self.launcher is not None and hasattr(self.launcher, "start_job"):
            self.launcher.start_job(args)
        if self.launcher is not None:
            for ip in args.dist.node_ips:
                for _ in range(args.dist.num_agents_per_node):
                    await self.launcher.launch(
                        ip, args.dist.master_ip, self.port, args
                    )

    async def _handle_register_agent(self, msg, reader, writer) -> None:
        """Reference register_agent_handler (master.py:156-190)."""
        ip = msg.get("ip") or writer.get_extra_info("peername")[0]
        if self.job is None:
            await send_response(writer, ResponseType.FAILURE,
                                {"error": "no job configured"})
            writer.close()
            return
        if self.policy.is_quarantined(ip):
            # Flap quarantine: a host that failed twice inside its MTBF
            # window is refused until it proves stable (hysteresis in
            # policy/health.py). The agent's bounded register backoff
            # turns the refusal into a clean exit, not a retry storm.
            logger.warning("refusing registration from quarantined host %s",
                           ip)
            metrics.flight_recorder().record("register_refused", ip=ip,
                                             reason="quarantined")
            await send_response(writer, ResponseType.FAILURE,
                                {"error": "quarantined"})
            writer.close()
            return
        interval = float(msg.get("ping_interval") or DEFAULT_PING_INTERVAL)
        info = AgentInfo(
            ip, reader, writer,
            protocol=int(msg.get("protocol") or 1),
            ping_interval=interval,
            read_deadline=read_deadline(interval),
        )
        self.agents[ip] = info
        self._m_registrations.inc()
        self._journal(journal_mod.EV_REGISTER, ip=ip,
                      tenant=self._train_tenant)
        # A re-registering host starts a fresh fleet-health life: stale
        # rows (and latched straggler flags) must not follow it in.
        self.fleet.clear(ip)
        if self.policy.health.consume_lift(ip):
            # A host whose flap quarantine lifted (hysteresis satisfied) is
            # re-registering: accepted like any other, but the handshake is
            # a REJOIN and the forensic record must say so — "this host was
            # refused, proved stable, and came back" reads very differently
            # from a first-contact register in a postmortem.
            metrics.flight_recorder().record(
                "quarantine_rejoin", ip=ip, protocol=info.protocol,
                ping_interval=info.ping_interval)
            logger.info("quarantined host %s rejoined after hysteresis "
                        "lift", ip)
        else:
            metrics.flight_recorder().record(
                "register", ip=ip, protocol=info.protocol,
                ping_interval=info.ping_interval)
        logger.info(
            "agent %s registered (protocol v%d, ping %.1fs, read deadline "
            "%.1fs)", ip, info.protocol, info.ping_interval,
            info.read_deadline,
        )
        await send_response(writer, ResponseType.SUCCESS,
                            {"args": self.job.to_dict()})
        if self.coordinator is not None:
            # Late registrant: replay the coordinator announcement it missed.
            await send_response(writer, ResponseType.FORWARD_COORDINATOR,
                                self._coordinator_payload())
        # Keep the channel open: this connection is the liveness signal.
        try:
            await self._agent_loop(info)
        finally:
            # Identity guard: an agent that re-dialed (register retry)
            # replaces its registry entry; when the OLD connection's loop
            # unwinds it must not evict the NEW live registration.
            if self.agents.get(ip) is info:
                await self._close_agent(ip)
            else:
                info.writer.close()

    async def _handle_join(self, msg, reader, writer) -> None:
        """Mid-training JOIN: a freshly provisioned host volunteering
        capacity to a running job. Distinct from initial bring-up (the
        host was never in node_ips) and from a quarantine-lifted host
        re-registering (that one replays REGISTER_AGENT and is tagged
        quarantine_rejoin). The handshake mirrors register — SUCCESS with
        job args, coordinator replay, long-lived liveness channel — but
        instead of filling a known slot it opens (or rides) a batched
        GROW incident."""
        ip = msg.get("ip") or writer.get_extra_info("peername")[0]
        if self.job is None:
            await send_response(writer, ResponseType.FAILURE,
                                {"error": "no job configured"})
            writer.close()
            return
        if self.policy.is_quarantined(ip):
            # A flapping host does not get to grow the cluster either; the
            # same hysteresis that gates re-registration gates JOIN.
            logger.warning("refusing JOIN from quarantined host %s", ip)
            metrics.flight_recorder().record("join_refused", ip=ip,
                                             reason="quarantined")
            await send_response(writer, ResponseType.FAILURE,
                                {"error": "quarantined"})
            writer.close()
            return
        if ip in self.agents or len(self.agents) >= MAX_NUM_HOSTS:
            reason = "already registered" if ip in self.agents \
                else f"cluster full (max {MAX_NUM_HOSTS})"
            metrics.flight_recorder().record("join_refused", ip=ip,
                                             reason=reason)
            await send_response(writer, ResponseType.FAILURE,
                                {"error": reason})
            writer.close()
            return
        interval = float(msg.get("ping_interval") or DEFAULT_PING_INTERVAL)
        info = AgentInfo(
            ip, reader, writer,
            protocol=int(msg.get("protocol") or 1),
            ping_interval=interval,
            read_deadline=read_deadline(interval),
        )
        self.agents[ip] = info
        self._m_registrations.inc()
        self._journal(journal_mod.EV_REGISTER, ip=ip,
                      tenant=self._train_tenant)
        self.fleet.clear(ip)
        # Expected-lifetime hint for the policy's amortization horizon: the
        # joiner may advertise one (spot instances know their own market),
        # else a chaos spot_lifetime directive supplies it for drills.
        hint: float | None = None
        raw_hint = msg.get("spot_lifetime_s")
        if raw_hint is not None:
            try:
                hint = float(raw_hint) or None
            except (TypeError, ValueError):
                hint = None
        if hint is None:
            hint = chaos().spot_lifetime(ip)
        metrics.flight_recorder().record(
            "join", ip=ip, protocol=info.protocol,
            ping_interval=info.ping_interval, spot_lifetime_s=hint)
        logger.info("host %s JOINed mid-training (protocol v%d, lifetime "
                    "hint %s)", ip, info.protocol, hint)
        await send_response(writer, ResponseType.SUCCESS,
                            {"args": self.job.to_dict()})
        if self.coordinator is not None:
            await send_response(writer, ResponseType.FORWARD_COORDINATOR,
                                self._coordinator_payload())
        self._pending_joins.append((ip, hint))
        if self._join_flush_task is None or self._join_flush_task.done():
            self._join_flush_task = asyncio.ensure_future(self._flush_joins())
        try:
            await self._agent_loop(info)
        finally:
            if self.agents.get(ip) is info:
                await self._close_agent(ip)
            else:
                info.writer.close()

    def _join_window_s(self) -> float:
        raw = os.environ.get(ENV_JOIN_WINDOW, "")
        try:
            return float(raw) if raw else DEFAULT_JOIN_WINDOW_S
        except ValueError:
            return DEFAULT_JOIN_WINDOW_S

    async def _flush_joins(self) -> None:
        """Close the batching window: every JOIN that landed inside it
        becomes ONE grow incident — one trace, one policy decision, one
        GROW broadcast (the grow-direction mirror of correlated-loss
        batching in the engine's _maybe_reconfigure)."""
        await asyncio.sleep(self._join_window_s())
        batch, self._pending_joins = self._pending_joins, []
        # Keep only joiners still registered: one that dialed in and died
        # inside the window is already handled by its own loss path.
        batch = [(ip, h) for ip, h in batch if ip in self.agents]
        if not batch:
            return
        joined = [ip for ip, _ in batch]
        hints = {ip: h for ip, h in batch if h is not None}
        trace_id = spans.new_trace_id()
        detected_at = time.time()
        with self._snap_lock:
            self._recoveries.append({
                "lost_ip": "", "joined_ips": list(joined), "cause": "join",
                "trace_id": trace_id, "detected_at": detected_at,
                "broadcast_at": None, "resolved_at": None,
            })
        spans.span_recorder().record(
            "incident.detect", detected_at, detected_at, trace_id=trace_id,
            joined_ips=",".join(joined), cause="join")
        fr = metrics.flight_recorder()
        fr.record("join_detected", joined_ips=",".join(joined),
                  trace_id=trace_id)
        fr.dump(f"join_detected:{'+'.join(joined)}")
        decision = self.decide_grow(joined, lifetime_hints=hints)
        await self._broadcast_grow(joined, decision,
                                   include=list(self.agents.values()))

    # -- shared chip pool (oobleck_tpu/pool) --------------------------- #

    async def _handle_pool_borrow(self, msg, writer) -> None:
        """POOL_BORROW: a serve replica group under traffic pressure asks
        to borrow training chips — or returns a lease it holds (the one
        verb covers both directions; the ``release`` key picks the
        reclaim path). The request is an INCIDENT: it flows through the
        arbiter's classify -> score -> broadcast chain exactly like a
        host loss, and a granted borrow reuses the proven proactive-drain
        path — the victim's worker flushes and exits cleanly (JOB_DONE,
        zero respawns) while survivors reroute in place."""
        if self.pool is None:
            await send_response(
                writer, ResponseType.FAILURE,
                {"error": "pool plane disabled "
                          f"(set {pool_arbiter.ENV_POOL}=1)"})
            writer.close()
            return
        tenant = str(msg.get(TENANT_KEY) or "serve")
        self.pool.tenants.register(TenantSpec(
            name=tenant, kind=KIND_SERVE, slo=dict(msg.get("slo") or {})))
        # Pressure is priced SERVE-SIDE (pool/pressure.py) and rides the
        # request: the master never needs serve-plane scrape access.
        pressure = msg.get("pressure") or {}
        try:
            slo_debt = max(float(pressure.get("slo_debt_s") or 0.0), 0.0)
        except (TypeError, ValueError):
            slo_debt = 0.0
        try:
            if msg.get("release"):
                await self._pool_release(msg, writer, slo_debt)
            else:
                await self._pool_grant(msg, writer, tenant, slo_debt)
        finally:
            writer.close()

    async def _pool_grant(self, msg, writer, tenant: str,
                          slo_debt: float) -> None:
        if self.job is None:
            await send_response(writer, ResponseType.FAILURE,
                                {"error": "no job configured"})
            return
        chips = max(int(msg.get("chips") or 1), 1)
        leased = self.pool.leases.leased_hosts()
        train_hosts = len([ip for ip in self.agents if ip not in leased])
        ttl: float | None = None
        if msg.get("lease_ttl_s") is not None:
            try:
                ttl = float(msg["lease_ttl_s"]) or None
            except (TypeError, ValueError):
                ttl = None
        # The live master keeps no standing spare pool — every registered
        # host is training — so drain-vs-deny is the live decision;
        # deployments with spares score them in the sim.
        decision = self.pool.decide_borrow(
            tenant, chips,
            train_hosts=train_hosts,
            spare_hosts=0,
            slo_debt_s=slo_debt,
            lease_ttl_s=ttl,
            lender=self._train_tenant,
            cause=str(msg.get("cause") or "pressure"),
        )
        if decision.mechanism != pool_arbiter.MECH_BORROW_DRAIN:
            # deny, or a forced spare arm that is infeasible live.
            await send_response(writer, ResponseType.FAILURE, {
                "error": f"borrow denied ({decision.reason})",
                DECISION_KEY: decision.as_payload()})
            return
        victims = self._pick_lease_hosts(chips)
        if len(victims) < chips:
            await send_response(writer, ResponseType.FAILURE, {
                "error": f"not enough leasable hosts "
                         f"({len(victims)}/{chips})",
                DECISION_KEY: decision.as_payload()})
            return
        ttl = ttl if ttl is not None else self.pool.lease_ttl_s
        lease = self.pool.leases.grant(
            tenant, victims, ttl, lender=self._train_tenant,
            trace_id=decision.trace_id or "")
        decision.hosts = list(victims)
        decision.lease_id = lease.lease_id
        # WAL before the fleet learns anything: a master that dies past
        # this line restarts knowing who holds whose chips.
        self._journal(journal_mod.EV_LEASE, lease_id=lease.lease_id,
                      state="active", tenant=tenant,
                      lender=self._train_tenant, hosts=list(victims),
                      expires_at=lease.expires_at)
        self._journal(journal_mod.EV_INCIDENT_OPEN,
                      trace_id=decision.trace_id,
                      lost_ip=",".join(victims), cause="pool_borrow")
        with self._snap_lock:
            self._recoveries.append({
                "lost_ip": ",".join(victims), "cause": "pool_borrow",
                "trace_id": decision.trace_id,
                "detected_at": decision.decided_at,
                "broadcast_at": None, "resolved_at": None,
            })
        fr = metrics.flight_recorder()
        fr.record("lease_granted", lease_id=lease.lease_id, tenant=tenant,
                  hosts=",".join(victims), ttl_s=ttl,
                  trace_id=decision.trace_id)
        fr.dump(f"lease_granted:{lease.lease_id}")
        # Cross-tenant attribution: the LENDER pays the projected
        # degraded-training seconds, charged under the arbiter's
        # incident trace so the incident file can total the bill.
        self.pool.tenants.attribute(
            decision.trace_id or "",
            {self._train_tenant: decision.projected_cost_s or 0.0},
            cause="pool_borrow")
        for ip in victims:
            victim = self.agents.get(ip)
            if victim is not None:
                # The drained worker's departure is a clean JOB_DONE
                # exit, not a second incident.
                victim.clean_exit = True
            await self._broadcast_lease_grant(ip, lease, decision)
            # Its telemetry row describes a training life that just
            # ended; returning via JOIN starts a fresh one.
            self.fleet.clear(ip)
        await send_response(writer, ResponseType.SUCCESS,
                            {LEASE_KEY: lease.as_record(),
                             DECISION_KEY: decision.as_payload()})

    async def _pool_release(self, msg, writer, slo_debt: float) -> None:
        """Early return: the borrower's peak passed. The arbiter still
        scores hold-vs-reclaim (a forced ``hold`` baseline extends the
        lease instead), and a reclaim flows the hosts back through the
        grow path."""
        lease_id = str(msg.get("release"))
        lease = self.pool.leases.get(lease_id)
        if lease is None:
            await send_response(writer, ResponseType.FAILURE,
                                {"error": f"unknown lease {lease_id}"})
            return
        leased = self.pool.leases.leased_hosts()
        train_hosts = len([ip for ip in self.agents if ip not in leased])
        decision = self.pool.decide_reclaim(
            lease, train_hosts=train_hosts, slo_debt_s=slo_debt,
            cause="release")
        if decision.mechanism == pool_arbiter.MECH_HOLD:
            extended = self.pool.leases.extend(lease_id,
                                               self.pool.lease_ttl_s)
            self._journal(journal_mod.EV_LEASE, lease_id=lease_id,
                          state="active", tenant=lease.tenant,
                          lender=lease.lender, hosts=list(lease.hosts),
                          expires_at=extended.expires_at)
            await send_response(writer, ResponseType.SUCCESS,
                                {LEASE_KEY: extended.as_record(),
                                 DECISION_KEY: decision.as_payload()})
            return
        ended = self.pool.leases.end(lease_id, ST_RETURNED)
        self._journal(journal_mod.EV_LEASE, lease_id=lease_id,
                      state=ST_RETURNED, tenant=ended.tenant)
        # Cross-tenant bill under ONE trace: the borrower pays whatever
        # pressure it still carries (re-exposure), the lender pays the
        # projected grow-absorption cost of taking the chips back.
        self.pool.tenants.attribute(
            decision.trace_id or "",
            {ended.tenant: slo_debt,
             ended.lender: decision.projected_cost_s or 0.0},
            cause="pool_release")
        metrics.flight_recorder().record(
            "lease_released", lease_id=lease_id, tenant=ended.tenant,
            hosts=",".join(ended.hosts), trace_id=decision.trace_id)
        await self._broadcast_lease_reclaim(ended, decision)
        await send_response(writer, ResponseType.SUCCESS,
                            {LEASE_KEY: ended.as_record(),
                             DECISION_KEY: decision.as_payload()})

    def _pick_lease_hosts(self, chips: int) -> list[str]:
        """Lease victims: most recently registered first (least pipeline
        seniority), never the coordinator host, never a host already out
        on a lease."""
        coord_ip = (self.coordinator or "").rsplit(":", 1)[0]
        leased = self.pool.leases.leased_hosts()
        return [ip for ip in reversed(list(self.agents))
                if ip not in leased and ip != coord_ip][:chips]

    async def _lease_sweep_loop(self) -> None:
        """Lease expiry is an incident, not a timer: every sweep feeds
        due leases to the arbiter, which scores hold-vs-reclaim with the
        same cost model. Pressure only ever rides POOL_BORROW requests,
        so no renewal arriving before expiry IS the off-peak signal: a
        due lease carries zero debt and its chips flow back through the
        grow path."""
        period = pool_arbiter.sweep_period_s()
        while True:
            await asyncio.sleep(period)
            for lease in self.pool.leases.due():
                leased = self.pool.leases.leased_hosts()
                train_hosts = len(
                    [ip for ip in self.agents if ip not in leased])
                decision = self.pool.decide_reclaim(
                    lease, train_hosts=train_hosts, cause="expiry")
                if decision.mechanism == pool_arbiter.MECH_HOLD:
                    # Unreachable under adaptive scoring (an expired
                    # lease makes hold infeasible) but a future forced
                    # baseline must extend, not leak the lease.
                    self.pool.leases.extend(lease.lease_id,
                                            self.pool.lease_ttl_s)
                    continue
                ended = self.pool.leases.end(lease.lease_id, ST_EXPIRED)
                if ended is None:
                    continue
                self._journal(journal_mod.EV_LEASE,
                              lease_id=ended.lease_id, state=ST_EXPIRED,
                              tenant=ended.tenant)
                self.pool.tenants.attribute(
                    decision.trace_id or "",
                    {ended.lender: decision.projected_cost_s or 0.0},
                    cause="pool_expiry")
                await self._broadcast_lease_reclaim(ended, decision)

    async def _handle_reattach(self, msg, reader, writer) -> None:
        """Post-outage re-attachment: an agent that rode out a master
        outage in masterless mode re-dials the restarted master. Its
        worker is ALIVE and mid-training — nothing is launched, nothing
        respawns; the handshake only restores the liveness channel,
        replays the agent's buffered masterless-era observations, and
        marks the host present for the reconcile window. Quarantine does
        NOT gate reattach: the host never left the job, and evicting a
        healthy running worker over pre-outage flap history would turn
        the master's own outage into a training incident."""
        ip = msg.get("ip") or writer.get_extra_info("peername")[0]
        if self.job is None:
            await send_response(writer, ResponseType.FAILURE,
                                {"error": "no job configured"})
            writer.close()
            return
        last_epoch = int(msg.get("last_epoch") or 0)
        if self.master_epoch and last_epoch > self.master_epoch:
            # The agent has applied verbs from a HIGHER epoch than ours:
            # we are the zombie (resurrected from an older journal or a
            # partitioned copy). Refuse — the fence cuts both ways.
            logger.error(
                "agent %s reports epoch %d > ours %d; this master is "
                "stale and must not drive the fleet", ip, last_epoch,
                self.master_epoch)
            metrics.flight_recorder().record(
                "stale_master_refused", ip=ip, agent_epoch=last_epoch,
                master_epoch=self.master_epoch)
            await send_response(writer, ResponseType.FAILURE,
                                {"error": "stale master epoch"})
            writer.close()
            return
        interval = float(msg.get("ping_interval") or DEFAULT_PING_INTERVAL)
        info = AgentInfo(
            ip, reader, writer,
            protocol=int(msg.get("protocol") or 1),
            ping_interval=interval,
            read_deadline=read_deadline(interval),
        )
        old = self.agents.get(ip)
        if old is not None:
            old.writer.close()  # superseded pre-outage connection
        self.agents[ip] = info
        self._m_reattaches.inc()
        self._reattached.add(ip)
        self._reattached_total += 1
        # Tenant-stamped like every registration: the reconciled fleet
        # must replay into the same tenant the job entry is keyed by.
        self._journal(journal_mod.EV_REGISTER, ip=ip,
                      tenant=self._train_tenant)
        worker_alive = bool(msg.get("worker_alive", True))
        metrics.flight_recorder().record(
            "reattach", ip=ip, last_epoch=last_epoch,
            epoch=self.master_epoch, worker_alive=worker_alive,
            buffered=len(msg.get("buffered") or ()))
        if self._outage_trace_id is not None:
            t = time.time()
            spans.span_recorder().record(
                "outage.reattached", t, t, trace_id=self._outage_trace_id,
                ip=ip, worker_alive=worker_alive)
        logger.info("agent %s reattached (last_epoch=%d, worker_alive=%s)",
                    ip, last_epoch, worker_alive)
        self._replay_buffered(ip, msg.get("buffered"))
        await send_response(
            writer, ResponseType.SUCCESS,
            {"args": self.job.to_dict(), EPOCH_KEY: self.master_epoch})
        if self.coordinator is not None:
            await send_response(writer, ResponseType.FORWARD_COORDINATOR,
                                self._coordinator_payload())
        try:
            await self._agent_loop(info)
        finally:
            if self.agents.get(ip) is info:
                await self._close_agent(ip)
            else:
                info.writer.close()

    def _replay_buffered(self, ip: str, buffered) -> None:
        """Fold an agent's masterless-era queue back into the planes that
        missed it: worker-observed failures feed the MTBF/quarantine
        estimator (and the journal), committed incident reports land in
        the /status forensics ring. Bounded — the agent's queue already
        is, but a hostile payload must not be."""
        if not isinstance(buffered, list):
            return
        for ev in buffered[:64]:
            if not isinstance(ev, dict):
                continue
            if ev.get("kind") == "failure" and ev.get("ip"):
                cause = str(ev.get("cause") or "masterless")
                self.policy.observe_failure(str(ev["ip"]), cause)
                self._journal(journal_mod.EV_FAILURE, ip=str(ev["ip"]),
                              cause=cause)
                metrics.flight_recorder().record(
                    "masterless_replay", ip=str(ev["ip"]), cause=cause,
                    via=ip)
            elif ev.get("kind") == "incident" \
                    and isinstance(ev.get("report"), dict):
                rep = ev["report"]
                tid = rep.get("trace_id")
                with self._snap_lock:
                    if not any(i.get("trace_id") == tid
                               for i in self._incidents):
                        self._incidents.append(rep)
                        del self._incidents[:-MAX_INCIDENTS]

    def _reattach_window_s(self) -> float:
        raw = os.environ.get(ENV_REATTACH_WINDOW, "")
        try:
            return float(raw) if raw else DEFAULT_REATTACH_WINDOW_S
        except ValueError:
            return DEFAULT_REATTACH_WINDOW_S

    async def _reconcile_after_window(self) -> None:
        """Close the post-restart reconciliation: journal-vs-reality.
        Every host the replayed journal expected that neither REATTACHed
        nor freshly registered inside the window died DURING the outage —
        all of them become ONE batched loss incident (one trace, one
        policy decision) through the normal recovery chain."""
        await asyncio.sleep(self._reattach_window_s())
        missing = sorted(ip for ip in self._expected_reattach
                         if ip not in self.agents)
        self._expected_reattach = set()
        fr = metrics.flight_recorder()
        fr.record("reattach_reconciled", epoch=self.master_epoch,
                  reattached=sorted(self._reattached), missing=missing)
        if self._outage_trace_id is not None:
            t = time.time()
            spans.span_recorder().record(
                "outage.reconciled", t, t, trace_id=self._outage_trace_id,
                reattached=len(self._reattached),
                missing=",".join(missing))
        if not missing:
            logger.info("reconciled after restart: all %d agents "
                        "reattached", len(self._reattached))
            return
        logger.warning("reconciled after restart: hosts %s died during "
                       "the outage", missing)
        trace_id = spans.new_trace_id()
        detected_at = time.time()
        for ip in missing:
            self.policy.observe_failure(ip, "master_outage")
            self._journal(journal_mod.EV_FAILURE, ip=ip,
                          cause="master_outage")
            self._journal(journal_mod.EV_DEPART, ip=ip)
            with self._snap_lock:
                self._recoveries.append({
                    "lost_ip": ip, "cause": "master_outage",
                    "trace_id": trace_id, "detected_at": detected_at,
                    "broadcast_at": None, "resolved_at": None,
                })
        self._journal(journal_mod.EV_INCIDENT_OPEN, trace_id=trace_id,
                      lost_ip=",".join(missing), cause="master_outage")
        spans.span_recorder().record(
            "incident.detect", detected_at, detected_at, trace_id=trace_id,
            lost_ip=",".join(missing), cause="master_outage")
        fr.record("detect", ip=",".join(missing), cause="master_outage",
                  trace_id=trace_id)
        fr.dump(f"failure_detected:{'+'.join(missing)}")
        recovery.mark(recovery.DETECT, lost_ip=",".join(missing),
                      cause="master_outage")
        # ONE policy decision for the correlated batch; the per-ip
        # broadcasts share it (agents prune membership one ip at a time).
        decision = self.decide_recovery(missing)
        for ip in missing:
            await self._broadcast_recovery(
                ip, decision, include=list(self.agents.values()))

    def _coordinator_payload(self) -> dict:
        """Coordinator relay payload; the generation tag is included only
        when the announcer supplied one (absent = legacy untagged trust)."""
        payload = {"address": self.coordinator}
        if self.coordinator_world is not None:
            payload["world"] = self.coordinator_world
        return payload

    async def _agent_loop(self, agent: AgentInfo) -> None:
        """Serve requests from one agent until it disconnects OR misses its
        heartbeat deadline (reference agent_handler, master.py:214-231 —
        which reads with timeout=None and therefore never detects a hung
        peer; here every read is bounded by the agent's own cadence)."""
        while True:
            try:
                msg = await recv_msg(agent.reader,
                                     timeout=agent.read_deadline)
            except (asyncio.TimeoutError, TimeoutError):
                if self._is_failure(agent):
                    logger.warning(
                        "agent %s sent nothing for %.1fs (ping interval "
                        "%.1fs); evicting hung peer", agent.ip,
                        agent.read_deadline, agent.ping_interval,
                    )
                    self._on_failure_detected(agent.ip, "heartbeat_deadline")
                    recovery.mark(recovery.DETECT, lost_ip=agent.ip,
                                  cause="heartbeat_deadline",
                                  deadline=agent.read_deadline)
                return
            except (asyncio.IncompleteReadError, ConnectionError):
                if self._is_failure(agent):
                    logger.warning("agent %s disconnected", agent.ip)
                    self._on_failure_detected(agent.ip, "disconnect")
                    recovery.mark(recovery.DETECT, lost_ip=agent.ip,
                                  cause="disconnect")
                return
            agent.last_seen = time.monotonic()
            kind = msg.get("kind")
            if kind == RequestType.PING.value:
                metrics.flight_recorder().record("heartbeat", ip=agent.ip)
                d = msg.get(TELEMETRY_KEY)
                if obs_telemetry.digest_ok(d):
                    # Piggybacked fleet-health digest (legacy agents send
                    # none — they simply contribute no row). The epoch
                    # stamp fences out samples describing a dead master
                    # incarnation's steps.
                    self.fleet.ingest(
                        agent.ip, d, epoch=d.get("epoch"),
                        min_epoch=self.master_epoch or None)
                    slow_ip = self.fleet.consume_straggler()
                    if slow_ip is not None:
                        await self._on_slowdown_detected(slow_ip)
                await send_response(agent.writer, ResponseType.PONG)
            elif kind == RequestType.METRICS.value:
                # Fire-and-forget: no response, never back-pressures pings.
                self._record_metrics_push(msg)
            elif kind == RequestType.GET_DIST_INFO.value:
                info = DistributionInfo(
                    agent_ips=list(self.agents.keys()),
                    world_size=len(self.agents) * (
                        self.job.dist.num_workers if self.job else 1
                    ),
                )
                await send_response(agent.writer, ResponseType.SUCCESS,
                                    {"dist_info": info.to_dict()})
            elif kind == RequestType.JOB_DONE.value:
                logger.info("agent %s reports training complete", agent.ip)
                agent.clean_exit = True
            elif kind == RequestType.PREEMPTION_NOTICE.value:
                await self._handle_preemption(agent, msg)
            elif kind == RequestType.FORWARD_COORDINATOR.value:
                # First agent's worker announces the JAX coordinator address;
                # relay to everyone (reference forward_rank0_port_handler,
                # master.py:137-154). The `world` generation tag rides along
                # so respawned workers can reject stale pre-failure
                # announcements (worker.coordinator_address_if_current).
                self.coordinator = msg["address"]
                self.coordinator_world = msg.get("world")
                for other in list(self.agents.values()):
                    await send_response(
                        other.writer, ResponseType.FORWARD_COORDINATOR,
                        self._coordinator_payload(),
                    )
            else:
                await send_response(agent.writer, ResponseType.FAILURE,
                                    {"error": f"unknown request {kind}"})

    def _is_failure(self, agent: AgentInfo) -> bool:
        """A read-loop exit counts as a host failure (DETECT mark +
        eviction warning) only when the connection still represents a live
        registration: not after JOB_DONE (completion is not a failure) and
        not when a re-registration already superseded this connection."""
        return not agent.clean_exit and self.agents.get(agent.ip) is agent

    def _on_failure_detected(self, lost_ip: str, cause: str) -> None:
        """Flight-record the detection, open a /status recovery entry, and
        dump the ring — this is the postmortem moment. Mints the incident's
        trace_id: every span and verb in this recovery, in every process,
        stitches onto it."""
        # Feed the online MTBF/flap estimator — the failure log IS the
        # policy plane's churn signal.
        self.policy.observe_failure(lost_ip, cause)
        # Its fleet-health row describes a host that no longer exists.
        self.fleet.clear(lost_ip)
        self._journal(journal_mod.EV_FAILURE, ip=lost_ip, cause=cause)
        if self.policy.is_quarantined(lost_ip):
            self._journal(journal_mod.EV_QUARANTINE, ip=lost_ip,
                          entered=True)
        trace_id = spans.new_trace_id()
        self._journal(journal_mod.EV_INCIDENT_OPEN, trace_id=trace_id,
                      lost_ip=lost_ip, cause=cause)
        with self._snap_lock:
            self._recoveries.append({
                "lost_ip": lost_ip, "cause": cause, "trace_id": trace_id,
                "detected_at": time.time(), "broadcast_at": None,
                "resolved_at": None,
            })
        t = time.time()
        spans.span_recorder().record(
            "incident.detect", t, t, trace_id=trace_id,
            lost_ip=lost_ip, cause=cause)
        fr = metrics.flight_recorder()
        fr.record("detect", ip=lost_ip, cause=cause, trace_id=trace_id)
        fr.dump(f"failure_detected:{lost_ip}")

    async def _on_slowdown_detected(self, ip: str) -> None:
        """Gray failure: the fleet tracker flagged `ip` as alive but
        persistently slow. Open a SLOWDOWN incident through the same
        classify -> policy chain real failures use — the host is NOT dead,
        so there is no observe_failure/EV_FAILURE, but the incident gets a
        trace_id, a /status recovery entry, and a scored decision. An
        active arm (drain / quarantine) reuses the preemption machinery:
        broadcast to everyone INCLUDING the victim, whose worker flushes a
        checkpoint and exits cleanly (JOB_DONE, zero respawns)."""
        self._m_slowdowns.inc()
        ratio = self.fleet.ratio(ip) or self.fleet.ratio_threshold
        trace_id = spans.new_trace_id()
        self._journal(journal_mod.EV_INCIDENT_OPEN, trace_id=trace_id,
                      lost_ip=ip, cause="slowdown")
        detected_at = time.time()
        entry = {
            "lost_ip": ip, "cause": "slowdown", "trace_id": trace_id,
            "detected_at": detected_at, "broadcast_at": None,
            "resolved_at": None, "slowdown_ratio": ratio,
        }
        with self._snap_lock:
            self._recoveries.append(entry)
        spans.span_recorder().record(
            "incident.detect", detected_at, detected_at, trace_id=trace_id,
            lost_ip=ip, cause="slowdown", ratio=ratio)
        fr = metrics.flight_recorder()
        fr.record("slowdown_detected", ip=ip, ratio=ratio,
                  trace_id=trace_id)
        fr.dump(f"slowdown_detected:{ip}")
        n = len(self.agents)
        decision = self.policy.decide_slowdown(
            ip, slowdown_ratio=ratio,
            survivor_frac=(n - 1) / n if n else 1.0)
        logger.warning(
            "slowdown incident for %s (ratio %.2f): %s (%s)", ip, ratio,
            decision.mechanism, decision.reason)
        if decision.mechanism == MECH_OBSERVE:
            # Passive arm: keep the host, keep watching. The incident
            # closes immediately — nothing was broadcast, so the usual
            # first-worker-snapshot close would never fire.
            with self._snap_lock:
                entry["mechanism"] = MECH_OBSERVE
                entry["resolved_at"] = detected_at
            self._journal(journal_mod.EV_INCIDENT_CLOSE, trace_id=trace_id)
            return
        victim = self.agents.get(ip)
        if victim is not None:
            # The drained worker's departure is a clean JOB_DONE exit,
            # not a second incident.
            victim.clean_exit = True
        await self._broadcast_recovery(ip, decision,
                                       include=list(self.agents.values()))
        # The drained host's telemetry row describes a life that just
        # ended; its next registration starts a fresh one.
        self.fleet.clear(ip)

    async def _handle_preemption(self, agent: AgentInfo, msg: dict) -> None:
        """Spot-preemption advance notice: the host will die in ~deadline_s.
        React BEFORE the corpse appears — policy decision now (proactive),
        recovery broadcast to everyone INCLUDING the victim, whose agent
        drains its worker (checkpoint flush) inside the warning window.
        The victim's later disconnect is then a clean exit, not a second
        incident."""
        ip = msg.get("ip") or agent.ip
        deadline_s = float(msg.get("deadline_s") or 0.0)
        logger.warning("preemption notice from %s: host dies in ~%.1fs",
                       ip, deadline_s)
        metrics.flight_recorder().record(
            "preemption_notice", ip=ip, deadline_s=deadline_s)
        self._on_failure_detected(ip, "preemption_notice")
        decision = self.decide_recovery([ip], proactive=True)
        victim = self.agents.get(ip)
        if victim is not None:
            # Its read-loop exit (the host dying) must not re-broadcast.
            victim.clean_exit = True
        await self._broadcast_recovery(ip, decision,
                                       include=list(self.agents.values()))

    async def _close_agent(self, ip: str) -> None:
        """Reference close_agent (master.py:192-203): drop the agent and
        broadcast the loss to survivors — unless the agent announced a clean
        JOB_DONE departure (completion is not a failure)."""
        agent = self.agents.pop(ip, None)
        if agent is not None:
            agent.writer.close()
            self._journal(journal_mod.EV_DEPART, ip=ip)
        if agent is not None and agent.clean_exit:
            if not self.agents and not (
                    self.pool is not None and self.pool.leases.active()):
                # The last agent's clean exit closes the job in the
                # journal: a later master restart must not wait for a
                # completed fleet to reattach. A lease-drained fleet is
                # NOT a completed job — chips out on loan come back.
                self._journal(journal_mod.EV_JOB_DONE,
                              tenant=self._train_tenant)
            return
        # Adaptive policy (oobleck_tpu/policy): score reroute /
        # reinstantiate / restore from live signals and broadcast the
        # cheapest feasible verb. OOBLECK_DEGRADE=0 stays a hard
        # feasibility gate on rerouting; OOBLECK_POLICY forces a fixed arm.
        decision = self.decide_recovery([ip])
        await self._broadcast_recovery(ip, decision,
                                       include=list(self.agents.values()))

    def _verb_for(self, mechanism: str) -> ResponseType:
        return {
            MECH_REROUTE: ResponseType.DEGRADE,
            MECH_REINSTANTIATE: ResponseType.RECONFIGURATION,
            MECH_RESTORE: ResponseType.RESTORE,
            # Slowdown arms ride the DEGRADE verb: survivors take the
            # in-place reroute path, the victim (included in the
            # broadcast, preemption-style) drains and exits cleanly.
            MECH_DRAIN: ResponseType.DEGRADE,
            MECH_QUARANTINE: ResponseType.DEGRADE,
        }[mechanism]

    async def _broadcast_recovery(self, ip: str, decision,
                                  include: list[AgentInfo]) -> None:
        """Broadcast the decided recovery verb for the loss of `ip` with
        the policy decision attached. The wire trace and flight recorder
        must show which recovery the master ASKED for (and why), not just
        which one the engine took."""
        verb = self._verb_for(decision.mechanism)
        # Trace context rides the verb (one extra JSON key; legacy agents
        # ignore it) carrying the incident's trace_id plus the master-side
        # wall-clock marks, so the worker's incident report can reconstruct
        # the full detect → broadcast → notified → apply chain.
        broadcast_at = time.time()
        trace_ctx: dict | None = None
        with self._snap_lock:
            for r in self._recoveries:
                if r["lost_ip"] == ip and r["broadcast_at"] is None:
                    r["broadcast_at"] = broadcast_at
                    r["mechanism"] = decision.mechanism
                    if r.get("trace_id"):
                        trace_ctx = {
                            "trace_id": r["trace_id"],
                            "detected_at": r["detected_at"],
                            "broadcast_at": broadcast_at,
                            "cause": r.get("cause"),
                        }
        payload: dict = {"lost_ip": ip, DECISION_KEY: decision.as_payload()}
        if self.master_epoch:
            # Split-brain fence: agents reject verbs below their
            # highest-applied epoch, so a zombie pre-restart master's
            # broadcasts are refused fleet-wide. Epoch 0 (journaling off)
            # stays unstamped — legacy untagged trust.
            payload[EPOCH_KEY] = self.master_epoch
        if trace_ctx is not None:
            payload[spans.TRACE_KEY] = trace_ctx
            decision.trace_id = trace_ctx["trace_id"]
            spans.span_recorder().record(
                "incident.broadcast", broadcast_at, broadcast_at,
                trace_id=trace_ctx["trace_id"], lost_ip=ip, verb=verb.value,
                mechanism=decision.mechanism, survivors=len(self.agents))
        for other in include:
            try:
                await send_response(other.writer, verb, payload)
            except ConnectionError:
                pass
        self._m_reconfigs.inc()
        fr = metrics.flight_recorder()
        fr.record("reconfiguration_broadcast", lost_ip=ip,
                  survivors=len(self.agents), verb=verb.value,
                  mechanism=decision.mechanism)
        # Second dump so the postmortem file holds the complete sequence
        # detect → broadcast (the detect-time dump races the broadcast).
        fr.dump(f"reconfiguration_broadcast:{ip}")
        recovery.mark(recovery.BROADCAST, lost_ip=ip,
                      survivors=len(self.agents))

    async def _broadcast_grow(self, joined_ips: list[str], decision,
                              include: list[AgentInfo]) -> None:
        """Broadcast the decided grow verb for a JOIN batch, policy
        decision attached. GROW always rides the one verb — the chosen arm
        (absorb_spare / grow_dp / grow_reshape) travels inside the
        decision payload, so legacy receivers that predate the verb skip
        the whole thing knowingly (absorption degrades to a no-op, never
        an outage). The empty lost_ip satisfies the shared broadcast
        machinery's core-key contract."""
        broadcast_at = time.time()
        trace_ctx: dict | None = None
        with self._snap_lock:
            for r in self._recoveries:
                if (r.get("joined_ips") == joined_ips
                        and r["broadcast_at"] is None):
                    r["broadcast_at"] = broadcast_at
                    r["mechanism"] = decision.mechanism
                    if r.get("trace_id"):
                        trace_ctx = {
                            "trace_id": r["trace_id"],
                            "detected_at": r["detected_at"],
                            "broadcast_at": broadcast_at,
                            "cause": r.get("cause"),
                        }
        payload: dict = {"lost_ip": "", DECISION_KEY: decision.as_payload()}
        payload[JOINED_KEY] = list(joined_ips)
        if self.master_epoch:
            payload[EPOCH_KEY] = self.master_epoch
        if trace_ctx is not None:
            payload[spans.TRACE_KEY] = trace_ctx
            decision.trace_id = trace_ctx["trace_id"]
            spans.span_recorder().record(
                "incident.broadcast", broadcast_at, broadcast_at,
                trace_id=trace_ctx["trace_id"],
                joined_ips=",".join(joined_ips),
                verb=ResponseType.GROW.value,
                mechanism=decision.mechanism, agents=len(self.agents))
        for other in include:
            try:
                await send_response(other.writer, ResponseType.GROW, payload)
            except ConnectionError:
                pass
        self._m_grows.inc(mechanism=decision.mechanism)
        fr = metrics.flight_recorder()
        fr.record("grow_broadcast", joined_ips=",".join(joined_ips),
                  agents=len(self.agents), mechanism=decision.mechanism)
        fr.dump(f"grow_broadcast:{'+'.join(joined_ips)}")

    async def _broadcast_lease_grant(self, ip: str, lease,
                                     decision) -> None:
        """LEASE_GRANT rides the proactive-drain DEGRADE shape: the verb
        carries the arbiter decision flagged proactive (the victim
        drains — checkpoint flush, clean exit) and inplace (survivors
        reroute at a step boundary, zero respawns), plus the lease
        record under LEASE_KEY. Legacy agents fall back to
        RECONFIGURATION semantics (message.py), so a mixed fleet still
        converges."""
        broadcast_at = time.time()
        with self._snap_lock:
            for r in self._recoveries:
                if (r.get("trace_id") == decision.trace_id
                        and r["broadcast_at"] is None):
                    r["broadcast_at"] = broadcast_at
                    r["mechanism"] = decision.mechanism
        wire_decision = dict(decision.as_payload(),
                             proactive=True, inplace=True)
        payload: dict = {"lost_ip": ip, DECISION_KEY: wire_decision}
        payload[LEASE_KEY] = lease.as_record()
        if self.master_epoch:
            payload[EPOCH_KEY] = self.master_epoch
        if decision.trace_id:
            payload[spans.TRACE_KEY] = {
                "trace_id": decision.trace_id,
                "detected_at": decision.decided_at,
                "broadcast_at": broadcast_at,
                "cause": "pool_borrow",
            }
            spans.span_recorder().record(
                "incident.broadcast", broadcast_at, broadcast_at,
                trace_id=decision.trace_id, lost_ip=ip,
                verb=ResponseType.LEASE_GRANT.value,
                mechanism=decision.mechanism, survivors=len(self.agents))
        for other in list(self.agents.values()):
            try:
                await send_response(other.writer,
                                    ResponseType.LEASE_GRANT, payload)
            except ConnectionError:
                pass
        self._m_lease_broadcasts.inc(verb=ResponseType.LEASE_GRANT.value)
        fr = metrics.flight_recorder()
        fr.record("lease_grant_broadcast", lost_ip=ip,
                  lease_id=lease.lease_id, tenant=lease.tenant,
                  mechanism=decision.mechanism)
        fr.dump(f"lease_grant_broadcast:{ip}")
        recovery.mark(recovery.BROADCAST, lost_ip=ip,
                      survivors=len(self.agents))

    async def _broadcast_lease_reclaim(self, lease, decision) -> None:
        """LEASE_RECLAIM rides the GROW shape: the returning hosts
        travel under JOINED_KEY so agents extend membership through
        on_grow, while the host processes themselves re-enter through
        the normal JOIN/grow machinery (relaunching the returned host's
        agent is the deployer's job, exactly as for any grown host). The
        empty lost_ip satisfies the shared broadcast core-key
        contract."""
        broadcast_at = time.time()
        payload: dict = {"lost_ip": "", DECISION_KEY: decision.as_payload()}
        payload[JOINED_KEY] = list(lease.hosts)
        payload[LEASE_KEY] = lease.as_record()
        if self.master_epoch:
            payload[EPOCH_KEY] = self.master_epoch
        if decision.trace_id:
            payload[spans.TRACE_KEY] = {
                "trace_id": decision.trace_id,
                "detected_at": decision.decided_at,
                "broadcast_at": broadcast_at,
                "cause": f"pool_{lease.state}",
            }
            spans.span_recorder().record(
                "incident.broadcast", broadcast_at, broadcast_at,
                trace_id=decision.trace_id,
                joined_ips=",".join(lease.hosts),
                verb=ResponseType.LEASE_RECLAIM.value,
                mechanism=decision.mechanism, agents=len(self.agents))
        for other in list(self.agents.values()):
            try:
                await send_response(other.writer,
                                    ResponseType.LEASE_RECLAIM, payload)
            except ConnectionError:
                pass
        self._m_lease_broadcasts.inc(
            verb=ResponseType.LEASE_RECLAIM.value)
        fr = metrics.flight_recorder()
        fr.record("lease_reclaim_broadcast", lease_id=lease.lease_id,
                  hosts=",".join(lease.hosts), state=lease.state,
                  mechanism=decision.mechanism)
        fr.dump(f"lease_reclaim_broadcast:{lease.lease_id}")


async def _amain(port: int, launcher: str, username: str | None,
                 node_port: int, log_dir: str | None) -> None:
    if launcher == "ssh":
        l = SSHLauncher(username, node_port=node_port, log_dir=log_dir)
    else:
        l = LocalLauncher()
    daemon = OobleckMasterDaemon(port=port, launcher=l)
    await daemon.start()
    await daemon.serve_forever()


if __name__ == "__main__":
    import argparse

    p = argparse.ArgumentParser()
    p.add_argument("--port", type=int, default=19191)
    p.add_argument("--launcher", choices=["local", "ssh"], default="local",
                   help="ssh: one agent per host over ssh, with per-host "
                        "log capture; local: subprocesses (single machine)")
    p.add_argument("--username", default=None)
    p.add_argument("--node-port", type=int, default=22)
    p.add_argument("--log-dir", default=None)
    a = p.parse_args()
    logging.basicConfig(level=logging.INFO)
    asyncio.run(_amain(a.port, a.launcher, a.username, a.node_port, a.log_dir))
