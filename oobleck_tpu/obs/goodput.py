"""Attributed goodput ledger: where every second of wall-clock went.

Oobleck's pitch is throughput *under* failures, so the honest scoreboard
is not tokens/sec in a quiet window — it is the fraction of total
wall-clock that produced training progress, with every lost second
attributed to a bucket and (when one caused it) an incident id:

    step        productive compute inside training steps
    bubble      pipeline-schedule bubbles inside those steps
    data_wait   input pipeline stalls (host-side staging waits)
    checkpoint  synchronous checkpoint flush time
    recovery    reconfigure/restore windows (attributed to incidents)
    masterless  control-plane outage riding (agent-reported)
    other       wall-clock the buckets above do not explain (startup,
                shutdown, anything unattributed — reported, never hidden)

The ledger is fed exclusively with host-side floats the engine already
measured (step wall time, bubble fraction, ``dl.last_wait_s``, the
checkpoint plane's stall return, recovery phase totals) — it performs no
measurement of its own and no host syncs. ``goodput_fraction`` =
step / wall; the MFU estimate rides next to it from the planner's FLOPs
model (parallel/train.py) so "as fast as the hardware allows" is one
measured, attributed number.

Incident attribution: ``attribute(trace_id, seconds, bucket)`` charges
lost time to the incident that caused it. ``incident_cost(trace_id)``
returns the charge — the ``goodput_cost`` section the PR-8 incident
files carry.
"""

from __future__ import annotations

import threading
import time

BUCKETS = ("step", "bubble", "data_wait", "checkpoint", "recovery",
           "masterless", "other")


class GoodputLedger:
    """Wall-clock partition + per-incident attribution for one worker.

    Thread-safe: the train loop accounts steps while the checkpoint/
    recovery paths attribute from other call sites."""

    def __init__(self, clock=time.monotonic):
        self._clock = clock
        self._lock = threading.Lock()
        self._started_at = clock()
        self._buckets = dict.fromkeys(BUCKETS, 0.0)
        self._incidents: dict[str, dict] = {}
        self._steps = 0

    # -- feeds -------------------------------------------------------------- #

    def account_step(self, step_s: float, *, bubble_frac: float = 0.0,
                     data_wait_s: float = 0.0) -> None:
        """One training step: ``step_s`` of wall-clock, of which
        ``bubble_frac`` was pipeline bubble; ``data_wait_s`` is the input
        stall paid before the step (outside ``step_s``)."""
        frac = min(max(bubble_frac, 0.0), 1.0)
        with self._lock:
            self._steps += 1
            self._buckets["step"] += step_s * (1.0 - frac)
            self._buckets["bubble"] += step_s * frac
            if data_wait_s > 0:
                self._buckets["data_wait"] += data_wait_s

    def account(self, bucket: str, seconds: float) -> None:
        """Charge unattributed seconds to a named bucket."""
        if bucket not in BUCKETS:
            raise ValueError(f"unknown goodput bucket {bucket!r}: "
                             f"want one of {BUCKETS}")
        if seconds <= 0:
            return
        with self._lock:
            self._buckets[bucket] += seconds

    def attribute(self, trace_id: str, seconds: float, *,
                  bucket: str = "recovery", cause: str = "") -> None:
        """Charge ``seconds`` to ``bucket`` AND to the incident that
        caused them, so the incident file and /status agree on what the
        failure cost."""
        if bucket not in BUCKETS:
            raise ValueError(f"unknown goodput bucket {bucket!r}: "
                             f"want one of {BUCKETS}")
        if seconds <= 0 or not trace_id:
            return
        with self._lock:
            self._buckets[bucket] += seconds
            inc = self._incidents.setdefault(
                trace_id, {"lost_s": 0.0, "buckets": {}, "cause": cause})
            inc["lost_s"] += seconds
            inc["buckets"][bucket] = inc["buckets"].get(bucket, 0.0) \
                + seconds
            if cause:
                inc["cause"] = cause

    # -- reads -------------------------------------------------------------- #

    def wall_s(self) -> float:
        return max(self._clock() - self._started_at, 0.0)

    def goodput_fraction(self) -> float:
        """Productive-step seconds over total wall-clock (0 before the
        first step)."""
        wall = self.wall_s()
        if wall <= 0:
            return 0.0
        with self._lock:
            return min(self._buckets["step"] / wall, 1.0)

    def incident_cost(self, trace_id: str) -> dict | None:
        """The ``goodput_cost`` section for one incident file, or None
        when nothing was attributed to that trace."""
        with self._lock:
            inc = self._incidents.get(trace_id)
            if inc is None:
                return None
            return {
                "lost_s": round(inc["lost_s"], 6),
                "buckets": {b: round(v, 6)
                            for b, v in inc["buckets"].items()},
                "cause": inc["cause"],
            }

    def snapshot(self, *, mfu: float | None = None) -> dict:
        """The ledger view that ships in the worker's metrics snapshot
        and lands in master /status.fleet_health. ``other`` is computed
        here as the unexplained remainder, so the buckets always sum to
        the wall-clock they claim to partition."""
        wall = self.wall_s()
        with self._lock:
            buckets = dict(self._buckets)
            explained = sum(buckets.values()) - buckets["other"]
            buckets["other"] = round(max(wall - explained, 0.0), 6)
            out = {
                "wall_s": round(wall, 6),
                "steps": self._steps,
                "buckets": {b: round(v, 6) for b, v in buckets.items()},
                "goodput_fraction": round(
                    min(buckets["step"] / wall, 1.0) if wall > 0 else 0.0,
                    6),
                "incidents": {
                    t: {"lost_s": round(i["lost_s"], 6),
                        "buckets": {b: round(v, 6)
                                    for b, v in i["buckets"].items()},
                        "cause": i["cause"]}
                    for t, i in self._incidents.items()
                },
            }
        if mfu is not None:
            out["mfu"] = round(mfu, 6)
        return out
