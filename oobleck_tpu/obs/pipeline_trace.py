"""Per-op pipeline timeline export: the dispatched instruction schedule as
Perfetto slices.

The engine's measured bubble gauge (oobleck_engine_pipeline_bubble_fraction,
kind=measured) replays the calibrated per-(stage, chunk) fwd/bwd durations
through ``schedule.replay_schedule``. This module runs the SAME replay with
an ``on_op`` observer and renders every scheduled compute unit as one
Chrome-trace "X" slice per (stage, chunk, microbatch) — so warmup/cooldown
bubbles, reroute-borrowed microbatches, and serialization stalls show up as
gaps between slices in the Perfetto UI, and the trace's measured gap
fraction equals the bubble gauge by construction (one computation, two
renderings).

Lanes: one trace process per pipeline replica (pid = pipeline_id), one
thread lane per physical stage. Slice names are ``F`` / ``B`` plus the
microbatch (and ``c<chunk>`` when interleaved); borrowed microbatches
(index >= the pipeline's original share after a reroute) are tagged in
``args.borrowed``.
"""

from __future__ import annotations

import json
import logging
import os

from oobleck_tpu.execution.schedule import Instruction, Op, replay_schedule

logger = logging.getLogger("oobleck.obs")

ENV_PIPELINE_TRACE = "OOBLECK_PIPELINE_TRACE"


def duration_fn_from_op_times(op_times: dict):
    """duration_fn(inst) from a PipelineInstance's calibrated
    ``last_op_times`` ({(stage, chunk, "f"/"b"): (total_s, count)}), with
    the same same-kind-average fallback the engine's bubble gauge uses for
    never-timed chunks."""

    def dur(inst: Instruction) -> float:
        kind = "f" if inst.op is Op.FORWARD else "b"
        tot, n = op_times.get((inst.stage, inst.chunk, kind), (0.0, 0))
        if n:
            return tot / n
        vals = [t / c for (_, _, k), (t, c) in op_times.items()
                if k == kind and c]
        return sum(vals) / len(vals) if vals else 1.0

    return dur


def replay_slices(num_stages: int, num_microbatches: int,
                  virtual_stages: int = 1, duration_fn=None, streams=None):
    """(slices, makespan, busy): the dependency replay with every scheduled
    unit captured as (instruction, start_s, end_s)."""
    slices: list[tuple[Instruction, float, float]] = []

    def on_op(stage: int, inst: Instruction, start: float, end: float):
        slices.append((inst, start, end))

    makespan, busy = replay_schedule(
        num_stages, num_microbatches, virtual_stages, duration_fn,
        streams=streams, on_op=on_op)
    return slices, makespan, busy


def pipeline_trace(pipes, *, extra_events: list[dict] | None = None) -> dict:
    """Chrome-trace dict for one or more PipelineInstance objects.

    Each pipeline is replayed from its calibrated per-op durations (or the
    fwd=1/bwd=2 cost model before any step has timed ops). The per-pipeline
    summary carries makespan/busy and the gap fraction
    ``1 - busy/(S*makespan)`` — numerically the engine's measured bubble.
    """
    events: list[dict] = []
    summaries: list[dict] = []
    for pipe in pipes:
        S = pipe.num_stages
        M = pipe.num_microbatches
        v = getattr(pipe, "virtual_stages", 1)
        pid = int(getattr(pipe, "pipeline_id", 0))
        op_times = getattr(pipe, "last_op_times", None) or {}
        dur = duration_fn_from_op_times(op_times) if op_times else None
        try:
            slices, makespan, busy = replay_slices(S, M, v, dur)
        except RuntimeError as e:  # replay deadlock: skip this replica
            logger.warning("pipeline trace: replay failed for pipeline %d: %s",
                           pid, e)
            continue
        borrowed_from = getattr(pipe, "original_num_microbatches", None)
        events.append({"name": "process_name", "ph": "M", "pid": pid,
                       "tid": 0, "args": {"name": f"pipeline-{pid}"}})
        for i in range(S):
            events.append({"name": "thread_name", "ph": "M", "pid": pid,
                           "tid": i, "args": {"name": f"stage {i}"}})
        for inst, start, end in slices:
            kind = "F" if inst.op is Op.FORWARD else "B"
            name = f"{kind} mb{inst.microbatch}"
            if v > 1:
                name += f" c{inst.chunk}"
            args = {"op": inst.op.value, "stage": inst.stage,
                    "chunk": inst.chunk, "microbatch": inst.microbatch}
            if borrowed_from is not None and inst.microbatch >= borrowed_from:
                args["borrowed"] = True
            events.append({
                "name": name, "ph": "X", "cat": "pipeline",
                "ts": round(start * 1e6, 3),
                "dur": round((end - start) * 1e6, 3),
                "pid": pid, "tid": inst.stage, "args": args,
            })
        gap = (max(0.0, 1.0 - busy / (S * makespan))
               if makespan > 0 and busy > 0 else 0.0)
        summaries.append({
            "pipeline_id": pid, "num_stages": S, "num_microbatches": M,
            "virtual_stages": v, "calibrated": bool(op_times),
            "makespan_s": makespan, "busy_s": busy,
            "bubble_fraction": gap,
        })
    events.extend(extra_events or [])
    return {"traceEvents": events, "displayTimeUnit": "ms",
            "otherData": {"pipelines": summaries}}


def write_pipeline_trace(path: str, pipes, **kwargs) -> dict:
    """Atomic (tmp + rename) write; returns the trace dict."""
    trace = pipeline_trace(pipes, **kwargs)
    tmp = f"{path}.tmp-{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(trace, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    logger.info("pipeline trace: %d events for %d pipeline(s) -> %s",
                len(trace["traceEvents"]),
                len(trace["otherData"]["pipelines"]), path)
    return trace
