"""Incident forensics: one committed ``incident-<n>.json`` per recovery.

An IncidentBuilder accumulates the wall-clock marks of one failure's
handling chain — detect → broadcast → notified → apply → first
post-recovery step — and on commit joins them with the spans recorded on
the incident's trace, the recent flight-recorder ring, and the relevant
metrics families into a single self-contained postmortem artifact.

Commit is atomic AND exclusive: the record is written to a temp file
(fsync'd) and published under the next free ``incident-<n>.json`` name via
``os.link`` — an all-or-nothing operation, so a crash mid-commit leaves no
torn report and two concurrent committers can never both claim one index.

Phase semantics (all adjacent-mark deltas; a mark the chain never reached
is simply absent, and its phases collapse out of the breakdown):

    detect      master observed the failure (or the engine resolved a
                chaos kill_stage directive in-process)
    broadcast   master sent DEGRADE/RECONFIGURATION to survivors
    notified    agent received the verb
    apply_start engine entered reconfigure()
    apply_end   reroute applied or plan re-instantiated
    first_step  first training step after recovery completed

``total_s`` = first mark → last mark, which for a complete chain is the
same failure-to-resume latency the recovery histogram observes.
"""

from __future__ import annotations

import json
import logging
import os
import re
import time
import uuid

from oobleck_tpu.obs import spans as spans_mod
from oobleck_tpu.utils import metrics

logger = logging.getLogger("oobleck.obs")

# Version stamped into every committed record. Readers (the sim corpus
# loader, future forensics tooling) skip-with-warning on versions they do
# not know rather than misparse them; bump on incompatible shape changes.
SCHEMA_VERSION = 1

# Canonical mark names, in chain order.
MARK_ORDER = ("detect", "broadcast", "notified", "apply_start", "apply_end",
              "first_step")

# Metric families worth freezing into the postmortem (recovery + degrade
# planes); everything else stays in the live registry/JSONL sink.
_METRIC_PREFIXES = ("oobleck_recovery_", "oobleck_degrade_",
                    "oobleck_engine_reconfig")

_INCIDENT_RE = re.compile(r"incident-(\d+)\.json$")


class IncidentBuilder:
    """Accumulates one incident's marks; ``commit()`` writes the report."""

    def __init__(self, lost_ip: str, *, trace_id: str | None = None,
                 cause: str | None = None, **attrs):
        self.trace_id = trace_id or spans_mod.new_trace_id()
        self.lost_ip = lost_ip
        self.cause = cause
        self.attrs = dict(attrs)
        self.marks: dict[str, float] = {}
        # Goodput attribution (obs/goodput.py ``incident_cost``): what
        # this incident cost in attributed wall-clock — a first-class
        # section of the committed record, set just before commit.
        self.goodput_cost: dict | None = None

    def mark(self, name: str, t: float | None = None) -> float:
        t = time.time() if t is None else float(t)
        self.marks[name] = t
        return t

    def adopt(self, trace_ctx: dict | None) -> None:
        """Fold wall-clock marks a propagated trace context carried along
        (detected_at/broadcast_at/notified_at from upstream processes)."""
        if not trace_ctx:
            return
        for key, name in (("detected_at", "detect"),
                          ("broadcast_at", "broadcast"),
                          ("notified_at", "notified")):
            v = trace_ctx.get(key)
            if isinstance(v, (int, float)):
                self.marks.setdefault(name, float(v))

    def phase_breakdown(self) -> dict:
        """{"phases": {"<a>_to_<b>": s, ...}, "total_s": s} over the marks
        actually present, in chain order."""
        present = [(n, self.marks[n]) for n in MARK_ORDER if n in self.marks]
        phases = {}
        for (a, ta), (b, tb) in zip(present, present[1:]):
            phases[f"{a}_to_{b}"] = round(tb - ta, 6)
        total = present[-1][1] - present[0][1] if len(present) > 1 else 0.0
        return {"phases": phases, "total_s": round(total, 6)}

    def build(self) -> dict:
        """The full incident record (not yet written anywhere)."""
        first = min(self.marks.values()) if self.marks else time.time()
        flight = [e for e in metrics.flight_recorder().events()
                  if e.get("t", 0.0) >= first - 5.0]
        snap = metrics.registry().snapshot()
        frozen = [m for m in snap.get("metrics", [])
                  if any(m.get("name", "").startswith(p)
                         for p in _METRIC_PREFIXES)]
        rec = {
            "schema_version": SCHEMA_VERSION,
            "trace_id": self.trace_id,
            "lost_ip": self.lost_ip,
            "cause": self.cause,
            "role": metrics.get_role(),
            "pid": os.getpid(),
            "committed_at": time.time(),
            "marks": {n: self.marks[n] for n in MARK_ORDER
                      if n in self.marks},
            **self.phase_breakdown(),
            "spans": spans_mod.span_recorder().for_trace(self.trace_id),
            "flight": flight,
            "metrics": frozen,
        }
        if self.goodput_cost:
            rec["goodput_cost"] = self.goodput_cost
        if self.attrs:
            rec["attrs"] = self.attrs
        return rec

    def commit(self, d: str | None = None) -> str | None:
        """Atomically publish the report as the next free incident-<n>.json
        under ``d`` (default OOBLECK_METRICS_DIR); None when no sink."""
        d = d or metrics.metrics_dir()
        if d is None:
            return None
        rec = self.build()
        tmp = os.path.join(d, f".incident-{os.getpid()}-{uuid.uuid4().hex[:8]}.tmp")
        try:
            with open(tmp, "w") as f:
                json.dump(rec, f, indent=1)
                f.flush()
                os.fsync(f.fileno())
            n = next_index(d)
            while True:
                final = os.path.join(d, f"incident-{n}.json")
                try:
                    os.link(tmp, final)
                    break
                except FileExistsError:
                    n += 1
                except OSError:
                    # Filesystem without hard links: exclusive-create the
                    # final name, then replace it with the fsync'd temp so
                    # the visible content transition is still atomic. A
                    # concurrent committer winning the index retries the
                    # next one, exactly like the os.link path above.
                    try:
                        fd = os.open(final,
                                     os.O_CREAT | os.O_EXCL | os.O_WRONLY)
                    except FileExistsError:
                        n += 1
                        continue
                    os.close(fd)
                    os.replace(tmp, final)
                    tmp = None
                    break
        except OSError as e:
            logger.warning("obs: cannot commit incident report: %s", e)
            return None
        finally:
            if tmp is not None:
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
        logger.warning("incident report committed: %s (lost_ip=%s total=%.3fs)",
                       final, self.lost_ip, rec["total_s"])
        return final


def next_index(d: str) -> int:
    """Smallest index >= every existing incident-<n>.json under ``d``."""
    n = 0
    try:
        for name in os.listdir(d):
            m = _INCIDENT_RE.match(name)
            if m:
                n = max(n, int(m.group(1)) + 1)
    except OSError:
        pass
    return n


def list_incidents(d: str) -> list[tuple[str, dict]]:
    """(path, record) for every parseable incident-<n>.json, index order."""
    out = []
    try:
        names = os.listdir(d)
    except OSError:
        return out
    indexed = sorted((int(m.group(1)), name) for name in names
                     if (m := _INCIDENT_RE.match(name)))
    for _, name in indexed:
        path = os.path.join(d, name)
        try:
            with open(path) as f:
                rec = json.load(f)
        except (OSError, ValueError) as e:
            logger.warning("obs: skipping unreadable incident %s: %s",
                           path, e)
            continue
        if isinstance(rec, dict):
            out.append((path, rec))
    return out
