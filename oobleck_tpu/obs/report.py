"""Incident forensics report CLI.

    python -m oobleck_tpu.obs.report [--dir DIR] [--trace OUT.json]
                                     [--incident N]

Reads the metrics sink directory (default: $OOBLECK_METRICS_DIR, falling
back to ./metrics) and renders every committed ``incident-<n>.json`` as a
phase-breakdown table, cross-checked against the recovery-latency
histogram collected by the same run. ``--trace`` additionally merges all
``spans-*.jsonl`` dumps plus incident spans into one Chrome-trace JSON
loadable in Perfetto (https://ui.perfetto.dev) or chrome://tracing.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys

from oobleck_tpu.obs import incident as incident_mod
from oobleck_tpu.obs import spans as spans_mod
from oobleck_tpu.utils import metrics


def _load_span_dumps(d: str) -> list[dict]:
    """All spans from every spans-*.jsonl dump under ``d`` (header lines
    have an "event" key and are skipped)."""
    out: list[dict] = []
    for path in sorted(glob.glob(os.path.join(d, "spans-*.jsonl"))):
        try:
            with open(path) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        rec = json.loads(line)
                    except ValueError:
                        continue
                    if isinstance(rec, dict) and "event" not in rec:
                        out.append(rec)
        except OSError:
            continue
    return out


def _dedupe_spans(spans: list[dict]) -> list[dict]:
    seen: set[tuple] = set()
    out = []
    for s in spans:
        key = (s.get("span_id"), s.get("t0"))
        if key in seen:
            continue
        seen.add(key)
        out.append(s)
    return out


def _recovery_histogram(d: str) -> dict | None:
    """Merged oobleck_recovery_latency_seconds across all JSONL sinks."""
    snapshots = metrics.read_jsonl_dir(d)
    if not snapshots:
        return None
    latest = metrics.latest_per_file(snapshots)
    series = metrics.find_series(latest, "oobleck_recovery_latency_seconds")
    return metrics.merge_histogram_series(series)


def _fmt_seconds(s: float) -> str:
    return f"{s * 1000:.1f} ms" if s < 1.0 else f"{s:.3f} s"


def render_incident(path: str, rec: dict, out=None) -> None:
    out = out if out is not None else sys.stdout
    print(f"\n== {os.path.basename(path)} ==", file=out)
    print(f"  trace_id : {rec.get('trace_id')}", file=out)
    print(f"  lost_ip  : {rec.get('lost_ip')}"
          f"   cause: {rec.get('cause')}", file=out)
    phases = rec.get("phases") or {}
    if phases:
        width = max(len(k) for k in phases)
        print("  phases:", file=out)
        for name, dt in phases.items():
            print(f"    {name:<{width}}  {_fmt_seconds(float(dt))}",
                  file=out)
    print(f"  total    : {_fmt_seconds(float(rec.get('total_s', 0.0)))}",
          file=out)
    nspans = len(rec.get("spans") or [])
    nflight = len(rec.get("flight") or [])
    print(f"  evidence : {nspans} span(s), {nflight} flight event(s)",
          file=out)


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m oobleck_tpu.obs.report", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--dir", default=None,
                    help="metrics sink dir (default: $OOBLECK_METRICS_DIR "
                         "or ./metrics)")
    ap.add_argument("--incident", type=int, default=None,
                    help="render only incident-<N>.json")
    ap.add_argument("--trace", default=None, metavar="OUT.json",
                    help="also write a merged Chrome-trace JSON here")
    args = ap.parse_args(argv)

    d = args.dir or metrics.metrics_dir() or "metrics"
    if not os.path.isdir(d):
        print(f"no metrics directory at {d!r} (set --dir or "
              f"{metrics.ENV_METRICS_DIR})", file=sys.stderr)
        return 1

    incidents = incident_mod.list_incidents(d)
    if args.incident is not None:
        want = f"incident-{args.incident}.json"
        incidents = [(p, r) for p, r in incidents
                     if os.path.basename(p) == want]

    if not incidents:
        print(f"no incident reports under {d}")
    for path, rec in incidents:
        render_incident(path, rec)

    hist = _recovery_histogram(d)
    if hist and hist.get("count"):
        p50 = metrics.histogram_percentile(hist, 0.50)
        p99 = metrics.histogram_percentile(hist, 0.99)
        print(f"\nrecovery latency histogram: n={hist['count']} "
              f"sum={hist['sum']:.3f}s p50={p50:.3f}s p99={p99:.3f}s")

    if args.trace:
        spans = _load_span_dumps(d)
        for _, rec in incidents:
            spans.extend(rec.get("spans") or [])
        spans = _dedupe_spans(spans)
        spans.sort(key=lambda s: s.get("t0", 0.0))
        spans_mod.write_chrome_trace(
            args.trace, spans,
            metadata={"source_dir": os.path.abspath(d),
                      "incidents": [os.path.basename(p)
                                    for p, _ in incidents]})
        print(f"\nwrote {len(spans)} span(s) -> {args.trace} "
              f"(load in https://ui.perfetto.dev)")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
