"""Observability plane: distributed tracing and incident forensics.

Three pieces, all dependency-free:

- ``spans``: bounded-ring span recorder with trace-context propagation
  over the elastic verbs and Chrome-trace/Perfetto export.
- ``pipeline_trace``: the dispatched pipeline instruction schedule
  rendered as per-(stage, chunk, microbatch) Perfetto slices, from the
  same replay that produces the measured bubble gauge.
- ``incident``: joins spans + flight-recorder rings + metrics snapshots
  into atomically committed ``incident-<n>.json`` postmortems with a
  recovery phase breakdown; rendered by ``python -m
  oobleck_tpu.obs.report`` (``make trace-report``).
"""

# NOTE: the pipeline_trace() builder function is intentionally NOT
# re-exported here — the bare name would shadow the submodule of the same
# name on this package.
from oobleck_tpu.obs.incident import IncidentBuilder, list_incidents
from oobleck_tpu.obs.pipeline_trace import (
    ENV_PIPELINE_TRACE,
    write_pipeline_trace,
)
from oobleck_tpu.obs.spans import (
    TRACE_KEY,
    SpanRecorder,
    event,
    extract,
    inject,
    new_trace_id,
    set_ambient,
    span,
    span_recorder,
    to_chrome_trace,
    write_chrome_trace,
)

__all__ = [
    "ENV_PIPELINE_TRACE",
    "IncidentBuilder",
    "SpanRecorder",
    "TRACE_KEY",
    "event",
    "extract",
    "inject",
    "list_incidents",
    "new_trace_id",
    "set_ambient",
    "span",
    "span_recorder",
    "to_chrome_trace",
    "write_chrome_trace",
    "write_pipeline_trace",
]
