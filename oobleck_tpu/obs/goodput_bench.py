"""Goodput/fleet-health bench: the observability plane's own perf gate.

Three measurements, one JSON line on stdout (``make goodput-bench``;
``bench.py`` records it under its ``goodput`` key for ``bench --diff``):

*   **straggler handling quality** — the 16-host straggler scenario
    through the REAL detector + policy chain (sim/cluster.py):
    goodput_fraction under the gray failures, how many SLOWDOWN
    incidents were raised (the blip must raise none), and the mean
    detect-to-drain latency.
*   **telemetry overhead** — the per-step cost of ``record_step`` as a
    fraction of a synthetic 1 ms step. The acceptance bar is < 1%; the
    digest cost is reported too but rides the publish cadence (~1/10
    steps), not the hot path.
*   **ledger overhead** — the per-step cost of ``account_step``, same
    bar.

CPU-only, jax-free, seeded — safe under the determinism gate.
"""

from __future__ import annotations

import json
import time

# Synthetic step wall time the overhead fractions are normalized to: a
# deliberately PESSIMISTIC 1 ms step (real steps are 100-1000x longer,
# so real overhead is 100-1000x smaller than reported here).
SYNTH_STEP_S = 0.001
OVERHEAD_STEPS = 5000


def _straggler_summary() -> dict:
    from oobleck_tpu.sim.cluster import SimCluster, SimConfig
    from oobleck_tpu.sim.scenarios import make_scenario

    scenario = make_scenario("straggler", seed=1117, hosts=16,
                             duration_s=300.0)
    t0 = time.perf_counter()
    run = SimCluster(SimConfig(hosts=16), scenario).run()
    elapsed = time.perf_counter() - t0
    slow = [i for i in run["incidents"] if "slowdown_ratio" in i]
    detect = run["detect_to_drain_s"]
    return {
        "goodput_fraction": run["goodput_ratio"],
        "slowdown_incidents": len(slow),
        "drained": sum(1 for i in slow
                       if i["mechanism"] in ("drain", "quarantine")),
        "detect_to_drain_s": (round(sum(detect) / len(detect), 6)
                              if detect else None),
        "elapsed_s": round(elapsed, 3),
    }


def _telemetry_summary() -> dict:
    from oobleck_tpu.obs import telemetry

    ring = telemetry.TelemetryRing(capacity=512, window=32)
    t0 = time.perf_counter()
    for i in range(OVERHEAD_STEPS):
        ring.record_step(i, SYNTH_STEP_S, compute_s=0.0008,
                         comm_s=0.0001, data_wait_s=0.00005,
                         ckpt_s=0.0, live_bytes=1 << 30)
    record_s = (time.perf_counter() - t0) / OVERHEAD_STEPS
    t0 = time.perf_counter()
    d = ring.digest()
    digest_s = time.perf_counter() - t0
    assert d is not None and d["n"] == 32
    return {
        "record_us": round(record_s * 1e6, 3),
        "overhead_frac_1ms_step": round(record_s / SYNTH_STEP_S, 6),
        "digest_us": round(digest_s * 1e6, 3),
    }


def _ledger_summary() -> dict:
    from oobleck_tpu.obs.goodput import GoodputLedger

    ledger = GoodputLedger()
    t0 = time.perf_counter()
    for _ in range(OVERHEAD_STEPS):
        ledger.account_step(SYNTH_STEP_S, bubble_frac=0.1,
                            data_wait_s=0.00005)
    account_s = (time.perf_counter() - t0) / OVERHEAD_STEPS
    snap = ledger.snapshot()
    return {
        "account_us": round(account_s * 1e6, 3),
        "overhead_frac_1ms_step": round(account_s / SYNTH_STEP_S, 6),
        "steps": snap["steps"],
    }


def measure() -> dict:
    t0 = time.perf_counter()
    out = {
        "straggler": _straggler_summary(),
        "telemetry": _telemetry_summary(),
        "ledger": _ledger_summary(),
    }
    out["elapsed_s"] = round(time.perf_counter() - t0, 3)
    return out


def main() -> None:
    print(json.dumps(measure()))


if __name__ == "__main__":
    main()
