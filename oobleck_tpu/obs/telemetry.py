"""Per-step per-host telemetry: the continuous fleet-health sample stream.

The metrics plane (utils/metrics.py) aggregates; the span plane
(obs/spans.py) explains single incidents. What neither provides is a
CONTINUOUS per-host signal the master can compare across the fleet — the
stream that makes a host that is alive-but-slow (a gray failure: thermal
throttling, a dying NIC, a noisy neighbor) visible *before* its heartbeat
deadline ever fires. This module is that stream's host-local half.

Design constraints, in order:

1.  **Zero host syncs.** Every value recorded here is a host-side float
    the caller already had (``time.perf_counter`` deltas, queue depths,
    shape metadata). Nothing in this module may read back a device value
    — it is covered by oobleck-lint's OBL002/OBL003 fence rules exactly
    like the step loop it instruments, so a readback cannot sneak in.
2.  **Bounded, allocation-light.** Samples land in a preallocated ring
    (a deque of tuples); recording is an append and nothing else. The
    steady-state cost is measured by ``make goodput-bench`` and must
    stay under 1% of step time.
3.  **Digest, not firehose.** The wire carries a compact windowed digest
    (piggybacked on the agent's existing heartbeat as one extra JSON
    key — legacy masters ignore it), never raw samples.

Knobs:
    OOBLECK_TELEMETRY=0            disable sampling entirely
    OOBLECK_TELEMETRY_CAPACITY     ring size in samples (default 512)
    OOBLECK_TELEMETRY_WINDOW       samples per digest (default 32)
"""

from __future__ import annotations

import collections
import os
import threading

ENV_TELEMETRY = "OOBLECK_TELEMETRY"
ENV_CAPACITY = "OOBLECK_TELEMETRY_CAPACITY"
ENV_WINDOW = "OOBLECK_TELEMETRY_WINDOW"

DEFAULT_CAPACITY = 512
DEFAULT_WINDOW = 32

# Digest schema version: receivers skip digests they do not understand
# (the same skip-with-warning posture as incident SCHEMA_VERSION).
DIGEST_VERSION = 1

# Sample tuple layout (kept positional: a tuple append is the cheapest
# thing CPython can do per step, and the digest is the only reader).
_STEP, _STEP_S, _COMPUTE_S, _COMM_S, _DATA_WAIT_S, _CKPT_S, _LIVE_BYTES = \
    range(7)


def _env_int(name: str, default: int) -> int:
    raw = os.environ.get(name, "")
    try:
        return int(raw) if raw else default
    except ValueError:
        return default


class TelemetryRing:
    """Bounded per-process sample ring + windowed digest builder.

    ``record_step`` is the hot-path entry point: pure-python tuple append
    under a lock that is uncontended in steady state (the digest reader
    runs on the publish cadence, every ~10 steps). Everything heavier —
    sorting for percentiles, dict building — happens in ``digest()``,
    off the per-step path.
    """

    def __init__(self, capacity: int | None = None,
                 window: int | None = None):
        self.enabled = os.environ.get(ENV_TELEMETRY, "1") != "0"
        if capacity is None:
            capacity = _env_int(ENV_CAPACITY, DEFAULT_CAPACITY)
        if window is None:
            window = _env_int(ENV_WINDOW, DEFAULT_WINDOW)
        self.window = max(window, 1)
        self._lock = threading.Lock()
        self._ring: collections.deque = collections.deque(
            maxlen=max(capacity, 1))

    # -- hot path ----------------------------------------------------------- #

    def record_step(self, step: int, step_s: float, *,
                    compute_s: float = 0.0, comm_s: float = 0.0,
                    data_wait_s: float = 0.0, ckpt_s: float = 0.0,
                    live_bytes: int = 0) -> None:
        """Append one step's host-side timings. All arguments are plain
        host floats the caller already measured — never device values."""
        if not self.enabled:
            return
        with self._lock:
            self._ring.append((step, step_s, compute_s, comm_s,
                               data_wait_s, ckpt_s, live_bytes))

    # -- digest (publish cadence, not per-step) ----------------------------- #

    def digest(self) -> dict | None:
        """Compact summary of the last ``window`` samples, or None when
        nothing was recorded. Short keys: the digest rides every
        heartbeat, so its wire weight is paid ~6x/minute per host."""
        with self._lock:
            tail = list(self._ring)[-self.window:]
        if not tail:
            return None
        n = len(tail)
        steps = sorted(s[_STEP_S] for s in tail)
        return {
            "v": DIGEST_VERSION,
            "n": n,
            "step": tail[-1][_STEP],
            "step_s": round(sum(steps) / n, 6),
            "step_p50_s": round(steps[n // 2], 6),
            "step_max_s": round(steps[-1], 6),
            "compute_s": round(sum(s[_COMPUTE_S] for s in tail) / n, 6),
            "comm_s": round(sum(s[_COMM_S] for s in tail) / n, 6),
            "data_wait_s": round(sum(s[_DATA_WAIT_S] for s in tail) / n, 6),
            "ckpt_s": round(sum(s[_CKPT_S] for s in tail), 6),
            "live_bytes": tail[-1][_LIVE_BYTES],
        }

    def samples(self) -> list[tuple]:
        with self._lock:
            return list(self._ring)

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)


def digest_ok(d) -> bool:
    """Whether a wire-received digest is one this reader understands —
    the legacy-tolerance gate: absent (old agent) and future-versioned
    digests are both skipped, never errors."""
    return (isinstance(d, dict) and d.get("v") == DIGEST_VERSION
            and isinstance(d.get("step_s"), (int, float)))


_instance: TelemetryRing | None = None


def telemetry() -> TelemetryRing:
    """Process-global ring, built from the env knobs on first use."""
    global _instance
    if _instance is None:
        _instance = TelemetryRing()
    return _instance


def reset(capacity: int | None = None,
          window: int | None = None) -> TelemetryRing:
    """Re-build the global ring (tests monkeypatch the env then call
    this)."""
    global _instance
    _instance = TelemetryRing(capacity, window)
    return _instance
