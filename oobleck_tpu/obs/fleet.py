"""Master-side fleet-health aggregator: robust per-host stats + straggler
detection.

Each agent heartbeat carries a telemetry digest (obs/telemetry.py); this
tracker keeps one row per host — latest digest, a step-time EWMA, and the
cross-fleet robust statistics (median / MAD z-score, ratio-vs-median)
that make a *relatively* slow host stand out regardless of the absolute
step time of the moment.

Detection is deliberately conservative, because the cost of a false
positive is a drained healthy host:

*   **robust, not mean/stddev** — one straggler inflates a mean badly
    enough to hide itself; the median/MAD pair is immune to the very
    outlier it is hunting.
*   **two independent thresholds** — the ratio-vs-median gate catches
    "meaningfully slower than the fleet" in absolute terms; the z-gate
    (applied when the fleet is large enough for MAD to mean anything)
    catches "statistically impossible under this fleet's spread".
*   **persistence hysteresis** — a host must breach on
    ``OOBLECK_STRAGGLER_PERSIST`` *consecutive* digests before it is
    flagged. A transient blip (GC pause, one slow input batch) resets to
    zero on the first healthy digest and never raises an incident.
*   **one flag per host** — ``consume_straggler()`` hands each flagged
    host out exactly once; the flag stays latched until ``clear(ip)``
    (the host was drained, lost, or re-registered), so a persistent
    straggler can never raise a second SLOWDOWN incident for the same
    degradation.

Knobs (read at construction; the sim injects explicit values instead):
    OOBLECK_STRAGGLER_RATIO     breach when step_s >= ratio * fleet
                                median (default 1.5)
    OOBLECK_STRAGGLER_Z         robust z threshold, fleets of >= 4 hosts
                                (default 3.0)
    OOBLECK_STRAGGLER_PERSIST   consecutive breaching digests before the
                                flag raises (default 3)
"""

from __future__ import annotations

import logging
import os
import time

logger = logging.getLogger("oobleck.obs")

ENV_RATIO = "OOBLECK_STRAGGLER_RATIO"
ENV_Z = "OOBLECK_STRAGGLER_Z"
ENV_PERSIST = "OOBLECK_STRAGGLER_PERSIST"

DEFAULT_RATIO = 1.5
DEFAULT_Z = 3.0
DEFAULT_PERSIST = 3

# MAD->sigma consistency constant for normal data: z = 0.6745*(x-med)/MAD.
MAD_SCALE = 0.6745
# Below this many reporting hosts the MAD is too degenerate to gate on;
# the ratio threshold alone decides.
MIN_HOSTS_FOR_Z = 4
# Step-time EWMA weight of the newest digest.
EWMA_ALPHA = 0.3


def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name, "")
    try:
        return float(raw) if raw else default
    except ValueError:
        return default


class _HostRow:
    __slots__ = ("digest", "ewma_s", "breaches", "flagged", "consumed",
                 "updated_at", "epoch", "z", "ratio")

    def __init__(self):
        self.digest: dict = {}
        self.ewma_s: float | None = None
        self.breaches = 0
        self.flagged = False
        self.consumed = False
        self.updated_at = 0.0
        self.epoch: int | None = None
        self.z: float | None = None
        self.ratio: float | None = None


class FleetTracker:
    """Per-host telemetry rows + straggler flags for the master.

    Not thread-safe by itself: the master's single event loop serializes
    ingestion, exactly like HostHealthTracker."""

    def __init__(self, *, clock=time.monotonic,
                 ratio: float | None = None, z: float | None = None,
                 persist: int | None = None):
        self._clock = clock
        self.ratio_threshold = (ratio if ratio is not None
                                else _env_float(ENV_RATIO, DEFAULT_RATIO))
        self.z_threshold = (z if z is not None
                            else _env_float(ENV_Z, DEFAULT_Z))
        self.persist = max(int(persist if persist is not None
                               else _env_float(ENV_PERSIST,
                                               DEFAULT_PERSIST)), 1)
        self._hosts: dict[str, _HostRow] = {}
        self._stale_digests = 0

    # -- ingestion ---------------------------------------------------------- #

    def ingest(self, ip: str, digest: dict, *,
               epoch: int | None = None,
               min_epoch: int | None = None) -> None:
        """Fold one heartbeat digest in and re-judge the host.

        ``min_epoch`` is the master's own epoch: a digest stamped with an
        OLDER epoch came from an agent that has not yet seen the fenced
        restart and describes a dead incarnation's steps — counted and
        dropped, mirroring the broadcast-side epoch fence."""
        if (min_epoch is not None and epoch is not None
                and epoch < min_epoch):
            self._stale_digests += 1
            return
        row = self._hosts.setdefault(ip, _HostRow())
        row.digest = dict(digest)
        row.epoch = epoch
        row.updated_at = self._clock()
        step_s = digest.get("step_s")
        if isinstance(step_s, (int, float)) and step_s > 0:
            row.ewma_s = (step_s if row.ewma_s is None else
                          (1 - EWMA_ALPHA) * row.ewma_s
                          + EWMA_ALPHA * step_s)
        self._judge(ip, row)

    def _judge(self, ip: str, row: _HostRow) -> None:
        """Recompute this host's z/ratio against the fleet and advance or
        reset its persistence counter."""
        step_s = row.digest.get("step_s")
        if not isinstance(step_s, (int, float)) or step_s <= 0:
            return
        peers = [r.digest.get("step_s") for r in self._hosts.values()]
        peers = sorted(v for v in peers
                       if isinstance(v, (int, float)) and v > 0)
        n = len(peers)
        if n < 2:
            return  # a fleet of one has no "relatively slow"
        med = peers[n // 2] if n % 2 else (peers[n // 2 - 1]
                                           + peers[n // 2]) / 2
        if med <= 0:
            return
        row.ratio = round(step_s / med, 6)
        mad = sorted(abs(v - med) for v in peers)[n // 2]
        row.z = (round(MAD_SCALE * (step_s - med) / mad, 6)
                 if mad > 0 else None)

        breach = row.ratio >= self.ratio_threshold and (
            n < MIN_HOSTS_FOR_Z or row.z is None
            or row.z >= self.z_threshold)
        if breach:
            row.breaches += 1
            if row.breaches >= self.persist and not row.flagged:
                row.flagged = True
                logger.warning(
                    "fleet: host %s flagged as straggler "
                    "(step=%.4fs median=%.4fs ratio=%.2f z=%s "
                    "breaches=%d)", ip, step_s, med, row.ratio,
                    row.z, row.breaches)
        else:
            # Healthy digest: the persistence counter resets (a blip dies
            # here), but an already-raised flag stays latched until
            # clear() — recovery does not un-raise the incident.
            row.breaches = 0

    # -- flag lifecycle ----------------------------------------------------- #

    def consume_straggler(self) -> str | None:
        """One-shot: the next flagged-but-unconsumed host ip, or None.
        Each flag is handed out exactly once — the dedup that makes one
        sustained slowdown exactly ONE SLOWDOWN incident."""
        for ip in sorted(self._hosts):
            row = self._hosts[ip]
            if row.flagged and not row.consumed:
                row.consumed = True
                return ip
        return None

    def flagged(self) -> list[str]:
        return sorted(ip for ip, r in self._hosts.items() if r.flagged)

    def ratio(self, ip: str) -> float | None:
        """Latest step-time ratio vs the fleet median for one host (the
        slowdown severity the policy arms are priced with)."""
        row = self._hosts.get(ip)
        return row.ratio if row is not None else None

    def clear(self, ip: str) -> None:
        """Drop a host's row and flag (drained, lost, or re-registered —
        its next digests describe a different life)."""
        self._hosts.pop(ip, None)

    # -- /status ------------------------------------------------------------ #

    def snapshot(self) -> dict:
        """Bounded per-host view for the master's /status fleet_health
        block."""
        now = self._clock()
        hosts = {}
        for ip, row in sorted(self._hosts.items()):
            hosts[ip] = {
                "step_s": row.digest.get("step_s"),
                "ewma_s": round(row.ewma_s, 6) if row.ewma_s else None,
                "z": row.z,
                "ratio": row.ratio,
                "breaches": row.breaches,
                "flagged": row.flagged,
                "step": row.digest.get("step"),
                "age_s": round(now - row.updated_at, 3),
            }
        return {
            "hosts": hosts,
            "flagged": self.flagged(),
            "stale_digests": self._stale_digests,
            "thresholds": {
                "ratio": self.ratio_threshold,
                "z": self.z_threshold,
                "persist": self.persist,
            },
        }
