"""Dependency-free span recorder with cross-process trace propagation.

The metrics plane (utils/metrics.py) answers *that* recovery took 21 s;
this module answers *where the time went*. A span is one named wall-clock
interval carrying ``trace_id`` / ``span_id`` / ``parent_id`` plus free-form
attributes. Finished spans land in a bounded ring (the flight-recorder
idiom) and can be dumped to ``OOBLECK_METRICS_DIR/spans-{role}-{pid}-{seq}
.jsonl`` or exported as Chrome-trace/Perfetto JSON (``to_chrome_trace``).

Trace context crosses processes by riding the elastic control-plane verbs
as one extra JSON key (``inject``/``extract`` — legacy peers parse fine,
payload dicts merge arbitrary keys) and crosses threads inside a process
via an explicit "ambient" context (``set_ambient``): the engine pins the
incident's trace around ``reconfigure()`` so spans recorded anywhere in
the recovery path (degrade apply, plan materialization, recovery marks)
stitch into one timeline without threading a context object through every
call signature.

Timestamps are wall-clock epoch seconds, same rationale as
utils/recovery.py: the chain crosses master/agent/worker processes, and
processes on one machine share a clock (TPU pods have NTP-class sync).
"""

from __future__ import annotations

import collections
import contextlib
import json
import logging
import os
import threading
import time
import uuid

from oobleck_tpu.utils import metrics

logger = logging.getLogger("oobleck.obs")

ENV_SPAN_CAPACITY = "OOBLECK_SPAN_CAPACITY"
# Payload key the elastic verbs carry trace context under. Receivers that
# predate the key ignore it (length-prefixed JSON merges arbitrary keys).
TRACE_KEY = "trace"


def new_trace_id() -> str:
    return uuid.uuid4().hex[:16]


def new_span_id() -> str:
    return uuid.uuid4().hex[:16]


class SpanRecorder:
    """Thread-safe bounded ring of finished spans (FlightRecorder idiom:
    always recording, cheap enough to leave on, dumped on demand)."""

    def __init__(self, capacity: int | None = None):
        if capacity is None:
            raw = os.environ.get(ENV_SPAN_CAPACITY, "")
            try:
                capacity = int(raw) if raw else 1024
            except ValueError:
                logger.warning("obs: malformed %s=%r ignored",
                               ENV_SPAN_CAPACITY, raw)
                capacity = 1024
        self._lock = threading.Lock()
        self._ring: collections.deque = collections.deque(
            maxlen=max(capacity, 1))
        self._seq = 0

    def record(self, name: str, t0: float, t1: float, *,
               trace_id: str | None = None, span_id: str | None = None,
               parent_id: str | None = None, **attrs) -> dict:
        """Append one finished span; returns the stored record."""
        span = {
            "name": name,
            "t0": t0,
            "t1": t1,
            "trace_id": trace_id or new_trace_id(),
            "span_id": span_id or new_span_id(),
            "parent_id": parent_id,
            "role": metrics.get_role(),
            "pid": os.getpid(),
            "tid": threading.get_native_id(),
        }
        if attrs:
            span["attrs"] = attrs
        with self._lock:
            self._ring.append(span)
        return span

    def spans(self) -> list[dict]:
        with self._lock:
            return list(self._ring)

    def for_trace(self, trace_id: str) -> list[dict]:
        return [s for s in self.spans() if s.get("trace_id") == trace_id]

    def dump(self, reason: str) -> str | None:
        """Write the whole ring to OOBLECK_METRICS_DIR/spans-{role}-{pid}-
        {seq}.jsonl; None when the sink is disabled."""
        d = metrics.metrics_dir()
        if d is None:
            return None
        with self._lock:
            spans = list(self._ring)
            self._seq += 1
            seq = self._seq
        path = os.path.join(
            d, f"spans-{metrics.get_role()}-{os.getpid()}-{seq}.jsonl")
        try:
            with open(path, "w") as f:
                f.write(json.dumps({"t": time.time(), "event": "dump",
                                    "reason": reason,
                                    "role": metrics.get_role()}) + "\n")
                for span in spans:
                    f.write(json.dumps(span) + "\n")
        except OSError as e:
            logger.warning("obs: cannot write span dump %s: %s", path, e)
            return None
        return path


_recorder = SpanRecorder()


def span_recorder() -> SpanRecorder:
    return _recorder


# ---------------------------------------------------------------------------
# context: thread-local span stack + process-wide ambient trace


_tls = threading.local()
_ambient_lock = threading.Lock()
_ambient: dict | None = None


def set_ambient(ctx: dict | None) -> None:
    """Pin a process-wide trace context ({"trace_id", "span_id"}) used when
    no thread-local span is open — how an incident's trace reaches spans
    recorded from other threads/modules during recovery."""
    global _ambient
    with _ambient_lock:
        _ambient = dict(ctx) if ctx else None


def ambient() -> dict | None:
    with _ambient_lock:
        return dict(_ambient) if _ambient else None


def current() -> dict | None:
    """The innermost open span's context, else the ambient one."""
    stack = getattr(_tls, "stack", None)
    if stack:
        return dict(stack[-1])
    return ambient()


@contextlib.contextmanager
def span(name: str, *, trace_id: str | None = None,
         parent_id: str | None = None, recorder: SpanRecorder | None = None,
         **attrs):
    """Record one span around a code region. Nested spans parent onto the
    enclosing one; the outermost parents onto the ambient context (if any).
    Yields the span's context dict ({"trace_id", "span_id"}) so callers can
    inject it into outbound messages."""
    ctx = current()
    if trace_id is None and ctx:
        trace_id = ctx.get("trace_id")
    if parent_id is None and ctx:
        parent_id = ctx.get("span_id")
    frame = {"trace_id": trace_id or new_trace_id(), "span_id": new_span_id()}
    stack = getattr(_tls, "stack", None)
    if stack is None:
        stack = _tls.stack = []
    stack.append(frame)
    t0 = time.time()
    try:
        yield frame
    finally:
        stack.pop()
        (recorder or _recorder).record(
            name, t0, time.time(), trace_id=frame["trace_id"],
            span_id=frame["span_id"], parent_id=parent_id, **attrs)


def event(name: str, t: float | None = None, **attrs) -> dict:
    """Record a zero-duration span (a point event) on the current trace."""
    ctx = current()
    t = time.time() if t is None else t
    return _recorder.record(
        name, t, t,
        trace_id=ctx.get("trace_id") if ctx else None,
        parent_id=ctx.get("span_id") if ctx else None, **attrs)


# ---------------------------------------------------------------------------
# wire propagation


def inject(ctx: dict | None = None) -> dict:
    """Trace context for an outbound message payload: {"trace_id",
    "span_id"}. Uses (and creates, if absent) the current context."""
    ctx = ctx or current()
    if not ctx:
        ctx = {"trace_id": new_trace_id(), "span_id": new_span_id()}
    return {"trace_id": ctx["trace_id"], "span_id": ctx.get("span_id")}


def extract(msg: dict | None) -> dict | None:
    """Trace context from an inbound message, or None. Tolerates anything:
    legacy peers send no TRACE_KEY, future peers may extend it."""
    if not isinstance(msg, dict):
        return None
    ctx = msg.get(TRACE_KEY)
    if not isinstance(ctx, dict) or not isinstance(ctx.get("trace_id"), str):
        return None
    return ctx


# ---------------------------------------------------------------------------
# Chrome-trace / Perfetto export


def to_chrome_trace(spans: list[dict], *, extra_events: list[dict] | None = None,
                    metadata: dict | None = None) -> dict:
    """Render spans as a Chrome-trace JSON object (complete "X" events,
    microsecond timestamps) loadable in Perfetto / chrome://tracing.

    Each distinct (role, pid) becomes one trace process with a
    ``process_name`` metadata event; ``tid`` passes through so spans from
    different threads land in different lanes."""
    events: list[dict] = []
    procs: dict[tuple, int] = {}
    for s in spans:
        key = (s.get("role", "proc"), s.get("pid", 0))
        if key not in procs:
            pid = len(procs) + 1
            procs[key] = pid
            events.append({
                "name": "process_name", "ph": "M", "pid": pid, "tid": 0,
                "args": {"name": f"{key[0]}-{key[1]}"},
            })
    for s in spans:
        pid = procs[(s.get("role", "proc"), s.get("pid", 0))]
        t0, t1 = float(s["t0"]), float(s["t1"])
        args = {
            "trace_id": s.get("trace_id"),
            "span_id": s.get("span_id"),
            "parent_id": s.get("parent_id"),
        }
        args.update(s.get("attrs") or {})
        events.append({
            "name": s["name"], "ph": "X", "cat": "span",
            "ts": round(t0 * 1e6, 3),
            "dur": round(max(t1 - t0, 0.0) * 1e6, 3),
            "pid": pid, "tid": int(s.get("tid", 0)),
            "args": args,
        })
    events.extend(extra_events or [])
    out = {"traceEvents": events, "displayTimeUnit": "ms"}
    if metadata:
        out["otherData"] = metadata
    return out


def write_chrome_trace(path: str, spans: list[dict], **kwargs) -> str:
    """Atomic (tmp + rename) Chrome-trace file write."""
    trace = to_chrome_trace(spans, **kwargs)
    tmp = f"{path}.tmp-{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(trace, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    return path
