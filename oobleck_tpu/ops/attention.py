"""Attention kernels.

The reference has no custom kernels (GPU compute goes through torch modules,
/root/reference/oobleck/module/model.py:71-83); on TPU the attention inner loop
is the one op worth a hand-written Pallas kernel. Three implementations behind
one functional interface:

  - "xla":    einsum + masked softmax; XLA fuses this well and it is the
              reference implementation for correctness tests.
  - "pallas": blockwise flash attention Pallas kernel (oobleck_tpu.ops.flash).
  - "ring":   ring attention over a sequence-parallel mesh axis
              (oobleck_tpu.ops.ring_attention) for long-context training.

All take [batch, heads, seq, head_dim] Q/K/V and return the same shape.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

NEG_INF = -1e9  # large-but-finite: jnp.finfo(bf16).min overflows under softmax subtraction


def _pallas_ok() -> bool:
    """True when Pallas TPU kernels run compiled (i.e. the backend is TPU).

    Shared by the flash/paged "auto" policies and the kernels' interpret
    toggles: off-TPU the kernels would run in interpreter mode — correct but
    slow — so auto selection falls back to XLA and explicit pallas requests
    flip `interpret=True` (CPU parity tests). One helper so the policy and
    the toggle can never disagree."""
    return jax.default_backend() == "tpu"


def _xla_causal_attention(
    q: jax.Array, k: jax.Array, v: jax.Array, *, scale: float | None = None,
    bias: jax.Array | None = None, causal: bool = True
) -> jax.Array:
    """Masked-softmax attention. [B, H, S, D] -> [B, H, S, D].

    `bias` ([H, Sq, Sk] or broadcastable) supports ALiBi (Bloom family);
    `causal=False` gives the bidirectional encoder form (BERT/ViT)."""
    *_, seq_q, head_dim = q.shape
    seq_k = k.shape[-2]
    if scale is None:
        scale = head_dim**-0.5
    logits = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    if bias is not None:
        logits = logits + bias.astype(logits.dtype)
    if causal:
        # Supports seq_q != seq_k (ring attention partial blocks).
        q_pos = jnp.arange(seq_q)[:, None] + (seq_k - seq_q)
        k_pos = jnp.arange(seq_k)[None, :]
        logits = jnp.where(q_pos >= k_pos, logits, NEG_INF)
    # Softmax in f32 for stability regardless of compute dtype.
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bhkd->bhqd", probs, v)


def alibi_slopes(num_heads: int) -> jax.Array:
    """ALiBi per-head slopes (Bloom): geometric sequence from 2^(-8/n)."""
    import math

    def pow2_slopes(n):
        start = 2.0 ** (-(2.0 ** -(math.log2(n) - 3)))
        return [start * (start ** i) for i in range(n)]

    if math.log2(num_heads).is_integer():
        s = pow2_slopes(num_heads)
    else:
        closest = 2 ** int(math.floor(math.log2(num_heads)))
        s = pow2_slopes(closest)
        extra = pow2_slopes(2 * closest)[0::2][: num_heads - closest]
        s = s + extra
    return jnp.asarray(s, jnp.float32)


def alibi_bias_from_slopes(slopes: jax.Array, seq_q: int, seq_k: int,
                           causal: bool = True) -> jax.Array:
    """[h, Sq, Sk] ALiBi bias for the GIVEN slopes only — callers holding a
    head slice (TP rank, Ulysses shard) materialize h=H_local rows instead
    of all H (the O(H S^2) buffer is the long-context memory hazard).

    Causal form: -slope * (q - k), the original ALiBi decoder penalty
    (future keys are masked anyway, so the sign of the k > q half never
    matters). Bidirectional (`causal=False`): -slope * |q - k| — the
    symmetric "nonsym" variant of the ALiBi encoder ablations. The signed
    form would REWARD attending to future keys (positive bias growing with
    k - q), which is never the intent."""
    q_pos = jnp.arange(seq_q)[:, None] + (seq_k - seq_q)
    k_pos = jnp.arange(seq_k)[None, :]
    dist = (q_pos - k_pos).astype(jnp.float32)
    if not causal:
        dist = jnp.abs(dist)
    return -slopes[:, None, None] * dist[None]


def alibi_bias(num_heads: int, seq_q: int, seq_k: int,
               causal: bool = True) -> jax.Array:
    """[H, Sq, Sk] ALiBi bias: -slope * (q - k) causal, -slope * |q - k|
    bidirectional."""
    return alibi_bias_from_slopes(alibi_slopes(num_heads), seq_q, seq_k,
                                  causal=causal)


# -- KV-cache decode path (serving) ------------------------------------- #

def cache_write(cache: jax.Array, new: jax.Array, pos: jax.Array) -> jax.Array:
    """Write one token's K or V into a slot cache at per-slot positions.

    cache [B, H, S, D]; new [B, H, D]; pos [B] int32 (each batch slot in a
    continuous batch sits at its own sequence position). Returns the updated
    cache; safe to donate — every write is a dynamic_update_slice."""
    def one(c, n, p):
        return jax.lax.dynamic_update_slice(
            c, n[:, None, :].astype(c.dtype), (0, p, 0))

    return jax.vmap(one)(cache, new, pos)


def decode_attention(
    q: jax.Array, k_cache: jax.Array, v_cache: jax.Array, pos: jax.Array, *,
    scale: float | None = None, alibi_slopes: jax.Array | None = None,
) -> jax.Array:
    """Single-token attention against a preallocated KV cache.

    q [B, Hq, D]; k_cache/v_cache [B, Hkv, S, D]; pos [B] is each slot's
    current position — keys at indices <= pos are live, later indices hold
    stale/garbage bytes from freed slots and are masked. Grouped-query
    caches (Hkv < Hq) fold query heads into [Hkv, G] groups against the
    unrepeated cache instead of materializing repeated K/V per step.
    Returns [B, Hq, D]."""
    b, hq, d = q.shape
    hkv, s = k_cache.shape[1], k_cache.shape[2]
    if scale is None:
        scale = d**-0.5
    g = hq // hkv
    qg = q.reshape(b, hkv, g, d)
    logits = jnp.einsum("bkgd,bksd->bkgs", qg, k_cache) * scale
    k_idx = jnp.arange(s)
    if alibi_slopes is not None:
        dist = (pos[:, None] - k_idx[None, :]).astype(jnp.float32)  # [B, S]
        slopes = alibi_slopes.reshape(hkv, g)
        logits = logits - slopes[None, :, :, None] * dist[:, None, None, :]
    live = k_idx[None, :] <= pos[:, None]                           # [B, S]
    logits = jnp.where(live[:, None, None, :], logits, NEG_INF)
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1).astype(q.dtype)
    return jnp.einsum("bkgs,bksd->bkgd", probs, v_cache).reshape(b, hq, d)


@functools.cache
def select_attention_impl(impl: str = "auto"):
    """Resolve an attention implementation name to a callable.

    "auto" picks pallas flash on TPU when the kernel supports the platform,
    otherwise the XLA path. Resolution is deferred so importing this module
    never triggers backend init.
    """
    if impl == "xla":
        return _xla_causal_attention
    if impl == "pallas":
        from oobleck_tpu.ops.flash import flash_attention

        return flash_attention
    if impl == "ring":
        from oobleck_tpu.ops.ring_attention import ring_attention

        return ring_attention
    if impl == "paged":
        # Ragged paged decode over block tables (serving hot path). The
        # callable has the paged signature (pools + block tables), not the
        # [B, H, S, D] one; it dispatches pallas/xla internally by backend.
        from oobleck_tpu.ops.paged_attention import paged_decode_attention

        return paged_decode_attention
    if impl == "ulysses":
        # The Ulysses all-to-all layout only exists under a sequence-
        # parallel mesh axis (models call ops.ulysses directly there);
        # without one it degenerates to the "auto" single-device choice —
        # flash on TPU, NOT the HBM-quadratic XLA path.
        return select_attention_impl("auto")
    if impl == "auto":
        # On TPU the Pallas flash kernel (fwd + bwd) is the default — it
        # keeps HBM traffic linear in S where the XLA path materializes
        # [S, S] logits. Elsewhere (CPU mesh tests) the kernel would run in
        # interpreter mode, so the fused XLA path is faster. Never silently
        # swallow an ImportError here — a masked fallback hides real bugs.
        if _pallas_ok():
            from oobleck_tpu.ops.flash import flash_attention

            return flash_attention
        return _xla_causal_attention
    raise ValueError(f"unknown attention impl: {impl!r}")


def causal_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    impl: str = "auto",
    scale: float | None = None,
    bias: jax.Array | None = None,
    alibi_slopes: jax.Array | None = None,
    causal: bool = True,
    constant_bias: bool = False,
) -> jax.Array:
    """Dispatching attention entry point.

    `constant_bias=True` asserts the bias carries no gradient (ALiBi and
    other position-only biases) — required for the flash kernel, whose VJP
    treats the bias as a constant. Learned/batch-dependent biases and
    cross-attention (seq_q != seq_k) always take the XLA path.

    Prefer `alibi_slopes` ([H] f32) over a materialized ALiBi `bias`: the
    flash kernel generates the bias block in-kernel from the slopes, so no
    O(H S^2) buffer exists in HBM at any S; non-flash fallbacks
    materialize it from the slopes only where unavoidable.
    """
    if bias is not None and alibi_slopes is not None:
        raise ValueError("pass bias OR alibi_slopes, not both")
    fn = select_attention_impl(impl)
    from oobleck_tpu.ops.ring_attention import ring_attention

    def slope_bias():
        # Non-flash fallback: materialize from slopes (constant, exact).
        return alibi_bias_from_slopes(alibi_slopes, q.shape[-2], k.shape[-2],
                                      causal=causal)

    if fn is ring_attention:
        # Ring handles unbiased causal self-attention only; anything else
        # falls back to XLA (single-device call — the sequence-parallel path
        # reaches ring_attention directly with its own checks).
        if bias is None and alibi_slopes is None and causal:
            return fn(q, k, v, scale=scale)
        if alibi_slopes is not None:
            bias = slope_bias()
        return _xla_causal_attention(q, k, v, scale=scale, bias=bias,
                                     causal=causal)
    flash_ok = (
        q.shape[-2] == k.shape[-2]
        and (bias is None
             or (constant_bias and (bias.ndim < 4 or bias.shape[0] == 1)))
    )
    if fn is _xla_causal_attention or not flash_ok:
        if alibi_slopes is not None:
            bias = slope_bias()
        return _xla_causal_attention(q, k, v, scale=scale, bias=bias,
                                     causal=causal)
    return fn(q, k, v, scale=scale, bias=bias, alibi_slopes=alibi_slopes,
              causal=causal)
