"""Attention kernels.

The reference has no custom kernels (GPU compute goes through torch modules,
/root/reference/oobleck/module/model.py:71-83); on TPU the attention inner loop
is the one op worth a hand-written Pallas kernel. Three implementations behind
one functional interface:

  - "xla":    einsum + masked softmax; XLA fuses this well and it is the
              reference implementation for correctness tests.
  - "pallas": blockwise flash attention Pallas kernel (oobleck_tpu.ops.flash).
  - "ring":   ring attention over a sequence-parallel mesh axis
              (oobleck_tpu.ops.ring_attention) for long-context training.

All take [batch, heads, seq, head_dim] Q/K/V and return the same shape.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

NEG_INF = -1e9  # large-but-finite: jnp.finfo(bf16).min overflows under softmax subtraction


def _xla_causal_attention(
    q: jax.Array, k: jax.Array, v: jax.Array, *, scale: float | None = None
) -> jax.Array:
    """Plain masked-softmax attention. [B, H, S, D] -> [B, H, S, D]."""
    *_, seq_q, head_dim = q.shape
    seq_k = k.shape[-2]
    if scale is None:
        scale = head_dim**-0.5
    logits = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    # Causal mask; supports seq_q != seq_k (ring attention partial blocks).
    q_pos = jnp.arange(seq_q)[:, None] + (seq_k - seq_q)
    k_pos = jnp.arange(seq_k)[None, :]
    mask = q_pos >= k_pos
    logits = jnp.where(mask, logits, NEG_INF)
    # Softmax in f32 for stability regardless of compute dtype.
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bhkd->bhqd", probs, v)


@functools.cache
def select_attention_impl(impl: str = "auto"):
    """Resolve an attention implementation name to a callable.

    "auto" picks pallas flash on TPU when the kernel supports the platform,
    otherwise the XLA path. Resolution is deferred so importing this module
    never triggers backend init.
    """
    if impl == "xla":
        return _xla_causal_attention
    if impl == "pallas":
        from oobleck_tpu.ops.flash import flash_attention

        return flash_attention
    if impl == "ring":
        from oobleck_tpu.ops.ring_attention import ring_attention

        return ring_attention
    if impl == "auto":
        # Pallas flash is opt-in until its perf is validated per-platform;
        # auto currently means the XLA path everywhere. Never silently
        # swallow an ImportError here — a masked fallback hides real bugs.
        return _xla_causal_attention
    raise ValueError(f"unknown attention impl: {impl!r}")


def causal_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    impl: str = "auto",
    scale: float | None = None,
) -> jax.Array:
    return select_attention_impl(impl)(q, k, v, scale=scale)
