"""TPU compute kernels: attention implementations (XLA, Pallas flash, ring)."""

from oobleck_tpu.ops.attention import causal_attention, select_attention_impl

__all__ = ["causal_attention", "select_attention_impl"]
