"""TPU compute kernels: attention (XLA, Pallas flash, ring, Ulysses) and
switch-MoE with expert parallelism. Heavy submodules import lazily at their
call sites; this surface re-exports the dispatching entry points."""

from oobleck_tpu.ops.attention import causal_attention, select_attention_impl


def ring_attention(*args, **kwargs):
    from oobleck_tpu.ops.ring_attention import ring_attention as fn

    return fn(*args, **kwargs)


def ulysses_attention(*args, **kwargs):
    from oobleck_tpu.ops.ulysses import ulysses_attention as fn

    return fn(*args, **kwargs)


def switch_moe(*args, **kwargs):
    from oobleck_tpu.ops.moe import switch_moe as fn

    return fn(*args, **kwargs)


__all__ = ["causal_attention", "select_attention_impl", "ring_attention",
           "ulysses_attention", "switch_moe"]
