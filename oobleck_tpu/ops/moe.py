"""Switch-style mixture-of-experts with expert parallelism.

BEYOND-reference capability (SURVEY §2.2 "EP: absent" — absent in the
reference too): a top-1 switch MoE MLP (Switch Transformer routing:
per-token argmax expert, static capacity, load-balancing aux loss)
formulated entirely as dense einsums over STATIC shapes — the TPU
discipline: no gather/scatter, no data-dependent shapes, everything lands
on the MXU.

Expert parallelism shards the expert dimension over a mesh axis: each
device holds NE/P experts, computes its experts' outputs from the
(replicated) token stream, and one `psum` combines — the dispatch/combine
einsums are cheap relative to the expert FFNs, so this trades a little
redundant routing math for zero all-to-all choreography. Exactness vs the
unsharded formulation is tested under shard_map on the CPU mesh.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def switch_moe(
    x: jax.Array,
    router_w: jax.Array,
    w1: jax.Array,
    b1: jax.Array,
    w2: jax.Array,
    b2: jax.Array,
    *,
    num_experts: int,
    capacity_factor: float = 1.25,
    axis_name: str | None = None,
) -> tuple[jax.Array, jax.Array]:
    """Top-1 switch MoE over the token stream.

    x: [B, S, M] tokens; router_w: [M, NE] (always the GLOBAL expert
    count); w1/b1/w2/b2: this shard's experts — [NE_local, M, F] /
    [NE_local, F] / [NE_local, F, M] / [NE_local, M]. Without `axis_name`,
    NE_local == num_experts (unsharded). Returns (y [B, S, M], aux_loss) —
    aux is the Switch load-balancing loss over the global router
    distribution (identical on every shard).
    """
    B, S, M = x.shape
    T = B * S
    NE = num_experts
    ne_local = w1.shape[0]
    xf = x.reshape(T, M)

    logits = (xf.astype(jnp.float32)
              @ router_w.astype(jnp.float32))          # [T, NE]
    probs = jax.nn.softmax(logits, axis=-1)
    expert = jnp.argmax(probs, axis=-1)                # [T]
    gate = jnp.max(probs, axis=-1)                     # [T]

    # Static per-expert capacity; tokens beyond it are DROPPED (pass
    # through the residual only), the standard switch behavior.
    capacity = max(1, int(capacity_factor * T / NE))
    onehot = jax.nn.one_hot(expert, NE, dtype=jnp.float32)      # [T, NE]
    position = (jnp.cumsum(onehot, axis=0) - 1.0) * onehot      # [T, NE]
    keep = (position < capacity).astype(jnp.float32) * onehot
    slot = jax.nn.one_hot(
        position.sum(-1).astype(jnp.int32), capacity, dtype=jnp.float32
    )                                                           # [T, C]
    dispatch = keep[:, :, None] * slot[:, None, :]              # [T, NE, C]

    # Local expert slice of the dispatch tensor (EP: this shard computes
    # only its experts; the trailing psum restores the full combine).
    if axis_name is not None:
        offset = lax.axis_index(axis_name) * ne_local
        local_dispatch = lax.dynamic_slice_in_dim(
            dispatch, offset, ne_local, axis=1
        )
    else:
        assert ne_local == NE, (ne_local, NE)
        local_dispatch = dispatch

    dt = x.dtype
    inp = jnp.einsum("tec,tm->ecm", local_dispatch.astype(dt), xf)
    h = jax.nn.gelu(jnp.einsum("ecm,emf->ecf", inp, w1.astype(dt))
                    + b1.astype(dt)[:, None, :])
    # Unoccupied slots never appear in the combine (their dispatch weights
    # are zero), so the bias can be added unconditionally.
    out = jnp.einsum("ecf,efm->ecm", h, w2.astype(dt)) + b2.astype(dt)[:, None, :]
    combine = (local_dispatch * gate[:, None, None]).astype(dt)
    y = jnp.einsum("tec,ecm->tm", combine, out)
    if axis_name is not None:
        y = lax.psum(y, axis_name)

    # Switch load-balancing loss: NE * sum_e(fraction_routed_e * mean_prob_e)
    # over the GLOBAL distribution (router inputs are replicated, so this is
    # identical on every shard — no collective needed).
    fraction = onehot.mean(axis=0)
    mean_prob = probs.mean(axis=0)
    aux = NE * jnp.sum(fraction * mean_prob)
    return y.reshape(B, S, M), aux.astype(jnp.float32)
