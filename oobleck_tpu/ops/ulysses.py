"""Ulysses sequence parallelism: all-to-all head-parallel attention.

BEYOND-reference capability (SURVEY §2.2 "Ulysses: absent"), complementing
ring attention as the second long-context layout:

  * ring (`ops/ring_attention.py`): K/V shards rotate over `ppermute`;
    memory per chip stays O(S_local), comm is P-1 hops of the K/V shard —
    best when S is huge and heads are few.
  * ulysses (this module): ONE `all_to_all` trades the sequence shard for a
    head shard, every chip runs FULL-sequence attention over H/P heads with
    any single-device kernel (Pallas flash included), then one `all_to_all`
    trades back — two collectives total, and position-dependent biases
    (ALiBi) work unchanged because the whole sequence is present. Best when
    H >= P and S fits per-chip once attention is head-sliced.

Layout contract matches ring: q, k, v are [B, H, S_local, D] shards over
`axis_name`; the result is the same shard. Requires H % P == 0.
"""

from __future__ import annotations

import jax
from jax import lax


def ulysses_attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
                      axis_name: str, scale: float | None = None,
                      bias: jax.Array | None = None,
                      alibi_slopes: jax.Array | None = None,
                      causal: bool = True,
                      inner_impl: str = "auto") -> jax.Array:
    """All-to-all attention over a sequence-parallel mesh axis.

    `bias` is the FULL-sequence bias ([H, S, S] or broadcastable), sliced
    per-device to the local heads here. Position-only ALiBi should come in
    as `alibi_slopes` ([H] for the local input heads) instead: the bias is
    then handed to the inner kernel, which generates the bias from them —
    IN-KERNEL for the Pallas flash path, so zero bias bytes touch HBM at
    any S; non-flash fallbacks materialize only this device's [H/P, S, S]
    block. A pre-built [H, S, S] bias would cost O(H S^2) HBM per device,
    defeating sequence parallelism at long S (round-4 advisor).
    `inner_impl` picks the single-device kernel for the full-sequence
    attention (the Pallas flash path on TPU).
    """
    from oobleck_tpu.ops.attention import causal_attention

    P = lax.psum(1, axis_name)
    H = q.shape[1]
    if H % P != 0:
        raise ValueError(
            f"ulysses needs heads % axis size == 0, got {H} % {P}"
        )
    if bias is not None and alibi_slopes is not None:
        raise ValueError("pass bias OR alibi_slopes, not both")

    def seq_to_heads(x):
        # [B, H, S/P, D] -> [B, H/P, S, D]: each device keeps H/P heads of
        # the full sequence.
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)

    qh, kh, vh = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
    per = H // P
    idx = lax.axis_index(axis_name)
    local_bias = bias
    local_slopes = None
    if alibi_slopes is not None:
        # Slice this device's heads' slopes; the inner kernel generates
        # the bias from them (in-kernel for flash — zero HBM bias bytes).
        local_slopes = lax.dynamic_slice_in_dim(
            alibi_slopes, idx * per, per, axis=0
        )
    elif bias is not None and bias.ndim >= 3 and bias.shape[-3] == H:
        # Per-head bias over global heads: tiled all_to_all hands device i
        # heads [i*H/P, (i+1)*H/P), so slice its block; head-broadcast
        # biases (dim 1 or ndim<3) pass through unchanged.
        local_bias = lax.dynamic_slice_in_dim(bias, idx * per, per, axis=-3)
    out = causal_attention(qh, kh, vh, impl=inner_impl, scale=scale,
                           bias=local_bias, alibi_slopes=local_slopes,
                           causal=causal, constant_bias=True)
    # [B, H/P, S, D] -> [B, H, S/P, D]
    return lax.all_to_all(out, axis_name, split_axis=2, concat_axis=1,
                          tiled=True)
