"""Ring attention: causal attention over a sequence-parallel mesh axis.

The reference has no long-context support at all (SURVEY §5 "Long-context /
sequence parallelism: absent") — this is green-field TPU capability: the
sequence dim is sharded over a mesh axis, K/V shards rotate around the ring
with `lax.ppermute` while each device folds every block into its local
queries' online-softmax state. HBM per device stays O(S/n · D) and the
permutes overlap with the block compute on ICI.

Must run inside a full-manual shard_map with `axis_name` manual. Causality is
handled by global position offsets: block (q_shard i, kv origin j) applies a
full/partial/empty mask depending on i vs j.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

NEG_INF = -1e9


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
                   axis_name: str = "sp", scale: float | None = None,
                   remat: bool = True) -> jax.Array:
    """Causal attention with seq sharded over `axis_name`.

    q, k, v: [B, H, S_local, D] — this device's sequence shard.
    Returns [B, H, S_local, D], the attention output for the local queries
    over the *global* (causal-visible) sequence.
    """
    if scale is None:
        scale = q.shape[-1] ** -0.5
    # lax.axis_size is a post-0.4.x name; psum of a literal is the classic
    # spelling and constant-folds to a concrete int on every version.
    n = (lax.axis_size(axis_name) if hasattr(lax, "axis_size")
         else int(lax.psum(1, axis_name)))
    idx = lax.axis_index(axis_name)
    s_local = q.shape[2]
    qf = q.astype(jnp.float32)

    def block(qf, k, v, kv_rank):
        """Unnormalized local attention of qf against one K/V shard."""
        s = jnp.einsum("bhqd,bhkd->bhqk", qf, k.astype(jnp.float32)) * scale
        q_pos = idx * s_local + jax.lax.broadcasted_iota(jnp.int32, s.shape, 2)
        k_pos = kv_rank * s_local + jax.lax.broadcasted_iota(jnp.int32, s.shape, 3)
        s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        m = jnp.max(s, axis=-1, keepdims=True)                     # [B,H,Ql,1]
        # Fully-masked rows (future blocks) produce m = NEG_INF; clamp so
        # exp() stays finite and their contribution is exactly zero.
        m = jnp.maximum(m, -1e30)
        p = jnp.exp(s - m)
        p = jnp.where(q_pos >= k_pos, p, 0.0)
        l = jnp.sum(p, axis=-1, keepdims=True)
        o = jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32))
        return o, m, l

    if remat:
        block = jax.checkpoint(block)

    perm = [(i, (i + 1) % n) for i in range(n)]

    def step(carry, _):
        k, v, acc, m, l, rot = carry
        kv_rank = (idx - rot) % n
        o_b, m_b, l_b = block(qf, k, v, kv_rank)
        m_new = jnp.maximum(m, m_b)
        c_old = jnp.exp(m - m_new)
        c_new = jnp.exp(m_b - m_new)
        acc = acc * c_old + o_b * c_new
        l = l * c_old + l_b * c_new
        k = lax.ppermute(k, axis_name, perm)
        v = lax.ppermute(v, axis_name, perm)
        return (k, v, acc, m_new, l, rot + 1), None

    from oobleck_tpu.parallel.collectives import pvary_to

    # Carry init must match the compute's varying-axes type: everything q
    # varies over, plus the ring axis itself.
    vary = tuple(getattr(qf.aval, "vma", ()) or ()) + (axis_name,)
    acc0 = pvary_to(jnp.zeros(qf.shape, jnp.float32), vary)
    m0 = pvary_to(jnp.full((*qf.shape[:3], 1), -1e30, jnp.float32), vary)
    l0 = pvary_to(jnp.zeros((*qf.shape[:3], 1), jnp.float32), vary)
    (_, _, acc, _, l, _), _ = lax.scan(
        step, (k, v, acc0, m0, l0, jnp.int32(0)), None, length=n
    )
    return (acc / jnp.maximum(l, 1e-30)).astype(q.dtype)
