"""Blockwise (flash) causal attention — Pallas TPU kernel.

The reference has no custom kernels (all GPU compute goes through torch
modules); on TPU the attention inner loop is the one op worth hand-writing:
the naive path materializes the [S, S] score matrix in HBM, while this kernel
streams K/V blocks through VMEM with the online-softmax recurrence, keeping
HBM traffic linear in S.

Layout: grid (batch*heads, q_blocks, kv_blocks); the kv dimension is the
innermost sequential grid axis, so the f32 VMEM scratch (acc, m, l) carries
across kv steps and is finalized on the last one. Head dim is padded to the
128-lane width and sequence to the block size outside the kernel.

Backward: the VJP recomputes attention through the XLA path (exact same math)
— a dedicated backward kernel is a later optimization; under jax.checkpoint
the backward dominates memory anyway and stays O(S·D) resident either way.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e9

BLOCK_Q = 128
BLOCK_K = 128
LANE = 128


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref,
                  *, scale: float, blocks_k: int):
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    # Fully-masked blocks (kv strictly after this q block) contribute exactly
    # zero — skip their compute; the grid still visits them, but the MXU work
    # (the actual cost) is predicated away, ~halving causal FLOPs.
    @pl.when(ki * BLOCK_K <= qi * BLOCK_Q + (BLOCK_Q - 1))
    def _():
        q = q_ref[0].astype(jnp.float32)          # [Bq, D]
        k = k_ref[0].astype(jnp.float32)          # [Bk, D]
        v = v_ref[0].astype(jnp.float32)          # [Bk, D]

        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale                                  # [Bq, Bk]

        # causal mask on global positions
        q_pos = qi * BLOCK_Q + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
        k_pos = ki * BLOCK_K + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        s = jnp.where(q_pos >= k_pos, s, NEG_INF)

        m_prev = m_ref[:, :1]                      # [Bq, 1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)                     # [Bq, Bk]
        correction = jnp.exp(m_prev - m_new)       # [Bq, 1]

        l_new = l_ref[:, :1] * correction + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[:] = acc_ref[:] * correction + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(ki == blocks_k - 1)
    def _():
        # Padded-out rows can have l == 0; guard the divide.
        l = jnp.maximum(l_ref[:, :1], 1e-30)
        o_ref[0] = (acc_ref[:] / l).astype(o_ref.dtype)


def _flash_forward(q: jax.Array, k: jax.Array, v: jax.Array, scale: float
                   ) -> jax.Array:
    b, h, s_len, d = q.shape
    # Pad head dim to the lane width and seq to the block size; zero padding
    # is exact (padded dims contribute nothing to scores / outputs).
    d_pad = (LANE - d % LANE) % LANE
    s_pad = (BLOCK_Q - s_len % BLOCK_Q) % BLOCK_Q
    if d_pad or s_pad:
        pad = ((0, 0), (0, 0), (0, s_pad), (0, d_pad))
        q, k, v = (jnp.pad(x, pad) for x in (q, k, v))
    bh = b * h
    sp, dp = q.shape[2], q.shape[3]
    q, k, v = (x.reshape(bh, sp, dp) for x in (q, k, v))
    blocks_q = sp // BLOCK_Q
    blocks_k = sp // BLOCK_K

    kernel = functools.partial(_flash_kernel, scale=scale, blocks_k=blocks_k)
    # Interpreter mode off-TPU: tests validate kernel math on the CPU mesh.
    interpret = jax.default_backend() != "tpu"
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((bh, sp, dp), q.dtype),
        grid=(bh, blocks_q, blocks_k),
        in_specs=[
            pl.BlockSpec((1, BLOCK_Q, dp), lambda b_, qi, ki: (b_, qi, 0)),
            pl.BlockSpec((1, BLOCK_K, dp), lambda b_, qi, ki: (b_, ki, 0)),
            pl.BlockSpec((1, BLOCK_K, dp), lambda b_, qi, ki: (b_, ki, 0)),
        ],
        out_specs=pl.BlockSpec((1, BLOCK_Q, dp), lambda b_, qi, ki: (b_, qi, 0)),
        scratch_shapes=[
            pltpu.VMEM((BLOCK_Q, dp), jnp.float32),
            pltpu.VMEM((BLOCK_Q, LANE), jnp.float32),
            pltpu.VMEM((BLOCK_Q, LANE), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v)

    out = out.reshape(b, h, sp, dp)
    return out[:, :, :s_len, :d]


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _flash(q, k, v, scale):
    return _flash_forward(q, k, v, scale)


def _flash_fwd(q, k, v, scale):
    return _flash_forward(q, k, v, scale), (q, k, v)


def _flash_bwd(scale, res, g):
    from oobleck_tpu.ops.attention import _xla_causal_attention

    q, k, v = res
    _, vjp = jax.vjp(
        lambda q_, k_, v_: _xla_causal_attention(q_, k_, v_, scale=scale),
        q, k, v,
    )
    return vjp(g)


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
                    scale: float | None = None) -> jax.Array:
    """Causal flash attention. [B, H, S, D] -> [B, H, S, D]."""
    if scale is None:
        scale = q.shape[-1] ** -0.5
    return _flash(q, k, v, scale)
